"""Benchmark: HIGGS-shape synthetic training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference CPU learner trains HIGGS (10.5M rows x
28 features, num_leaves=255, 500 iterations) in 130.094 s on 2x E5-2690 v4.
The headline is MEASURED at the full 10.5M x 28 shape (u8-binned ~294 MB —
fits one chip's HBM with room): per-iteration wall-clock over REPEATS
timed blocks, median reported, spread recorded.  vs_baseline is
baseline_wall / (median_per_iter * 500)  (>1 means faster than the
reference CPU).

Because the chip is attached through a tunnel whose dispatch latency is
known to drift (PERF.md "tunnel health note"), the JSON also records a
dispatch-latency probe taken right before training; a noisy tunnel shows
up in `tunnel` instead of silently deflating the verdict.  A smaller row
count (BENCH_ROWS2, default 1M) adds an affine-fit diagnostic
t(N) = fixed + slope*N — diagnostics only, never the headline.
"""

import json
import os
import sys
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 10_500_000))
ROWS2 = int(os.environ.get("BENCH_ROWS2", 1_000_000))
FEATURES = 28
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
ITERS = int(os.environ.get("BENCH_ITERS", 20))
REPEATS = int(os.environ.get("BENCH_REPEATS", 5))
BASELINE_WALL_S = 130.094
BASELINE_ROWS = 10_500_000
BASELINE_ITERS = 500


def _dispatch_probe():
    """Per-dispatch and host-materialization round-trip latency through
    the attachment, measured on a trivial program (PERF.md: healthy is
    ~9-28 ms dispatch, ~105-120 ms materialization)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8, 128), jnp.float32)
    float(jnp.sum(f(x)))                      # compile + settle
    t0 = time.time()
    n = 20
    for _ in range(n):
        x = f(x)
    dispatch_s = (time.time() - t0) / n
    t0 = time.time()
    float(jnp.sum(x))
    mat_s = time.time() - t0
    return {"dispatch_ms": round(dispatch_s * 1e3, 2),
            "materialize_ms": round(mat_s * 1e3, 2)}


def _make_data(rows):
    rng = np.random.RandomState(7)
    X = rng.normal(size=(rows, FEATURES)).astype(np.float32)
    w = rng.normal(size=FEATURES)
    logit = X.dot(w) * 0.5
    y = (logit + rng.normal(size=rows) > 0).astype(np.float32)
    return X, y


def _train_blocks(lgb, rows, iters, repeats):
    X, y = _make_data(rows)
    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1,
        "max_bin": 255,
        "verbosity": -1,
        "metric": "",
    }
    if os.environ.get("BENCH_CHUNK"):
        params["tpu_row_chunk"] = int(os.environ["BENCH_CHUNK"])
    ds = lgb.Dataset(X, label=y)
    t0 = time.time()
    ds.construct(params)
    construct_s = time.time() - t0

    import jax.numpy as jnp

    bst = lgb.Booster(params=params, train_set=ds)

    def sync():
        # a host materialization is the only reliable completion barrier on
        # remote-attached TPUs (block_until_ready returns early there)
        return float(jnp.sum(bst._gbdt.scores))

    # warmup: compile the tree builder (1 iteration)
    t0 = time.time()
    bst.update()
    sync()
    warm = time.time() - t0

    # settling block (untimed): the first post-compile iterations through
    # the tunnel occasionally run an order of magnitude slow (observed:
    # a 5.5 s/iter first block against 0.25 steady-state); let the
    # attachment reach steady state before the timed blocks
    for _ in range(max(int(os.environ.get("BENCH_SETTLE_ITERS", 5)), 0)):
        bst.update()
    sync()

    blocks = []
    for _ in range(repeats):
        t0 = time.time()
        for _ in range(iters):
            bst.update()
        sync()
        blocks.append((time.time() - t0) / iters)
    return blocks, warm, construct_s


def _real_data_accuracy():
    """AUC parity on REAL data (round-4 verdict #3).  UCI HIGGS at 10.5M
    is not fetchable here (zero-egress env); the reference's bundled
    binary_classification example (7000 train / 500 test rows, a real
    HIGGS-derived sample per docs/) is the strongest real dataset
    available.  REF_* are the reference CLI's numbers measured LIVE on
    this machine (round 5: lightgbm built from /root/reference source,
    deterministic config = train.conf with sampling off)."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.textio import load_text_file

    REF_AUC = 0.828367        # live reference run, deterministic config
    REF_LOGLOSS = 0.509429
    base = None
    for root in ("/root/reference", os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            ".refbuild", "reftree")):
        cand = os.path.join(root, "examples", "binary_classification")
        if os.path.exists(os.path.join(cand, "binary.train")):
            base = cand
            break
    if base is None:
        return {"skipped": "reference example data not present"}
    tr = load_text_file(os.path.join(base, "binary.train"),
                        label_column="0")
    te = load_text_file(os.path.join(base, "binary.test"),
                        label_column="0")
    params = {"objective": "binary", "num_leaves": 63, "max_bin": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 50,
              "min_sum_hessian_in_leaf": 5.0, "verbosity": -1,
              "metric": ""}
    bst = lgb.train(params, lgb.Dataset(tr.X, label=tr.label),
                    num_boost_round=100)
    p = np.asarray(bst.predict(te.X))
    y = np.asarray(te.label)
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum()
    auc = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / \
        (npos * (len(y) - npos))
    eps = 1e-12
    ll = float(-np.mean(y * np.log(p + eps)
                        + (1 - y) * np.log(1 - p + eps)))
    return {"dataset": "reference binary_classification (real HIGGS "
                       "sample, 7000/500)",
            "auc": round(float(auc), 6), "logloss": round(ll, 6),
            "ref_auc": REF_AUC, "ref_logloss": REF_LOGLOSS,
            "auc_vs_ref": round(float(auc) - REF_AUC, 6),
            "note": "500-row test; f32 summation-order variants of the "
                    "same config measured 0.8227-0.8293 here vs ref "
                    "0.8284 — deltas within that band are noise"}


def _baseline_configs_block():
    """BASELINE.md "target configs to reproduce" rows that were missing
    from the detail table (round-6 verdict ask #3): lambdarank
    (NDCG@10 + s/iter), GOSS+EFB regression, and multiclass +
    categorical — at CPU-feasible sizes so the rows exist every round
    even without a TPU attachment.  Quality numbers are training-set
    diagnostics (synthetic data), not the published-dataset targets;
    they exist to catch per-config regressions in s/iter and learning
    behavior."""
    import time

    import numpy as np
    import lightgbm_tpu as lgb

    rows = int(os.environ.get("BENCH_CFG_ROWS", 40_000))
    iters = int(os.environ.get("BENCH_CFG_ITERS", 12))
    rng = np.random.RandomState(11)
    out = []

    def timed_train(params, ds):
        bst = lgb.Booster(params=params, train_set=ds)
        t0 = time.time()
        bst.update()
        warm = time.time() - t0
        t0 = time.time()
        for _ in range(iters - 1):
            bst.update()
        per = (time.time() - t0) / max(iters - 1, 1)
        return bst, round(per, 4), round(warm, 2)

    # 1) lambdarank (BASELINE.md target #3; Yahoo-LTR-shaped queries)
    qsize = 20
    nq = max(rows // qsize, 1)
    Xr = rng.normal(size=(nq * qsize, 30)).astype(np.float32)
    util = Xr[:, 0] + 0.5 * Xr[:, 1] + 0.2 * rng.normal(size=nq * qsize)
    rel = np.digitize(util, np.quantile(
        util, [0.5, 0.75, 0.9, 0.97])).astype(np.float64)
    params = {"objective": "lambdarank", "num_leaves": 63,
              "metric": "", "verbosity": -1}
    ds = lgb.Dataset(Xr, label=rel, group=np.full(nq, qsize))
    ds.construct(params)
    bst, per, warm = timed_train(params, ds)
    scores = np.asarray(bst.predict(Xr, raw_score=True))
    disc = 1.0 / np.log2(np.arange(2, 12))
    ndcg = []
    for qi in range(nq):
        sl = slice(qi * qsize, (qi + 1) * qsize)
        r = rel[sl]
        gains = (2.0 ** r[np.argsort(-scores[sl], kind="stable")][:10]
                 - 1) * disc
        ideal = (2.0 ** np.sort(r)[::-1][:10] - 1) * disc
        ndcg.append(gains.sum() / ideal.sum() if ideal.sum() > 0 else 1.0)
    out.append({"config": "lambdarank L63 (BASELINE target 3)",
                "rows": nq * qsize, "s_per_iter": per,
                "train_ndcg_at_10": round(float(np.mean(ndcg)), 5),
                "warmup_s": warm})

    # 2) GOSS + EFB regression (BASELINE.md target #2): sparse one-hot
    # blocks exercise the bundler, GOSS samples by gradient magnitude
    Xg = np.zeros((rows, 24), dtype=np.float32)
    Xg[:, :4] = rng.normal(size=(rows, 4))
    hot = rng.randint(0, 20, size=rows)
    Xg[np.arange(rows), 4 + hot] = 1.0
    yg = (Xg[:, 0] * 2 + hot * 0.1 +
          0.1 * rng.normal(size=rows)).astype(np.float64)
    params = {"objective": "regression", "num_leaves": 63,
              "data_sample_strategy": "goss", "enable_bundle": True,
              "metric": "", "verbosity": -1}
    ds = lgb.Dataset(Xg, label=yg)
    ds.construct(params)
    bst, per, warm = timed_train(params, ds)
    pred = np.asarray(bst.predict(Xg))
    out.append({"config": "GOSS+EFB regression L63 (BASELINE target 2)",
                "rows": rows, "s_per_iter": per,
                "train_l2": round(float(np.mean((pred - yg) ** 2)), 5),
                "warmup_s": warm})

    # 3) multiclass + categorical (BASELINE.md target #4)
    K = 5
    Xm = rng.normal(size=(rows, 12)).astype(np.float32)
    Xm[:, 3] = rng.randint(0, 30, size=rows)
    Xm[:, 7] = rng.randint(0, 8, size=rows)
    logits = rng.normal(size=(30, K))[Xm[:, 3].astype(int)] + \
        Xm[:, [0]] * rng.normal(size=(1, K))
    ym = np.argmax(logits + rng.gumbel(size=(rows, K)),
                   axis=1).astype(np.float64)
    params = {"objective": "multiclass", "num_class": K,
              "num_leaves": 31, "categorical_feature": [3, 7],
              "metric": "", "verbosity": -1}
    ds = lgb.Dataset(Xm, label=ym)
    ds.construct(params)
    bst, per, warm = timed_train(params, ds)
    prob = np.asarray(bst.predict(Xm))
    eps = 1e-12
    ll = float(-np.mean(np.log(
        prob[np.arange(rows), ym.astype(int)] + eps)))
    out.append({"config": "multiclass K5 + categorical (BASELINE "
                          "target 4)",
                "rows": rows, "s_per_iter": per,
                "train_multi_logloss": round(ll, 5),
                "warmup_s": warm})
    return out


def _multichip_block(n_dev):
    """Sharded fused data-parallel training over every local device:
    rows sharded on a 1-D mesh, one fused dispatch per iteration
    (models/boosting.py _setup_fused_sharded).  Small row count on CPU
    meshes (BENCH_MULTICHIP smoke), BENCH_MC_ROWS on real multi-chip."""
    import time as _time

    import jax
    import numpy as np
    import lightgbm_tpu as lgb

    rows = int(os.environ.get(
        "BENCH_MC_ROWS",
        200_000 if jax.default_backend() == "cpu" else ROWS))
    iters = int(os.environ.get("BENCH_MC_ITERS", 10))
    X, y = _make_data(rows)
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "learning_rate": 0.1, "max_bin": 255, "verbosity": -1,
              "metric": "", "tree_learner": "data"}
    ds = lgb.Dataset(X, label=y)
    ds.construct(params)
    bst = lgb.Booster(params=params, train_set=ds)
    fused = bst._gbdt._fused is not None

    def sync():
        import jax.numpy as jnp
        return float(jnp.sum(bst._gbdt.scores))

    bst.update()
    sync()
    t0 = _time.time()
    for _ in range(iters):
        bst.update()
    sync()
    per = (_time.time() - t0) / iters
    return {"devices": len(jax.devices()), "rows": rows, "iters": iters,
            "fused_sharded": fused,
            "s_per_iter": round(per, 4)}


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if os.environ.get("BENCH_PLATFORM"):
        import jax
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs

    # telemetry at counters for the whole bench: the BENCH_obs.json
    # artifact below records compile events and memory peaks alongside
    # the headline (zero-HLO; span cost is noise at these block sizes)
    obs.get().enable("counters")

    # kernel self-check FIRST, in a subprocess, before this process
    # touches the backend (single-host TPUs enforce single-process
    # ownership): the Pallas partition/search kernels' bug class (Mosaic
    # addressing / DMA windows, e.g. the round-3 pass-2 OOB) is
    # invisible to the CPU suite, so the bench — the one thing that
    # ALWAYS runs on TPU — guards it.  The child prints SKIP and exits 0
    # off-TPU; skip entirely with BENCH_SKIP_SELFCHECK=1.
    if not os.environ.get("BENCH_SKIP_SELFCHECK"):
        import subprocess
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            r = subprocess.run([sys.executable,
                                os.path.join(here, "tpu_selfcheck.py")],
                               capture_output=True, timeout=1200)
            out = r.stdout.decode()
            tail = out[-400:] + r.stderr.decode()[-400:]
            ok = r.returncode == 0 and ("ALL OK" in out or "SKIP" in out)
        except subprocess.TimeoutExpired as exc:
            tail = "tpu_selfcheck timed out after 1200s: " + \
                str(exc.stdout or b"")[-400:]
            ok = False
        if not ok:
            print(json.dumps({
                "metric": "tpu_selfcheck", "value": 0.0,
                "unit": "failed", "vs_baseline": 0.0,
                "detail": {"tail": tail}}))
            return
        print("tpu_selfcheck:", "ALL OK" if "ALL OK" in tail else "skip",
              file=sys.stderr)

    # export-on-failure guard: if the measured run dies below here, the
    # BENCH_obs artifact (and its BENCH_history.jsonl trajectory entry)
    # is still emitted with aborted=true, so a crashed round leaves
    # machine-readable evidence instead of a missing file
    from lightgbm_tpu.obs import benchio
    with benchio.abort_guard(
            "bench",
            {"rows": ROWS, "features": FEATURES, "leaves": NUM_LEAVES,
             "iters": ITERS, "repeats": REPEATS}) as obs_guard:
        _bench_body(lgb, obs_guard)


def _bench_body(lgb, obs_guard):
    tunnel = _dispatch_probe()
    blocks, warm, construct_s = _train_blocks(lgb, ROWS, ITERS, REPEATS)
    per_iter = float(np.median(blocks))

    mad = float(np.median(np.abs(np.asarray(blocks) - per_iter)))
    detail = {
        "iters_per_block": ITERS,
        "blocks_s_per_iter": [round(b, 4) for b in blocks],
        "mad_s_per_iter": round(mad, 5),
        "mad_pct": round(100.0 * mad / per_iter, 2),
        "spread_pct": round(100.0 * (max(blocks) - min(blocks))
                            / per_iter, 1),
        "warmup_compile_s": round(warm, 2),
        # dataset construction wall-clock (binning + EFB + device
        # ingest; ops/construct.py — see tools/profile_construct.py for
        # the per-stage host-loop/vectorized/device breakdown)
        "construct_s": round(construct_s, 2),
        "baseline_higgs_500iter_s": BASELINE_WALL_S,
        "per_iter_s": {str(ROWS): round(per_iter, 4)},
        "tunnel": tunnel,
    }

    if ROWS == BASELINE_ROWS:
        est_500 = per_iter * BASELINE_ITERS
        detail["projection"] = "measured at the baseline row count"
    else:
        est_500 = per_iter * BASELINE_ITERS * (BASELINE_ROWS / ROWS)
        detail["projection"] = "linear in rows from one point"

    # real-data accuracy parity (round-4 verdict #3)
    if not os.environ.get("BENCH_SKIP_ACCURACY"):
        try:
            detail["real_data_accuracy"] = _real_data_accuracy()
        except Exception as exc:
            detail["real_data_accuracy"] = {"error": str(exc)[:200]}

    # BASELINE target-config rows (round-6 verdict ask #3): lambdarank,
    # GOSS+EFB, multiclass+categorical at CPU-feasible sizes
    if not os.environ.get("BENCH_SKIP_CONFIGS"):
        try:
            detail["baseline_configs"] = _baseline_configs_block()
        except Exception as exc:
            detail["baseline_configs"] = {"error": str(exc)[:200]}

    # multi-chip readiness (round-4 verdict #10): when the attachment has
    # more than one device (or BENCH_MULTICHIP forces it on a virtual CPU
    # mesh), also time the sharded fused trainer over ALL local devices so
    # the multi-chip number is one command away the day hardware exists.
    # No-op on a single chip.
    import jax as _jax
    n_dev = len(_jax.devices())
    if n_dev > 1 or os.environ.get("BENCH_MULTICHIP"):
        try:
            detail["multichip"] = _multichip_block(n_dev)
        except Exception as exc:          # never sink the headline
            detail["multichip"] = {"error": str(exc)[:200]}

    if ROWS2 and ROWS2 != ROWS:
        # affine-fit diagnostic from a second, smaller row count
        blocks2, _, _ = _train_blocks(lgb, ROWS2, max(ITERS, 20), 1)
        per_iter2 = float(np.median(blocks2))
        detail["per_iter_s"][str(ROWS2)] = round(per_iter2, 4)
        slope = (per_iter - per_iter2) / (ROWS - ROWS2)
        if slope < 0:       # measurement noise: don't let a negative slope
            slope = 0.0     # inflate the fixed cost past the measurements
            fixed = min(per_iter, per_iter2)
        else:
            fixed = max(per_iter2 - slope * ROWS2, 0.0)
        detail["fit"] = {"fixed_s": round(fixed, 4),
                         "slope_s_per_mrow": round(slope * 1e6, 4)}

    detail["extrapolated_higgs_500iter_s"] = round(est_500, 2)
    vs_baseline = BASELINE_WALL_S / est_500

    print(json.dumps({
        "metric": f"higgs_synth_{ROWS}x{FEATURES}_L{NUM_LEAVES}_wall_per_iter",
        "value": round(per_iter, 4),
        "unit": "s/iter",
        "vs_baseline": round(vs_baseline, 4),
        "detail": detail,
    }))

    # machine-readable perf artifact (schema: lightgbm-tpu/bench-obs/v3;
    # path overridable via BENCH_OBS_PATH) — the PERF.md round gets a
    # diffable companion with compile counts, memory peaks and a
    # fingerprinted BENCH_history.jsonl trajectory entry that
    # `tools/perfwatch.py check` gates future rounds against
    path = obs_guard.write(
        {"per_iter_s": round(per_iter, 4),
         "vs_baseline": round(vs_baseline, 4), "detail": detail},
        metrics={"per_iter_s": per_iter, "vs_baseline": vs_baseline,
                 "construct_s": construct_s, "warmup_compile_s": warm},
        rows=ROWS, features=FEATURES)
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
