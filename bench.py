"""Benchmark: HIGGS-like synthetic training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference CPU learner trains HIGGS (10.5M rows x
28 features, num_leaves=255, 500 iterations) in 130.094 s on 2x E5-2690 v4.
Until the real HIGGS file is available in-image, this benchmark trains on a
synthetic dataset with HIGGS' shape at BENCH_ROWS (default 1M) rows AND at a
second row count (BENCH_ROWS2, default 4M), fits the affine model
t(N) = fixed + slope*N to the two points, and projects the baseline workload
(10.5M rows, 500 iters) from the FIT — a linear-in-rows extrapolation from one
point over-penalizes because the per-iteration fixed cost (~per-split
bookkeeping) does not scale with rows.  vs_baseline is
baseline_wall / projected_wall (>1 means faster than the reference CPU).
"""

import json
import os
import sys
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
ROWS2 = int(os.environ.get("BENCH_ROWS2", 4_000_000))
FEATURES = 28
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
ITERS = int(os.environ.get("BENCH_ITERS", 50))
BASELINE_WALL_S = 130.094
BASELINE_ROWS = 10_500_000
BASELINE_ITERS = 500


def _train_per_iter(lgb, rows, iters):
    rng = np.random.RandomState(7)
    X = rng.normal(size=(rows, FEATURES)).astype(np.float32)
    w = rng.normal(size=FEATURES)
    logit = X.dot(w) * 0.5
    y = (logit + rng.normal(size=rows) > 0).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1,
        "max_bin": 255,
        "verbosity": -1,
        "metric": "",
    }
    if os.environ.get("BENCH_CHUNK"):
        params["tpu_row_chunk"] = int(os.environ["BENCH_CHUNK"])
    ds = lgb.Dataset(X, label=y)
    ds.construct(params)

    import jax.numpy as jnp

    def sync():
        # a host materialization is the only reliable completion barrier on
        # remote-attached TPUs (block_until_ready returns early there)
        return float(jnp.sum(bst._gbdt.scores))

    # warmup: compile the tree builder (1 iteration)
    bst = lgb.Booster(params=params, train_set=ds)
    t0 = time.time()
    bst.update()
    sync()
    warm = time.time() - t0

    t0 = time.time()
    for _ in range(iters):
        bst.update()
    sync()
    return (time.time() - t0) / iters, warm


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if os.environ.get("BENCH_PLATFORM"):
        import jax
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import lightgbm_tpu as lgb

    per_iter, warm = _train_per_iter(lgb, ROWS, ITERS)

    detail = {
        "iters_timed": ITERS,
        "warmup_compile_s": round(warm, 2),
        "baseline_higgs_500iter_s": BASELINE_WALL_S,
        "per_iter_s": {str(ROWS): round(per_iter, 4)},
    }

    if ROWS2 and ROWS2 != ROWS:
        iters2 = max(ITERS // 4, 5)
        per_iter2, _ = _train_per_iter(lgb, ROWS2, iters2)
        detail["per_iter_s"][str(ROWS2)] = round(per_iter2, 4)
        # affine fit t(N) = fixed + slope*N from the two measured points
        slope = (per_iter2 - per_iter) / (ROWS2 - ROWS)
        if slope < 0:       # measurement noise: don't let a negative slope
            slope = 0.0     # inflate the fixed cost past the measurements
            fixed = min(per_iter, per_iter2)
        else:
            fixed = max(per_iter - slope * ROWS, 0.0)
        t_baseline_iter = fixed + slope * BASELINE_ROWS
        detail["fit"] = {"fixed_s": round(fixed, 4),
                         "slope_s_per_mrow": round(slope * 1e6, 4)}
        est_500 = t_baseline_iter * BASELINE_ITERS
        detail["projection"] = "affine fit over two row counts"
    else:
        est_500 = per_iter * BASELINE_ITERS * (BASELINE_ROWS / ROWS)
        detail["projection"] = "linear in rows from one point"
    detail["extrapolated_higgs_500iter_s"] = round(est_500, 2)
    vs_baseline = BASELINE_WALL_S / est_500

    print(json.dumps({
        "metric": f"higgs_synth_{ROWS}x{FEATURES}_L{NUM_LEAVES}_wall_per_iter",
        "value": round(per_iter, 4),
        "unit": "s/iter",
        "vs_baseline": round(vs_baseline, 4),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
