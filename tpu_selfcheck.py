"""One-command TPU verification: run on a real TPU attachment to validate
everything the CPU suite cannot (`python tpu_selfcheck.py`).

Covers, in order:
  1. partition kernel vs the NumPy oracle (bit-exact, incl. rowid rows);
  2. radix-4 compaction network vs the same oracle (tpu_compact_radix);
  3. split-search kernel vs the XLA fast search;
  4. rowid-row integrity through a full build_tree (guards the tunnel-XLA
     stack+concat miscompile found in round 3 — see PERF.md);
  5. hist-state RMW kernel vs numpy;
  6. split mega-kernel vs the NumPy partition oracle + the XLA
     both-children histogram oracle (bit-exact, incl. the zero-count
     trash-slot call);
  7. end-to-end train parity: Pallas kernels vs the XLA fallback path
     (tpu_megakernel=off), then mega-pallas vs mega-xla (the mega path
     is bit-identical to ITS oracle, not to the subtraction path).
"""
import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import numpy as np
import jax
import jax.numpy as jnp

if jax.default_backend() != "tpu":
    # callers (bench.py) treat SKIP as success: the check is only
    # meaningful on a real TPU attachment
    print(f"TPU SELF-CHECK: SKIP (backend is {jax.default_backend()})")
    sys.exit(0)
import lightgbm_tpu as lgb
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)
from lightgbm_tpu.ops import split as so
from lightgbm_tpu.ops.split_pallas import best_split_pair_pallas

# ---- 1. partition kernel vs oracle ----
def _oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl):
    pb = pb.copy(); pg = pg.copy()
    colv = pb[col, start:start+cnt].astype(np.int32)
    fb_raw = colv - bstart
    in_r = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = np.where(isb == 1, np.where(in_r, fb_raw, dbin), colv)
    miss = (fb == dbin) if mtype == 1 else ((fb == nb-1) if mtype == 2
                                            else np.zeros_like(fb, bool))
    gl = np.where(miss, dl != 0, fb <= thr)
    order = np.concatenate([np.where(gl)[0], np.where(~gl)[0]]) + start
    pb[:, start:start+cnt] = pb[:, order]
    pg[:, start:start+cnt] = pg[:, order]
    return pb, pg, int(gl.sum())

C, G32 = 1024, 32
Np = 10 * C
rng = np.random.RandomState(7)
for trial in range(6):
    pack = trial >= 3          # trials 3-5 exercise pack_rowid
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    if pack:
        pb[28:] = 0            # pad-row invariant pack_rowid relies on
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 5*C)); cnt = int(rng.randint(0, 4*C))
    col = int(rng.randint(0, 28)); isb = int(rng.rand() < 0.3)
    nb = int(rng.randint(10, 250)); bstart = int(rng.randint(0, 5)) if isb else 0
    dbin = int(rng.randint(0, nb)); mtype = int(rng.randint(0, 3))
    thr = int(rng.randint(0, nb)); dl = int(rng.rand() < 0.5)
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl)
    sc = make_scalars(start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl)
    rpb, rpg, _, rnl = partition_leaf_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc, row_chunk=C,
        ghi_live=5 if pack else 3, pack_rowid=pack)
    assert int(np.asarray(rnl)[0, 0]) == enl, trial
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    nliv = 5 if pack else 3
    np.testing.assert_array_equal(np.asarray(rpg)[:nliv].view(np.int32),
                                  epg[:nliv].view(np.int32))
print("[1/7] partition kernel vs oracle (incl pack_rowid): OK", flush=True)

# ---- 2. radix-4 compaction network vs oracle ----
for trial in range(3):
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 5*C)); cnt = int(rng.randint(0, 4*C))
    col = int(rng.randint(0, 28)); nb = int(rng.randint(10, 250))
    thr = int(rng.randint(0, nb)); dl = int(rng.rand() < 0.5)
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, 0, 0, thr, dl)
    sc = make_scalars(start, cnt, col, 0, 0, nb, 0, 0, thr, dl)
    rpb, rpg, _, rnl = partition_leaf_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc, row_chunk=C,
        compact_radix=True)
    assert int(np.asarray(rnl)[0, 0]) == enl, trial
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(np.asarray(rpg)[:3].view(np.int32),
                                  epg[:3].view(np.int32))
print("[2/7] radix-4 compaction network vs oracle: OK", flush=True)

# ---- 3. search kernel vs XLA fast search ----
F, BF = 28, 255
num_bin = rng.randint(3, BF + 1, size=F).astype(np.int32)
missing = rng.randint(0, 3, size=F).astype(np.int32)
dflt = np.where(missing == 1, rng.randint(0, 3, size=F), 0).astype(np.int32)
ctx = so.SplitContext(jnp.asarray(num_bin), jnp.asarray(missing),
                      jnp.asarray(dflt), jnp.zeros(F, jnp.int32),
                      jnp.arange(F, dtype=jnp.int32))
half = np.zeros((F, 8), np.int32)
half[:, 0] = num_bin; half[:, 1] = missing; half[:, 2] = dflt
fmeta = jnp.asarray(np.concatenate([half, half]))
hists, infos, refs = [], [], []
for c in range(2):
    hist = np.zeros((F, BF, 2), np.float32)
    for f in range(F):
        hist[f, :num_bin[f], 0] = rng.normal(size=num_bin[f])
        hist[f, :num_bin[f], 1] = rng.uniform(0.01, 2.0, size=num_bin[f])
    sum_g = float(hist[0, :, 0].sum()); sum_h = float(hist[0, :, 1].sum())
    mask = rng.rand(F) > 0.2
    refs.append(so.find_best_split_fast(
        jnp.asarray(hist), ctx, jnp.float32(sum_g), jnp.float32(sum_h),
        jnp.int32(2000), 0.0, 1e-3, 0.0, 0.0, 5, 1e-3, jnp.asarray(mask)))
    hists.append(hist)
    info = np.zeros((F, 8), np.float32)
    info[:, 0] = sum_g; info[:, 1] = sum_h; info[:, 2] = 2000
    info[:, 3] = 1.0; info[:, 4] = mask
    infos.append(info)
tile = np.asarray(best_split_pair_pallas(
    jnp.asarray(np.concatenate([hists[0][..., 0], hists[1][..., 0]])),
    jnp.asarray(np.concatenate([hists[0][..., 1], hists[1][..., 1]])),
    fmeta, jnp.asarray(np.concatenate(infos)),
    l1=0.0, l2=1e-3, max_delta_step=0.0, min_gain_to_split=0.0,
    min_data_in_leaf=5, min_sum_hessian=1e-3, max_depth=0))
for c, ref in enumerate(refs):
    assert tile[c, 1:2].view(np.int32)[0] == int(ref.feature)
    assert tile[c, 2:3].view(np.int32)[0] == int(ref.threshold)
print("[3/7] search kernel vs XLA fast search: OK", flush=True)

# ---- 4. rowid integrity through build_tree ----
N = 40000
X = rng.normal(size=(N, 8)).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
ds = lgb.Dataset(X, label=y)
bst = lgb.Booster(params={"objective": "binary", "num_leaves": 31,
                          "verbosity": -1, "metric": ""}, train_set=ds)
g = bst._gbdt
grad, hess = g._compute_gradients()
rec = g.learner.build_tree(grad, hess, N, g._feature_mask(0), seed=1)
idx = np.asarray(rec["indices"])
r0 = g.learner.row0
assert np.array_equal(np.sort(idx[r0:r0+N]), np.arange(N)), \
    "rowid row corrupted (stack+concat miscompile regression?)"
print("[4/7] rowid integrity: OK", flush=True)

# ---- 5. hist-state RMW kernel vs numpy ----
from lightgbm_tpu.ops.hist_state_pallas import flat_geometry, hist_rmw_pallas
Gf, Bf, WL = flat_geometry(28, 255)
st_h = rng.randn(34, 8, WL).astype(np.float32)
small = rng.randn(8, WL).astype(np.float32)
for (bl, wa, wb, sil) in [(3, 3, 7, 1), (5, 5, 9, 0), (2, 33, 33, 1)]:
    out, lft, rgt = hist_rmw_pallas(
        jnp.asarray(st_h), jnp.asarray(small),
        jnp.asarray([bl, wa, wb, sil], jnp.int32))
    large = st_h[bl] - small
    el = small if sil else large
    er = large if sil else small
    np.testing.assert_array_equal(np.asarray(lft), el)
    np.testing.assert_array_equal(np.asarray(rgt), er)
    exp = st_h.copy(); exp[wa] = el; exp[wb] = er
    np.testing.assert_array_equal(np.asarray(out), exp)
print("[5/7] hist-state RMW kernel: OK", flush=True)

# ---- 6. mega-kernel vs oracles (kernel-level) ----
from lightgbm_tpu.ops.split_megakernel_pallas import (
    both_children_hist_xla, split_megakernel_pallas)
G, B = 28, 255
for trial in range(4):
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 5*C))
    cnt = 0 if trial == 3 else int(rng.randint(1, 4*C))   # 3: trash slot
    col = int(rng.randint(0, G)); nb = int(rng.randint(10, 250))
    mtype = int(rng.randint(0, 3)); dbin = int(rng.randint(0, nb))
    thr = int(rng.randint(0, nb)); dl = int(rng.rand() < 0.5)
    radix = trial == 2
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, dbin,
                            mtype, thr, dl)
    sc = make_scalars(start, cnt, col, 0, 0, nb, dbin, mtype, thr, dl)
    rpb, rpg, _, rnl, acc = split_megakernel_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc, row_chunk=C,
        num_bins=B, num_groups=G, compact_radix=radix)
    assert int(np.asarray(rnl)[0, 0]) == enl, trial
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(np.asarray(rpg)[:3].view(np.int32),
                                  epg[:3].view(np.int32))
    acc_o = both_children_hist_xla(
        jnp.asarray(pb), jnp.asarray(pg), jnp.int32(start),
        jnp.int32(cnt), jnp.int32(col),
        tuple(jnp.int32(v) for v in (0, 0, nb, dbin, mtype, thr, dl)),
        row_chunk=C, num_bins=B, num_groups=G)
    np.testing.assert_array_equal(np.asarray(acc), np.asarray(acc_o))
    if cnt == 0:
        assert not np.asarray(acc).any()
print("[6/7] mega-kernel vs partition+hist oracles: OK", flush=True)

# ---- 7. E2E pallas (flat + xla hist state) vs xla; then mega ----
def train(pallas, hist_state="auto", mega="off", radix=False):
    params = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
              "min_data_in_leaf": 20, "tpu_hist_state": hist_state,
              "tpu_megakernel": mega, "tpu_compact_radix": radix}
    if not pallas:
        params["tpu_partition_kernel"] = "xla"
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    return b.predict(X[:3000], raw_score=True)
ref = train(False)
d1 = float(np.abs(train(True) - ref).max())
d2 = float(np.abs(train(True, "xla") - ref).max())
assert d1 == 0.0 and d2 == 0.0, (d1, d2)
# mega-pallas must equal ITS oracle (mega-xla) bit-exactly on device;
# both differ from the subtraction path only by f32 summation grouping
mega_ref = train(True, mega="xla")
d3 = float(np.abs(train(True, mega="pallas") - mega_ref).max())
d4 = float(np.abs(train(True, mega="pallas", radix=True) - mega_ref).max())
assert d3 == 0.0 and d4 == 0.0, (d3, d4)
d5 = float(np.abs(mega_ref - ref).max())
assert d5 < 1e-4, d5
print(f"[7/7] e2e pallas vs xla (diff 0.0) + mega vs mega-oracle "
      f"(diff 0.0; vs subtraction path {d5:.2e}): OK", flush=True)
print("TPU SELF-CHECK: ALL OK")
