import sys
import numpy as np, jax, jax.numpy as jnp
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)
C = 8192; G32 = 32
Np = 8192*130
SCR = sc_rows_for(G32)
rng = np.random.RandomState(1)
pb0 = jnp.asarray(rng.randint(0, 255, (G32, Np)).astype(np.uint8))
pg0 = jnp.asarray(rng.randn(8, Np).astype(np.float32))
sp0 = jnp.zeros((SCR, Np), jnp.int32)
live = int(sys.argv[1]) if len(sys.argv) > 1 else 6
sc = make_scalars(136229, 491755, 12, 0, 0, 82, 79, 1, 9, 1)
out = partition_leaf_pallas(pb0, pg0, sp0, sc, row_chunk=C, ghi_live=live)
print("sum", float(jnp.sum(out[3])))
