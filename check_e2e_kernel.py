"""TPU end-to-end: pallas-search trees vs XLA-search trees."""
import numpy as np, jax
assert jax.default_backend() == "tpu"
import lightgbm_tpu as lgb

rng = np.random.RandomState(3)
N, F = 50000, 12
X = rng.randn(N, F)
y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.3 * rng.randn(N) > 0).astype(float)

def train(use_pallas_search):
    params = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
              "min_data_in_leaf": 20}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)
    if not use_pallas_search:
        bst._gbdt.learner._use_pallas_search = False
    for _ in range(10):
        bst.update()
    return bst.predict(X[:2000], raw_score=True)

p_k = train(True)
p_x = train(False)
d = np.abs(p_k - p_x).max()
print("max |pallas - xla| =", d)
assert d < 2e-4 * max(1.0, np.abs(p_x).max()), d
print("E2E OK")
