import numpy as np, jax, jax.numpy as jnp
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)
C, G32 = 8192, 32
Np = 8192*130
SCR = sc_rows_for(G32)
rng = np.random.RandomState(1)
pb0 = jnp.asarray(rng.randint(0, 255, (G32, Np)).astype(np.uint8))
pg0 = jnp.asarray(rng.randn(8, Np).astype(np.float32))
sp0 = jnp.zeros((SCR, Np), jnp.int32)
for trial in range(40):
    start = int(rng.randint(C, Np//2))
    cnt = int(rng.randint(0, Np - start - 3*C))
    col = int(rng.randint(0, 28)); nb = int(rng.randint(10, 255))
    thr = int(rng.randint(0, nb)); mtype = int(rng.randint(0, 3))
    dbin = int(rng.randint(0, nb)); dl = int(rng.rand() < 0.5)
    sc = make_scalars(start, cnt, col, 0, 0, nb, dbin, mtype, thr, dl)
    out = partition_leaf_pallas(pb0, pg0, sp0, sc, row_chunk=C, ghi_live=6)
    s = float(jnp.sum(out[3])); _ = float(jnp.sum(out[0].astype(jnp.int32))); _ = float(jnp.sum(out[1]))
    print("trial", trial, "cnt", cnt, "nl", s/ (8*128), flush=True)
print("OK")
