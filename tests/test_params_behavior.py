"""Behavior tests for formerly accepted-but-ignored parameters:
extra_trees, pos/neg_bagging_fraction, feature_contri,
forcedbins_filename (reference: feature_histogram.hpp USE_RAND arms,
bagging.hpp balanced bagging, feature_contri penalty, bin.cpp
FindBinWithPredefinedBin)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(rng, n=3000, f=6):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 2) + 0.3 * rng.normal(size=n)
    return X, y


BASE = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
        "min_data_in_leaf": 20, "metric": ""}


def test_extra_trees_changes_model_and_still_learns(rng):
    X, y = _data(rng)
    plain = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=20)
    xt = lgb.train(dict(BASE, extra_trees=True, extra_seed=3),
                   lgb.Dataset(X, label=y), num_boost_round=20)
    p_plain = plain.predict(X)
    p_xt = xt.predict(X)
    assert not np.allclose(p_plain, p_xt)      # random thresholds differ
    mse0 = float(np.mean((y - np.mean(y)) ** 2))
    assert float(np.mean((y - p_xt) ** 2)) < 0.5 * mse0   # still learns
    # different seed -> different trees
    xt2 = lgb.train(dict(BASE, extra_trees=True, extra_seed=77),
                    lgb.Dataset(X, label=y), num_boost_round=20)
    assert not np.allclose(p_xt, xt2.predict(X))


def test_balanced_bagging(rng):
    X, _ = _data(rng, n=4000)
    y = (rng.rand(4000) < 0.15).astype(float)     # unbalanced classes
    params = dict(BASE, objective="binary", bagging_freq=1,
                  pos_bagging_fraction=1.0, neg_bagging_fraction=0.3)
    # balanced bagging now rides the fused program (label signs from the
    # payload); it must engage, train, and stay class-aware
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)
    g = bst._gbdt
    assert g.balanced_bagging and g.need_bagging
    assert g._fused is not None
    for _ in range(5):
        bst.update()
    g._flush_pending()
    assert np.isfinite(np.asarray(bst.predict(X))).all()

    # the eager path's mask keeps the per-class Bernoulli semantics
    ds2 = lgb.Dataset(X, label=y)
    bst2 = lgb.Booster(params=dict(params), train_set=ds2)
    g2 = bst2._gbdt
    g2._fused = None
    g2._fused_phys = None
    for _ in range(2):
        bst2.update()
    mask, cnt = g2._cached_bag
    mask = np.asarray(mask)
    pos = y > 0
    assert mask[pos].all()                        # every positive in bag
    neg_frac = mask[~pos].mean()
    assert 0.2 < neg_frac < 0.4                   # ~30% of negatives
    # the count is the ACTUAL draw (bagging.hpp:46), not an estimate
    assert cnt == int(mask.sum())


def test_feature_contri_downweights_feature(rng):
    X, y = _data(rng)
    # crush feature 0's gain; the model must lean on other features
    fc = "0.001,1.0,1.0,1.0,1.0,1.0"
    bst = lgb.train(dict(BASE, feature_contri=fc),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    plain = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=10)
    imp = bst.feature_importance(importance_type="split")
    imp_plain = plain.feature_importance(importance_type="split")
    assert imp_plain[0] > 0                       # feature 0 used normally
    assert imp[0] < imp_plain[0]                  # and demoted under contri


def test_forcedbins_bounds_respected(rng, tmp_path):
    X, y = _data(rng, n=2000)
    forced = [-0.5, 0.75]
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(
        [{"feature": 0, "bin_upper_bound": forced}]))
    ds = lgb.Dataset(X, label=y)
    ds.construct(dict(BASE, forcedbins_filename=str(path)))
    bm = ds._inner.bin_mappers[0]
    ub = np.asarray(bm.bin_upper_bound)
    for b in forced:
        assert np.any(np.isclose(ub, b)), (b, ub[:10])
    # other features keep the default binning
    bm1 = ds._inner.bin_mappers[1]
    assert not np.any(np.isclose(np.asarray(bm1.bin_upper_bound), -0.5,
                                 atol=1e-9))


def test_bagging_by_query_warns(rng):
    X, y = _data(rng, n=500)
    from lightgbm_tpu.utils import log as _log
    msgs = []
    _log.register_callback(msgs.append)
    try:
        lgb.train(dict(BASE, verbosity=0, bagging_by_query=True,
                       bagging_freq=1, bagging_fraction=0.5),
                  lgb.Dataset(X, label=y), num_boost_round=2)
    finally:
        _log.register_callback(None)
    assert any("bagging_by_query" in m for m in msgs)


def test_unknown_parameter_warns(rng, capsys):
    """Unknown keys must surface, not silently drop (reference:
    config.h:1242 "Unknown parameter: %s"; round-4 verdict item 2)."""
    from lightgbm_tpu.config import Config, _WARNED_UNKNOWN
    from lightgbm_tpu.utils import log
    _WARNED_UNKNOWN.clear()            # warnings dedupe per process
    log.set_verbosity(1)
    Config({"num_leafs": 31})          # classic typo of num_leaves
    err = capsys.readouterr().err
    assert "Unknown parameter: num_leafs" in err
    # negative verbosity in the same dict suppresses, like the reference
    Config({"verbosity": -1, "bogus_key_xyz": 1})
    assert "bogus_key_xyz" not in capsys.readouterr().err
    log.set_verbosity(1)
    # aliases and tpu-specific params are NOT unknown
    Config({"n_estimators": 5, "tpu_row_chunk": 4096})
    assert "Unknown parameter" not in capsys.readouterr().err


def test_predict_shape_check(rng):
    """Feature-count mismatch raises unless predict_disable_shape_check
    (reference: c_api predictor ncol check, config.h predict section)."""
    X, y = _data(rng)
    bst = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=5)
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(X[:, :4])
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(np.concatenate([X, X[:, :1]], axis=1))
    # disabled: extra columns ignored; missing columns ride as NaN
    p_ref = bst.predict(X)
    p_wide = bst.predict(np.concatenate([X, X[:, :1]], axis=1),
                         predict_disable_shape_check=True)
    np.testing.assert_allclose(p_wide, p_ref)
    p_narrow = bst.predict(X[:, :4], predict_disable_shape_check=True)
    assert p_narrow.shape == p_ref.shape
    # 1-D input predicts as a single row (and still shape-checks)
    np.testing.assert_allclose(bst.predict(X[0]), p_ref[:1])
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(X[0, :4])


def test_saved_feature_importance_type_gain(rng, tmp_path):
    """saved_feature_importance_type=1 writes gain importances to the
    model file (reference: GBDT::FeatureImportance, config.h)."""
    X, y = _data(rng)
    f = str(tmp_path / "m.txt")
    bst = lgb.train(dict(BASE, saved_feature_importance_type=1),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    bst.save_model(f)
    sec = open(f).read().split("feature_importances:")[1]
    first = sec.strip().splitlines()[0]
    gains = bst.feature_importance("gain")
    top = max(range(len(gains)), key=lambda i: gains[i])
    assert first.split("=")[0] == f"Column_{top}"
    assert float(first.split("=")[1]) == pytest.approx(gains[top], rel=1e-5)
    # split-count mode (default) writes integer counts
    bst2 = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=5)
    f2 = str(tmp_path / "m2.txt")
    bst2.save_model(f2)
    first2 = open(f2).read().split("feature_importances:")[1] \
        .strip().splitlines()[0]
    assert float(first2.split("=")[1]) == int(float(first2.split("=")[1]))
