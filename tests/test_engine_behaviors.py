"""Behavioral tests mirroring reference python_package_test/test_engine.py
families that were thin here (round-4 verdict weak #6): sparse training
input, init_score on multiclass, weights x bagging, all-NaN predict
rows, forced-splits deep nesting, and missing-value handling."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb

BASE = {"verbosity": -1, "min_data_in_leaf": 5, "metric": ""}


def test_sparse_training_matches_dense(rng):
    """scipy.sparse train input == dense train input
    (reference: test_engine.py test_sparse_classification /
    test_multiclass with csr)."""
    scipy = pytest.importorskip("scipy.sparse")
    X = rng.normal(size=(1500, 10))
    X[np.abs(X) < 0.7] = 0.0
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    params = dict(BASE, objective="binary", num_leaves=15)
    dense = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    sparse = lgb.train(params, lgb.Dataset(scipy.csr_matrix(X), label=y),
                       num_boost_round=8)
    np.testing.assert_array_equal(dense.predict(X), sparse.predict(X))
    # sparse PREDICT input equals dense predict too
    np.testing.assert_array_equal(dense.predict(scipy.csr_matrix(X)),
                                  dense.predict(X))


def test_init_score_multiclass(rng):
    """(n, K) init_score shifts multiclass training (reference:
    test_engine.py test_init_with_subset + multiclass custom-objective
    init_score paths)."""
    n, K = 1200, 3
    X = rng.normal(size=(n, 6))
    y = rng.randint(0, K, size=n).astype(np.float64)
    params = dict(BASE, objective="multiclass", num_class=K, num_leaves=7)
    init = np.zeros((n, K))
    init[:, 0] = 2.0        # bias class 0 upward
    b_plain = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=5)
    b_init = lgb.train(params, lgb.Dataset(X, label=y, init_score=init),
                       num_boost_round=5)
    p_plain = b_plain.predict(X, raw_score=True)
    p_init = b_init.predict(X, raw_score=True)
    assert p_plain.shape == (n, K) and p_init.shape == (n, K)
    # trained corrections differ because gradients saw the shifted scores
    assert not np.allclose(p_plain, p_init)
    # full prediction = raw + init contribution was consumed in training
    # only (predict does not re-add init_score, like the reference)
    pr = b_init.predict(X)
    np.testing.assert_allclose(pr.sum(axis=1), 1.0, rtol=1e-5)


def test_weights_x_bagging(rng):
    """Weighted training composes with bagging (reference:
    test_engine.py test_train_with_weights + bagging params): in-bag
    gradients scale by weight, and extreme weights dominate the fit."""
    n = 3000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    # flip labels on a slice but give it ~zero weight: the model must
    # follow the DOMINANT weights even with row subsampling active
    y_bad = y.copy()
    y_bad[:500] = 1 - y_bad[:500]
    w = np.ones(n)
    w[:500] = 1e-6
    params = dict(BASE, objective="binary", num_leaves=15,
                  bagging_fraction=0.6, bagging_freq=1, bagging_seed=3)
    bst = lgb.train(params, lgb.Dataset(X, label=y_bad, weight=w),
                    num_boost_round=15)
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.9
    # and the weights actually mattered: without them the flipped slice
    # pulls accuracy (vs the true labels) down
    bst_unw = lgb.train(params, lgb.Dataset(X, label=y_bad),
                        num_boost_round=15)
    acc_unw = ((bst_unw.predict(X[:500]) > 0.5) == y[:500]).mean()
    assert acc_unw < ((bst.predict(X[:500]) > 0.5) == y[:500]).mean()


def test_predict_all_nan_rows(rng):
    """All-NaN rows predict through the default (missing) branches and
    produce finite outputs (reference: test_engine.py
    test_missing_value_handle)."""
    X = rng.normal(size=(1500, 5))
    X[rng.rand(1500, 5) < 0.2] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=15,
                         use_missing=True),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    allnan = np.full((7, 5), np.nan)
    p = bst.predict(allnan)
    assert np.isfinite(p).all()
    # identical all-NaN rows land in one leaf -> identical outputs
    assert np.unique(p).size == 1
    # leaf-index prediction works on all-NaN rows too
    leaves = bst.predict(allnan, pred_leaf=True)
    assert (leaves == leaves[0]).all()


def test_forced_splits_deep_nesting(rng, tmp_path):
    """Nested forced-splits JSON (left-in-left-in-left) is honored in
    order (reference: test_engine.py test_forced_split)."""
    n = 4000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.25 * X[:, 2]
         + 0.1 * rng.normal(size=n))
    forced = {
        "feature": 0, "threshold": 0.0,
        "left": {
            "feature": 1, "threshold": -0.3,
            "left": {"feature": 2, "threshold": 0.1},
        },
    }
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps(forced))
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=31,
                         forcedsplits_filename=str(fpath)),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    d = bst.dump_model()
    root = d["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 0
    lvl1 = root["left_child"]
    assert lvl1["split_feature"] == 1
    lvl2 = lvl1["left_child"]
    assert lvl2["split_feature"] == 2
    # the forced chain persists across trees
    root2 = d["tree_info"][-1]["tree_structure"]
    assert root2["split_feature"] == 0


def test_zero_as_missing(rng):
    """zero_as_missing=True routes zeros through the missing branch
    (reference: test_engine.py test_missing_value_handle_zero)."""
    n = 2000
    X = rng.normal(size=(n, 3))
    X[rng.rand(n) < 0.3, 0] = 0.0
    y = ((X[:, 0] != 0) & (X[:, 0] > 0)).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=15,
                         zero_as_missing=True),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    # under zero-as-missing, a 0 and a NaN in the same cell are the SAME
    # missing value (reference: MissingType::Zero folds NaN into the
    # zero bucket) -> identical predictions row-for-row
    Xz = X.copy()
    Xz[:, 0] = 0.0
    Xn = X.copy()
    Xn[:, 0] = np.nan
    np.testing.assert_array_equal(bst.predict(Xz), bst.predict(Xn))
    assert np.isfinite(bst.predict(X)).all()


def test_constant_and_allnan_features(rng):
    """Constant and all-NaN columns are unsplittable but harmless
    (reference: test_engine.py test_trivial datasets behavior)."""
    n = 1200
    X = rng.normal(size=(n, 5))
    X[:, 2] = 3.14
    X[:, 4] = np.nan
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=15),
                    lgb.Dataset(X, label=y), num_boost_round=8)
    assert ((bst.predict(X) > 0.5) == y).mean() > 0.9
    imp = bst.feature_importance()
    assert imp[2] == 0 and imp[4] == 0


def test_max_depth_caps_leaves(rng):
    """max_depth bounds the tree even when num_leaves allows more
    (reference: test_engine.py test_max_depth* behaviors)."""
    X = rng.normal(size=(3000, 6))
    y = X[:, 0] * np.sin(X[:, 1]) + 0.1 * rng.normal(size=3000)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=255,
                         max_depth=3),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    d = bst.dump_model()

    def depth(node):
        if "leaf_value" in node:
            return 0
        return 1 + max(depth(node["left_child"]),
                       depth(node["right_child"]))

    for t in d["tree_info"]:
        assert depth(t["tree_structure"]) <= 3
        assert t["num_leaves"] <= 8


def test_binary_proba_vs_raw(rng):
    """predict() is sigmoid(raw_score) for binary (reference:
    basic predict contract)."""
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=7),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    raw = bst.predict(X, raw_score=True)
    p = bst.predict(X)
    np.testing.assert_allclose(p, 1.0 / (1.0 + np.exp(-raw)), rtol=1e-6)


def test_param_aliases_apply(rng):
    """Aliases (eta, n_estimators, sub_row...) resolve like the
    reference alias table (config_auto.cpp parameter2aliases)."""
    X = rng.normal(size=(1000, 4))
    y = X[:, 0] + 0.1 * rng.normal(size=1000)
    b1 = lgb.train(dict(BASE, objective="regression", num_leaves=7,
                        eta=0.3, n_estimators=7),
                   lgb.Dataset(X, label=y))
    assert len(b1.dump_model()["tree_info"]) == 7
    b2 = lgb.train(dict(BASE, objective="regression", num_leaves=7,
                        learning_rate=0.3, num_iterations=7),
                   lgb.Dataset(X, label=y))
    np.testing.assert_array_equal(b1.predict(X), b2.predict(X))


def test_subset_training(rng):
    """Dataset.subset trains on the row subset only (reference:
    test_engine.py test_subset_group / used_indices paths)."""
    X = rng.normal(size=(2000, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    full = lgb.Dataset(X, label=y)
    idx = np.arange(0, 2000, 2)
    sub = full.subset(idx)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=7),
                    sub, num_boost_round=5)
    direct = lgb.train(dict(BASE, objective="binary", num_leaves=7),
                       lgb.Dataset(X[idx], label=y[idx]),
                       num_boost_round=5)
    np.testing.assert_allclose(bst.predict(X), direct.predict(X),
                               rtol=1e-5, atol=1e-7)


def test_multiclass_proba_normalized(rng):
    """Multiclass predict() rows sum to 1 and argmax tracks labels
    (reference: test_engine.py test_multiclass)."""
    n, K = 1500, 4
    X = rng.normal(size=(n, 6))
    y = np.argmax(X[:, :K] + 0.3 * rng.normal(size=(n, K)),
                  axis=1).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="multiclass", num_class=K,
                         num_leaves=15),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    p = bst.predict(X)
    assert p.shape == (n, K)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (np.argmax(p, axis=1) == y).mean() > 0.7


def test_refit_keeps_structure(rng):
    """refit() reuses tree structure with new leaf values (reference:
    test_engine.py test_refit)."""
    X = rng.normal(size=(1500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=15),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    y2 = (X[:, 1] > 0).astype(np.float64)
    refitted = bst.refit(X, y2)
    d0 = bst.dump_model()
    d1 = refitted.dump_model()
    for t0, t1 in zip(d0["tree_info"], d1["tree_info"]):
        s0 = t0["tree_structure"]
        s1 = t1["tree_structure"]
        assert s0.get("split_feature") == s1.get("split_feature")
        assert s0.get("threshold") == s1.get("threshold")
    assert not np.allclose(bst.predict(X), refitted.predict(X))


def test_continue_train_from_file_and_booster(rng, tmp_path):
    """init_model continuation from a file equals continuation from the
    in-memory booster (reference: test_engine.py test_continue_train)."""
    X = rng.normal(size=(1500, 5))
    y = X[:, 0] + 0.2 * rng.normal(size=1500)
    params = dict(BASE, objective="regression", num_leaves=15)
    b0 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    f = str(tmp_path / "m.txt")
    b0.save_model(f)
    c_file = lgb.train(params, lgb.Dataset(X, label=y),
                       num_boost_round=5, init_model=f)
    c_mem = lgb.train(params, lgb.Dataset(X, label=y),
                      num_boost_round=5, init_model=b0)
    np.testing.assert_allclose(c_file.predict(X), c_mem.predict(X),
                               rtol=1e-6, atol=1e-9)
    assert len(c_file.dump_model()["tree_info"]) == 10


def test_dataset_params_conflict_warning(rng, capsys):
    """Changing dataset-construction params between Dataset and train
    keeps working (construct-once semantics like the reference
    free_raw_data path)."""
    X = rng.normal(size=(800, 4))
    y = X[:, 0]
    ds = lgb.Dataset(X, label=y)
    ds.construct({"objective": "regression", "max_bin": 63,
                  "verbosity": -1})
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=7),
                    ds, num_boost_round=3)
    assert np.isfinite(bst.predict(X)).all()
