"""Leaf-size-adaptive chunk policy (ops/chunkpolicy.py).

The tentpole contract: ``tpu_chunk_policy=adaptive`` trains trees
BIT-IDENTICAL to ``fixed`` (the base-grid oracle) while the per-leaf
histogram/partition passes band small leaves onto smaller menu widths.
Covered here:

* the bit-identity matrix across bagging / GOSS / quantized /
  categorical / multiclass / cegb-lazy / frontier-K / mega-xla /
  eager-path configurations;
* the compiled-variant registry pin: <= menu-size traced variants per
  pass over a full training run, and warm updates add none;
* ``tpu_row_chunk=auto`` / ``tpu_chunk_policy=auto`` consulting a
  planted same-fingerprint chunk-sweep trajectory entry;
* the ``train.chunk.waste`` telemetry gauges;
* the PR-10 ``rec["hist"]`` dead-export deletion.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.models.learner import SerialTreeLearner
from lightgbm_tpu.ops import chunkpolicy


def _data(seed=7, n=3000, f=8, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if cat:
        X[:, -1] = rng.randint(0, 12, size=n)
    y = (X[:, 0] + 0.5 * np.sin(X[:, 1] * 2)
         + 0.4 * rng.randn(n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
        "min_data_in_leaf": 5, "metric": ""}


def _trees(bst):
    """Model text minus the [param] dump (tpu_chunk_policy legitimately
    differs between the arms; the TREES must not)."""
    return [ln for ln in bst.model_to_string().splitlines()
            if not ln.startswith("[")]


def _train(X, y, nbr=3, cat=False, **kw):
    p = {**BASE, **kw}
    if cat:
        p["categorical_feature"] = [X.shape[1] - 1]
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=nbr)


# ---------------------------------------------------------------------------
# policy unit behavior
# ---------------------------------------------------------------------------
def test_menu_derivation_and_bands():
    pol = chunkpolicy.ChunkPolicy(4096, adaptive=True)
    assert pol.sizes == (4096, 1024, 256, 64)
    assert pol.hist_sizes == (4096, 256, 64)
    assert chunkpolicy.ChunkPolicy(256, adaptive=True).sizes == (256, 64)
    assert len(chunkpolicy.ChunkPolicy(1 << 15, adaptive=True).sizes) <= 4
    # band_of: smallest covering width; multi-chunk leaves stay base
    assert pol.band_of(5000) == 0
    assert pol.band_of(2000) == 0     # (1024, 4096]: base single chunk
    assert pol.band_of(1000) == 1
    assert pol.band_of(200) == 2
    assert pol.band_of(64) == 3
    assert pol.padded_rows(200) == 256
    assert pol.padded_rows(5000) == 8192
    fixed = chunkpolicy.ChunkPolicy(4096, adaptive=False)
    assert fixed.band_of(10) == 0
    assert fixed.padded_rows(10) == 4096


def test_traced_band_matches_host_band():
    import jax.numpy as jnp
    pol = chunkpolicy.ChunkPolicy(4096, adaptive=True)
    for cnt in (0, 1, 64, 65, 256, 257, 1024, 1025, 4096, 9000):
        got = int(pol.band(jnp.int32(cnt), pol.sizes))
        want = pol.band_of(max(cnt, 1))
        if cnt:
            assert got == want, cnt
        trips = [int(t) for t in pol.small_trips(jnp.int32(cnt),
                                                 pol.sizes)]
        assert sum(trips) == (1 if 0 < cnt <= 1024 else 0), cnt
        cover = int(pol.base_cover(jnp.int32(cnt), pol.sizes))
        assert cover == (0 if cnt <= 1024 else -(-cnt // 4096)), cnt


def test_parse_row_chunk():
    assert chunkpolicy.parse_row_chunk("auto") is None
    assert chunkpolicy.parse_row_chunk(512) == 512
    assert chunkpolicy.parse_row_chunk("512") == 512
    with pytest.raises(ValueError):
        chunkpolicy.parse_row_chunk("never")
    with pytest.raises(ValueError):
        chunkpolicy.parse_row_chunk(-4)


def test_waste_stats():
    pol = chunkpolicy.ChunkPolicy(4096, adaptive=True)
    s = chunkpolicy.waste_stats([10, 100, 1000, 5000], pol)
    assert s["live_rows"] == 6110
    # partition bands process 64 + 256 + 1024 + 8192 rows; the
    # histogram bands (capped at 256) 64 + 256 + 4096 + 8192 — the
    # 1000-row leaf's full base-width hist chunk must be counted
    assert s["padded_rows"] == 9536 + 12608
    assert s["waste"] == pytest.approx(1 - 2 * 6110 / (9536 + 12608))
    assert s["fixed_waste"] == pytest.approx(1 - 6110 / 20480)
    assert 0.0 < s["waste"] < s["fixed_waste"] < 1.0
    assert s["band_64.leaves"] == 1
    assert s["band_256.occupancy"] == pytest.approx(100 / 256)


# ---------------------------------------------------------------------------
# bit-identity matrix vs the fixed-grid oracle
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("extra,cat", [
    ({}, False),                                              # plain
    ({"bagging_fraction": 0.6, "bagging_freq": 1}, False),    # bagging
    ({"data_sample_strategy": "goss"}, False),                # GOSS
    ({"use_quantized_grad": True}, False),                    # quantized
    ({}, True),                                               # categorical
    ({"objective": "multiclass", "num_class": 3}, False),     # multiclass
    ({"tpu_frontier_k": 3}, False),                           # frontier
    ({"tpu_megakernel": "xla"}, False),                       # mega oracle
    # bonus lanes beyond the required matrix ride the slow tier
    # (tier-1 window; the fast lanes above are the representatives)
    pytest.param({"cegb_tradeoff": 0.5,
                  "cegb_penalty_feature_lazy": ",".join(["0.1"] * 8)},
                 False, marks=pytest.mark.slow),
    pytest.param({"tpu_fused_iteration": False}, False,
                 marks=pytest.mark.slow),                     # eager path
])
def test_chunk_bitidentity(extra, cat):
    X, y = _data(cat=cat)
    if extra.get("objective") == "multiclass":
        y = ((X[:, 0] > 0).astype(float) + (X[:, 1] > 0))
    bf = _train(X, y, cat=cat, tpu_chunk_policy="fixed", **extra)
    ba = _train(X, y, cat=cat, tpu_chunk_policy="adaptive", **extra)
    assert ba._gbdt.learner._chunk_policy.adaptive
    assert len(ba._gbdt.learner._chunk_policy.sizes) >= 2
    assert _trees(bf) == _trees(ba)
    d = np.abs(np.asarray(bf.predict(X[:200]))
               - np.asarray(ba.predict(X[:200]))).max()
    assert float(d) == 0.0


def test_chunk_bitidentity_deep_small_leaves():
    """num_leaves larger than rows/min_data forces the small-leaf
    regime every band is exercised in (the padding-waste case the
    policy targets)."""
    X, y = _data(n=4000)
    bf = _train(X, y, num_leaves=255, min_data_in_leaf=3,
                tpu_chunk_policy="fixed")
    ba = _train(X, y, num_leaves=255, min_data_in_leaf=3,
                tpu_chunk_policy="adaptive")
    assert _trees(bf) == _trees(ba)


@pytest.mark.slow
def test_chunk_interpret_megakernel_fallback():
    """Kernel (Pallas) paths keep their proven base grid: under the
    interpreted mega-kernel the policy must resolve to fixed and trees
    must match a fixed-policy run exactly."""
    X, y = _data(n=600, f=6)
    kw = {"tpu_kernel_interpret": True, "tpu_megakernel": "pallas",
          "tpu_row_chunk": 256}
    bf = _train(X, y, nbr=1, tpu_chunk_policy="fixed", **kw)
    ba = _train(X, y, nbr=1, tpu_chunk_policy="adaptive", **kw)
    assert ba._gbdt.learner._use_mega == "pallas"
    assert not ba._gbdt.learner._chunk_policy.adaptive
    assert _trees(bf) == _trees(ba)


# ---------------------------------------------------------------------------
# compiled-variant pin (the (pass, chunk-size) compile-count contract)
# ---------------------------------------------------------------------------
def test_variant_counts_bounded_by_menu():
    X, y = _data()
    chunkpolicy.reset_variant_log()
    bst = _train(X, y, nbr=3, tpu_chunk_policy="adaptive")
    pol = bst._gbdt.learner._chunk_policy
    log = chunkpolicy.variant_log()
    per_pass = {}
    for (pass_name, width), n in log.items():
        per_pass.setdefault(pass_name, set()).add(width)
    assert set(per_pass) >= {"hist", "partition"}
    assert per_pass["hist"] == set(pol.hist_sizes)
    assert per_pass["partition"] == set(pol.sizes)
    for pass_name, widths in per_pass.items():
        assert len(widths) <= len(pol.sizes), (pass_name, widths)
    # warm updates reuse the compiled program: no new traced variants
    snap = chunkpolicy.variant_log()
    bst.update()
    bst.update()
    assert chunkpolicy.variant_log() == snap


# ---------------------------------------------------------------------------
# auto modes consult the measured trajectory (ROADMAP item 7 slice)
# ---------------------------------------------------------------------------
def test_row_chunk_auto_consults_history(tmp_path, monkeypatch):
    from lightgbm_tpu.obs import regress
    hist_path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("BENCH_HISTORY_PATH", hist_path)
    X, y = _data(n=3000)
    cfg = Config({**BASE, "tpu_row_chunk": "auto"})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    # no history yet: the static default (capped by the row count)
    lr = SerialTreeLearner(ds, cfg)
    assert lr.row_chunk == min(chunkpolicy.DEFAULT_ROW_CHUNK, 4096)
    # a same-fingerprint sweep entry flips the chosen chunk size
    regress.append_entry(
        chunkpolicy.SWEEP_TOOL, {"best_row_chunk": 512},
        fingerprint_doc=chunkpolicy.sweep_fingerprint(
            ds.num_data, ds.num_total_features),
        path=hist_path)
    lr2 = SerialTreeLearner(ds, cfg)
    assert lr2.row_chunk == 512
    # a DIFFERENT shape band must not flip anything (series isolation)
    regress.append_entry(
        chunkpolicy.SWEEP_TOOL, {"best_row_chunk": 2048},
        fingerprint_doc=chunkpolicy.sweep_fingerprint(
            10 * ds.num_data, ds.num_total_features),
        path=hist_path)
    assert SerialTreeLearner(ds, cfg).row_chunk == 512


def test_chunk_policy_auto_consults_history(tmp_path, monkeypatch):
    from lightgbm_tpu.obs import regress
    hist_path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("BENCH_HISTORY_PATH", hist_path)
    X, y = _data(n=3000)
    cfg = Config(dict(BASE))
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    # heuristic default at this shape: small-leaf regime -> adaptive
    assert SerialTreeLearner(ds, cfg)._chunk_policy.adaptive
    # a measured same-fingerprint verdict that adaptive LOST overrides
    regress.append_entry(
        chunkpolicy.SWEEP_TOOL,
        {"best_row_chunk": 4096, "adaptive_speedup": 0.8},
        fingerprint_doc=chunkpolicy.sweep_fingerprint(
            ds.num_data, ds.num_total_features),
        path=hist_path)
    assert not SerialTreeLearner(ds, cfg)._chunk_policy.adaptive
    # explicit settings ignore the trajectory
    cfg_forced = Config({**BASE, "tpu_chunk_policy": "adaptive"})
    assert SerialTreeLearner(ds, cfg_forced)._chunk_policy.adaptive


# ---------------------------------------------------------------------------
# telemetry: padding-waste gauges
# ---------------------------------------------------------------------------
def test_chunk_waste_gauges():
    from lightgbm_tpu import obs
    X, y = _data()
    sess = obs.get()
    prev = sess.mode
    try:
        sess.set_mode("counters")
        bst = _train(X, y, nbr=2, tpu_chunk_policy="adaptive")
        bst._gbdt._flush_pending()
        rep = bst.telemetry_report()
    finally:
        sess.set_mode(prev)
    gauges = rep["gauges"]
    assert 0.0 <= gauges["train.chunk.waste"] < 1.0
    # the adaptive bands must beat the fixed grid's padding on this
    # small-leaf-heavy shape
    assert gauges["train.chunk.waste"] < gauges["train.chunk.fixed_waste"]
    assert any(k.startswith("train.chunk.band_") for k in gauges)


# ---------------------------------------------------------------------------
# rec["hist"] dead export (PR-10 note) is gone
# ---------------------------------------------------------------------------
def test_record_drops_hist_state():
    X, y = _data(n=800, f=5)
    cfg = Config(dict(BASE))
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    lr = SerialTreeLearner(ds, cfg)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(len(y), 0.25, np.float32)
    rec = lr.build_tree(grad, hess)
    assert "hist" not in rec
    assert "leaf_cnt" in rec and "indices" in rec
