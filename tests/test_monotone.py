"""Monotone constraint tests (reference model:
tests/python_package_test/test_engine.py test_monotone_constraints)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_mono_data(n=800, seed=3):
    rng = np.random.RandomState(seed)
    x1 = rng.uniform(size=n)          # constrained +1
    x2 = rng.uniform(size=n)          # constrained -1
    x3 = rng.uniform(size=n)          # unconstrained
    y = (5 * x1 + np.sin(10 * np.pi * x1)
         - 5 * x2 - np.cos(10 * np.pi * x2)
         + 10 * np.sin(2 * np.pi * x3)
         + rng.normal(scale=0.1, size=n))
    X = np.column_stack([x1, x2, x3])
    return X, y


def is_increasing(bst, X, col, sign):
    """Sweep `col` over a grid for each of a few fixed rows; check direction."""
    grid = np.linspace(0, 1, 50)
    for row in X[:20]:
        probe = np.tile(row, (50, 1))
        probe[:, col] = grid
        pred = bst.predict(probe)
        diffs = np.diff(pred) * sign
        if not np.all(diffs >= -1e-10):
            return False
    return True


@pytest.mark.parametrize("as_list", [False, True])
def test_monotone_constraints_enforced(as_list):
    X, y = make_mono_data()
    mc = [1, -1, 0] if as_list else "1,-1,0"
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "verbosity": -1,
              "monotone_constraints": mc}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=40)
    assert is_increasing(bst, X, 0, +1)
    assert is_increasing(bst, X, 1, -1)
    # the model still learns: better than predicting the mean
    pred = bst.predict(X)
    assert np.mean((y - pred) ** 2) < 0.5 * np.var(y)


def test_unconstrained_violates():
    """Sanity: without constraints the wiggly signal is non-monotone."""
    X, y = make_mono_data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=40)
    assert not is_increasing(bst, X, 0, +1)


@pytest.mark.slow  # 7.7 + 10.1 s: tier-1 window trim (PR 12, per
# test_durations.json); test_advanced_mode_enforces and
# test_advanced_finds_split_intermediate_clamps keep fast in-window
# representatives of both constraint methods
@pytest.mark.parametrize("method", ["intermediate", "advanced"])
def test_monotone_intermediate_enforced(method):
    """Region-exact intermediate mode keeps the constraint AND fits at
    least as well as basic (reference: test_monotone_constraints with
    monotone_constraints_method)."""
    X, y = make_mono_data()
    base = {"objective": "regression", "num_leaves": 31,
            "min_data_in_leaf": 5, "verbosity": -1,
            "monotone_constraints": "1,-1,0"}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({**base, "monotone_constraints_method": method},
                    ds, num_boost_round=40)
    assert is_increasing(bst, X, 0, +1)
    assert is_increasing(bst, X, 1, -1)
    mse_int = np.mean((y - bst.predict(X)) ** 2)

    ds2 = lgb.Dataset(X, label=y)
    bst_basic = lgb.train({**base, "monotone_constraints_method": "basic"},
                          ds2, num_boost_round=40)
    mse_basic = np.mean((y - bst_basic.predict(X)) ** 2)
    # intermediate's looser (exact) constraints should not fit WORSE than
    # basic's over-constrained outputs by any meaningful margin
    assert mse_int <= mse_basic * 1.1


def test_monotone_penalty_discourages_splits():
    """With a huge penalty, monotone features should never be split on
    near the root (reference: test_monotone_penalty)."""
    X, y = make_mono_data()
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "verbosity": -1,
              "monotone_constraints": "1,-1,0",
              "monotone_penalty": 2.0,
              "max_depth": 2}
    bst = lgb.train(params, ds, num_boost_round=10)
    # depth<=2, penalty=2 -> depth-0 and depth-1 splits on constrained
    # features are heavily penalized; feature 2 must dominate importance
    imp = bst.feature_importance(importance_type="split")
    assert imp[2] >= imp[0]
    assert imp[2] >= imp[1]


def test_advanced_mode_enforces(rng):
    """`advanced` evaluates candidate children against per-threshold
    bound segments (reference: AdvancedLeafConstraints,
    monotone_constraints.hpp:858) and still enforces monotonicity."""
    import lightgbm_tpu as lgb
    n = 2000
    X = rng.normal(size=(n, 4))
    y = 2 * X[:, 0] + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "monotone_constraints": "1,0,0,0",
                     "monotone_constraints_method": "advanced",
                     "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    # monotonicity holds along feature 0
    base = np.zeros((50, 4))
    base[:, 1:] = rng.normal(size=(1, 3))
    base[:, 0] = np.linspace(-2, 2, 50)
    p = bst.predict(base)
    assert np.all(np.diff(p) >= -1e-6)
    # and advanced is never WORSE on train loss than intermediate
    inter = lgb.train({"objective": "regression", "num_leaves": 15,
                       "verbosity": -1,
                       "monotone_constraints": "1,0,0,0",
                       "monotone_constraints_method": "intermediate",
                       "metric": ""},
                      lgb.Dataset(X, label=y), num_boost_round=10)
    mse_a = np.mean((bst.predict(X) - y) ** 2)
    mse_i = np.mean((inter.predict(X) - y) ** 2)
    assert mse_a <= mse_i * 1.05


@pytest.mark.slow  # 11.8 s: tier-1 window trim (PR 14) — advanced
# monotone mode keeps its fast in-window representative in
# test_advanced_mode_enforces
def test_advanced_finds_split_intermediate_clamps(tmp_path):
    """The reference's motivating case for advanced mode
    (monotone_constraints.hpp:858 AdvancedLeafConstraints): two upper
    leaves with different f-ranges cap the lower leaf DIFFERENTLY per
    threshold of a candidate split on f.  Intermediate's single scalar
    cap (the min over both) clamps the right child's output; advanced's
    per-threshold segments see only the overlapping upper leaf and let
    the right child take its true value."""
    import json
    # 2-D grid; x0 monotone +1, x1 free.  True function (monotone in x0):
    #   x0>=.5: 1 if x1<=.5 else 5       x0<.5: 0 if x1<=.5 else 4
    g = np.linspace(0.05, 0.95, 10)
    xx0, xx1 = np.meshgrid(g, g)
    X = np.column_stack([xx0.ravel(), xx1.ravel()])
    X = np.repeat(X, 4, axis=0)
    y = np.where(X[:, 0] >= 0.5,
                 np.where(X[:, 1] <= 0.5, 1.0, 5.0),
                 np.where(X[:, 1] <= 0.5, 0.0, 4.0))
    # force root x0@.5, then the upper branch x1@.5 — the lower branch's
    # own x1 split is where the two modes diverge
    forced = {"feature": 0, "threshold": 0.5,
              "right": {"feature": 1, "threshold": 0.5}}
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps(forced))
    out = {}
    for mode in ("intermediate", "advanced"):
        bst = lgb.train({"objective": "regression", "num_leaves": 5,
                         "min_data_in_leaf": 5, "learning_rate": 1.0,
                         "verbosity": -1,
                         "monotone_constraints": "1,0",
                         "monotone_constraints_method": mode,
                         "forcedsplits_filename": str(fpath)},
                        lgb.Dataset(X, label=y), num_boost_round=1)
        pred = bst.predict(X)
        out[mode] = float(np.mean((pred - y) ** 2))
        # monotonicity in x0 must hold in BOTH modes
        assert is_increasing(bst, X, 0, +1), mode
    # intermediate clamps the (x0<.5, x1>.5) region to the min upper cap
    # (1.0), a large train error; advanced recovers the true value 4.0
    assert out["advanced"] < 0.5
    assert out["intermediate"] > 1.0
    assert out["advanced"] < out["intermediate"] * 0.5
