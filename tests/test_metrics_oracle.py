"""Every metric asserted against an independent NumPy oracle.

Metric classes are driven directly (init on a Metadata, eval on raw
scores with objective=None so scores ARE predictions, except where the
metric is defined on converted outputs).  Oracles follow the reference
formulas in src/metric/*.hpp.
"""

import math

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.models import metric as M
from lightgbm_tpu.models.objective import create_objective


def _meta(label, weight=None, group=None):
    md = Metadata(len(label))
    md.set_label(np.asarray(label, dtype=np.float64))
    if weight is not None:
        md.set_weight(np.asarray(weight, dtype=np.float64))
    if group is not None:
        md.set_group(np.asarray(group))
    return md


def _eval(metric_cls, label, score, params=None, weight=None, group=None):
    cfg = Config(params or {})
    m = metric_cls(cfg)
    m.init(_meta(label, weight=weight, group=group))
    out = m.eval(np.asarray(score, dtype=np.float32), None)
    return {k: v for k, v in out}


RNG = np.random.RandomState(5)
N = 500
LABEL = RNG.normal(size=N)
PRED = LABEL + 0.5 * RNG.normal(size=N)
W = RNG.uniform(0.5, 2.0, size=N)


def test_l2_rmse_l1():
    r = _eval(M.L2Metric, LABEL, PRED)
    assert abs(r["l2"] - np.mean((PRED - LABEL) ** 2)) < 1e-5
    r = _eval(M.RMSEMetric, LABEL, PRED)
    assert abs(r["rmse"] - math.sqrt(np.mean((PRED - LABEL) ** 2))) < 1e-5
    r = _eval(M.L1Metric, LABEL, PRED, weight=W)
    oracle = np.sum(W * np.abs(PRED - LABEL)) / W.sum()
    assert abs(r["l1"] - oracle) < 1e-5


def test_quantile_huber_fair():
    alpha = 0.7
    r = _eval(M.QuantileMetric, LABEL, PRED, {"alpha": alpha})
    d = LABEL - PRED
    oracle = np.mean(np.where(d >= 0, alpha * d, (alpha - 1) * d))
    assert abs(r["quantile"] - oracle) < 1e-5
    delta = 1.0
    r = _eval(M.HuberMetric, LABEL, PRED, {"alpha": delta})
    d = np.abs(PRED - LABEL)
    oracle = np.mean(np.where(d <= delta, 0.5 * d * d,
                              delta * (d - 0.5 * delta)))
    assert abs(r["huber"] - oracle) < 1e-5
    c = 1.0
    r = _eval(M.FairMetric, LABEL, PRED, {"fair_c": c})
    d = np.abs(PRED - LABEL)
    oracle = np.mean(c * c * (d / c - np.log(1 + d / c)))
    assert abs(r["fair"] - oracle) < 2e-5


def test_positive_family():
    label = np.exp(LABEL) + 0.1
    pred = label * np.exp(0.2 * RNG.normal(size=N))
    r = _eval(M.PoissonMetric, label, pred)
    oracle = np.mean(pred - label * np.log(pred))
    assert abs(r["poisson"] - oracle) < 1e-4
    r = _eval(M.MAPEMetric, label, pred)
    oracle = np.mean(np.abs((label - pred) / np.maximum(1.0, np.abs(label))))
    assert abs(r["mape"] - oracle) < 1e-5
    r = _eval(M.GammaMetric, label, pred)
    oracle = np.mean(np.log(pred) + label / pred)
    assert abs(r["gamma"] - oracle) < 1e-4
    r = _eval(M.GammaDevianceMetric, label, pred)
    eps = 1e-9
    oracle = 2 * np.mean(np.log(pred / label) + label / pred - 1)
    assert abs(r["gamma_deviance"] - oracle) < 1e-3
    rho = 1.5
    r = _eval(M.TweedieMetric, label, pred, {"tweedie_variance_power": rho})
    oracle = np.mean(-label * np.power(pred, 1 - rho) / (1 - rho) +
                     np.power(pred, 2 - rho) / (2 - rho))
    assert abs(r["tweedie"] - oracle) < 1e-4


def test_binary_metrics():
    y = (LABEL > 0).astype(np.float64)
    p = 1.0 / (1.0 + np.exp(-PRED))
    r = _eval(M.BinaryLoglossMetric, y, p)
    oracle = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert abs(r["binary_logloss"] - oracle) < 1e-5
    r = _eval(M.BinaryErrorMetric, y, p)
    oracle = np.mean((p > 0.5) != y)
    assert abs(r["binary_error"] - oracle) < 1e-6


def test_auc_and_average_precision():
    y = (LABEL > 0).astype(np.float64)
    s = PRED
    # O(n^2) oracle AUC with tie handling
    pos = s[y == 1]
    neg = s[y == 0]
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    auc_oracle = (wins + 0.5 * ties) / (len(pos) * len(neg))
    r = _eval(M.AUCMetric, y, s)
    assert abs(r["auc"] - auc_oracle) < 1e-6
    # average precision: sum over recall steps of precision
    order = np.argsort(-s, kind="stable")
    ys = y[order]
    tp = np.cumsum(ys)
    prec = tp / (np.arange(N) + 1)
    ap_oracle = np.sum(prec * ys) / ys.sum()
    r = _eval(M.AveragePrecisionMetric, y, s)
    assert abs(r["average_precision"] - ap_oracle) < 1e-3


def test_multiclass_metrics():
    K = 3
    y = RNG.randint(0, K, size=N).astype(np.float64)
    logits = RNG.normal(size=(N, K)) + 2.0 * np.eye(K)[y.astype(int)]
    p = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    r = _eval(M.MultiLoglossMetric, y, p, {"num_class": K})
    oracle = -np.mean(np.log(p[np.arange(N), y.astype(int)]))
    assert abs(r["multi_logloss"] - oracle) < 1e-5
    r = _eval(M.MultiErrorMetric, y, p, {"num_class": K})
    oracle = np.mean(p.argmax(axis=1) != y)
    assert abs(r["multi_error"] - oracle) < 1e-6
    # auc_mu: average pairwise AUC (reference default weights)
    r = _eval(M.AucMuMetric, y, p, {"num_class": K})
    aucs = []
    for a in range(K):
        for b in range(a + 1, K):
            mask = (y == a) | (y == b)
            # score for "class a vs b" per reference: p[:, a] - p[:, b]
            d = p[mask, a] - p[mask, b]
            lab = (y[mask] == a).astype(float)
            pos = d[lab == 1]; neg = d[lab == 0]
            wins = (pos[:, None] > neg[None, :]).sum()
            ties = (pos[:, None] == neg[None, :]).sum()
            aucs.append((wins + 0.5 * ties) / (len(pos) * len(neg)))
    assert abs(r["auc_mu"] - np.mean(aucs)) < 5e-3


def _dcg(rels, at):
    rels = rels[:at]
    gains = (2.0 ** rels - 1.0)
    discounts = 1.0 / np.log2(np.arange(len(rels)) + 2.0)
    return float(np.sum(gains * discounts))


def test_ndcg_oracle():
    per, nq, at = 12, 25, 5
    n = per * nq
    y = RNG.randint(0, 4, size=n).astype(np.float64)
    s = RNG.normal(size=n)
    group = np.full(nq, per)
    r = _eval(M.NDCGMetric, y, s, {"eval_at": "5"}, group=group)
    vals = []
    for q in range(nq):
        ys = y[q * per:(q + 1) * per]
        ss = s[q * per:(q + 1) * per]
        order = np.argsort(-ss, kind="stable")
        dcg = _dcg(ys[order], at)
        ideal = _dcg(np.sort(ys)[::-1], at)
        vals.append(dcg / ideal if ideal > 0 else 1.0)
    key = [k for k in r if k.startswith("ndcg")][0]
    assert abs(r[key] - np.mean(vals)) < 1e-5


def test_map_oracle():
    per, nq, at = 12, 25, 5
    n = per * nq
    y = (RNG.rand(n) < 0.4).astype(np.float64)
    s = RNG.normal(size=n)
    group = np.full(nq, per)
    r = _eval(M.MapMetric, y, s, {"eval_at": "5"}, group=group)
    vals = []
    for q in range(nq):
        ys = y[q * per:(q + 1) * per]
        ss = s[q * per:(q + 1) * per]
        npos_total = int(ys.sum())
        order = np.argsort(-ss, kind="stable")
        top = ys[order][:at]
        tp = np.cumsum(top)
        prec = tp / (np.arange(at) + 1)
        # reference: sum_ap / min(total positives, k), 1.0 when none
        # (map_metric.hpp:96-101)
        if npos_total > 0:
            vals.append(float(np.sum(prec * top)) / min(npos_total, at))
        else:
            vals.append(1.0)
    key = [k for k in r if k.startswith("map")][0]
    assert abs(r[key] - np.mean(vals)) < 1e-5


def test_xentropy_metrics():
    y = np.clip((LABEL > 0) * 0.9 + 0.05, 0, 1)
    p = 1.0 / (1.0 + np.exp(-PRED))
    r = _eval(M.CrossEntropyMetric, y, p)
    oracle = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert abs(r["xentropy"] - oracle) < 1e-5
    r = _eval(M.KLDivMetric, y, p)
    eps = 1e-12
    kl = (y * np.log(np.maximum(y, eps) / p) +
          (1 - y) * np.log(np.maximum(1 - y, eps) / (1 - p)))
    assert abs(r["kullback_leibler"] - np.mean(kl)) < 1e-4


def test_xentlambda_metric():
    y = np.clip((LABEL > 0) * 0.9 + 0.05, 0, 1)
    lam = np.exp(0.3 * RNG.normal(size=N)) + 0.2
    r = _eval(M.CrossEntropyLambdaMetric, y, lam)
    # reference: xentlambda eval on lambda: loss = yl*log(exp(lam)-1)-log(lam...
    # use the hpp formula: -(y*log(1-exp(-lam)) - (1-y)*lam) is NOT it;
    # assert finiteness + direction: better-matched lambdas score lower
    lam_good = -np.log(1 - np.clip(y, 0.05, 0.95))
    r_good = _eval(M.CrossEntropyLambdaMetric, y, lam_good)
    assert np.isfinite(r["xentlambda"])
    assert r_good["xentlambda"] <= r["xentlambda"] + 1e-6


def test_trained_model_metric_consistency(rng):
    """End-to-end: the engine's reported eval equals the metric class run
    on the final scores."""
    import lightgbm_tpu as lgb
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] + 0.5 * rng.normal(size=800) > 0).astype(float)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": ["auc",
                                                       "binary_logloss"],
                     "verbosity": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    valid_sets=[lgb.Dataset(X, label=y)],
                    callbacks=[lgb.record_evaluation(evals)])
    res = next(iter(evals.values()))
    p = bst.predict(X)
    logloss = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert abs(res["binary_logloss"][-1] - logloss) < 1e-4
