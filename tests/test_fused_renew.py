"""Fused leaf renewal for the L1-family objectives: the per-leaf residual
percentile runs INSIDE the fused physical program
(models/boosting.py _renew_leaves_percentile; reference:
RegressionL1loss/RegressionQuantileloss/RegressionMAPELOSS::RenewTreeOutput
via PercentileFun/WeightedPercentileFun, regression_objective.hpp:18-80)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture
def reg_data(rng):
    X = rng.normal(size=(2000, 6))
    y = X[:, 0] * 2 + np.abs(X[:, 1]) + 0.3 * rng.standard_t(3, size=2000) + 5
    return X, y


def _train(X, y, params, force_eager=False, weight=None, rounds=8):
    ds = lgb.Dataset(X, label=y, weight=weight)
    bst = lgb.Booster(params=dict(params), train_set=ds)
    if force_eager:
        bst._gbdt._fused = None
        bst._gbdt._fused_phys = None
    for _ in range(rounds):
        bst.update()
    bst._gbdt._flush_pending()
    return bst


@pytest.mark.parametrize("obj,extra", [
    ("regression_l1", {}),
    ("quantile", {"alpha": 0.7}),
    ("quantile", {"alpha": 0.2}),
    ("mape", {}),
])
def test_fused_renewal_matches_host_renewal(reg_data, obj, extra):
    X, y = reg_data
    params = {"objective": obj, "num_leaves": 15, "min_data_in_leaf": 5,
              "verbosity": -1, **extra}
    fused = _train(X, y, params)
    assert fused._gbdt._fused is not None, f"{obj} should fuse"
    eager = _train(X, y, params, force_eager=True)
    # iteration 0 sees the identity permutation: the device percentile
    # must reproduce the host percentile bit-for-bit on the first tree
    t_f, t_e = fused._gbdt.models[0], eager._gbdt.models[0]
    assert t_f.num_leaves == t_e.num_leaves
    assert np.allclose(t_f.leaf_value, t_e.leaf_value, atol=2e-5), \
        np.abs(np.asarray(t_f.leaf_value) - np.asarray(t_e.leaf_value)).max()
    mae_f = np.abs(fused.predict(X) - y).mean()
    mae_e = np.abs(eager.predict(X) - y).mean()
    assert mae_f == pytest.approx(mae_e, rel=0.02)


def test_fused_renewal_weighted(reg_data, rng):
    X, y = reg_data
    w = rng.rand(len(y)) + 0.5
    params = {"objective": "quantile", "alpha": 0.6, "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    fused = _train(X, y, params, weight=w)
    assert fused._gbdt._fused is not None
    eager = _train(X, y, params, weight=w, force_eager=True)
    t_f, t_e = fused._gbdt.models[0], eager._gbdt.models[0]
    assert np.allclose(t_f.leaf_value, t_e.leaf_value, atol=2e-5)


def test_fused_renewal_with_bagging(reg_data):
    # bag draws differ by scheme (Bernoulli-by-rowid in-program vs the
    # host permutation bag), so assert quality parity only
    X, y = reg_data
    params = {"objective": "regression_l1", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1,
              "bagging_fraction": 0.7, "bagging_freq": 1}
    fused = _train(X, y, params)
    assert fused._gbdt._fused is not None
    eager = _train(X, y, params, force_eager=True)
    mae_f = np.abs(fused.predict(X) - y).mean()
    mae_e = np.abs(eager.predict(X) - y).mean()
    assert mae_f == pytest.approx(mae_e, rel=0.05)


def test_goss_renew_stays_eager(reg_data):
    # GOSS's in-bag set is not recoverable post-partition; the combo
    # must fall back to the eager path, not silently mis-renew
    X, y = reg_data
    params = {"objective": "regression_l1", "num_leaves": 15,
              "verbosity": -1, "data_sample_strategy": "goss"}
    bst = _train(X, y, params, rounds=4)
    assert bst._gbdt._fused is None
    assert np.isfinite(bst.predict(X)).all()
