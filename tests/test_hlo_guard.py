"""Tier-1 guard on the compiled tree while-body's HLO op counts
(tools/hlo_report.py).

The per-split fixed cost is op-count bound (PERF.md round 2: ~1.5 us
dispatch overhead per op x 327 body ops WAS the 0.45 ms/split), so a
bookkeeping-op regression is a perf regression — and through the
tunnel's +/-6% noise floor it would land silently.  This test fails
tier-1 instead.

Two guards:
  * ceilings on the default path's body counts (generous headroom over
    the measured values — a tripwire for gross regressions, not a
    byte-exact pin);
  * the mega-kernel split body must carry ZERO histogram-state copies
    (the round-4 "two contextual f32[L+1, G, B, 2] copies per split"
    are structurally gone — there is no histogram state in its carry).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))

from hlo_report import body_counts, compile_tree_build, report  # noqa: E402


@pytest.fixture(scope="module")
def reports():
    base = report({})
    mega = report({"tpu_megakernel": "xla"})
    return base, mega


def test_baseline_body_ceilings(reports):
    base, _ = reports
    # measured on the pinned CPU toolchain: 171 ops / 77 fusions / 22
    # copies with the default (leaf-size-adaptive) chunk policy — the
    # band variants add zero-trip loop headers and s32[] trip-counter
    # copies only (ops/chunkpolicy.py; the explicitly fixed grid
    # measures 112/61/14).  Ceilings leave ~30% headroom for
    # legitimate drift.
    assert base["total_ops"] <= 225, base
    assert base["fusions"] <= 100, base
    assert base["copies"] <= 28, base


def test_fixed_grid_body_ceilings():
    """The explicitly fixed-grid body keeps its OWN (tighter) ceilings
    — the adaptive default's headroom above must not hide a
    bookkeeping regression on the base formulation every band variant
    still contains (measured: 112 ops / 61 fusions / 14 copies after
    the rec["hist"] dead-export deletion)."""
    fixed = report({"tpu_chunk_policy": "fixed"})
    assert fixed["total_ops"] <= 150, fixed
    assert fixed["fusions"] <= 80, fixed
    assert fixed["copies"] <= 19, fixed
    assert fixed["hist_state_copies"] == 2, fixed["copies_by_shape"]


def test_baseline_has_the_parent_hist_copies(reports):
    """The detector must actually see the smoking gun on the
    subtraction path, or the mega assertion below proves nothing."""
    base, _ = reports
    assert base["hist_state_copies"] == 2, base["copies_by_shape"]


def test_mega_body_drops_hist_state_copies(reports):
    base, mega = reports
    assert mega["mega"] == "xla"
    assert mega["hist_state_copies"] == 0, mega["copies_by_shape"]
    assert mega["hist_state_copies"] < base["hist_state_copies"]


def test_mega_body_has_no_hist_state_buffer():
    """Stronger than no-copies: the (L+1)-slot state SHAPE must not
    appear anywhere in the mega while-body — the buffer does not exist."""
    hlo, learner = compile_tree_build({"tpu_megakernel": "xla"})
    counts = body_counts(hlo)
    L1, G, B = learner.L + 1, learner.G, learner.B
    state_token = f"f32[{L1},{G},{B},2]"
    assert learner._use_mega == "xla"
    from hlo_report import _computation_blocks
    body_lines = _computation_blocks(hlo)[counts["body"]]
    assert not any(state_token in ln for ln in body_lines), state_token
