"""Native C++ text parser tests: parity with the Python fallback
(reference analog: src/io/parser.cpp parsers)."""

import numpy as np
import pytest

from lightgbm_tpu.native import get_native, parse_delim, parse_libsvm


pytestmark = pytest.mark.skipif(get_native() is None,
                                reason="no native toolchain")


def test_parse_delim_basic():
    text = "1.5,2,3\n4,,6\n7,nan,NA"
    m = parse_delim(text, ",")
    assert m.shape == (3, 3)
    np.testing.assert_allclose(m[0], [1.5, 2, 3])
    assert np.isnan(m[1, 1]) and m[1, 2] == 6
    assert np.isnan(m[2, 1]) and np.isnan(m[2, 2])


def test_parse_delim_ragged_and_garbage():
    text = "1\t2\t3\t4\n5\t6\nx\t7\t1e300\t-2.5e-3"
    m = parse_delim(text, "\t")
    assert m.shape == (3, 4)
    assert np.isnan(m[1, 2]) and np.isnan(m[1, 3])     # padded
    assert np.isnan(m[2, 0])                            # 'x' -> NaN
    np.testing.assert_allclose(m[2, 1:], [7, 1e300, -2.5e-3])


def test_parse_delim_crlf_and_blank_lines():
    text = "1,2\r\n\r\n3,4\n\n"
    m = parse_delim(text, ",")
    assert m.shape == (2, 2)
    np.testing.assert_allclose(m, [[1, 2], [3, 4]])


def test_parse_libsvm():
    text = "1 0:1.5 3:2.25\n0 1:-4\n1\n"
    X, y, q = parse_libsvm(text)
    assert X.shape == (3, 4)
    np.testing.assert_allclose(y, [1, 0, 1])
    np.testing.assert_allclose(X[0], [1.5, 0, 0, 2.25])
    np.testing.assert_allclose(X[1], [0, -4, 0, 0])
    np.testing.assert_allclose(X[2], [0, 0, 0, 0])
    assert np.isnan(q).all()


def test_parse_libsvm_qid():
    """qid tokens map to group info, never to feature 0 (standard ranking
    LibSVM files)."""
    text = "2 qid:1 0:0.5 2:1.0\n1 qid:1 1:0.25\n0 qid:2 0:3.0\n"
    X, y, q = parse_libsvm(text)
    assert X.shape == (3, 3)
    np.testing.assert_allclose(X[0], [0.5, 0, 1.0])
    np.testing.assert_allclose(X[1], [0, 0.25, 0])      # no qid leak into f0
    np.testing.assert_allclose(X[2], [3.0, 0, 0])
    np.testing.assert_allclose(q, [1, 1, 2])


def test_parse_delim_python_float_parity():
    """Hex floats rejected, single underscores between digits accepted —
    exactly like Python float()."""
    m = parse_delim("0x10,1_0,1__0,_1,1_,inf,-inf", ",")
    assert np.isnan(m[0, 0])          # hex rejected
    assert m[0, 1] == 10.0            # 1_0 -> 10
    assert np.isnan(m[0, 2])          # double underscore rejected
    assert np.isnan(m[0, 3]) and np.isnan(m[0, 4])
    assert np.isinf(m[0, 5]) and np.isinf(m[0, 6])


def test_native_matches_python_fallback(tmp_path, rng):
    """End-to-end: load_text_file must give identical results with and
    without the native parser."""
    import lightgbm_tpu.utils.textio as textio
    from lightgbm_tpu.utils.textio import load_text_file
    X = rng.normal(size=(200, 5))
    X[rng.uniform(size=X.shape) < 0.1] = np.nan
    y = rng.randint(0, 2, size=200)
    path = tmp_path / "data.csv"
    with open(path, "w") as f:
        for i in range(200):
            f.write(f"{y[i]}," + ",".join(
                "" if np.isnan(v) else repr(v) for v in X[i]) + "\n")
    lf_native = load_text_file(str(path))
    import lightgbm_tpu.native as native_mod
    orig = native_mod.get_native
    try:
        native_mod.get_native = lambda: None
        import importlib
        lf_py = load_text_file(str(path))
    finally:
        native_mod.get_native = orig
    np.testing.assert_allclose(lf_native.X, lf_py.X, equal_nan=True)
    np.testing.assert_allclose(lf_native.label, lf_py.label)
