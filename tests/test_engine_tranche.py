"""Engine-behavior tranche (PR-3, round-6 verdict ask #8): the
reference predict start_iteration/num_iteration slicing matrix plus
previously-uncovered behaviors, each citing its reference counterpart.
These double as regression cover for the device serving engine, which
now carries raw/leaf/contrib slicing on its tree-mask path."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

BASE = {"verbosity": -1, "min_data_in_leaf": 5, "metric": ""}
N, F = 2500, 6


def _data(seed=0, n=N, f=F):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.normal(size=n)
    return X, y


@pytest.fixture(scope="module")
def reg_model():
    X, y = _data()
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=15),
                    lgb.Dataset(X, label=y), num_boost_round=12)
    return bst, X, y


def test_predict_slicing_matrix(reg_model):
    """The reference slicing matrix (reference: test_engine.py
    test_predict_with_start_iteration): for every pred kind, predicting
    [0, a) then [a, end) composes to the full prediction; raw scores
    add, leaves/contribs concatenate/add per-column."""
    bst, X, _ = reg_model
    for a in (1, 5, 11):
        head = bst.predict(X, raw_score=True, num_iteration=a)
        tail = bst.predict(X, raw_score=True, start_iteration=a)
        full = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(head + tail, full, rtol=1e-5,
                                   atol=1e-5)
        lh = bst.predict(X, pred_leaf=True, num_iteration=a)
        lt = bst.predict(X, pred_leaf=True, start_iteration=a)
        lf = bst.predict(X, pred_leaf=True)
        np.testing.assert_array_equal(
            np.concatenate([lh, lt], axis=1), lf)
        ch = bst.predict(X[:150], pred_contrib=True, num_iteration=a)
        ct = bst.predict(X[:150], pred_contrib=True, start_iteration=a)
        cf = bst.predict(X[:150], pred_contrib=True)
        np.testing.assert_allclose(ch + ct, cf, rtol=1e-9, atol=1e-9)


def test_predict_num_iteration_zero_and_overrun(reg_model):
    """num_iteration=0 predicts with ALL iterations (reference:
    basic.py Booster.predict num_iteration<=0 semantics), and a range
    past the model end clamps instead of raising (reference:
    test_engine.py test_predict_with_start_iteration overrun arm)."""
    bst, X, _ = reg_model
    np.testing.assert_allclose(
        bst.predict(X, raw_score=True, num_iteration=0),
        bst.predict(X, raw_score=True), rtol=0, atol=0)
    # the same zero-means-all rule holds on the contrib path (and on
    # the GBDT-level API the wrapper's 0 -> -1 rewrite doesn't reach)
    np.testing.assert_allclose(
        bst._gbdt.predict_contrib(X[:50], 0, 0),
        bst.predict(X[:50], pred_contrib=True), rtol=0, atol=1e-12)
    np.testing.assert_allclose(
        bst.predict(X, raw_score=True, num_iteration=999),
        bst.predict(X, raw_score=True), rtol=0, atol=0)
    assert bst.predict(X, pred_leaf=True,
                       start_iteration=10, num_iteration=999).shape == \
        (len(X), 2)


def test_feature_penalty_blocks_and_discourages():
    """feature_contri (alias feature_penalty) scales per-feature split
    gain; 0 forbids the feature outright (reference: config.h
    feature_contri / ``feature_penalty`` alias; gain scaling in
    serial_tree_learner.cpp GetSplitGains)."""
    rng = np.random.RandomState(5)
    n = 1500
    X = rng.normal(size=(n, 4))
    y = X[:, 0] * 3 + X[:, 1] + 0.1 * rng.normal(size=n)
    params = dict(BASE, objective="regression", num_leaves=15)
    free = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    assert free.feature_importance("split")[0] > 0
    # hard-zero penalty on the dominant feature: never split on it
    pen = lgb.train(dict(params, feature_penalty="0,1,1,1"),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    assert pen.feature_importance("split")[0] == 0
    # soft penalty reduces but does not forbid
    soft = lgb.train(dict(params, feature_penalty="0.1,1,1,1"),
                     lgb.Dataset(X, label=y), num_boost_round=5)
    assert soft.feature_importance("split")[0] <= \
        free.feature_importance("split")[0]


def test_max_bin_by_feature_edges():
    """Per-feature bin caps are respected, including the minimum legal
    cap of 2 bins next to an uncapped feature (reference:
    test_engine.py test_max_bin_by_feature)."""
    rng = np.random.RandomState(6)
    n = 1500
    X = np.column_stack([rng.normal(size=n), rng.normal(size=n)])
    y = X[:, 0] + 0.5 * X[:, 1]
    ds = lgb.Dataset(X, label=y)
    ds.construct(dict(BASE, objective="regression",
                      max_bin_by_feature="2,255", max_bin=255))
    bms = ds._inner.bin_mappers
    assert bms[0].num_bin <= 3        # 2 value bins (+ missing bin)
    assert bms[1].num_bin > 64
    # training still works and feature 0 can only produce one threshold
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=15,
                         max_bin_by_feature="2,255"),
                    lgb.Dataset(X, label=y), num_boost_round=4)
    thr0 = {float(t.threshold[i])
            for t in bst._gbdt.models
            for i in range(t.num_nodes())
            if int(t.split_feature[i]) == 0}
    assert len(thr0) <= 1


def test_refit_with_weights(reg_model):
    """refit keeps the tree structures, re-derives leaf values from the
    NEW data's gradients, and respects sample weights (reference:
    test_engine.py test_refit; GBDT::RefitTree gbdt.cpp:252)."""
    bst, X, y = reg_model
    X2, y2 = _data(seed=7)
    plain = bst.refit(X2, y2)
    # structures identical, outputs differ from the original model
    for t0, t1 in zip(bst._gbdt.models, plain._gbdt.models):
        np.testing.assert_array_equal(t0.split_feature, t1.split_feature)
        np.testing.assert_array_equal(t0.threshold, t1.threshold)
    assert not np.allclose(bst.predict(X2), plain.predict(X2))
    # weights steer the refitted leaf values: upweighting rows with a
    # +2 label shift pulls predictions toward the shifted target
    w = np.where(np.arange(len(y2)) % 2 == 0, 10.0, 0.1)
    y_shift = y2 + np.where(np.arange(len(y2)) % 2 == 0, 2.0, 0.0)
    heavy = bst.refit(X2, y_shift, weight=w)
    light = bst.refit(X2, y_shift,
                      weight=np.where(np.arange(len(y2)) % 2 == 0, 0.1,
                                      10.0))
    assert heavy.predict(X2).mean() > light.predict(X2).mean()


def test_refit_decay_rate(reg_model):
    """decay_rate blends old and new leaf values: decay 1.0 keeps the
    original model exactly (reference: test_engine.py test_refit
    decay_rate arm; gbdt.cpp RefitTree shrinkage blend)."""
    bst, X, _ = reg_model
    rng = np.random.RandomState(9)
    X2 = rng.normal(size=X.shape)
    y2 = rng.normal(size=len(X))
    keep = bst.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(keep.predict(X), bst.predict(X),
                               rtol=1e-6, atol=1e-6)
    blend = bst.refit(X2, y2, decay_rate=0.5)
    fresh = bst.refit(X2, y2, decay_rate=0.0)
    d_keep = np.abs(blend.predict(X) - bst.predict(X)).mean()
    d_fresh = np.abs(blend.predict(X) - fresh.predict(X)).mean()
    assert d_keep > 0 and d_fresh > 0


def test_multiclass_contrib_layout():
    """Multiclass pred_contrib is (n, K*(F+1)) with per-class blocks
    [phi_0..phi_F-1, bias] matching per-class raw scores (reference:
    c_api.cpp contrib layout; test_engine.py contrib assertions)."""
    rng = np.random.RandomState(8)
    n, f, K = 1200, 6, 3
    X = rng.normal(size=(n, f))
    y = rng.randint(0, K, size=n).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="multiclass", num_class=K,
                         num_leaves=7),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    contrib = bst.predict(X[:200], pred_contrib=True)
    assert contrib.shape == (200, K * (f + 1))
    raw = bst.predict(X[:200], raw_score=True)
    per_class = contrib.reshape(200, K, f + 1).sum(axis=2)
    np.testing.assert_allclose(per_class, raw, rtol=1e-5, atol=1e-5)


def test_early_stop_freq_past_end():
    """pred_early_stop with a freq larger than the iteration count
    degenerates to plain prediction (reference:
    prediction_early_stop.cpp round-up behavior)."""
    X, y = _data(seed=4, n=1500)
    yb = (y > np.median(y)).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=15),
                    lgb.Dataset(X, label=yb), num_boost_round=4)
    np.testing.assert_allclose(
        bst.predict(X, raw_score=True, pred_early_stop=True,
                    pred_early_stop_freq=50,
                    pred_early_stop_margin=0.001),
        bst.predict(X, raw_score=True), rtol=2e-6, atol=2e-6)


def test_validate_features_names():
    """validate_features checks frame columns against the model's
    feature names (reference: sklearn.py predict validate_features;
    c_api Predictor name check)."""
    pd = pytest.importorskip("pandas")
    X, y = _data(seed=2, n=800, f=4)
    cols = ["a", "b", "c", "d"]
    df = pd.DataFrame(X, columns=cols)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=15),
                    lgb.Dataset(df, label=y), num_boost_round=2)
    bst.predict(df, validate_features=True)      # matching names: fine
    bad = df.rename(columns={"c": "zz"})
    with pytest.raises(lgb.LightGBMError, match="mismatch"):
        bst.predict(bad, validate_features=True)
    # sklearn wrapper forwards the flag
    reg = lgb.LGBMRegressor(n_estimators=2, num_leaves=15,
                            verbosity=-1).fit(df, y)
    reg.predict(df, validate_features=True)
    with pytest.raises(lgb.LightGBMError, match="mismatch"):
        reg.predict(bad, validate_features=True)
