"""Leafwise-gain piece-wise-linear trees (linear_tree_mode=
leafwise_gain): the in-search PL split gain must bit-match a dense
NumPy normal-equations oracle on its discrete decisions, degenerate
leaves must fall back to constant models, both linear modes must
round-trip through save/load/pickle, and linear forests must serve
through the device engine (one trace per (kind, bucket)) in agreement
with the host oracle."""

import pickle

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import split as so

K_EPS = so.K_EPSILON


def _ctx(F, BF, rng):
    num_bin = rng.randint(3, BF + 1, size=F).astype(np.int32)
    missing = rng.randint(0, 3, size=F).astype(np.int32)
    default_bin = np.where(missing == so.MISSING_ZERO,
                           rng.randint(0, 3, size=F), 0).astype(np.int32)
    return so.SplitContext(
        num_bin=jnp.asarray(num_bin),
        missing_type=jnp.asarray(missing),
        default_bin=jnp.asarray(default_bin),
        is_categorical=jnp.zeros(F, jnp.int32),
        feature_index=jnp.arange(F, dtype=jnp.int32))


def _lin_side(g, h, xg, xh, xxh, l2, lam):
    """Float64 oracle of ops/split.py:_linear_side (centered ridge)."""
    xm = xh / h
    xgc = xg - xm * g
    var = xxh - xm * xh
    ok = var > 0.0
    denom = np.where(ok, var + lam, 1.0)
    coeff = np.where(ok, -xgc / denom, 0.0)
    gain = g * g / (h + l2) + np.where(ok, xgc * xgc / denom, 0.0)
    const = -g / (h + l2) - coeff * xm
    return gain, coeff, const


def _oracle(hist, rep, ctx, sum_g, sum_h, num_data, l2, mgts, mdl, msh,
            lam, feature_mask):
    """Dense NumPy normal-equations replay of find_best_split_linear:
    same masks, same candidate order (reverse-reversed ++ forward),
    same self-model shift, float64 accumulation."""
    F, BF, _ = hist.shape
    G = hist[..., 0].astype(np.float64)
    H = hist[..., 1].astype(np.float64)
    sum_h_tot = sum_h + 2 * K_EPS
    cnt_factor = num_data / sum_h_tot
    bins = np.arange(BF)[None, :]
    nb = np.asarray(ctx.num_bin)[:, None]
    in_range = bins < nb
    missing = np.asarray(ctx.missing_type)[:, None]
    dflt = np.asarray(ctx.default_bin)[:, None]
    is_zero = missing == so.MISSING_ZERO
    is_nan = missing == so.MISSING_NAN
    two_scan = (nb > 2) & (missing != so.MISSING_NONE)
    cnt_bin = np.floor(H * cnt_factor + 0.5) * in_range
    mask_f = in_range & ~(is_zero & (bins == dflt))
    bmax = nb - 1 - (is_nan & two_scan).astype(np.int64)
    mask_r = (in_range & ~(two_scan & is_zero & (bins == dflt)) &
              (bins <= bmax))

    repm = np.where(in_range, rep.astype(np.float64), 0.0)
    XG, XH = repm * G, repm * H
    XXH = repm * XH
    csf = lambda a, m: np.cumsum(np.where(m, a, 0.0), axis=1)  # noqa: E731
    lgf, lhf, lcf = csf(G, mask_f), csf(H, mask_f) + K_EPS, \
        csf(cnt_bin, mask_f)
    lxg, lxh, lxxh = csf(XG, True), csf(XH, True), csf(XXH, True)
    rxg, rxh, rxxh = (lxg[:, -1:] - lxg, lxh[:, -1:] - lxh,
                      lxxh[:, -1:] - lxxh)
    rgf, rhf, rcf = sum_g - lgf, sum_h_tot - lhf, num_data - lcf
    gr, hr, cr = csf(G, mask_r), csf(H, mask_r), csf(cnt_bin, mask_r)
    rgr, rhr, rcr = gr[:, -1:] - gr, hr[:, -1:] - hr + K_EPS, \
        cr[:, -1:] - cr
    lgr, lhr, lcr = sum_g - rgr, sum_h_tot - rhr, num_data - rcr

    gain_f = (_lin_side(lgf, lhf, lxg, lxh, lxxh, l2, lam)[0] +
              _lin_side(rgf, rhf, rxg, rxh, rxxh, l2, lam)[0])
    gain_r = (_lin_side(lgr, lhr, lxg, lxh, lxxh, l2, lam)[0] +
              _lin_side(rgr, rhr, rxg, rxh, rxxh, l2, lam)[0])

    sf_gain, sf_coeff, sf_const = _lin_side(
        sum_g, sum_h_tot, lxg[:, -1], lxh[:, -1], lxxh[:, -1], l2, lam)
    cand = sf_gain if feature_mask is None else \
        np.where(feature_mask, sf_gain, -np.inf)
    sf_j = int(np.argmax(cand))
    shift = sf_gain[sf_j] + mgts

    ok = lambda lc, rc, lh, rh: ((lc >= mdl) & (rc >= mdl) &  # noqa: E731
                                 (lh >= msh) & (rh >= msh))
    valid_f = (two_scan & in_range & (bins <= nb - 2) &
               ~(is_zero & (bins == dflt)) &
               ok(lcf, rcf, lhf, rhf) & (gain_f > shift))
    valid_r = (in_range & (bins <= bmax - 1) &
               ~(two_scan & is_zero & (bins == dflt - 1)) &
               ok(lcr, rcr, lhr, rhr) & (gain_r > shift))
    if feature_mask is not None:
        valid_f &= feature_mask[:, None]
        valid_r &= feature_mask[:, None]
    cf = np.where(valid_f, gain_f, -np.inf)
    crev = np.where(valid_r, gain_r, -np.inf)
    gains = np.concatenate([crev[:, ::-1], cf], axis=1).ravel()
    w = int(np.argmax(gains))
    f, r = w // (2 * BF), w % (2 * BF)
    t = BF - 1 - r if r < BF else r - BF
    dl = bool((two_scan | ~is_nan)[f, 0]) if r < BF else False
    return {"valid": gains[w] > -np.inf, "gain": gains[w] - shift,
            "feature": f, "threshold": t, "default_left": dl,
            "self_feature": sf_j, "self_coeff": sf_coeff[sf_j],
            "self_const": sf_const[sf_j]}


# The matrix rides through the histogram contents: bagging zeroes
# sampled-out mass, GOSS amplifies small-gradient hessian weight,
# quantized snaps gradients to an int grid, multiclass shrinks
# hessians to p(1-p) scale.  The search only ever sees (G, H) planes,
# so shaping them IS exercising those configs at the decision level.
def _hist_for(scenario, F, BF, nb, rng):
    hist = np.zeros((F, BF, 2), np.float32)
    for f in range(F):
        n = nb[f]
        g = rng.normal(size=n)
        h = rng.uniform(0.5, 1.5, size=n)
        if scenario == "bagging":
            keep = rng.rand(n) > 0.4
            g, h = g * keep, h * keep
        elif scenario == "goss":
            amp = np.where(np.abs(g) < 0.5, 5.0, 1.0)
            g, h = g * amp, h * amp
        elif scenario == "quantized":
            g = np.round(g * 8) / 8
        elif scenario == "multiclass":
            p = rng.uniform(0.05, 0.95, size=n)
            g, h = p - (rng.rand(n) < p), np.maximum(p * (1 - p), 1e-3)
        hist[f, :n, 0] = g
        hist[f, :n, 1] = h
    return hist


@pytest.mark.parametrize("scenario", ["plain", "bagging", "goss",
                                      "quantized", "multiclass"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_leafwise_matches_numpy_oracle(scenario, seed):
    scen_id = ["plain", "bagging", "goss", "quantized",
               "multiclass"].index(scenario)
    rng = np.random.RandomState(100 * seed + 7 * scen_id)
    F, BF = 6, 31
    ctx = _ctx(F, BF, rng)
    nb = np.asarray(ctx.num_bin)
    hist = _hist_for(scenario, F, BF, nb, rng)
    # rep values: 0 at the NaN bin and the MISSING_ZERO default bin
    # (the contract rep tables honour — moment mass of missing rows
    # must vanish in both scan directions)
    rep = rng.uniform(-2.0, 2.0, size=(F, BF)).astype(np.float32)
    missing = np.asarray(ctx.missing_type)
    dflt = np.asarray(ctx.default_bin)
    for f in range(F):
        if missing[f] == so.MISSING_NAN:
            rep[f, nb[f] - 1] = 0.0
        if missing[f] == so.MISSING_ZERO:
            rep[f, dflt[f]] = 0.0
        rep[f, nb[f]:] = 0.0
    sum_g = float(hist[0, :, 0].sum())
    sum_h = float(hist[0, :, 1].sum())
    num_data = 900.0
    l2, mgts, mdl, msh, lam = 1e-3, 0.0, 3, 1e-3, 1e-2
    mask = (rng.rand(F) > 0.25) if seed % 2 else None

    got = so.find_best_split_linear(
        jnp.asarray(hist), ctx, jnp.float32(sum_g), jnp.float32(sum_h),
        jnp.int32(num_data), l2, mgts, mdl, msh,
        jnp.asarray(rep), lam,
        feature_mask=None if mask is None else jnp.asarray(mask))
    want = _oracle(hist, rep, ctx, sum_g, sum_h, num_data, l2, mgts,
                   mdl, msh, lam, mask)

    if not want["valid"]:
        assert float(got.gain) == -np.inf
        return
    # discrete decisions are exact; float stats carry the f32-vs-f64
    # accumulation noise of the prefix sums
    for name in ("feature", "threshold", "default_left", "self_feature"):
        assert int(np.asarray(getattr(got, name))) == int(want[name]), \
            (scenario, seed, name)
    np.testing.assert_allclose(float(got.gain), want["gain"],
                               rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(float(got.self_coeff), want["self_coeff"],
                               rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(float(got.self_const), want["self_const"],
                               rtol=3e-4, atol=3e-4)


def _smooth(n=1500, f=5, seed=0, nan_col=None, const_col=None):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    if const_col is not None:
        X[:, const_col] = 1.5
    if nan_col is not None:
        X[rng.rand(n) < 0.9, nan_col] = np.nan
    y = (2.0 * X[:, 0] + np.sin(2.0 * X[:, 1])
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    return X, y


BASE = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
        "learning_rate": 0.2, "min_data_in_leaf": 20}
LEAFWISE = {**BASE, "linear_tree": True,
            "linear_tree_mode": "leafwise_gain"}


# Regression for the _fit_linear_leaves degenerate-leaf bug: a leaf
# whose candidate features are constant (or NaN-saturated) used to feed
# a singular normal-equations solve; it must drop the degenerate
# columns / ridge the diagonal and fall back to the constant output.
@pytest.mark.parametrize("mode", ["refit", "leafwise_gain"])
def test_degenerate_leaves_fall_back_to_constant(mode):
    X, y = _smooth(seed=3, nan_col=2, const_col=3)
    p = {**BASE, "linear_tree": True, "linear_tree_mode": mode}
    bst = lgb.train(p, lgb.Dataset(X, label=y), 8)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    # degenerate columns must never be fitted with a slope
    for t in bst._gbdt.models:
        for fs, cs in zip(t.leaf_features or [], t.leaf_coeff or []):
            for f, c in zip(fs, cs):
                assert f != 3, "constant column fitted with a slope"
                assert np.isfinite(c)
    mse_c = np.mean((y - lgb.train(BASE, lgb.Dataset(X, label=y), 8)
                     .predict(X)) ** 2)
    assert np.mean((y - pred) ** 2) < mse_c * 1.05


@pytest.mark.parametrize("mode", ["refit", "leafwise_gain"])
def test_linear_save_load_pickle_bit_parity(mode, tmp_path):
    X, y = _smooth(seed=5)
    p = {**BASE, "linear_tree": True, "linear_tree_mode": mode}
    bst = lgb.train(p, lgb.Dataset(X, label=y), 10)
    ref = bst.predict(X, raw_score=True)
    # pickle: bit parity (same packs, same kernels)
    clone = pickle.loads(pickle.dumps(bst))
    np.testing.assert_array_equal(clone.predict(X, raw_score=True), ref)
    # save/load: the text round-trip re-serves from the host oracle
    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    loaded = lgb.Booster(model_file=str(f))
    np.testing.assert_allclose(loaded.predict(X, raw_score=True), ref,
                               rtol=1e-5, atol=1e-5)


def test_leafwise_device_engine_matches_host_oracle(tmp_path):
    """In-session serving of a leafwise-gain forest runs on the device
    engine; the text-round-tripped booster serves the same trees from
    the host linear oracle.  They must agree — including NaN fallback
    rows and start/num_iteration slicing."""
    X, y = _smooth(n=5000, seed=7)
    bst = lgb.train(LEAFWISE, lgb.Dataset(X, label=y), 12)
    assert any(t.is_linear for t in bst._gbdt.models)
    eng = bst._gbdt.serving
    bst.predict(X, raw_score=True)      # past the cold-batch gate

    Xq = X[:800].copy()
    Xq[::7, 0] = np.nan          # NaN in a fitted feature -> fallback
    Xq[::11, 1] = np.nan
    pred = bst.predict(Xq, raw_score=True)
    assert eng._warm("insession"), "linear forest must serve on-device"

    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    loaded = lgb.Booster(model_file=str(f))
    np.testing.assert_allclose(pred, loaded.predict(Xq, raw_score=True),
                               rtol=1e-5, atol=1e-5)
    for kw in ({"num_iteration": 5}, {"start_iteration": 4},
               {"start_iteration": 2, "num_iteration": 6}):
        np.testing.assert_allclose(
            bst.predict(Xq, raw_score=True, **kw),
            loaded.predict(Xq, raw_score=True, **kw),
            rtol=1e-5, atol=1e-5, err_msg=str(kw))


def test_leafwise_serving_one_trace_per_bucket():
    X, y = _smooth(n=5000, seed=9)
    bst = lgb.train(LEAFWISE, lgb.Dataset(X, label=y), 10)
    eng = bst._gbdt.serving
    snap = eng.trace_snapshot()
    for _ in range(3):
        bst.predict(X, raw_score=True)       # same bucket every time
    assert eng._warm("insession")
    new = eng.new_traces_since(snap)
    raw = {k: v for k, v in new.items() if k[0] == "raw"}
    assert raw and all(v == 1 for v in raw.values()), new
    # slicing re-traces at most once per distinct range
    snap = eng.trace_snapshot()
    bst.predict(X, raw_score=True, num_iteration=5)
    bst.predict(X, raw_score=True, num_iteration=5)
    new = eng.new_traces_since(snap)
    assert all(v == 1 for v in new.values()), new


def test_leafwise_falls_back_on_categorical():
    """Categorical features leave the fast-search envelope: leafwise
    mode must warn and train as refit, not crash."""
    from lightgbm_tpu.utils import log

    rng = np.random.RandomState(2)
    n = 800
    Xc = rng.randint(0, 5, size=n).astype(np.float32)
    X = np.column_stack([rng.normal(size=n).astype(np.float32), Xc])
    y = (X[:, 0] * 2 + (Xc == 2) + 0.1 * rng.normal(size=n)
         ).astype(np.float32)
    lines = []
    old_verbosity = log.get_verbosity()
    log.register_callback(lines.append)
    try:
        bst = lgb.train({**LEAFWISE, "verbosity": 0,
                         "categorical_feature": [1]},
                        lgb.Dataset(X, label=y), 5)
    finally:
        log.register_callback(None)
        log.set_verbosity(old_verbosity)
    assert any("falling back" in ln for ln in lines), lines
    assert np.isfinite(bst.predict(X)).all()


def test_leafwise_multiclass_and_bagging_smoke():
    """Training-level matrix ride-along: multiclass + bagging + GOSS
    configs stay eligible (no fallback warning) and out-predict
    constant trees on the smooth target."""
    X, y = _smooth(n=2500, seed=13)
    for extra in ({"bagging_fraction": 0.7, "bagging_freq": 1},
                  {"boosting": "goss"}):
        bst = lgb.train({**LEAFWISE, **extra},
                        lgb.Dataset(X, label=y), 15)
        assert any(t.is_linear for t in bst._gbdt.models), extra
        assert np.isfinite(bst.predict(X)).all(), extra
    yc = (X[:, 0] > 0).astype(np.float32) + (X[:, 1] > 0)
    bst = lgb.train({**LEAFWISE, "objective": "multiclass",
                     "num_class": 3}, lgb.Dataset(X, label=yc), 8)
    p = bst.predict(X)
    assert p.shape == (len(X), 3) and np.isfinite(p).all()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
