"""Device batch prediction for LOADED models (no bin mappers):
threshold-index conversion must match the host float64 walk exactly
(reference: predictor.hpp batch predictor parity)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train_and_reload(rng, params, n=6000, f=10, rounds=12):
    X = rng.normal(size=(n, f))
    X[rng.rand(n, f) < 0.05] = np.nan            # exercise NaN handling
    X[:, 3] = np.where(rng.rand(n) < 0.4, 0.0, X[:, 3])   # zero-heavy
    y = (np.nan_to_num(X[:, 0]) * 2 +
         np.sin(np.nan_to_num(X[:, 1])) + 0.2 * rng.normal(size=n))
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    return X, bst, loaded


def test_loaded_device_predict_matches_host(rng):
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 20, "metric": ""}
    X, bst, loaded = _train_and_reload(rng, params)
    g = loaded._gbdt
    dev = g._predict_raw_device_loaded(X, 0, len(g.models))
    assert dev is not None, "device path did not engage"
    # host oracle: per-tree float64 walk
    host = np.zeros(len(X))
    for t in g.models:
        host += t.predict(X)
    np.testing.assert_allclose(dev[:, 0], host, rtol=1e-6, atol=1e-7)
    # and the public API takes the device path transparently
    p = loaded.predict(X)
    np.testing.assert_allclose(p, host, rtol=1e-6, atol=1e-7)


def test_loaded_device_predict_multiclass(rng):
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 20, "metric": ""}
    n, f = 6000, 8
    X = rng.normal(size=(n, f))
    y = rng.randint(0, 3, size=n).astype(float)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    g = loaded._gbdt
    dev = g._predict_raw_device_loaded(X, 0, len(g.models) // 3)
    assert dev is not None and dev.shape == (n, 3)
    host = np.zeros((n, 3))
    for t_idx, t in enumerate(g.models):
        host[:, t_idx % 3] += t.predict(X)
    np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)


def test_loaded_device_refuses_categorical(rng):
    n = 5000
    Xc = rng.randint(0, 6, size=(n, 3)).astype(float)
    y = (Xc[:, 0] == 2).astype(float) + 0.1 * rng.normal(size=n)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 20, "metric": "",
              "categorical_feature": "0,1,2", "min_data_per_group": 5}
    bst = lgb.train(params, lgb.Dataset(
        Xc, label=y, categorical_feature=[0, 1, 2]), num_boost_round=5)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    g = loaded._gbdt
    assert g._predict_raw_device_loaded(Xc, 0, len(g.models)) is None
    # the host fallback still answers correctly
    host = np.zeros(n)
    for t in g.models:
        host += t.predict(Xc)
    np.testing.assert_allclose(loaded.predict(Xc), host, rtol=1e-6)


def test_predict_leaf_index_device_matches_host(rng):
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 20, "metric": ""}
    X, bst, loaded = _train_and_reload(rng, params, rounds=6)
    g = loaded._gbdt
    dev = g.predict_leaf_index(X)         # >= 4096 rows -> device
    host = np.column_stack([t.predict_leaf(X) for t in g.models])
    np.testing.assert_array_equal(dev, host)
    # small batches fall back to the host walk and agree too
    np.testing.assert_array_equal(g.predict_leaf_index(X[:100]),
                                  host[:100])
