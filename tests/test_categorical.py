"""Categorical split tests.

Mirrors the reference's categorical coverage in
tests/python_package_test/test_engine.py (test_categorical_handling et al.):
one-hot mode (few categories), sorted-subset mode (many categories),
missing/unseen categories routed right, and model text round-trips.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_data(n=4000, k=12, seed=0, in_set=(2, 5, 7, 11)):
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, k, size=n)
    x1 = rng.normal(size=n)
    y = (np.isin(cat, list(in_set)).astype(float) * 2.0 + 0.3 * x1 +
         0.1 * rng.normal(size=n))
    X = np.column_stack([cat.astype(float), x1])
    return X, y


def test_sorted_mode_recovers_category_set():
    # 12 categories > max_cat_to_onehot=4 -> sorted-subset scan
    X, y = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "learning_rate": 0.2, "verbosity": -1,
                     "min_data_in_leaf": 20}, ds, num_boost_round=30)
    pred = bst.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05
    ncat = sum(t["num_cat"] for t in bst.dump_model()["tree_info"])
    assert ncat > 0


def test_onehot_mode():
    # 3 categories <= max_cat_to_onehot -> one-vs-rest
    X, y = _cat_data(k=3, in_set=(1,))
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "learning_rate": 0.3, "verbosity": -1,
                     "min_data_in_leaf": 20}, ds, num_boost_round=20)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.05
    assert sum(t["num_cat"] for t in bst.dump_model()["tree_info"]) > 0


def test_text_roundtrip_and_unseen_category():
    X, y = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 20},
                    ds, num_boost_round=10)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    Xq = X.copy()
    Xq[:5, 0] = 99.0          # unseen category -> not in any left set
    Xq[5:10, 0] = np.nan      # missing -> right
    p1 = bst.predict(Xq)
    p2 = bst2.predict(Xq)
    np.testing.assert_allclose(p1, p2, rtol=1e-12)
    assert np.all(np.isfinite(p1))


def test_categorical_binary_classification():
    rng = np.random.RandomState(7)
    n = 3000
    cat = rng.randint(0, 20, size=n)
    logit = np.where(np.isin(cat, [1, 3, 8, 13, 17]), 1.5, -1.5)
    yb = (logit + rng.logistic(size=n) > 0).astype(float)
    X = np.column_stack([cat.astype(float), rng.normal(size=n)])
    ds = lgb.Dataset(X, label=yb, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 20},
                    ds, num_boost_round=30)
    pred = bst.predict(X)
    acc = float(np.mean((pred > 0.5) == yb))
    assert acc > 0.7


def test_categorical_predict_edge_values():
    """Huge, fractional-negative and NaN values must not crash and must
    follow the reference's int-truncation semantics (tree.h:400)."""
    rng = np.random.RandomState(0)
    X = rng.randint(0, 5, size=(400, 1)).astype(float)
    y = (X[:, 0] % 2).astype(float)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=5)
    for v in (1e19, -1e19, -0.5, np.nan, np.inf, -np.inf):
        p = bst.predict(np.array([[v]]))      # must not raise
        assert np.isfinite(p).all()
    # truncation toward zero: -0.5 behaves like category 0
    assert np.allclose(bst.predict(np.array([[-0.5]])),
                       bst.predict(np.array([[0.0]])))
