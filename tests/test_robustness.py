"""Fault-tolerant training runtime (lightgbm_tpu/robustness/).

Covers the ISSUE-1 acceptance surface: kill-at-iteration-k -> resume
parity (model text identical to an uninterrupted run, including under
bagging/GOSS RNG state), every nonfinite_policy mode, checkpoint
retention/atomicity, and bootstrap retry-then-succeed via deterministic
fault injection.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.robustness import faultinject
from lightgbm_tpu.robustness.checkpoint import (CheckpointCallback,
                                                CheckpointManager)
from lightgbm_tpu.robustness.retry import retry_with_backoff
from lightgbm_tpu.utils import log as _log
from lightgbm_tpu.utils.log import LightGBMError


def _data(rng, n=400, f=8, binary=True):
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    raw = X @ w + rng.normal(size=n)
    y = (raw > 0).astype(np.float64) if binary else raw
    return X, y


def _norm(model_text):
    """Model text modulo the config-echo lines that legitimately differ
    between runs (the checkpoint paths themselves)."""
    return "\n".join(l for l in model_text.split("\n")
                     if not l.startswith(("[checkpoint_dir",
                                          "[checkpoint_resume")))


def _kill_and_resume(params, X, y, rounds, kill_at, valid=None):
    """Train-to-kill then resume; returns the resumed model text."""
    def mk_valid():
        return ([lgb.Dataset(v[0], label=v[1]) for v in valid]
                if valid else None)
    try:
        with faultinject.injected(kill_at_iteration=kill_at):
            lgb.train(params, lgb.Dataset(X, label=y),
                      num_boost_round=rounds, valid_sets=mk_valid())
        raise AssertionError("fault injection did not kill training")
    except faultinject.TrainingKilled:
        pass
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds,
                    valid_sets=mk_valid(), resume=True)
    return bst.model_to_string()


# ---------------------------------------------------------------------------
# kill -> resume parity
# ---------------------------------------------------------------------------
@pytest.mark.slow  # 2.8 s: tier-1 window offender per
# test_durations.json; test_resume_parity_goss keeps a fast in-window
# representative of the kill->resume parity lane
def test_resume_parity_bagging_fused(rng, tmp_path):
    """Kill at iteration 13 of 20, resume from the iteration-10
    checkpoint: model text must be byte-identical to an uninterrupted
    run — under bagging + feature_fraction RNG (fused physical path)."""
    X, y = _data(rng)
    base = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                bagging_fraction=0.7, bagging_freq=2, feature_fraction=0.8,
                seed=7, verbosity=-1, metric="", checkpoint_interval=4)
    ref = lgb.train(dict(base, checkpoint_dir=str(tmp_path / "a")),
                    lgb.Dataset(X, label=y), num_boost_round=14)
    resumed = _kill_and_resume(dict(base, checkpoint_dir=str(tmp_path / "b")),
                               X, y, rounds=14, kill_at=10)
    assert _norm(ref.model_to_string()) == _norm(resumed)


def test_resume_parity_goss(rng, tmp_path):
    """Same parity under GOSS sampling RNG state."""
    X, y = _data(rng)
    base = dict(objective="binary", num_leaves=15,
                data_sample_strategy="goss", seed=5, verbosity=-1,
                metric="", checkpoint_interval=4)
    ref = lgb.train(dict(base, checkpoint_dir=str(tmp_path / "a")),
                    lgb.Dataset(X, label=y), num_boost_round=12)
    resumed = _kill_and_resume(dict(base, checkpoint_dir=str(tmp_path / "b")),
                               X, y, rounds=12, kill_at=9)
    assert _norm(ref.model_to_string()) == _norm(resumed)


@pytest.mark.slow  # 7.9 s: tier-1 window offender per
# test_durations.json; test_resume_parity_goss keeps a fast in-window
# representative of the resume lane
def test_resume_parity_eager_custom_objective(rng, tmp_path):
    """Parity on the eager path (callable objective disables fusion),
    with a validation set whose restored scores must also match."""
    X, y = _data(rng, binary=False)

    def fobj(preds, ds):
        return preds - ds.get_label(), np.ones_like(preds)

    base = dict(objective=fobj, num_leaves=15, feature_fraction=0.7,
                seed=11, verbosity=-1, metric="l2", checkpoint_interval=4)
    valid = [(X[:100], y[:100])]
    ref = lgb.train(dict(base, checkpoint_dir=str(tmp_path / "a")),
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    resumed = _kill_and_resume(dict(base, checkpoint_dir=str(tmp_path / "b")),
                               X, y, rounds=10, kill_at=7, valid=valid)
    assert _norm(ref.model_to_string()) == _norm(resumed)


def test_resume_without_checkpoint_starts_fresh(rng, tmp_path):
    """resume=True over an empty checkpoint_dir trains from scratch."""
    X, y = _data(rng)
    params = dict(objective="binary", num_leaves=7, verbosity=-1, metric="",
                  checkpoint_dir=str(tmp_path / "empty"),
                  checkpoint_interval=5)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6,
                    resume=True)
    assert bst.num_trees() == 6


def test_resume_requires_checkpoint_config(rng):
    X, y = _data(rng)
    with pytest.raises(LightGBMError, match="checkpoint_dir"):
        lgb.train(dict(objective="binary", verbosity=-1),
                  lgb.Dataset(X, label=y), num_boost_round=2, resume=True)


# ---------------------------------------------------------------------------
# checkpoint files: retention + atomicity
# ---------------------------------------------------------------------------
def test_checkpoint_retention_and_layout(rng, tmp_path):
    X, y = _data(rng)
    ckdir = tmp_path / "ck"
    lgb.train(dict(objective="binary", num_leaves=7, verbosity=-1,
                   metric="", checkpoint_dir=str(ckdir),
                   checkpoint_interval=2, checkpoint_keep=2),
              lgb.Dataset(X, label=y), num_boost_round=10)
    entries = sorted(os.listdir(ckdir))
    # keep-last-2 of the 5 aligned iterations, no temp leftovers
    assert entries == ["ckpt_00000008", "ckpt_00000010"]
    for e in entries:
        assert sorted(os.listdir(ckdir / e)) == [
            "arrays.npz", "model.txt", "state.json"]


def test_checkpoint_latest_skips_torn_write(rng, tmp_path):
    """A truncated newest checkpoint (crash mid-stage would be a tmp dir;
    a corrupted one is worse) degrades to the previous snapshot."""
    X, y = _data(rng)
    ckdir = tmp_path / "ck"
    lgb.train(dict(objective="binary", num_leaves=7, verbosity=-1,
                   metric="", checkpoint_dir=str(ckdir),
                   checkpoint_interval=3, checkpoint_keep=3),
              lgb.Dataset(X, label=y), num_boost_round=9)
    mgr = CheckpointManager(str(ckdir), keep=3)
    assert mgr.iterations() == [3, 6, 9]
    # tear the newest: drop its arrays file
    os.remove(ckdir / "ckpt_00000009" / "arrays.npz")
    state = mgr.latest()
    assert state is not None and state.iteration == 6


def test_checkpoint_history_delta_log(rng, tmp_path):
    """The eval history is an append-only history.jsonl shared by all
    checkpoints: state.json carries only the length (per-checkpoint
    cost no longer grows with iterations trained), and restore
    reconstructs the full history capped at that length."""
    import json as _json

    X, y = _data(rng)
    ck = str(tmp_path / "hist")
    params = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                  seed=3, verbosity=-1, checkpoint_dir=ck,
                  checkpoint_interval=3, checkpoint_keep=2)
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=12,
              valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    # every evaluated iteration appended one line
    hist_path = os.path.join(ck, "history.jsonl")
    with open(hist_path) as fh:
        lines = [l for l in fh.read().splitlines() if l.strip()]
    assert len(lines) == 12
    # state.json stores the LENGTH, never the history itself
    newest = sorted(d for d in os.listdir(ck) if d.startswith("ckpt_"))[-1]
    with open(os.path.join(ck, newest, "state.json")) as fh:
        meta = _json.load(fh)
    assert "eval_history" not in meta
    assert meta["eval_history_len"] == 12
    # restore reconstructs the full capped history
    from lightgbm_tpu.robustness.checkpoint import CheckpointManager
    state = CheckpointManager(ck).latest()
    assert len(state.eval_history) == 12
    assert state.eval_history[0][0][0] == "valid_0"
    # torn trailing line (crash mid-append) degrades to the parsed prefix
    with open(hist_path, "a") as fh:
        fh.write('[["valid_0", "binary_log')
    state2 = CheckpointManager(ck).latest()
    assert len(state2.eval_history) == 12


@pytest.mark.slow  # 6.0 s: tier-1 window offender per
# test_durations.json; test_checkpoint_history_delta_log keeps a fast
# in-window representative of the history-log lane
def test_checkpoint_history_resume_truncates_stale_tail(rng, tmp_path):
    """A killed run leaves history lines past the resumed checkpoint;
    the first post-resume save must rewrite the log so the resumed
    run's history is exactly the uninterrupted run's."""
    import json as _json

    X, y = _data(rng)
    base = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                seed=5, verbosity=-1, checkpoint_interval=3)
    va = [(X[:100], y[:100])]
    ref = lgb.train(dict(base, checkpoint_dir=str(tmp_path / "a")),
                    lgb.Dataset(X, label=y), num_boost_round=12,
                    valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    resumed = _kill_and_resume(dict(base,
                                    checkpoint_dir=str(tmp_path / "b")),
                               X, y, rounds=12, kill_at=8, valid=va)
    assert _norm(ref.model_to_string()) == _norm(resumed)
    for arm in ("a", "b"):
        with open(tmp_path / arm / "history.jsonl") as fh:
            lines = [l for l in fh.read().splitlines() if l.strip()]
        assert len(lines) == 12, arm
    a = [_json.loads(l) for l in
         open(tmp_path / "a" / "history.jsonl").read().splitlines()]
    b = [_json.loads(l) for l in
         open(tmp_path / "b" / "history.jsonl").read().splitlines()]
    assert a == b


@pytest.mark.slow  # 1.6 s: tier-1 window trim per test_durations.json;
# test_checkpoint_history_delta_log keeps the fast in-window
# representative of the history-format lane (the legacy v1 reader has
# no other consumer in the window)
def test_checkpoint_legacy_full_history_state_loads(rng, tmp_path):
    """format_version-1 checkpoints (full eval_history inline in
    state.json) must keep loading."""
    import json as _json

    X, y = _data(rng)
    ck = str(tmp_path / "legacy")
    params = dict(objective="binary", num_leaves=15, verbosity=-1,
                  checkpoint_dir=ck, checkpoint_interval=4)
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
              valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    newest = sorted(d for d in os.listdir(ck) if d.startswith("ckpt_"))[-1]
    sp = os.path.join(ck, newest, "state.json")
    with open(sp) as fh:
        meta = _json.load(fh)
    meta.pop("eval_history_len")
    meta["format_version"] = 1
    meta["eval_history"] = [[["valid_0", "binary_logloss", 0.5, False]]]
    with open(sp, "w") as fh:
        _json.dump(meta, fh)
    os.remove(os.path.join(ck, "history.jsonl"))
    from lightgbm_tpu.robustness.checkpoint import CheckpointManager
    state = CheckpointManager(ck).latest()
    assert state.eval_history == [[("valid_0", "binary_logloss", 0.5,
                                    False)]]


def test_checkpoint_callback_rejects_cv(rng, tmp_path):
    X, y = _data(rng)
    cb = CheckpointCallback(str(tmp_path / "ck"), interval=2)
    with pytest.raises(LightGBMError, match="cv"):
        lgb.cv(dict(objective="binary", num_leaves=7, verbosity=-1),
               lgb.Dataset(X, label=y), num_boost_round=4, nfold=2,
               callbacks=[cb])


# ---------------------------------------------------------------------------
# non-finite guard rails
# ---------------------------------------------------------------------------
def _train_policy(X, y, policy, rounds=8, corrupt_at=3, capture=None):
    if capture is not None:
        _log.register_callback(capture.append)
    try:
        with faultinject.injected(corrupt_gradients_at=corrupt_at):
            return lgb.train(
                dict(objective="regression", num_leaves=7, verbosity=1,
                     metric="", nonfinite_policy=policy),
                lgb.Dataset(X, label=y), num_boost_round=rounds)
    finally:
        if capture is not None:
            _log.register_callback(None)


def test_nonfinite_skip_iteration(rng):
    """Injected NaN batch at iteration 3: training completes with that
    iteration dropped and EXACTLY one warning naming it."""
    X, y = _data(rng, binary=False)
    msgs = []
    bst = _train_policy(X, y, "skip_iteration", rounds=8, corrupt_at=3,
                        capture=msgs)
    assert bst.num_trees() == 7          # 8 rounds, one skipped
    warns = [m for m in msgs
             if "skip" in m and "iteration 3" in m and "Warning" in m]
    assert len(warns) == 1
    assert np.isfinite(bst.predict(X)).all()


def test_nonfinite_raise(rng):
    X, y = _data(rng, binary=False)
    with pytest.raises(LightGBMError, match="iteration 2"):
        _train_policy(X, y, "raise", rounds=5, corrupt_at=2)


def test_nonfinite_clamp(rng):
    X, y = _data(rng, binary=False)
    bst = _train_policy(X, y, "clamp", rounds=5, corrupt_at=2)
    assert bst.num_trees() == 5          # poisoned rows dropped, no skip
    assert np.isfinite(bst.predict(X)).all()


def test_nonfinite_policy_off_by_default(rng):
    """No policy -> no guard: the fused fast path stays enabled."""
    X, y = _data(rng)
    bst = lgb.train(dict(objective="binary", num_leaves=7, verbosity=-1,
                         metric=""),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst._gbdt._nf_guard is None
    assert bst._gbdt._fused is not None


def test_nonfinite_unknown_policy_rejected(rng):
    X, y = _data(rng)
    with pytest.raises(LightGBMError, match="nonfinite_policy"):
        lgb.train(dict(objective="binary", nonfinite_policy="bogus",
                       verbosity=-1),
                  lgb.Dataset(X, label=y), num_boost_round=2)


# ---------------------------------------------------------------------------
# hardened distributed bootstrap
# ---------------------------------------------------------------------------
def test_bootstrap_retry_then_succeed(monkeypatch):
    """First 2 bootstrap attempts fail (injected); the retry loop in
    init_network lands the third attempt."""
    import jax

    from lightgbm_tpu.parallel import network

    calls = []
    monkeypatch.setattr(network, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with faultinject.injected(fail_bootstrap_attempts=2):
        network.init_network(machines="hostA:9999,hostB:9999",
                             num_machines=2, time_out=60,
                             retries=5, retry_base_delay=0.01)
    assert len(calls) == 1
    assert faultinject.bootstrap_attempts_seen == 3
    monkeypatch.setattr(network, "_initialized", False)


def test_bootstrap_exhausted_attempts_raise(monkeypatch):
    import jax

    from lightgbm_tpu.parallel import network

    monkeypatch.setattr(network, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: None)
    with faultinject.injected(fail_bootstrap_attempts=10):
        with pytest.raises(LightGBMError, match="bootstrap"):
            network.init_network(machines="hostA:9999,hostB:9999",
                                 num_machines=2, time_out=60,
                                 retries=3, retry_base_delay=0.01)


def test_bootstrap_num_machines_disagreement(monkeypatch):
    """machines list length vs num_machines mismatch fails fast with a
    clear error instead of hanging the coordinator barrier."""
    from lightgbm_tpu.parallel import network

    monkeypatch.setattr(network, "_initialized", False)
    with pytest.raises(LightGBMError, match="num_machines=3"):
        network.init_network(machines="hostA:1,hostB:2", num_machines=3)


def test_bootstrap_process_count_disagreement(monkeypatch):
    """Bootstrap that comes up with the wrong group size raises the
    rank-disagreement error, not a later hang."""
    import jax

    from lightgbm_tpu.parallel import network

    monkeypatch.setattr(network, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    with pytest.raises(LightGBMError, match="disagree"):
        network.init_network(machines="hostA:9999,hostB:9999",
                             num_machines=2, retries=1)
    monkeypatch.setattr(network, "_initialized", False)


def test_retry_with_backoff_does_not_retry_fatal():
    calls = []

    def fn():
        calls.append(1)
        raise RuntimeError("coordinator already initialized")

    with pytest.raises(RuntimeError, match="already initialized"):
        retry_with_backoff(fn, attempts=5, base_delay=0.01,
                           fatal_if=lambda e: "already initialized"
                           in str(e),
                           sleep=lambda s: None)
    assert len(calls) == 1


def test_backoff_schedule_deadline_truncates():
    """The deadline prunes the schedule where the CUMULATIVE sleep
    budget runs out (len(schedule) = retry sleeps afforded), and the
    seeded jitter stream stays positionally identical with or without
    it — tightening a budget never re-rolls surviving delays."""
    from lightgbm_tpu.robustness.retry import backoff_schedule
    full = backoff_schedule(5, base_delay=1.0)
    assert full == [1.0, 2.0, 4.0, 8.0, 16.0]
    cut = backoff_schedule(5, base_delay=1.0, deadline=10.0)
    assert cut == [1.0, 2.0, 4.0]          # +8 would cross 10
    assert backoff_schedule(5, base_delay=1.0, deadline=0.5) == []
    jf = backoff_schedule(5, base_delay=1.0, jitter=0.3, seed=9)
    jc = backoff_schedule(5, base_delay=1.0, jitter=0.3, seed=9,
                          deadline=sum(jf[:2]) + 0.01)
    assert jc == jf[:2]


def test_retry_deadline_stops_and_reports_attempts():
    """retry_with_backoff under a deadline: attempts stop when the
    budget is exhausted (never sleeping past it), the terminal error
    reports attempts-used and the budget, and the ManualClock replay
    contract holds — virtual time at exhaustion equals the truncated
    schedule exactly."""
    from lightgbm_tpu.robustness.retry import (ManualClock,
                                               retry_with_backoff)
    clock = ManualClock()
    calls = []

    def fn():
        calls.append(1)
        raise RuntimeError("flaky")

    with pytest.raises(LightGBMError) as ei:
        retry_with_backoff(fn, attempts=5, base_delay=1.0,
                           deadline=10.0, sleep=clock.sleep,
                           clock=clock, describe="op")
    # schedule [1, 2, 4]: 4 attempts (3 sleeps), stop before the 8s
    # sleep that would cross the 10s budget
    assert len(calls) == 4
    assert clock.now == pytest.approx(7.0)
    assert "4 attempt(s)" in str(ei.value)
    assert "deadline 10.0s" in str(ei.value)
    # without a deadline the same policy runs all 5 attempts
    clock2 = ManualClock()
    calls.clear()
    with pytest.raises(LightGBMError):
        retry_with_backoff(fn, attempts=5, base_delay=1.0,
                           sleep=clock2.sleep, clock=clock2)
    assert len(calls) == 5 and clock2.now == pytest.approx(15.0)


def test_continual_retrain_consumes_deadline(rng):
    """The continual retrain loop passes continual_retrain_deadline
    through to the retry policy: a deadline too small for any retry
    sleep degrades to last-good after the attempts the budget affords,
    at the virtual time the truncated schedule predicts."""
    from lightgbm_tpu.continual import ContinualBooster
    from lightgbm_tpu.robustness.retry import ManualClock
    X = rng.normal(size=(200, 4))
    y = X[:, 0] + 0.05 * rng.normal(size=200)
    clock = ManualClock()
    cb = ContinualBooster(
        {"objective": "regression", "num_leaves": 5, "verbosity": -1,
         "metric": "", "num_iterations": 3, "min_data_in_leaf": 5,
         "continual_window": 1, "continual_cooldown": 0,
         "continual_retrain_attempts": 4,
         "continual_backoff_base": 1.0,
         "continual_backoff_jitter": 0.0,
         "continual_retrain_deadline": 2.5},
        X, y, sleep=clock.sleep, clock=clock)
    # poison retraining itself so every attempt dies retriably
    cb._retrain_once = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected retrain failure"))
    # two ticks of wildly regressed labels trip detection
    r = None
    for tick in range(4):
        r = cb.tick(X[:64], y[:64] + 100.0 * (tick >= 1))
        if r.retrain_failed:
            break
    assert r is not None and r.retrain_failed and r.degraded
    # base 1.0 under a 2.5s deadline affords ONE retry sleep
    # (schedule [1]; +2 would cross): 2 attempts, 1.0 virtual seconds
    # — not the 4 attempts / 7.0s the deadline-less policy would run
    assert clock.now == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# satellites riding this PR
# ---------------------------------------------------------------------------
@pytest.mark.slow  # 4.9 s: tier-1 window offender per
# test_durations.json; tests/test_engine.py::test_early_stopping keeps
# a fast in-window early-stopping representative
def test_early_stopping_custom_train_name(rng):
    """A train set named anything but "training" must not drive early
    stopping, and its eval rows carry the user's name (ADVICE round 5:
    callback.py:96)."""
    X, y = _data(rng)
    ds = lgb.Dataset(X, label=y)
    history = {}
    bst = lgb.train(
        dict(objective="binary", num_leaves=7, verbosity=-1,
             metric="binary_logloss", early_stopping_round=3),
        ds, num_boost_round=30,
        valid_sets=[ds, lgb.Dataset(X[:80], label=y[:80])],
        valid_names=["train", "v0"],
        callbacks=[lgb.record_evaluation(history)])
    assert "train" in history and "v0" in history
    assert "training" not in history
    # train loss improves monotonically -> stopping must come from v0's
    # patience, not be blocked forever by the improving train rows
    assert bst.best_iteration >= 1


def test_predict_disable_shape_check_pads_zero(rng):
    """Absent feature columns pad with 0.0, matching the reference's
    zero-initialized row buffer (ADVICE round 5: basic.py:595)."""
    X, y = _data(rng, f=8)
    bst = lgb.train(dict(objective="binary", num_leaves=15, verbosity=-1,
                         metric=""),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    narrow = X[:50, :5]
    padded = np.concatenate([narrow, np.zeros((50, 3))], axis=1)
    got = bst.predict(narrow, predict_disable_shape_check=True)
    want = bst.predict(padded)
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_unknown_param_warns_per_train_call(rng):
    """The unknown-parameter warning fires again in a LATER train() call
    (dedupe scoped per call, not per process — ADVICE round 5:
    config.py:395)."""
    X, y = _data(rng)
    msgs = []
    _log.register_callback(msgs.append)
    try:
        for _ in range(2):
            lgb.train(dict(objective="binary", num_leaves=7, verbosity=1,
                           metric="", num_leafs=31),
                      lgb.Dataset(X, label=y), num_boost_round=1)
    finally:
        _log.register_callback(None)
    warns = [m for m in msgs if "Unknown parameter: num_leafs" in m]
    assert len(warns) == 2


# ---------------------------------------------------------------------------
# non-finite guard rails through the REFIT path (ISSUE-6 satellite):
# the continual runtime's per-tick refit must be guarded exactly like
# full training iterations, with per-iteration fault targeting
# ---------------------------------------------------------------------------
def _refit_base(rng, rounds=5):
    X, y = _data(rng, binary=False)
    bst = lgb.train(dict(objective="regression", num_leaves=7,
                         verbosity=-1, metric=""),
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    bst._gbdt._flush_pending()   # host tree list must exist to snapshot
    X2 = rng.normal(size=X.shape)
    y2 = X2[:, 0] * 2.0 + rng.normal(size=len(X2))
    return bst, X2, y2


def test_refit_nonfinite_raise_names_iteration(rng):
    bst, X2, y2 = _refit_base(rng)
    with faultinject.injected(corrupt_gradients_at=2):
        with pytest.raises(LightGBMError, match="iteration 2"):
            bst.refit(X2, y2, nonfinite_policy="raise")
    # the aborted refit must not have half-committed: predictions of
    # the original booster are untouched
    assert np.isfinite(bst.predict(X2)).all()


def test_refit_nonfinite_skip_keeps_old_leaves(rng):
    """Corrupt refit iteration 1 only: that iteration's trees keep
    their OLD leaf values while every other iteration refits."""
    bst, X2, y2 = _refit_base(rng)
    old = [np.asarray(t.leaf_value).copy() for t in bst._gbdt.models]
    with faultinject.injected(corrupt_gradients_at=1):
        refitted = bst.refit(X2, y2, decay_rate=0.0,
                             nonfinite_policy="skip_iteration")
    assert refitted._refit_guard.skipped_iterations == [1]
    new = [np.asarray(t.leaf_value) for t in refitted._gbdt.models]
    np.testing.assert_array_equal(new[1], old[1])   # skipped: unchanged
    assert not np.allclose(new[0], old[0])          # refit applied
    assert not np.allclose(new[2], old[2])
    assert np.isfinite(refitted.predict(X2)).all()


def test_refit_nonfinite_clamp_drops_poisoned_rows(rng):
    bst, X2, y2 = _refit_base(rng)
    with faultinject.injected(corrupt_gradients_at=2):
        refitted = bst.refit(X2, y2, nonfinite_policy="clamp")
    assert refitted._refit_guard.clamped_iterations == [2]
    assert refitted._refit_guard.skipped_iterations == []
    assert np.isfinite(refitted.predict(X2)).all()
    # clamped rows drop out of iteration 2's leaf sums, so its trees
    # still moved (unlike skip_iteration)
    assert not np.allclose(np.asarray(refitted._gbdt.models[2].leaf_value),
                           np.asarray(bst._gbdt.models[2].leaf_value))


def test_refit_nan_labels_guarded_every_iteration(rng):
    """NaN labels (a poisoned upstream join, no injection) poison the
    gradients of EVERY refit iteration; skip_iteration must keep the
    whole model unchanged rather than commit garbage."""
    bst, X2, y2 = _refit_base(rng)
    y_bad = y2.copy()
    y_bad[::3] = np.nan
    before = bst.predict(X2)
    refitted = bst.refit(X2, y_bad, decay_rate=0.0,
                         nonfinite_policy="skip_iteration")
    assert len(refitted._refit_guard.skipped_iterations) == 5
    np.testing.assert_array_equal(refitted.predict(X2), before)


def test_refit_inplace_invalidates_serving_eagerly(rng):
    """In-place refit must bump the serving mutation counter AT COMMIT
    (like update/rollback) — a pack warmed before the refit serving
    pre-refit leaf values afterwards would be a stale-read bug.  The
    warm pack takes the leaf-refresh fast path: values change, zero new
    traces."""
    rng_big = np.random.RandomState(7)
    X = rng_big.normal(size=(4096, 6))
    y = X @ rng_big.normal(size=6) + rng_big.normal(size=4096)
    bst = lgb.train(dict(objective="regression", num_leaves=15,
                         verbosity=-1, metric=""),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    before = bst.predict(X)                   # warms the device pack
    bst.predict(X, pred_leaf=True)            # refit reuses this program
    eng = bst._gbdt.serving
    ver0 = bst._gbdt._model_version
    snap = eng.trace_snapshot()
    out = bst.refit(X, -y, decay_rate=0.0, inplace=True)
    assert out is bst
    assert bst._gbdt._model_version > ver0
    after = bst.predict(X)                    # same warm bucket
    assert not np.allclose(after, before), \
        "warm pack served pre-refit leaf values after in-place refit"
    assert eng.new_traces_since(snap) == {}, \
        "refit must ride the leaf-refresh fast path, not re-trace"
    # the refreshed pack serves exactly what a cold rebuild would
    clean = lgb.Booster(model_str=bst.model_to_string()).predict(X)
    np.testing.assert_allclose(after, clean, rtol=1e-6, atol=1e-6)
