"""find_best_split_fast must match find_best_split bit-for-bit on plain
configs (it is the compiled hot path for all-numerical trees)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import split as so


def _ctx(F, BF, rng):
    num_bin = rng.randint(3, BF + 1, size=F).astype(np.int32)
    missing = rng.randint(0, 3, size=F).astype(np.int32)
    default_bin = np.where(missing == so.MISSING_ZERO,
                           rng.randint(0, 3, size=F), 0).astype(np.int32)
    return so.SplitContext(
        num_bin=jnp.asarray(num_bin),
        missing_type=jnp.asarray(missing),
        default_bin=jnp.asarray(default_bin),
        is_categorical=jnp.zeros(F, jnp.int32),
        feature_index=jnp.arange(F, dtype=jnp.int32))


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_fast_matches_reference_search(seed):
    rng = np.random.RandomState(seed)
    F, BF = 7, 31
    ctx = _ctx(F, BF, rng)
    nb = np.asarray(ctx.num_bin)
    hist = np.zeros((F, BF, 2), np.float32)
    for f in range(F):
        hist[f, :nb[f], 0] = rng.normal(size=nb[f])
        hist[f, :nb[f], 1] = rng.uniform(0.01, 2.0, size=nb[f])
    sum_g = jnp.float32(hist[0, :, 0].sum())
    sum_h = jnp.float32(hist[0, :, 1].sum())
    cnt = jnp.int32(1000)
    mask = jnp.asarray(rng.rand(F) > 0.2)
    args = (jnp.asarray(hist), ctx, sum_g, sum_h, cnt,
            0.0 if seed % 2 else 0.5, 1e-3, 0.0, 0.0, 5, 1e-3, mask)
    slow = so.find_best_split(*args)
    fast = so.find_best_split_fast(*args)
    # exact on the discrete choice; float stats may differ by the f32
    # reassociation of the matmul-based prefix sums vs the serial scan
    for name in ("feature", "threshold", "default_left"):
        assert np.array_equal(np.asarray(getattr(slow, name)),
                              np.asarray(getattr(fast, name))), name
    for name in ("gain", "left_sum_g", "left_sum_h", "right_sum_g",
                 "right_sum_h", "left_output", "right_output"):
        np.testing.assert_allclose(
            np.asarray(getattr(slow, name)), np.asarray(getattr(fast, name)),
            rtol=2e-5, atol=1e-6, err_msg=name)
    for name in ("left_count", "right_count"):
        assert abs(int(getattr(slow, name)) - int(getattr(fast, name))) <= 1, \
            name


def test_fast_no_valid_split():
    rng = np.random.RandomState(9)
    F, BF = 3, 8
    ctx = _ctx(F, BF, rng)
    hist = np.zeros((F, BF, 2), np.float32)   # empty: nothing to split
    out = so.find_best_split_fast(
        jnp.asarray(hist), ctx, jnp.float32(0), jnp.float32(0),
        jnp.int32(0), 0.0, 1e-3, 0.0, 0.0, 5, 1e-3, None)
    assert np.asarray(out.gain) == -np.inf
