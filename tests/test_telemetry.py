"""Runtime telemetry layer (lightgbm_tpu/obs/) — ISSUE-8 surface.

The load-bearing invariants:

* ``telemetry=off`` is bit-identical end-to-end — same trained trees,
  same predictions — and so are ``counters`` and ``trace`` (the whole
  layer is host-side bookkeeping; the jaxlint tier-B ``telemetry.off``
  budget separately pins that the lowered train while-body is
  op-for-op unchanged);
* with ``telemetry=counters`` the session's runtime ``serving.*``
  compile events reproduce EXACTLY the per-(kind, bucket) trace
  counts the serving engine pins in tests/test_predict_engine.py;
* a warmed booster with ``telemetry=counters`` survives
  pickle/deepcopy (mirrors the PR-4 jitted-closure fix) and the
  session resets cleanly;
* exporters emit a loadable Chrome trace, JSONL, and Prometheus text;
* memory accounting attributes HBM to the named owners.
"""

import copy
import json
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs.telemetry import Histogram, Telemetry


@pytest.fixture(autouse=True)
def _fresh_session():
    """Every test starts and ends with a clean, disabled session (the
    session is process-wide; leaking trace mode into other test files
    would silently slow them)."""
    obs.get().reset(mode="off")
    yield
    obs.get().reset(mode="off")


def _data(n=4000, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


def _train(X, y, telemetry=None, rounds=5):
    p = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
         "min_data_in_leaf": 10, "metric": ""}
    if telemetry is not None:
        p["telemetry"] = telemetry
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)
    bst._gbdt._flush_pending()
    return bst


# ---------------------------------------------------------------------------
# mode semantics
# ---------------------------------------------------------------------------
def test_off_mode_records_nothing():
    X, y = _data()
    bst = _train(X, y)                      # default: telemetry=off
    bst.predict(X, raw_score=True)
    rep = obs.get().report()
    assert rep["mode"] == "off"
    assert rep["spans"] == {} and rep["compiles"] == {}
    assert rep["counters"] == {} and rep["events_recorded"] == 0


def test_modes_are_bit_identical():
    """off / counters / trace train the SAME model and serve the SAME
    predictions — telemetry never touches the device computation."""
    X, y = _data()
    models, preds = [], []
    for mode in ("off", "counters", "trace"):
        obs.get().reset(mode="off")
        bst = _train(X, y, telemetry=mode)
        # trees + importances; the parameters section legitimately
        # differs in its [telemetry: ...] line
        models.append(bst.model_to_string().split("\nparameters:")[0])
        preds.append(np.asarray(bst.predict(X, raw_score=True)))
    assert models[0] == models[1] == models[2]
    np.testing.assert_array_equal(preds[0], preds[1])
    np.testing.assert_array_equal(preds[0], preds[2])


def test_upgrade_only_mode_switch():
    s = obs.get()
    s.enable("trace")
    s.enable("counters")                    # must not downgrade
    assert s.mode == "trace"
    with pytest.raises(ValueError):
        s.enable("bogus")
    with pytest.raises(lgb.LightGBMError):
        _train(*_data(n=300), telemetry="loud")


def test_spans_counters_and_train_compile_detector():
    X, y = _data()
    bst = _train(X, y, telemetry="counters", rounds=5)
    rep = bst.telemetry_report()
    assert rep["mode"] == "counters"
    assert rep["spans"]["train.iteration"]["count"] == 5
    assert rep["spans"]["train.total"]["count"] == 1
    assert rep["spans"]["dataset.construct"]["count"] == 1
    # the fused step traced exactly once over 5 iterations — the
    # runtime analog of the train.donation / retrace pins
    assert rep["compiles"]["train.fused_step"] == 1
    # counters mode records no trace events
    assert rep["events_recorded"] == 0


# ---------------------------------------------------------------------------
# serving: runtime compile counters == the engine's pinned trace counts
# ---------------------------------------------------------------------------
def test_serving_compile_counters_match_engine_pins():
    """Replicates the call pattern of
    test_predict_engine.test_compile_count_one_trace_per_bucket and
    asserts the telemetry session saw EXACTLY the engine's
    per-(kind, bucket) compile counts."""
    X, y = _data(n=4500)
    bst = _train(X, y, telemetry="counters")
    eng = bst._gbdt.serving
    eng.trace_counts.clear()
    eng.call_counts.clear()
    obs.get().reset(mode="counters")

    bst.predict(X, raw_score=True)          # >= COLD_MIN_ROWS: warms
    for n in (700, 700, 600, 900):          # all pad to bucket 1024
        bst.predict(X[:n], raw_score=True)
        bst.predict(X[:n], pred_leaf=True)
        bst.predict(X[:n], pred_contrib=True)

    want = {f"serving.{k}@{b}": v
            for (k, b), v in eng.trace_counts.items()}
    got = {k: v for k, v in obs.get().report()["compiles"].items()
           if k.startswith("serving.")}
    assert got == want and want, (got, want)
    assert all(v == 1 for v in want.values()), want
    # per-(kind, bucket) latency histograms exist for the served calls
    spans = obs.get().report()["spans"]
    for (k, b), calls in eng.call_counts.items():
        assert spans[f"serve.{k}@{b}"]["count"] == calls


# ---------------------------------------------------------------------------
# pickle / deepcopy round trip (mirrors the PR-4 jitted-closure fix)
# ---------------------------------------------------------------------------
def test_pickle_deepcopy_round_trip_with_counters():
    X, y = _data(n=4500)
    bst = _train(X, y, telemetry="counters")
    before = np.asarray(bst.predict(X, raw_score=True))  # warms the pack
    assert bst.telemetry_report(include_memory=False)["mode"] == "counters"

    restored = pickle.loads(pickle.dumps(bst))
    cloned = copy.deepcopy(bst)
    for other in (restored, cloned):
        out = np.asarray(other.predict(X[:700], raw_score=True))
        np.testing.assert_allclose(out, before[:700], rtol=1e-6, atol=1e-6)
        rep = other.telemetry_report(include_memory=False)
        assert rep["mode"] == "counters"     # model params re-enabled it

    # counters reset cleanly: a fresh slate, and the restored booster
    # keeps counting into it
    obs.get().reset(mode="counters")
    assert obs.get().report()["compiles"] == {}
    restored.predict(X[:700], raw_score=True)
    rep = restored.telemetry_report(include_memory=False)
    # a restored booster serves through the loaded (threshold-index)
    # pack — its bucket latency histogram restarts from the clean slate
    assert rep["spans"]["serve.raw_loaded@1024"]["count"] == 1


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
def test_exporters_emit_valid_artifacts(tmp_path):
    X, y = _data(n=4500)
    bst = _train(X, y, telemetry="trace", rounds=3)
    bst.predict(X, raw_score=True)
    obs.memory_snapshot()
    paths = obs.export_session(str(tmp_path))

    doc = json.loads(open(paths["trace"]).read())
    evs = doc["traceEvents"]
    assert any(e.get("ph") == "X" and e["name"] == "train.iteration"
               for e in evs)
    assert any(e.get("ph") == "i" and
               e["name"].startswith("compile:") for e in evs)
    assert any(e.get("ph") == "C" and e["name"].startswith("mem.")
               for e in evs)
    for e in evs:
        if e.get("ph") == "X":
            assert isinstance(e["dur"], int) and e["dur"] >= 0
            assert "ts" in e

    lines = open(paths["jsonl"]).read().splitlines()
    header = json.loads(lines[0])
    assert header["type"] == "report" and header["mode"] == "trace"
    assert all(json.loads(ln)["type"] == "event" for ln in lines[1:])

    prom = open(paths["prometheus"]).read()
    assert 'lightgbm_tpu_span_count{name="train.iteration"} 3' in prom
    assert "lightgbm_tpu_compiles_total" in prom
    assert "lightgbm_tpu_gauge" in prom


def test_event_ring_keeps_newest():
    t = Telemetry(mode="trace", max_events=10)
    for i in range(50):
        with t.span("s", i=i):
            pass
    rep = t.report()
    assert rep["events_recorded"] == 10
    assert rep["events_dropped"] == 40
    # a true ring: the OLDEST events evict, so an incident at the end
    # of a long run is always in the exported window
    kept = [ev["args"]["i"] for ev in t.snapshot_events()]
    assert kept == list(range(40, 50))
    # aggregation never drops even when the ring is full
    assert rep["spans"]["s"]["count"] == 50


def test_histogram_quantiles():
    h = Histogram()
    for us in (100, 200, 400, 800, 100_000):
        h.observe(us * 1e-6)
    j = h.to_json()
    assert j["count"] == 5
    assert j["min_s"] == pytest.approx(1e-4)
    assert j["max_s"] == pytest.approx(0.1)
    assert j["p50_s"] <= j["p99_s"] <= j["max_s"]
    assert j["p50_s"] >= j["min_s"]


# ---------------------------------------------------------------------------
# memory accounting
# ---------------------------------------------------------------------------
def test_memory_owners_attributed():
    X, y = _data(n=4500)
    bst = _train(X, y, telemetry="counters")
    bst.predict(X, raw_score=True)          # builds the serving pack
    snap = obs.memory_snapshot()
    owners = snap["owners"]
    assert owners["serving.packs"]["device_bytes"] > 0
    assert owners["train.binned"]["device_bytes"] > 0
    assert owners["dataset.binned"]["host_bytes"] > 0 \
        or owners["dataset.binned"]["device_bytes"] > 0
    # the backend total (when enumerable) is at least what we attribute
    if snap["live_device_bytes"] is not None:
        attributed = sum(o["device_bytes"] for o in owners.values())
        assert snap["live_device_bytes"] >= owners[
            "serving.packs"]["device_bytes"]
        assert attributed > 0
    # owner gauges landed in the session
    gauges = obs.get().report()["gauges"]
    assert gauges["mem.serving.packs.device_bytes"] == \
        owners["serving.packs"]["device_bytes"]


def test_memory_ledger_drops_dead_owners():
    from lightgbm_tpu.obs import memory as obs_mem

    class Holder:
        pass

    h = Holder()
    h.arr = np.zeros(1024, np.float64)
    obs_mem.register("test.owner", h, lambda o: [o.arr])
    assert obs_mem.snapshot()["owners"]["test.owner"]["host_bytes"] == 8192
    del h
    assert "test.owner" not in obs_mem.snapshot()["owners"]
    # the weakref callback pruned the registry entry itself — no
    # snapshot needed, so an off-mode forever-process never leaks
    assert all(k[0] != "test.owner" for k in obs_mem.LEDGER._providers)


# ---------------------------------------------------------------------------
# continual runtime: lifecycle spans + swap compile attribution
# ---------------------------------------------------------------------------
def test_continual_tick_spans_and_zero_steady_state_compiles():
    from lightgbm_tpu.continual import ContinualBooster, DriftStream
    from lightgbm_tpu.continual.drift import _DRILL_PARAMS

    p = dict(_DRILL_PARAMS)
    p.update({"num_iterations": 5, "num_leaves": 7,
              "telemetry": "counters"})
    warm = DriftStream(num_features=5, rows=512, seed=61)
    X0, y0 = warm.batch(0)
    cb = ContinualBooster(p, X0, y0)
    stream = DriftStream(num_features=5, rows=128, seed=62)
    cb.tick(*stream.batch(0))               # settles the per-kind compiles
    obs.get().reset(mode="counters")
    for t in range(1, 4):
        cb.tick(*stream.batch(t))
    rep = obs.get().report()
    assert rep["spans"]["continual.tick"]["count"] == 3
    assert rep["spans"]["continual.refit"]["count"] == 3
    # steady-state ticks add ZERO serving compiles — the runtime
    # counter now shows what the jaxlint continual.tick budget pins
    assert not any(k.startswith("serving.") for k in rep["compiles"]), \
        rep["compiles"]


# ---------------------------------------------------------------------------
# Prometheus exposition-format conformance (ISSUE-9 satellite): the
# exported text must survive a STRICT parser of the text format —
# metric/label name grammar, escaping, TYPE declaration rules, summary
# family suffix ownership, duplicate-sample detection
# ---------------------------------------------------------------------------
import re as _re

_METRIC_NAME = _re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = _re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_label_block(s, errors, lineno):
    """Parse `name="value",...` with the format's three escapes; returns
    (labels dict) and flags bad names/escapes/structure."""
    labels = {}
    i = 0
    while i < len(s):
        m = _re.match(r"([^=,{}\s]+)=", s[i:])
        if not m:
            errors.append(f"line {lineno}: bad label syntax at {s[i:]!r}")
            return labels
        lname = m.group(1)
        if not _LABEL_NAME.match(lname):
            errors.append(f"line {lineno}: bad label name {lname!r}")
        i += m.end()
        if i >= len(s) or s[i] != '"':
            errors.append(f"line {lineno}: label value not quoted")
            return labels
        i += 1
        val = []
        while i < len(s):
            c = s[i]
            if c == "\\":
                if i + 1 >= len(s) or s[i + 1] not in ('\\', '"', 'n'):
                    errors.append(f"line {lineno}: bad escape in label")
                i += 2
                continue
            if c == '"':
                break
            if c == "\n":
                errors.append(f"line {lineno}: raw newline in label")
            val.append(c)
            i += 1
        labels[lname] = "".join(val)
        i += 1                                     # closing quote
        if i < len(s):
            if s[i] != ",":
                errors.append(f"line {lineno}: expected ',' in labels")
                return labels
            i += 1
    return labels


def parse_exposition(text):
    """Strict text-exposition parser; returns (samples, types, errors).
    Enforces: name grammar, one TYPE per family declared before its
    samples, samples grouped per family, summary/histogram suffix
    ownership (X_sum/X_count/X_bucket belong to family X and must not
    be declared as their own family), float-parseable values, and no
    duplicate (name, labelset) sample."""
    samples, types, errors = [], {}, []
    seen_families = set()
    seen_samples = set()
    for lineno, line in enumerate(text.split("\n"), 1):
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) < 4:
                    errors.append(f"line {lineno}: malformed TYPE")
                    continue
                fam, typ = parts[2], parts[3].strip()
                if not _METRIC_NAME.match(fam):
                    errors.append(f"line {lineno}: bad family {fam!r}")
                if typ not in _TYPES:
                    errors.append(f"line {lineno}: bad type {typ!r}")
                if fam in types:
                    errors.append(f"line {lineno}: duplicate TYPE {fam}")
                if fam in seen_families:
                    errors.append(
                        f"line {lineno}: TYPE {fam} after its samples")
                types[fam] = typ
            continue
        m = _re.match(r"^([^\s{]+)(\{(.*)\})?\s+(\S+)(\s+\S+)?$", line)
        if not m:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name, _, lbl, value, _ts = m.groups()
        if not _METRIC_NAME.match(name):
            errors.append(f"line {lineno}: bad metric name {name!r}")
        labels = _parse_label_block(lbl, errors, lineno) if lbl else {}
        try:
            float(value)
        except ValueError:
            errors.append(f"line {lineno}: bad value {value!r}")
        # resolve the family: summary/histogram suffixes fold in
        fam = name
        for suffix in ("_sum", "_count", "_bucket"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) in ("summary", "histogram"):
                fam = base
                break
        if fam != name and name in types:
            errors.append(f"{name} declared as its own family AND owned "
                          f"by the {fam} {types[fam]}")
        if fam in types and types[fam] == "summary" and fam == name \
                and "quantile" not in labels:
            errors.append(f"line {lineno}: summary sample {name} "
                          "without quantile label")
        seen_families.add(fam)
        key = (name, tuple(sorted(labels.items())))
        if key in seen_samples:
            errors.append(f"line {lineno}: duplicate sample {key}")
        seen_samples.add(key)
        samples.append((name, labels, value))
    return samples, types, errors


def test_prometheus_text_round_trips_a_strict_parser():
    sess = obs.get()
    sess.reset(mode="trace")
    # populate every family, including awkward label values the
    # escaping must survive
    with obs.span("train.iteration"):
        pass
    with obs.span('serve.raw@1024 "quoted"\\back\nline'):
        pass
    obs.counter("health.skew.alerts", 3)
    obs.gauge("memory.dataset.binned", 12345.5)
    sess.compile_event("serving.raw@1024")
    text = obs.prometheus_text(sess)
    samples, types, errors = parse_exposition(text)
    assert not errors, "\n".join(errors)
    names = {s[0] for s in samples}
    assert "lightgbm_tpu_span_count" in names
    assert "lightgbm_tpu_span_seconds_sum" in names
    assert "lightgbm_tpu_span_seconds_count" in names
    assert "lightgbm_tpu_counter_total" in names
    assert "lightgbm_tpu_compiles_total" in names
    assert "lightgbm_tpu_gauge" in names
    # the summary family owns its _sum/_count (no separate TYPE)
    assert types["lightgbm_tpu_span_seconds"] == "summary"
    assert "lightgbm_tpu_span_seconds_sum" not in types
    # every non-comment line of the export parsed as exactly one sample
    n_lines = sum(1 for ln in text.strip().split("\n")
                  if ln and not ln.startswith("#"))
    assert len(samples) == n_lines


def test_prometheus_parser_rejects_the_old_nonconforming_shape():
    """The parser itself must have teeth: the pre-fix export shape
    (summary's _sum declared as its own counter family; raw newline in
    a label) must fail it."""
    bad = ('# TYPE x_seconds_sum counter\n'
           '# TYPE x_seconds summary\n'
           'x_seconds_sum{name="a"} 1.0\n')
    _, _, errors = parse_exposition(bad)
    assert any("own family" in e for e in errors)
    bad2 = 'm{name="a\nb"} 1\n'
    _, _, errors2 = parse_exposition(bad2)
    assert errors2


# ---------------------------------------------------------------------------
# model-load re-arm is OPT-IN (ISSUE-10 satellite)
# ---------------------------------------------------------------------------
def test_model_load_does_not_rearm_sessions(monkeypatch):
    """Loading a model whose saved params carry telemetry/health=counters
    must NOT silently arm the process-wide sessions; the skip warns once
    and `obs_rearm_on_load=True` (or the env knob) opts back in."""
    from lightgbm_tpu.obs import health as obs_health
    from lightgbm_tpu.obs import telemetry as obs_tel

    monkeypatch.delenv("LIGHTGBM_TPU_OBS_REARM_ON_LOAD", raising=False)
    X, y = _data(n=600)
    bst = _train(X, y)
    s = bst.model_to_string() \
        .replace("[telemetry: off]", "[telemetry: counters]") \
        .replace("[health: off]", "[health: counters]")
    assert "[telemetry: counters]" in s and "[health: counters]" in s

    # force a clean slate (sessions are process-wide; other tests arm them)
    obs.get().reset(mode="off")
    obs_health.get().set_mode("off")
    for k in obs_tel._REARM_WARNED:
        obs_tel._REARM_WARNED[k] = False

    try:
        b2 = lgb.Booster(model_str=s)
        assert obs.get().mode == "off"
        assert obs_health.get().mode == "off"
        # the one-time warning fired for both kinds
        assert obs_tel._REARM_WARNED["telemetry"]
        assert obs_tel._REARM_WARNED["health"]
        assert np.asarray(b2.predict(X[:50])).shape == (50,)

        # per-load opt-in re-arms — and must NOT leak into the re-saved
        # model: a later plain load of that file stays un-armed
        b3 = lgb.Booster(model_str=s,
                         params={"obs_rearm_on_load": True})
        assert obs.get().mode == "counters"
        assert obs_health.get().mode == "counters"
        resaved = b3.model_to_string()
        obs.get().reset(mode="off")
        obs_health.get().set_mode("off")
        lgb.Booster(model_str=resaved)
        assert obs.get().mode == "off"
        assert obs_health.get().mode == "off"

        # env opt-in re-arms too; falsy spellings do not
        from lightgbm_tpu.config import Config
        monkeypatch.setenv("LIGHTGBM_TPU_OBS_REARM_ON_LOAD", "FALSE")
        assert not obs_tel.rearm_on_load_allowed(Config({}))
        monkeypatch.setenv("LIGHTGBM_TPU_OBS_REARM_ON_LOAD", "1")
        lgb.Booster(model_str=s)
        assert obs.get().mode == "counters"
        assert obs_health.get().mode == "counters"

        # an ALREADY-armed session is untouched either way
        # (upgrade-only): the pickle round-trip of a counters-trained
        # booster keeps counting
        monkeypatch.delenv("LIGHTGBM_TPU_OBS_REARM_ON_LOAD",
                           raising=False)
        obs.get().reset(mode="counters")
        lgb.Booster(model_str=s)
        assert obs.get().mode == "counters"
    finally:
        # leave the sessions off for whatever runs next
        obs.get().reset(mode="off")
        obs_health.get().set_mode("off")
