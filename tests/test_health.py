"""Model & data health observability (lightgbm_tpu/obs/digest.py +
health.py): device-digest bit-parity against the NumPy oracle across
the awkward dataset shapes (NaN, zero-as-missing, categorical,
max_bin_by_feature), the reference profile, the training flight
recorder, serving-side skew digests, the continual runtime's drift
attribution (the planted covariate-shift feature must rank #1), and
the telemetry span stack unwinding through exceptions."""

import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import digest, health
from lightgbm_tpu.obs import telemetry as obs_tel

BASE = {"objective": "regression", "verbosity": -1, "num_leaves": 7,
        "min_data_in_leaf": 5, "metric": ""}


@pytest.fixture(autouse=True)
def _reset_sessions():
    """Health/telemetry sessions are process-global; tests must not
    leak modes into each other (or into other files)."""
    hs, ts = health.get(), obs_tel.get()
    h_prev, t_prev = hs.mode, ts.mode
    yield
    hs.set_mode(h_prev)
    ts.reset(mode=t_prev)


def _datasets(rng):
    """The four awkward binning shapes the digest must count exactly."""
    n = 600
    base = rng.normal(size=(n, 5))
    nan = base.copy()
    nan[rng.rand(n) < 0.15, 1] = np.nan                    # NaN missing
    zeros = base.copy()
    zeros[rng.rand(n) < 0.5, 2] = 0.0                      # exact zeros
    cat = base.copy()
    cat[:, 4] = rng.randint(0, 6, size=n)                  # categorical
    return [
        ("nan", nan, {}),
        ("zero_as_missing", zeros, {"zero_as_missing": True}),
        ("categorical", cat, {"categorical_feature": [4]}),
        ("max_bin_by_feature", base,
         {"max_bin_by_feature": "255,15,7,255,31"}),
    ]


# ---------------------------------------------------------------------------
# digest bit-parity: device reduction vs the NumPy oracle
# ---------------------------------------------------------------------------
def test_bin_counts_device_matches_oracle_across_datasets(rng):
    y = rng.normal(size=600)
    for name, X, extra in _datasets(rng):
        ds = lgb.Dataset(X, label=y, params={**BASE, **extra})
        ds.construct({**BASE, **extra})
        binned = ds._inner.host_binned()
        nb = ds._inner.max_group_bins
        host = digest.bin_counts_host(binned, nb)
        dev = np.asarray(digest.bin_counts_device(jnp.asarray(binned),
                                                  nb))
        assert np.array_equal(host, dev), name
        # transposed (learner-layout) twin over the same data
        dev_t = np.asarray(digest.bin_counts_device_t(
            jnp.asarray(np.ascontiguousarray(binned.T)), nb))
        assert np.array_equal(host, dev_t), name
        # per-feature unbundling is a partition of the rows
        feats = digest.per_feature_counts(
            ds._inner.groups, ds._inner.bin_mappers,
            ds._inner.num_data, host)
        for f, counts in feats.items():
            assert counts.sum() == ds._inner.num_data, (name, f)
            assert (counts >= 0).all(), (name, f)


def test_snapshot_device_pad_correction(rng):
    b = rng.randint(0, 9, size=(6, 40)).astype(np.uint8)   # (G, n) layout
    padded = np.concatenate([b, np.zeros((6, 24), np.uint8)], axis=1)
    snap = digest.snapshot_device(jnp.asarray(padded), 9,
                                  transposed=True, pad_cols=24)
    host = digest.bin_counts_host(b.T, 9)
    assert np.array_equal(snap["group_counts"], host)


def test_margin_hist_device_matches_oracle(rng):
    raw1 = (rng.normal(size=500) * 10 ** rng.uniform(-8, 8, size=500)) \
        .astype(np.float32)
    raw1[:7] = 0.0
    h = digest.margin_hist_host(raw1)
    d = np.asarray(digest._margin_hist_dev(jnp.asarray(raw1)))
    assert np.array_equal(h, d)
    assert h.sum() == 500 and h[0] >= 7
    # multiclass margins (top1 - top2)
    rawk = rng.normal(size=(200, 4)).astype(np.float32)
    hk = digest.margin_hist_host(rawk)
    dk = np.asarray(digest._margin_hist_dev(jnp.asarray(rawk)))
    assert np.array_equal(hk, dk)
    assert hk.sum() == 200


# ---------------------------------------------------------------------------
# the reference profile
# ---------------------------------------------------------------------------
def test_reference_profile_rates_and_cardinality(rng):
    n = 800
    X = rng.normal(size=(n, 3))
    X[rng.rand(n) < 0.25, 0] = np.nan
    X[rng.rand(n) < 0.4, 1] = 0.0
    X[:, 2] = rng.randint(0, 5, size=n)
    y = rng.normal(size=n)
    health.get().set_mode("counters")
    ds = lgb.Dataset(X, label=y,
                     params={**BASE, "health": "counters",
                             "categorical_feature": [2]})
    ds.construct({**BASE, "health": "counters",
                  "categorical_feature": [2]})
    prof = ds._inner.reference_profile()
    assert prof["num_data"] == n
    by_idx = {fe["index"]: fe for fe in prof["features"]}
    nan_rate = float(np.isnan(X[:, 0]).mean())
    assert abs(by_idx[0]["missing_rate"] - nan_rate) < 1e-6
    zero_rate = float((X[:, 1] == 0.0).mean())
    assert abs(by_idx[1]["zero_rate"] - zero_rate) < 0.02
    assert by_idx[2]["cardinality"] == 5
    # counts are a partition of the rows
    for fe in prof["features"]:
        assert sum(fe["counts"]) == n


def test_reference_profile_device_path_matches_host(rng):
    """construct_device=on + free_host_binned leaves only the (G, N_pad)
    ingest buffer: the profile then comes from the DEVICE digest (one
    fused reduction + one sync, pad-corrected) and must equal the host
    oracle's profile bit-for-bit."""
    X = rng.normal(size=(700, 6))
    X[rng.rand(700) < 0.2, 3] = 0.0
    y = X[:, 0] + 0.1 * rng.normal(size=700)
    health.get().set_mode("counters")
    p_off = {**BASE, "health": "counters", "construct_device": "off"}
    p_on = {**BASE, "health": "counters", "construct_device": "on",
            "free_host_binned": True}
    ds_off = lgb.Dataset(X, label=y, params=p_off)
    ds_off.construct(p_off)
    ds_on = lgb.Dataset(X, label=y, params=p_on)
    ds_on.construct(p_on)
    prof_off = ds_off._inner.reference_profile()
    prof_on = ds_on._inner.reference_profile()
    if ds_on._inner.device_ingest is None:
        pytest.skip("device ingest unavailable on this backend")
    assert prof_on == prof_off


def test_profile_survives_model_string_and_pickle(rng):
    X = rng.normal(size=(400, 4))
    y = X[:, 0] + rng.normal(size=400) * 0.1
    bst = lgb.train({**BASE, "health": "counters"},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst._gbdt.health_profile is not None
    s = bst.model_to_string()
    assert "health_profile:" in s
    b2 = lgb.Booster(model_str=s)
    assert b2._gbdt.health_profile == bst._gbdt.health_profile
    b3 = pickle.loads(pickle.dumps(bst))
    assert b3._gbdt.health_profile == bst._gbdt.health_profile
    # the loaded model still predicts (profile line must not corrupt
    # the tree parser)
    p = b2.predict(X[:50], raw_score=True)
    assert np.isfinite(np.asarray(p)).all()


def test_health_off_is_a_noop(rng):
    X = rng.normal(size=(300, 4))
    y = X[:, 0]
    bst = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=2)
    assert bst._gbdt.flight is None
    assert bst._gbdt.health_profile is None
    rep = bst.health_report()
    assert rep["mode"] == "off"
    assert rep["flight_recorder"] is None
    assert rep["serving_skew"] is None


# ---------------------------------------------------------------------------
# training flight recorder
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("fused", [True, False])
def test_flight_recorder_records_every_tree(rng, fused):
    X = rng.normal(size=(500, 5))
    y = 3.0 * X[:, 2] + rng.normal(size=500) * 0.1
    bst = lgb.train({**BASE, "health": "counters",
                     "tpu_fused_iteration": fused},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    rep = bst.health_report()["flight_recorder"]
    assert rep["trees_recorded"] == 6
    # the informative feature dominates the cumulative gain totals
    assert rep["top_features"][0]["feature"] == 2
    last = rep["last_tree"]
    assert last["leaves"] >= 2 and "top_splits" in last
    assert last["top_splits"][0]["gain"] > 0
    assert last["leaf_l2"] > 0 and last["leaf_cnt_max"] >= 5
    assert last["effective_rows"] == 500
    assert len(rep["gain_trajectory"]) == 6


def test_flight_recorder_effective_rows_sampling(rng):
    X = rng.normal(size=(1000, 4))
    y = X[:, 0] + rng.normal(size=1000) * 0.1
    goss = lgb.train({**BASE, "health": "counters", "boosting": "goss",
                      "top_rate": 0.2, "other_rate": 0.1},
                     lgb.Dataset(X, label=y), num_boost_round=2)
    assert goss.health_report()["flight_recorder"][
        "effective_rows_last"] == 300
    bag = lgb.train({**BASE, "health": "counters",
                     "bagging_fraction": 0.5, "bagging_freq": 1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    assert bag.health_report()["flight_recorder"][
        "effective_rows_last"] == 500


def test_flight_recorder_trace_marks_ride_the_telemetry_ring(rng):
    obs_tel.get().reset(mode="off")
    X = rng.normal(size=(300, 4))
    y = X[:, 0]
    lgb.train({**BASE, "health": "trace"}, lgb.Dataset(X, label=y),
              num_boost_round=3).health_report()
    # health=trace upgraded the telemetry session; tree marks recorded
    assert obs_tel.get().mode == "trace"
    names = {e.get("name") for e in obs_tel.get().snapshot_events()}
    assert "health.tree" in names


# ---------------------------------------------------------------------------
# serving-side skew digests
# ---------------------------------------------------------------------------
def test_serving_skew_ranks_shifted_feature(rng):
    n = 5000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 + np.sin(X[:, 1])
    bst = lgb.train({**BASE, "health": "counters"},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    bst.predict(X, raw_score=True)          # warm + self-digest
    mon = bst._gbdt.serving._skew
    assert mon not in (None, False)
    rep = mon.report()
    assert rep["rows_seen"] == n
    # beyond OBSERVE_CAP the digest stride-samples (the hot-path cost
    # cap); the sampled count is what the distributions are over
    assert 1024 <= rep["rows_total"] <= n
    assert rep["top"][0]["psi"] < 0.05      # same distribution: no skew
    assert sum(rep["margin_hist"]) == rep["rows_total"]
    Xs = X.copy()
    Xs[:, 3] += 3.0
    bst.predict(Xs, raw_score=True)
    rep2 = mon.report()
    assert rep2["top"][0]["feature"] == 3
    assert rep2["top"][0]["psi"] > 0.5
    assert rep2["alerts"] >= 1              # threshold crossing fired
    assert obs_tel.get().counters if obs_tel.enabled() else True


def test_serving_skew_off_means_no_monitor(rng):
    X = rng.normal(size=(5000, 4))
    y = X[:, 0]
    bst = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=2)
    bst.predict(X, raw_score=True)
    assert bst._gbdt.serving._skew is None


# ---------------------------------------------------------------------------
# drift attribution: the acceptance drill
# ---------------------------------------------------------------------------
def test_attribution_drill_ranks_planted_feature_first():
    from lightgbm_tpu.continual import run_drift_drill
    rep = run_drift_drill("attribution", rows=192, drift_at=4,
                          post_ticks=6, seed=11)
    assert rep["detect_tick"] is not None
    assert rep["detected_within_window"]
    assert rep["planted_rank"] == 1, rep["skew_top"]
    # clear separation, not a photo finish
    assert rep["skew_top"][0]["psi"] > 3 * rep["skew_top"][1]["psi"]


def test_tick_reports_carry_skew_attribution(rng):
    from lightgbm_tpu.continual.drift import (DriftSpec, DriftStream,
                                              _DRILL_PARAMS)
    from lightgbm_tpu.continual.runtime import ContinualBooster
    planted = int(np.argmax(np.abs(
        np.random.RandomState(5).normal(size=5))))   # the stream's coef
    spec = DriftSpec(covariate_shift_at=3, covariate_shift_feature=planted,
                     covariate_shift=3.0)
    stream = DriftStream(num_features=5, rows=192, seed=5, spec=spec)
    X0, y0 = DriftStream(num_features=5, rows=768, seed=6).batch(0)
    p = dict(_DRILL_PARAMS)
    p["health"] = "counters"
    cb = ContinualBooster(p, X0, y0)
    top = None
    for t in range(10):
        r = cb.tick(*stream.batch(t))
        if r.drift_detected:
            top = r.skew_top
            break
    assert top, "regression tick never carried an attribution"
    assert top[0]["feature"] == planted


# ---------------------------------------------------------------------------
# telemetry span-stack hygiene (satellite)
# ---------------------------------------------------------------------------
def test_span_stack_unwinds_when_wrapped_op_raises():
    sess = obs_tel.get()
    sess.reset(mode="counters")
    with pytest.raises(ValueError):
        with obs_tel.span("outer"):
            with obs_tel.span("inner"):
                raise ValueError("boom")
    assert sess.current_span() is None
    # nested partial failure: outer survives an inner raise
    with obs_tel.span("outer2"):
        with pytest.raises(RuntimeError):
            with obs_tel.span("inner2"):
                raise RuntimeError("boom")
        assert sess.current_span() == "outer2"
    assert sess.current_span() is None
    # both spans still recorded their histograms despite the raise
    rep = sess.report()
    for name in ("outer", "inner", "outer2", "inner2"):
        assert rep["spans"][name]["count"] == 1
