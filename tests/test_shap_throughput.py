"""TreeSHAP throughput gates on the DEVICE serving path (PR-3: the
round-5 150s host-path relaxation is deleted; the device kernel
restores the verdict's <5s budget on the TPU/large lane, with a
proportionally scaled tier-1 bound pinning the CPU backend).

The device kernel (ops/shap.py) re-expresses the unwound-path
recursion as dense per-(element, row) quadrature ops; the host
recursion (models/shap.py) stays the exact oracle, asserted here on a
subsample."""

import time

import numpy as np
import pytest

import lightgbm_tpu as lgb

# Measured on the 2-core CPU CI host (see PERF.md round 7): the device
# kernel runs the tier-1 shape (20k x 30 trees) in ~1.0 s warm.  The
# bound is ~5x that measurement — tight enough to catch a return to the
# host path's ~30x-slower regime, loose enough for CI noise.
TIER1_ROWS, TIER1_TREES, TIER1_BOUND_S = 20_000, 30, 5.0
# full verdict shape; <5 s applies on an accelerator backend (the
# budget the round-4 verdict set for the benchmark host).  The 2-core
# CPU lane pins its own measured envelope instead (~33 s, bound ~3x;
# the host recursion projects to ~104 s on the same shape).
FULL_ROWS, FULL_TREES = 100_000, 100
FULL_BOUND_CPU_S = 90.0


def _train(rng, n_train, trees, f=10):
    X = rng.normal(size=(n_train, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    bst._gbdt._flush_pending()
    return bst


def test_pred_contrib_device_tier1_bound(rng):
    """Scaled serving-shape gate for the tier-1 CPU lane: the DEVICE
    path must engage and beat a bound ~30x under the old host-path
    cost for the same shape."""
    bst = _train(rng, 5_000, TIER1_TREES)
    g = bst._gbdt
    Xp = rng.normal(size=(TIER1_ROWS, 10))
    # warm: pack build + per-bucket trace are one-time serving costs
    bst.predict(Xp[:4096], pred_contrib=True)
    assert g.serving._warm("contrib"), "device TreeSHAP must engage"
    t0 = time.time()
    contrib = bst.predict(Xp, pred_contrib=True)
    wall = time.time() - t0
    assert contrib.shape == (TIER1_ROWS, 11)
    assert wall < TIER1_BOUND_S, \
        f"device pred_contrib took {wall:.1f}s for " \
        f"{TIER1_ROWS}x{TIER1_TREES} (bound {TIER1_BOUND_S}s)"
    # additivity invariant on the full batch
    raw = bst.predict(Xp, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-6, atol=1e-6)


@pytest.mark.slow
def test_pred_contrib_throughput_and_parity(rng):
    """Verdict shape: 100k rows x 100 trees pred_contrib through the
    device engine — <5s on an accelerator backend, measured CPU
    envelope otherwise — plus exact parity vs the per-row recursion
    oracle on a subsample and the additivity invariant."""
    import jax
    bst = _train(rng, 20_000, FULL_TREES)
    Xp = rng.normal(size=(FULL_ROWS, 10))
    bst.predict(Xp[:4096], pred_contrib=True)       # warm
    t0 = time.time()
    contrib = bst.predict(Xp, pred_contrib=True)
    wall = time.time() - t0
    assert contrib.shape == (FULL_ROWS, 11)
    raw = bst.predict(Xp, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-6, atol=1e-6)
    bound = 5.0 if jax.default_backend() != "cpu" else FULL_BOUND_CPU_S
    assert wall < bound, f"pred_contrib took {wall:.1f}s (bound {bound}s)"

    # exact parity vs the per-(row,tree) recursion oracle on 50 rows
    from lightgbm_tpu.models import shap as shap_mod
    g = bst._gbdt
    sub = Xp[:50].astype(np.float64)
    oracle = np.zeros((50, 11))
    for tree in g.models:
        if tree.num_leaves <= 1:
            oracle[:, -1] += tree.leaf_value[0]
            continue
        oracle[:, -1] += shap_mod._expected_value(tree)
        maxd = tree.num_leaves + 2
        parent = [shap_mod._PathElement() for _ in range(maxd + 2)]
        for r in range(50):
            phi = np.zeros(11)
            shap_mod._tree_shap(tree, sub[r], phi, 0, 0, parent,
                                1.0, 1.0, -1)
            oracle[r, :-1] += phi[:-1]
    np.testing.assert_allclose(contrib[:50], oracle, rtol=1e-9, atol=1e-9)


def test_stacked_variant_parity(rng, monkeypatch):
    """The env-gated stacked unwound-sum variant of the HOST oracle is
    bit-identical to its per-position loop."""
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    from lightgbm_tpu.models.shap import predict_contrib as host_contrib
    g = bst._gbdt
    g._flush_pending()
    Xp = np.asarray(rng.normal(size=(500, 8)), np.float64)
    base = host_contrib(g, Xp, 0, -1)
    monkeypatch.setenv("LIGHTGBM_TPU_SHAP_STACKED", "1")
    np.testing.assert_array_equal(host_contrib(g, Xp, 0, -1), base)
