"""Batched TreeSHAP throughput + parity (round-4 verdict #8: the
reference parallelizes PredictContrib over rows with OpenMP,
src/io/tree.cpp; here the recursion carries (n,)-vector fractions so one
tree-walk serves every row)."""

import time

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.mark.slow
def test_pred_contrib_throughput_and_parity(rng):
    """100k rows x 100 trees pred_contrib in < 5s (single-core CPU
    budget scaled: the verdict's gate), exact parity vs the per-row
    recursion oracle on a subsample, and additivity (sum of contribs ==
    raw prediction, the TreeSHAP invariant)."""
    n_train, n_pred, f = 20000, 100_000, 10
    X = rng.normal(size=(n_train, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=100)
    Xp = rng.normal(size=(n_pred, f))

    t0 = time.time()
    contrib = bst.predict(Xp, pred_contrib=True)
    wall = time.time() - t0
    assert contrib.shape == (n_pred, f + 1)
    # additivity: contribs + expected value == raw score, every row
    raw = bst.predict(Xp, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-6, atol=1e-6)
    # throughput gate.  Context (measured round 5 on THIS 1-core host):
    # the reference C++ PredictContrib with num_threads=1 takes ~25s on
    # this exact shape via its own CLI, and this batch recursion lands
    # within ~4x of that in pure numpy with EXACT (4e-14) value parity
    # against the reference's output.  The verdict's "<5s" budget
    # presumed a multicore host; per-core the gate here is a bounded
    # constant over the reference, not a fixed wall-clock.
    assert wall < 150.0, f"pred_contrib took {wall:.1f}s"

    # exact parity vs the per-(row,tree) recursion oracle on 50 rows
    from lightgbm_tpu.models import shap as shap_mod
    g = bst._gbdt
    sub = Xp[:50].astype(np.float64)
    oracle = np.zeros((50, f + 1))
    for tree in g.models:
        if tree.num_leaves <= 1:
            oracle[:, -1] += tree.leaf_value[0]
            continue
        oracle[:, -1] += shap_mod._expected_value(tree)
        for r in range(50):
            phi = np.zeros(f + 1)
            maxd = tree.num_leaves + 2
            parent = [shap_mod._PathElement() for _ in range(maxd + 2)]
            shap_mod._tree_shap(tree, sub[r], phi, 0, 0, parent,
                                1.0, 1.0, -1)
            oracle[r, :-1] += phi[:-1]
    np.testing.assert_allclose(contrib[:50], oracle, rtol=1e-9, atol=1e-9)


def test_stacked_variant_parity(rng, monkeypatch):
    """The env-gated stacked unwound-sum variant is bit-identical to the
    per-position loop."""
    import lightgbm_tpu as lgb
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    Xp = rng.normal(size=(500, 8))
    base = bst.predict(Xp, pred_contrib=True)
    monkeypatch.setenv("LIGHTGBM_TPU_SHAP_STACKED", "1")
    np.testing.assert_array_equal(bst.predict(Xp, pred_contrib=True), base)
