"""Quantized-gradient training tests (reference model:
tests/python_package_test/test_engine.py test_quantized_training)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=1500, f=12, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = 2 * X[:, 0] + X[:, 1] - X[:, 2]
    y = (logit + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


@pytest.mark.parametrize(
    "renew",
    [False,
     pytest.param(True, marks=pytest.mark.slow)])  # 14 s: tier-1
# window trim (PR 12, per test_durations.json); renew=False keeps the
# fast in-window close-to-fp representative and
# test_quant_renew_device_matches_host_oracle covers the renew path
def test_quantized_binary_close_to_fp(renew):
    X, y = _make_binary()
    base = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 5,
            "verbosity": -1}
    bst_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=30)
    bst_q = lgb.train({**base, "use_quantized_grad": True,
                       "num_grad_quant_bins": 4,
                       "quant_train_renew_leaf": renew},
                      lgb.Dataset(X, label=y), num_boost_round=30)
    acc_fp = np.mean((bst_fp.predict(X) > 0.5) == y)
    acc_q = np.mean((bst_q.predict(X) > 0.5) == y)
    assert acc_q > acc_fp - 0.03, (acc_q, acc_fp)


def test_quantized_regression_learns():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1000, 8))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.1 * rng.normal(size=1000)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "min_data_in_leaf": 5, "verbosity": -1,
                     "use_quantized_grad": True, "num_grad_quant_bins": 8,
                     "quant_train_renew_leaf": True},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    mse = np.mean((y - bst.predict(X)) ** 2)
    assert mse < 0.3 * np.var(y)


def test_quantized_deterministic_rounding():
    """stochastic_rounding=false must be reproducible run-to-run."""
    X, y = _make_binary(600, 6)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "min_data_in_leaf": 5}
    p1 = lgb.train(params, lgb.Dataset(X, label=y), 10).predict(X)
    p2 = lgb.train(params, lgb.Dataset(X, label=y), 10).predict(X)
    np.testing.assert_allclose(p1, p2)


def test_int_hist_bf16_matches_f32_oracle():
    """Integer gradient carriers accumulate EXACTLY in the bfloat16
    one-hot matmuls (the int16-histogram analog): bf16 and f32 paths
    must agree bit-for-bit (VERDICT r2 'int-hist sums equal the f32
    oracle')."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import leaf_hist_slice
    rng = np.random.RandomState(0)
    G, N, C = 5, 4096, 1024
    bins = jnp.asarray(rng.randint(0, 64, (G, N)).astype(np.uint8))
    ig = rng.randint(-8, 9, N).astype(np.float32)     # int carriers
    ih = rng.randint(0, 5, N).astype(np.float32)
    ghi = jnp.asarray(np.stack([ig, ih, np.zeros(N, np.float32)]))
    h16 = leaf_hist_slice(bins, ghi, jnp.int32(0), jnp.int32(N),
                          num_bins=64, row_chunk=C, dtype=jnp.bfloat16)
    h32 = leaf_hist_slice(bins, ghi, jnp.int32(0), jnp.int32(N),
                          num_bins=64, row_chunk=C, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(h16), np.asarray(h32))
    # and both equal the numpy oracle
    oracle = np.zeros((G, 64, 2), np.float32)
    bn = np.asarray(bins)
    for g in range(G):
        for b in range(64):
            m = bn[g] == b
            oracle[g, b, 0] = ig[m].sum()
            oracle[g, b, 1] = ih[m].sum()
    np.testing.assert_allclose(np.asarray(h32), oracle, rtol=0, atol=0)


def test_quant_renew_device_matches_host_oracle():
    """The device prefix-difference renewal must match per-leaf numpy
    sums of the true gradients (reference: RenewIntGradTreeOutput)."""
    X, y = _make_binary(n=4000)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "use_quantized_grad": True, "quant_train_renew_leaf": True,
            "num_grad_quant_bins": 4, "learning_rate": 0.1}
    bst = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=3)
    g = bst._gbdt
    g._flush_pending()
    # oracle: recompute every leaf value of the LAST tree from the true
    # gradients of the scores before that tree
    import jax.numpy as jnp
    from lightgbm_tpu.ops.split import leaf_output
    tree = g.models[-1]
    # scores before the last tree
    raw_before = np.zeros(len(X))
    for t in g.models[:-1]:
        raw_before += t.predict(X)
    sc = jnp.asarray(raw_before.astype(np.float32)) + g.init_scores[0] * 0
    grad, hess = g.objective.get_gradients(jnp.asarray(
        raw_before.astype(np.float32)))
    leaves = tree.predict_leaf(X)
    for leaf in range(int(leaves.max()) + 1):
        m = leaves == leaf
        if not m.any():
            continue
        want = float(leaf_output(
            float(np.asarray(grad)[m].sum()),
            float(np.asarray(hess)[m].sum()) + 2e-15,
            0.0, base.get("lambda_l2", 1e-3) if False else 0.0, 0.0))
        # tree leaf values carry shrinkage
        got = tree.leaf_value[leaf] / g.shrinkage_rate
        assert abs(got - want) < 5e-3 * max(1.0, abs(want)), (leaf, got, want)
