"""Quantized-gradient training tests (reference model:
tests/python_package_test/test_engine.py test_quantized_training)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=1500, f=12, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = 2 * X[:, 0] + X[:, 1] - X[:, 2]
    y = (logit + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


@pytest.mark.parametrize("renew", [False, True])
def test_quantized_binary_close_to_fp(renew):
    X, y = _make_binary()
    base = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 5,
            "verbosity": -1}
    bst_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=30)
    bst_q = lgb.train({**base, "use_quantized_grad": True,
                       "num_grad_quant_bins": 4,
                       "quant_train_renew_leaf": renew},
                      lgb.Dataset(X, label=y), num_boost_round=30)
    acc_fp = np.mean((bst_fp.predict(X) > 0.5) == y)
    acc_q = np.mean((bst_q.predict(X) > 0.5) == y)
    assert acc_q > acc_fp - 0.03, (acc_q, acc_fp)


def test_quantized_regression_learns():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1000, 8))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.1 * rng.normal(size=1000)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "min_data_in_leaf": 5, "verbosity": -1,
                     "use_quantized_grad": True, "num_grad_quant_bins": 8,
                     "quant_train_renew_leaf": True},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    mse = np.mean((y - bst.predict(X)) ** 2)
    assert mse < 0.3 * np.var(y)


def test_quantized_deterministic_rounding():
    """stochastic_rounding=false must be reproducible run-to-run."""
    X, y = _make_binary(600, 6)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "min_data_in_leaf": 5}
    p1 = lgb.train(params, lgb.Dataset(X, label=y), 10).predict(X)
    p2 = lgb.train(params, lgb.Dataset(X, label=y), 10).predict(X)
    np.testing.assert_allclose(p1, p2)
