"""Frontier-batched tree growth (tpu_frontier_k, models/learner.py
_build_tree_frontier): growing the top-K frontier leaves per while-loop
step must produce trees BIT-IDENTICAL to the K=1 oracle — including at
the num_leaves budget boundary, where the oracle-order replay prunes
speculative splits and the tree-end undo pass restores the pruned
ranges' physical row order (next-iteration f32 accumulation order).

Order-dependent machinery (forced splits, monotone constraints, CEGB,
extra_trees, bynode sampling, interaction constraints, parallel
learners) must fall back to K=1 with a warning.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.models.learner import SerialTreeLearner


def _data(seed=7, n=700, f=6, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if cat:
        X[:, -1] = rng.randint(0, 10, size=n)
    y = (X[:, 0] + 0.5 * np.sin(X[:, 1] * 2)
         + 0.4 * rng.randn(n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "min_data_in_leaf": 5, "metric": ""}


def _trees(bst):
    """Model text minus the [param] dump (tpu_frontier_k legitimately
    differs between the arms; the TREES must not)."""
    return [ln for ln in bst.model_to_string().splitlines()
            if not ln.startswith("[")]


def _train(X, y, nbr=2, cat=False, **kw):
    p = {**BASE, **kw}
    if cat:
        p["categorical_feature"] = [X.shape[1] - 1]
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=nbr)


# ---------------------------------------------------------------------------
# bit-identity matrix vs the K=1 oracle
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("extra,cat", [
    ({}, False),                                              # plain
    ({"bagging_fraction": 0.6, "bagging_freq": 1}, False),    # bagging
    ({"data_sample_strategy": "goss"}, False),                # GOSS
    ({"use_quantized_grad": True}, False),                    # quantized
    ({}, True),                                               # categorical
    ({"min_gain_to_split": 5.0}, False),                      # early stop
    ({"lambda_l1": 0.5, "lambda_l2": 3.0,
      "path_smooth": 1.0}, False),                            # regularized
])
def test_frontier_bitidentity(extra, cat):
    X, y = _data(cat=cat)
    b1 = _train(X, y, cat=cat, **extra)
    bk = _train(X, y, cat=cat, tpu_frontier_k=3, **extra)
    assert bk._gbdt.learner.frontier_k == 3
    assert _trees(b1) == _trees(bk)
    d = np.abs(np.asarray(b1.predict(X[:200]))
               - np.asarray(bk.predict(X[:200]))).max()
    assert float(d) == 0.0


def test_frontier_budget_boundary_partial_steps():
    """num_leaves budgets that do not divide by K force partial final
    steps (k_step shrinks to the remaining budget); trees must still be
    bit-identical, for several K including K > the frontier width of
    the early tree."""
    X, y = _data(seed=3)
    for L, K in ((8, 5), (12, 4), (15, 7)):
        b1 = _train(X, y, num_leaves=L)
        bk = _train(X, y, num_leaves=L, tpu_frontier_k=K)
        assert _trees(b1) == _trees(bk), (L, K)


@pytest.mark.slow  # 6.7 s: tier-1 window trim (PR 14) — frontier
# bit-identity keeps its fast in-window representatives in
# test_frontier_bitidentity (the multiclass lane also rides
# test_chunkpolicy.py::test_chunk_bitidentity)
def test_frontier_multiclass_and_regression():
    X, y = _data(seed=11)
    ym = (np.abs(X[:, 0]) + X[:, 1] > 1).astype(float) + (X[:, 2] > 0)
    for params, yy in ((
            {"objective": "multiclass", "num_class": 3}, ym), (
            {"objective": "regression"}, X[:, 0] + 0.3 * X[:, 1])):
        p1 = {**BASE, **params}
        b1 = lgb.train(p1, lgb.Dataset(X, label=yy), num_boost_round=2)
        bk = lgb.train({**p1, "tpu_frontier_k": 4},
                       lgb.Dataset(X, label=yy), num_boost_round=2)
        assert _trees(b1) == _trees(bk), params["objective"]


def test_frontier_eager_path():
    X, y = _data(seed=5)
    b1 = _train(X, y, tpu_fused_iteration=False)
    bk = _train(X, y, tpu_fused_iteration=False, tpu_frontier_k=3)
    assert _trees(b1) == _trees(bk)


def test_frontier_mega_xla_interplay():
    """The mega-kernel XLA-oracle path has no histogram state at all;
    the frontier body must reuse its per-leaf both-children pass and
    stay bit-identical to the K=1 mega learner."""
    X, y = _data(seed=9)
    b1 = _train(X, y, tpu_megakernel="xla")
    bk = _train(X, y, tpu_megakernel="xla", tpu_frontier_k=3)
    assert b1._gbdt.learner._use_mega == "xla"
    assert bk._gbdt.learner._use_mega == "xla"
    assert bk._gbdt.learner.frontier_k == 3
    assert _trees(b1) == _trees(bk)


@pytest.mark.slow
def test_frontier_megakernel_interpret_interplay():
    """Interpreter-mode Pallas mega-kernel under frontier batching:
    the k-loop drives one mega program per selected leaf and trees stay
    bit-identical to the K=1 mega learner (slow: interpreter)."""
    X, y = _data(seed=13, n=600)
    kw = {"tpu_kernel_interpret": True, "tpu_megakernel": "pallas",
          "tpu_row_chunk": 256}
    b1 = _train(X, y, nbr=1, **kw)
    bk = _train(X, y, nbr=1, tpu_frontier_k=3, **kw)
    assert b1._gbdt.learner._use_mega == "pallas"
    assert bk._gbdt.learner._use_mega == "pallas"
    assert _trees(b1) == _trees(bk)


# ---------------------------------------------------------------------------
# speculation/prune internals: the replay's invariants where pruning
# actually engages
# ---------------------------------------------------------------------------
def test_frontier_prune_engages_and_stays_bitidentical():
    """Noisy (bagged) gains at a binding budget make children outrank
    speculative picks, so some speculative splits must be PRUNED
    (made > committed); the replay bounds the overshoot by K-1 and the
    renumber+undo passes keep the record bit-identical to the oracle."""
    import jax.numpy as jnp
    X, y = _data(seed=7, n=900)
    g0 = (0.5 - y).astype(np.float32)
    K = 4
    pruned_seen = 0
    for seed in range(6):
        r2 = np.random.RandomState(seed)
        mask = r2.rand(len(y)) < 0.55
        grad = np.where(mask, g0, 0.0).astype(np.float32)
        hess = np.where(mask, 0.25, 0.0).astype(np.float32)
        recs = {}
        for k in (1, K):
            cfg = Config({**BASE, "num_leaves": 12, "tpu_frontier_k": k})
            ds = BinnedDataset.from_matrix(X, cfg, label=y)
            lr = SerialTreeLearner(ds, cfg)
            lr._frontier_debug = True
            recs[k] = lr.build_tree(jnp.asarray(grad), jnp.asarray(hess),
                                    bag_cnt=int(mask.sum()))
        a, b = recs[1], recs[K]
        for field in ("s", "leaf_start", "leaf_cnt", "leaf_value",
                      "leaf_sum_g", "leaf_sum_h", "best_gain",
                      "node_feature", "node_threshold", "node_gain",
                      "node_left", "node_right", "indices"):
            assert np.array_equal(np.asarray(a[field]),
                                  np.asarray(b[field])), (seed, field)
        dbg = b["frontier_debug"]
        made = int(np.asarray(dbg["made"]))
        m = int(np.asarray(b["s"]))
        assert made - m <= K - 1          # overshoot bound
        pruned_seen += int(made > m)
    assert pruned_seen > 0, \
        "no seed engaged pruning: the boundary lane tests nothing"


# ---------------------------------------------------------------------------
# fallbacks and config plumbing
# ---------------------------------------------------------------------------
def _learner_for(params, X, y):
    cfg = Config({**BASE, **params})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    return SerialTreeLearner(ds, cfg)


def test_frontier_fallbacks_to_k1(tmp_path):
    X, y = _data()
    forced = tmp_path / "forced.json"
    forced.write_text(json.dumps({"feature": 0, "threshold": 0.0}))
    fallback_params = [
        {"monotone_constraints": "1,0,0,0,0,0"},
        {"monotone_constraints": "1,0,0,0,0,0",
         "monotone_constraints_method": "intermediate"},
        {"forcedsplits_filename": str(forced)},
        {"cegb_penalty_split": 0.1},
        {"cegb_penalty_feature_lazy": "0.1,0.1,0.1,0.1,0.1,0.1"},
        {"extra_trees": True},
        {"feature_fraction_bynode": 0.5},
        {"interaction_constraints": "[0,1],[2,3]"},
    ]
    for p in fallback_params:
        lr = _learner_for({**p, "tpu_frontier_k": 4}, X, y)
        assert lr.frontier_k == 1, p
    # a fallback-engaged training equals the plain learner exactly
    b1 = _train(X, y, monotone_constraints="1,0,0,0,0,0")
    bk = _train(X, y, monotone_constraints="1,0,0,0,0,0",
                tpu_frontier_k=4)
    assert bk._gbdt.learner.frontier_k == 1
    assert _trees(b1) == _trees(bk)


def test_frontier_k_plumbing():
    X, y = _data()
    # auto on CPU stays 1 (compile-budget heuristic; README)
    assert _learner_for({}, X, y).frontier_k == 1
    assert _learner_for({"tpu_frontier_k": "auto"}, X, y).frontier_k == 1
    # explicit K engages anywhere, capped at num_leaves - 1
    assert _learner_for({"tpu_frontier_k": 6}, X, y).frontier_k == 6
    assert _learner_for({"tpu_frontier_k": 99}, X, y).frontier_k == 14
    assert _learner_for({"tpu_frontier_k": 1}, X, y).frontier_k == 1
    with pytest.raises(ValueError):
        _learner_for({"tpu_frontier_k": 0}, X, y)
    with pytest.raises(ValueError):
        _learner_for({"tpu_frontier_k": "bogus"}, X, y)


def test_frontier_model_io_round_trip(tmp_path):
    """Frontier-trained boosters save/load/predict like any other."""
    X, y = _data(seed=21)
    bk = _train(X, y, tpu_frontier_k=3)
    p1 = np.asarray(bk.predict(X[:100]))
    out = tmp_path / "m.txt"
    bk.save_model(str(out))
    b2 = lgb.Booster(model_file=str(out))
    p2 = np.asarray(b2.predict(X[:100]))
    np.testing.assert_array_equal(p1, p2)
