"""Golden cross-artifact validation: reference-PRODUCED model files load
into this framework and reproduce the reference CLI's own predictions;
a model SAVED by this framework was consumed by the reference CLI
(fixture captures its output).  See tests/golden/README.md for
provenance.  Format spec: src/io/gbdt_model_text.cpp."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def _load(csv):
    raw = np.genfromtxt(os.path.join(GOLDEN, csv), delimiter=",")
    return raw[:, 1:], raw[:, 0]


@pytest.mark.parametrize("name,csv", [
    ("binary", "binary.csv"),
    ("catbinary", "binary.csv"),          # categorical_feature=2
    ("regression", "regression.csv"),
    ("multiclass", "multiclass.csv"),
])
def test_reference_model_loads_and_predicts(name, csv):
    X, _ = _load(csv)
    bst = lgb.Booster(
        model_file=os.path.join(GOLDEN, f"ref_{name}.model.txt"))
    pred = np.asarray(bst.predict(X))
    ref = np.loadtxt(os.path.join(GOLDEN, f"ref_{name}.pred.tsv"))
    assert pred.shape == ref.shape
    assert np.allclose(pred, ref, atol=5e-6), np.abs(pred - ref).max()


def test_reference_model_roundtrips_through_save(tmp_path):
    # load reference text -> save -> reload: predictions identical
    X, _ = _load("binary.csv")
    bst = lgb.Booster(
        model_file=os.path.join(GOLDEN, "ref_binary.model.txt"))
    p1 = np.asarray(bst.predict(X, raw_score=True))
    out = tmp_path / "resaved.txt"
    bst.save_model(str(out))
    bst2 = lgb.Booster(model_file=str(out))
    p2 = np.asarray(bst2.predict(X, raw_score=True))
    assert np.allclose(p1, p2, atol=1e-7)


def test_our_model_was_consumed_by_reference_cli():
    """tpu_binary.refpred.tsv is the reference CLI's predict output when
    loading tpu_binary.model.txt (a model THIS framework saved): the
    reverse compatibility direction.  This framework must agree with
    what the reference computed from its model file."""
    X, _ = _load("binary.csv")
    bst = lgb.Booster(
        model_file=os.path.join(GOLDEN, "tpu_binary.model.txt"))
    pred = np.asarray(bst.predict(X))
    refpred = np.loadtxt(os.path.join(GOLDEN, "tpu_binary.refpred.tsv"))
    assert np.allclose(pred, refpred, atol=5e-6), \
        np.abs(pred - refpred).max()
