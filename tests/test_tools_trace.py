"""Tier-1 lane for tools/trace_report.py (ISSUE-8): the --smoke
self-check must drive the continual drift drills at telemetry=trace,
export a VALID Chrome trace containing the tick/retrain/swap/rollback
spans plus runtime compile events, and exit 0 — and the summarize path
must read back what the exporters write (both formats)."""

import importlib.util
import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(HERE, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_smoke(capsys):
    tool = _load_tool("trace_report")
    rc = tool.main(["--smoke", "--rows", "160"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(out)
    assert rc == 0, payload
    assert payload["ok"] is True
    assert payload["problems"] == []
    spans = payload["spans"]
    for name in ("continual.tick", "continual.retrain",
                 "continual.swap", "continual.rollback"):
        assert spans.get(name, 0) >= 1, (name, spans)
    assert payload["compiles"], "no runtime compile events in the trace"
    # the swap drill's kill+resume means the retrain span fired twice
    assert spans["continual.retrain"] >= 2


def test_trace_report_reads_both_export_formats(tmp_path, capsys):
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs

    tool = _load_tool("trace_report")
    sess = obs.get()
    sess.reset(mode="trace")
    try:
        rng = np.random.RandomState(1)
        X = rng.normal(size=(600, 5))
        y = X[:, 0] + 0.1 * rng.normal(size=600)
        lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7, "metric": ""},
                  lgb.Dataset(X, label=y), num_boost_round=3)
        paths = obs.export_session(str(tmp_path))
    finally:
        sess.reset(mode="off")

    for key in ("trace", "jsonl"):
        rc = tool.main([paths[key]])
        out = capsys.readouterr().out.strip().splitlines()[-1]
        summary = json.loads(out)
        assert rc == 0, summary
        assert summary["problems"] == []
        assert summary["spans"]["train.iteration"]["count"] == 3

    # a malformed artifact fails loudly
    bad = tmp_path / "bad.json"
    bad.write_text('{"traceEvents": [{"name": "x"}]}')
    rc = tool.main([str(bad)])
    capsys.readouterr()
    assert rc != 0


def test_trace_report_merge_distinct_pids(tmp_path, capsys):
    """`merge` combines per-rank exports into one Chrome trace with a
    distinct pid (and a process_name row) per input file."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs

    tool = _load_tool("trace_report")
    sess = obs.get()
    rank_files = []
    try:
        for rank in range(3):
            sess.reset(mode="trace")
            rng = np.random.RandomState(rank)
            X = rng.normal(size=(400, 4))
            y = X[:, 0] + 0.1 * rng.normal(size=400)
            lgb.train({"objective": "regression", "verbosity": -1,
                       "num_leaves": 7, "metric": ""},
                      lgb.Dataset(X, label=y), num_boost_round=2)
            # mix the two export formats like a mixed-rank run would
            if rank % 2:
                p = str(tmp_path / f"rank{rank}.jsonl")
                obs.export_jsonl(sess, p)
            else:
                p = str(tmp_path / f"rank{rank}.json")
                obs.export_chrome_trace(sess, p)
            rank_files.append(p)
    finally:
        sess.reset(mode="off")

    out_path = str(tmp_path / "merged.json")
    rc = tool.main(["merge", "-o", out_path] + rank_files)
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, summary
    assert summary["problems"] == []
    assert summary["pids"] == [1, 2, 3]
    # every rank's spans merged: 3 ranks x 2 iterations
    assert summary["spans"]["train.iteration"]["count"] == 6

    with open(out_path) as fh:
        doc = json.load(fh)
    names = [(e.get("pid"), e["args"]["name"])
             for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert len(names) == 3 and len({p for p, _ in names}) == 3
    # the merged artifact itself validates through the normal path
    rc = tool.main([out_path])
    capsys.readouterr()
    assert rc == 0
