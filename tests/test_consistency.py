"""CLI-vs-Python consistency using the shipped examples
(reference model: tests/python_package_test/test_consistency.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.textio import load_text_file

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run_cli(conf_dir, conf, extra=()):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", f"config={conf}", *extra],
        cwd=conf_dir, capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    return r


@pytest.mark.parametrize("example,objective,train_file", [
    ("binary_classification", "binary", "binary.train"),
    # tier-1 window trim (PR 14): the binary case is the fast
    # in-window representative of the CLI-vs-python parity lane
    pytest.param("regression", "regression", "regression.train",
                 marks=pytest.mark.slow),
])
def test_cli_matches_python(example, objective, train_file, tmp_path):
    """CLI and the Python API must train the SAME model from the same
    config.  The CLI runs IN-PROCESS so both sides share one set of
    compiled executables: on this infrastructure, separate processes can
    receive differently-lowered (remote- vs locally-compiled) XLA CPU
    binaries whose float summation order differs, flipping near-tie splits
    — that is a toolchain property, not an API inconsistency."""
    from lightgbm_tpu.cli import main as cli_main
    d = os.path.join(EXAMPLES, example)
    cli_model = tmp_path / "cli.txt"
    cwd = os.getcwd()
    try:
        os.chdir(d)
        cli_main(["config=train.conf", f"output_model={cli_model}",
                  "num_iterations=15", "verbosity=-1"])
    finally:
        os.chdir(cwd)
    lf = load_text_file(os.path.join(d, train_file))
    bst_py = lgb.train({"objective": objective, "num_leaves": 31,
                        "learning_rate": 0.1, "verbosity": -1},
                       lgb.Dataset(lf.X, label=lf.label), 15)
    bst_cli = lgb.Booster(model_file=str(cli_model))
    np.testing.assert_allclose(bst_cli.predict(lf.X, raw_score=True),
                               bst_py.predict(lf.X, raw_score=True),
                               rtol=1e-5, atol=1e-5)


# tier-1 window trim (PR 17): the ranking-CLI-conf lane's fast
# in-window representative is test_cli.py::
# test_example_confs_train[xendcg]; the lambdarank objective itself
# stays covered in-window by the objectives suite
@pytest.mark.slow
def test_cli_lambdarank_example(tmp_path):
    d = os.path.join(EXAMPLES, "lambdarank")
    model_path = tmp_path / "model.txt"
    _run_cli(d, "train.conf", (f"output_model={model_path}", "verbosity=-1"))
    bst = lgb.Booster(model_file=str(model_path))
    lf = load_text_file(os.path.join(d, "rank.train"))
    s = bst.predict(lf.X)
    # scores must rank high-relevance docs above low within the train set
    assert np.corrcoef(s, lf.label)[0, 1] > 0.5
