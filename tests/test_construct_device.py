"""Device-vectorized dataset construction (ops/construct.py).

Acceptance for the construction PR: the vectorized / device path must
be BIT-IDENTICAL to the host oracle at every level — BinMappers
(incl. NaN, zero-as-bin, categorical, max_bin_by_feature, forced
bins), EFB bundles, the packed binned matrix, and the trees of a model
trained through the new ingest.  Plus the streaming-construction
chunk-boundary guarantee (Sequence batch sizes straddling sequence
boundaries change nothing) and the DeviceIngest buffer contract.
"""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.ops.binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
from lightgbm_tpu.ops.construct import (BatchedMapper, DeviceIngest,
                                        conflict_matrix, find_bin_sorted,
                                        row_geometry, sorted_sample_columns)

BASE = {"verbosity": -1}


def _mapper_dicts(ds):
    return [json.dumps(bm.to_dict(), sort_keys=True)
            for bm in ds.bin_mappers]


def _group_tuples(ds):
    return [(tuple(g.feature_indices), g.num_total_bin,
             tuple(g.bin_offsets)) for g in ds.groups]


def _tree_part(model_str: str) -> str:
    """The model string minus the echoed parameter block (the only part
    that legitimately differs between construct_device settings)."""
    head, sep, tail = model_str.partition("parameters:")
    return head


def _columns_matrix(rng, n):
    """A matrix exercising every mapper branch: dense normal, heavy
    zeros (sparse/EFB candidates), NaN, few-distinct, constant,
    all-negative, categorical (with a negative code), integer grid."""
    X = rng.normal(size=(n, 12))
    X[:, 1] = np.where(rng.rand(n) < 0.9, 0.0, X[:, 1])
    X[:, 2] = np.where(rng.rand(n) < 0.85, 0.0, X[:, 2])
    X[rng.rand(n) < 0.07, 3] = np.nan
    X[:, 4] = rng.randint(0, 5, size=n).astype(float)       # few distinct
    X[:, 5] = 3.25                                          # constant
    X[:, 6] = -np.abs(rng.normal(size=n)) - 0.5             # all negative
    X[:, 7] = rng.randint(0, 9, size=n).astype(float)       # categorical
    X[rng.rand(n) < 0.02, 7] = -1.0                         # negative cat
    X[:, 8] = rng.randint(0, 3, size=n).astype(float)
    X[:, 9] = np.where(rng.rand(n) < 0.5, 0.0,
                       np.abs(X[:, 9]))                     # all >= 0
    X[rng.rand(n) < 0.04, 9] = np.nan
    return X


# ---------------------------------------------------------------------------
# Stage parity: sorted-columns bin finding vs BinMapper.find_bin
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opts", [
    {},
    {"max_bin": 15},
    {"zero_as_missing": True},
    {"use_missing": False},
    {"min_data_in_bin": 25},
    {"pre_filter": True, "min_split_data": 40},
])
def test_find_bin_sorted_matches_oracle(rng, opts):
    X = _columns_matrix(rng, 4000)
    info = sorted_sample_columns(X)
    sv = info["sorted"]
    for f in range(X.shape[1]):
        col = X[:, f]
        nonzero = col[(np.abs(col) > 1e-35) | np.isnan(col)]
        bt = BIN_CATEGORICAL if f == 7 else BIN_NUMERICAL
        kw = dict(max_bin=255, min_data_in_bin=3, min_split_data=0,
                  pre_filter=False, bin_type=bt, use_missing=True,
                  zero_as_missing=False)
        kw.update(opts)
        ref = BinMapper()
        ref.find_bin(nonzero, total_sample_cnt=len(col), **kw)
        lo, hi, m = info["lo"][f], info["hi"][f], info["non_nan"][f]
        nz_sorted = np.concatenate([sv[:lo, f], sv[hi:m, f]])
        got = find_bin_sorted(nz_sorted, na_cnt=int(info["nan_cnt"][f]),
                              total_sample_cnt=len(col), **kw)
        assert (json.dumps(got.to_dict(), sort_keys=True)
                == json.dumps(ref.to_dict(), sort_keys=True)), f


def test_find_bin_sorted_forced_bounds(rng):
    col = np.concatenate([rng.normal(size=3000),
                          np.zeros(500), [np.nan] * 40])
    rng.shuffle(col)
    nonzero = col[(np.abs(col) > 1e-35) | np.isnan(col)]
    kw = dict(total_sample_cnt=len(col), max_bin=63, min_data_in_bin=3,
              forced_upper_bounds=[-0.5, 0.5, 1.5])
    ref = BinMapper()
    ref.find_bin(nonzero, **kw)
    nz = np.sort(nonzero[~np.isnan(nonzero)])
    got = find_bin_sorted(nz, na_cnt=int(np.isnan(nonzero).sum()), **kw)
    assert (json.dumps(got.to_dict(), sort_keys=True)
            == json.dumps(ref.to_dict(), sort_keys=True))


def test_find_bin_sorted_many_distinct_no_big_bins(rng):
    """The searchsorted cut-to-cut fast path (num_distinct > max_bin,
    no big bins) — the dominant production shape."""
    col = rng.normal(size=20000) * 10
    kw = dict(total_sample_cnt=len(col), max_bin=63, min_data_in_bin=3)
    ref = BinMapper()
    ref.find_bin(col, **kw)
    got = find_bin_sorted(np.sort(col), na_cnt=0, **kw)
    assert got.bin_upper_bound == ref.bin_upper_bound
    assert (json.dumps(got.to_dict(), sort_keys=True)
            == json.dumps(ref.to_dict(), sort_keys=True))


# ---------------------------------------------------------------------------
# Stage parity: BatchedMapper vs per-feature values_to_bins
# ---------------------------------------------------------------------------
def test_batched_mapper_matches_values_to_bins(rng):
    X = _columns_matrix(rng, 3000)
    cfg = Config(dict(BASE, construct_device="off"))
    ds = BinnedDataset.from_matrix(X, cfg, label=X[:, 0],
                                   categorical_features=[7])
    bmap = BatchedMapper(ds.bin_mappers, ds.used_features)
    Q = _columns_matrix(np.random.RandomState(9), 500)
    Q[0, 7] = 999.0                    # unseen category
    for oov in (False, True):
        got = bmap.map_chunk(Q[:, ds.used_features], oov_sentinel=oov)
        for i, f in enumerate(ds.used_features):
            bm = ds.bin_mappers[f]
            ref = bm.values_to_bins(
                Q[:, f], oov_sentinel=(oov and
                                       bm.bin_type == BIN_CATEGORICAL))
            np.testing.assert_array_equal(np.asarray(got[:, i]), ref,
                                          err_msg=f"feature {f} oov={oov}")


def test_batched_mapper_device_path_matches_host(rng):
    import jax.numpy as jnp
    X = _columns_matrix(rng, 2000)
    cfg = Config(dict(BASE, construct_device="off"))
    ds = BinnedDataset.from_matrix(X, cfg, label=X[:, 0],
                                   categorical_features=[7])
    bmap = BatchedMapper(ds.bin_mappers, ds.used_features)
    Q = _columns_matrix(np.random.RandomState(3), 300)
    host = bmap.map_chunk(Q[:, ds.used_features])
    dev = np.asarray(bmap.map_chunk(jnp.asarray(Q[:, ds.used_features]),
                                    xp=jnp))
    np.testing.assert_array_equal(host, dev)


def test_grid_search_tables_exact_vs_searchsorted(rng):
    """The host uniform-grid search accelerator must reproduce
    np.searchsorted('left') bit-exactly on adversarial inputs: values
    exactly on bounds, one-ulp neighbours, +-inf, and bound sets
    clustered tightly enough to force the per-feature fallback."""
    from lightgbm_tpu.ops.construct import _GRID_MAXSPAN
    cols = []
    cols.append(rng.normal(size=4000))                   # dense normal
    cols.append(rng.uniform(-1e-9, 1e-9, size=4000))     # tight cluster
    cols.append(np.exp(rng.normal(size=4000) * 8)
                * np.sign(rng.normal(size=4000)))        # huge dynamic range
    X = np.column_stack(cols + [rng.normal(size=4000)])
    cfg = Config(dict(BASE, construct_device="off"))
    ds = BinnedDataset.from_matrix(X, cfg, label=X[:, 0])
    bmap = BatchedMapper(ds.bin_mappers, ds.used_features)
    spans = [t[4] for t in bmap._grid if t is not None]
    assert spans and max(spans) <= _GRID_MAXSPAN
    # adversarial probe rows: every feature's exact bounds, one-ulp
    # neighbours, and infinities, padded to a rectangular matrix
    probes = []
    for i, f in enumerate(bmap.used_features):
        b = bmap.bounds[i, : bmap._blen[i]]
        b = b[np.isfinite(b)]
        probes.append(np.concatenate(
            [b, np.nextafter(b, np.inf), np.nextafter(b, -np.inf),
             [np.inf, -np.inf, 0.0, -0.0]]))
    n = max(p.size for p in probes)
    Q = np.zeros((n, len(probes)))
    for i, p in enumerate(probes):
        Q[: p.size, i] = p
    got = bmap.map_chunk_T(Q)
    for i, f in enumerate(bmap.used_features):
        ref = ds.bin_mappers[f].values_to_bins(Q[:, i])
        np.testing.assert_array_equal(
            got[i], ref, err_msg=f"feature {f} grid-search mismatch")


# ---------------------------------------------------------------------------
# Stage parity: conflict matmul vs pairwise mask loop; bundle identity
# ---------------------------------------------------------------------------
def test_conflict_matrix_matches_pairwise(rng):
    masks = (rng.rand(17, 4000) < 0.08)
    got = conflict_matrix(masks)
    for i in range(17):
        for j in range(17):
            assert got[i, j] == int((masks[i] & masks[j]).sum()), (i, j)


def test_efb_bundles_bit_identical(rng):
    n = 4000
    X = np.zeros((n, 24))
    # mutually exclusive one-hot-ish block: bundles expected
    hot = rng.randint(0, 20, size=n)
    for j in range(20):
        X[:, j] = np.where(hot == j, rng.rand(n) + 0.5, 0.0)
    X[:, 20:] = rng.normal(size=(n, 4))
    y = X[:, 20]
    ds0 = BinnedDataset.from_matrix(
        X, Config(dict(BASE, construct_device="off")), label=y)
    ds1 = BinnedDataset.from_matrix(
        X, Config(dict(BASE, construct_device="auto")), label=y)
    assert _group_tuples(ds0) == _group_tuples(ds1)
    assert any(len(g.feature_indices) > 1 for g in ds0.groups), \
        "matrix must actually exercise bundling"
    assert np.array_equal(ds0.binned, ds1.binned)


# ---------------------------------------------------------------------------
# Acceptance: dataset-level parity + tree-identical training
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["auto", "on"])
def test_construct_parity_and_tree_identity(rng, mode):
    X = _columns_matrix(rng, 4000)
    y = (X[:, 0] + np.nan_to_num(X[:, 3]) + X[:, 1] * 2
         + 0.1 * rng.normal(size=len(X)))
    params = dict(BASE, objective="regression", num_leaves=15,
                  num_iterations=8, seed=3, deterministic=True,
                  categorical_feature=[7],
                  max_bin_by_feature=",".join(["255"] * 6 + ["31"] * 6))
    ds0 = BinnedDataset.from_matrix(
        X, Config(dict(params, construct_device="off")), label=y,
        categorical_features=[7])
    dsm = BinnedDataset.from_matrix(
        X, Config(dict(params, construct_device=mode)), label=y,
        categorical_features=[7])
    assert _mapper_dicts(ds0) == _mapper_dicts(dsm)
    assert _group_tuples(ds0) == _group_tuples(dsm)
    if mode == "auto":
        assert dsm.binned is not None
        assert np.array_equal(ds0.binned, dsm.binned)
    else:
        assert dsm.binned is None, "construct_device=on keeps no host copy"
    assert dsm.device_ingest is not None
    np.testing.assert_array_equal(dsm.device_ingest.host_binned(),
                                  ds0.binned)

    m_off = lgb.train(dict(params, construct_device="off"),
                      lgb.Dataset(X, label=y, categorical_feature=[7]))
    m_new = lgb.train(dict(params, construct_device=mode),
                      lgb.Dataset(X, label=y, categorical_feature=[7]))
    assert (_tree_part(m_off.model_to_string())
            == _tree_part(m_new.model_to_string())), \
        f"trees must be bit-identical through construct_device={mode}"


def test_validation_dataset_parity(rng):
    X = _columns_matrix(rng, 3000)
    y = X[:, 0] + 0.1 * rng.normal(size=len(X))
    Xv, yv = _columns_matrix(np.random.RandomState(5), 500), None
    evals = {}
    models = {}
    for mode in ("off", "auto"):
        params = dict(BASE, objective="regression", num_leaves=15,
                      num_iterations=6, seed=3, metric="l2",
                      construct_device=mode)
        dtr = lgb.Dataset(X, label=y)
        dva = lgb.Dataset(Xv, label=Xv[:, 0], reference=dtr)
        rec = {}
        bst = lgb.train(params, dtr, valid_sets=[dva],
                        valid_names=["v"], callbacks=[
                            lgb.record_evaluation(rec)])
        evals[mode] = rec
        models[mode] = _tree_part(bst.model_to_string())
    assert models["off"] == models["auto"]
    assert evals["off"] == evals["auto"]


# ---------------------------------------------------------------------------
# Sequence / two_round chunk-boundary construction
# ---------------------------------------------------------------------------
class _Seq(lgb.Sequence):
    def __init__(self, mat, batch_size):
        self._m = mat
        self.batch_size = batch_size

    def __getitem__(self, idx):
        return self._m[idx]

    def __len__(self):
        return len(self._m)


@pytest.mark.parametrize("mode", ["off", "auto", "on"])
@pytest.mark.parametrize("batches", [(173,), (1024,), (97, 211)])
def test_sequence_chunk_boundaries_bit_identical(rng, mode, batches):
    """Chunk sizes that straddle sequence boundaries must produce
    bit-identical mappers/bins vs one-shot construction — this guards
    the streaming device ingest too (rows enter the (G, N_pad) buffer
    in arbitrary chunk sizes)."""
    X = _columns_matrix(rng, 2611)     # prime-ish row count: never aligned
    y = X[:, 0]
    cfg = Config(dict(BASE, construct_device=mode))
    one = BinnedDataset.from_matrix(
        X, Config(dict(BASE, construct_device=mode)), label=y)
    # split rows across sequences at awkward places, with batch sizes
    # that straddle both sequence boundaries and each other
    cuts = [0, 611, 1900, len(X)]
    for bs in batches:
        seqs = [_Seq(X[a:b], bs) for a, b in zip(cuts[:-1], cuts[1:])]
        ds = BinnedDataset.from_sequences(seqs, cfg, label=y)
        assert _mapper_dicts(ds) == _mapper_dicts(one)
        assert _group_tuples(ds) == _group_tuples(one)
        a = ds.host_binned()
        b = one.host_binned()
        np.testing.assert_array_equal(a, b)
        if mode == "on":
            assert ds.binned is None and ds.device_ingest is not None


def test_two_round_dataset_matches_in_memory(rng, tmp_path):
    """two_round loading (file -> Sequence-style chunked construction)
    agrees with in-memory construction through the vectorized path."""
    X = _columns_matrix(rng, 1500)[:, :8]
    y = X[:, 0]
    data = np.column_stack([y, X])
    path = tmp_path / "train.csv"
    np.savetxt(path, data, delimiter=",")
    p = dict(BASE, objective="regression", num_iterations=3, seed=1,
             num_leaves=7)
    m_mem = lgb.train(dict(p, construct_device="auto"),
                      lgb.Dataset(X, label=y))
    m_two = lgb.train(dict(p, construct_device="auto", two_round=True),
                      lgb.Dataset(str(path)))
    assert (_tree_part(m_mem.model_to_string())
            == _tree_part(m_two.model_to_string()))


# ---------------------------------------------------------------------------
# DeviceIngest buffer contract
# ---------------------------------------------------------------------------
def test_device_ingest_contract(rng):
    G, N = 5, 1000
    c, row0, n_pad = row_geometry(4096, N)
    ing = DeviceIngest(G, N, np.uint8, 4096)
    assert (ing.row_chunk, ing.row0, ing.n_pad) == (c, row0, n_pad)
    mat = rng.randint(0, 200, size=(N, G)).astype(np.uint8)
    for start in (0, 137, 512):
        stop = (137, 512, N)[(0, 137, 512).index(start)]
        ing.push(mat[start:stop])
    buf = ing.finish()
    assert buf.shape == (G, n_pad)
    np.testing.assert_array_equal(ing.host_binned(), mat)
    # padding rows stay zero; part0 pads on device
    p = np.asarray(ing.part0(G + 3))
    assert p.shape == (G + 3, n_pad)
    assert (p[G:] == 0).all()
    np.testing.assert_array_equal(p[:G, row0:row0 + N], mat.T)
    # overflow / underflow raise
    with pytest.raises(ValueError):
        ing.push(mat[:1])
    ing2 = DeviceIngest(G, N, np.uint8, 4096)
    ing2.push(mat[:10])
    with pytest.raises(ValueError):
        ing2.finish()


def test_free_host_binned_and_state_round_trips(rng):
    X = _columns_matrix(rng, 2000)
    y = X[:, 0]
    cfg = Config(dict(BASE, construct_device="auto",
                      free_host_binned=True))
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    assert ds.binned is None and ds.device_ingest is not None
    oracle = BinnedDataset.from_matrix(
        X, Config(dict(BASE, construct_device="off")), label=y)
    # pickling materializes the host matrix back (no data loss)
    ds2 = pickle.loads(pickle.dumps(ds))
    np.testing.assert_array_equal(ds2.binned, oracle.binned)
    assert ds2.device_ingest is None
    # save_binary materializes from the device buffer too
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        pth = os.path.join(td, "ds.bin")
        ds.save_binary(pth)
        ds3 = BinnedDataset.load_binary(pth, cfg)
        np.testing.assert_array_equal(ds3.binned, oracle.binned)


def test_sharded_trainer_recovers_host_binned(rng):
    """Single-process multi-device sharded training (tree_learner=data)
    consumes the host matrix via ``host_binned()``, so datasets built
    with construct_device=on / free_host_binned (host copy absent,
    recoverable from the DeviceIngest buffer) train tree-identically
    instead of crashing on ``dataset.binned is None``."""
    X = _columns_matrix(rng, 1500)
    y = X[:, 0] + 0.1 * rng.normal(size=len(X))
    p = dict(BASE, objective="regression", num_leaves=15,
             num_iterations=5, seed=3, tree_learner="data")
    m_off = lgb.train(dict(p, construct_device="off"),
                      lgb.Dataset(X, label=y))
    for mode in ({"construct_device": "on"}, {"free_host_binned": True}):
        m = lgb.train(dict(p, **mode), lgb.Dataset(X, label=y))
        assert (_tree_part(m_off.model_to_string())
                == _tree_part(m.model_to_string())), mode


def test_learner_geometry_mismatch_recovers_host(rng):
    """Training with a different tpu_row_chunk than construction (so
    the prebuilt device buffer's geometry no longer matches) recovers
    the host matrix from the buffer and trains identically."""
    X = _columns_matrix(rng, 1500)
    y = X[:, 0] + 0.1 * rng.normal(size=len(X))
    p = dict(BASE, objective="regression", num_leaves=15,
             num_iterations=5, seed=3)
    # the oracle must train on the SAME row chunk: the chunk grid sets
    # the histogram accumulation order, so only the construct path may
    # differ between the two models
    m_off = lgb.train(dict(p, construct_device="off", tpu_row_chunk=512),
                      lgb.Dataset(X, label=y))
    ds = lgb.Dataset(X, label=y)
    ds.construct({**p, "construct_device": "on"})
    inner = ds._inner
    assert inner.binned is None and inner.device_ingest is not None
    # shrink the training row chunk: ingest geometry no longer matches
    m_mismatch = lgb.train(dict(p, construct_device="on",
                                tpu_row_chunk=512), ds)
    assert (_tree_part(m_off.model_to_string())
            == _tree_part(m_mismatch.model_to_string()))


# ---------------------------------------------------------------------------
# tools/profile_construct.py --smoke (tier-1 wiring)
# ---------------------------------------------------------------------------
def test_profile_construct_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "profile_construct.py"), "--smoke"],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    line = [ln for ln in out.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["parity_ok"] is True
    assert rec["grid"], "smoke grid must not be empty"
    for cell in rec["grid"]:
        assert cell["host_loop_s"] > 0 and cell["vectorized_s"] > 0
