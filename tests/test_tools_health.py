"""Tier-1 lane for tools/health_report.py (ISSUE-9): the --smoke
self-check must validate Booster.health_report() end to end (flight
recorder, reference profile, serving skew digests, model-string
persistence) AND the covariate-shift attribution drill (planted
feature ranked #1), exiting 0; the model-summary path must print the
embedded profile of a saved model and fail loudly on one saved without
health."""

import importlib.util
import json
import os

import numpy as np

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(HERE, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_health_report_smoke(capsys):
    tool = _load_tool("health_report")
    rc = tool.main(["--smoke", "--rows", "160"])
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, payload
    assert payload["ok"] is True and payload["problems"] == []
    assert payload["trees_recorded"] == 8
    assert payload["planted_rank"] == 1
    assert payload["serving_rows"] >= 4608


def test_health_report_model_summary(tmp_path, capsys):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import health

    tool = _load_tool("health_report")
    prev = health.get().mode
    try:
        rng = np.random.RandomState(2)
        X = rng.normal(size=(400, 3))
        y = X[:, 0] + 0.1 * rng.normal(size=400)
        with_prof = str(tmp_path / "with.txt")
        lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7, "metric": "", "health": "counters"},
                  lgb.Dataset(X, label=y), num_boost_round=2) \
            .save_model(with_prof)
        rc = tool.main([with_prof])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0
        assert doc["num_features"] == 3 and doc["num_data"] == 400

        health.get().set_mode("off")
        without = str(tmp_path / "without.txt")
        bst = lgb.Booster(model_file=with_prof)
        bst._gbdt.health_profile = None
        bst.save_model(without)
        rc = tool.main([without])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc != 0 and doc["health_profile"] is None
    finally:
        health.get().set_mode(prev)
