"""Continued training (init_model) and periodic snapshots.

Reference behaviors: engine.py train(init_model=) (continued training
seeds scores from the loaded model — application.cpp:94-97), GBDT::Train
snapshot saves (gbdt.cpp:244-248).
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(rng, n=1200, f=8):
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 2) + X[:, 2] * 0.5 +
         0.2 * rng.normal(size=n))
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 20, "learning_rate": 0.1, "metric": "l2"}


def _l2(bst, X, y):
    p = bst.predict(X)
    return float(np.mean((p - y) ** 2))


def test_continue_train_matches_straight(rng, tmp_path):
    X, y = _data(rng)
    ds = lambda: lgb.Dataset(X, label=y)
    straight = lgb.train(PARAMS, ds(), num_boost_round=20)

    first = lgb.train(PARAMS, ds(), num_boost_round=10)
    cont = lgb.train(PARAMS, ds(), num_boost_round=10, init_model=first)
    assert cont.num_trees() == 20
    # scores are rebuilt from the init model's raw predictions, so the
    # continued run must track the straight run closely (float32 score
    # accumulation vs rebuilt-from-doubles can flip exact ties)
    l_straight = _l2(straight, X, y)
    l_cont = _l2(cont, X, y)
    l_first = _l2(first, X, y)
    assert l_cont < l_first * 0.9          # it genuinely kept training
    assert abs(l_cont - l_straight) < 0.05 * max(l_straight, 1e-6)


def test_continue_from_model_file(rng, tmp_path):
    X, y = _data(rng)
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5)
    path = str(tmp_path / "m.txt")
    first.save_model(path)
    cont = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=path)
    assert cont.num_trees() == 10
    # head trees are the loaded ones: predictions with num_iteration=5
    # match the saved model exactly
    p_head = cont.predict(X[:200], num_iteration=5)
    p_first = first.predict(X[:200])
    np.testing.assert_allclose(p_head, p_first, rtol=1e-6, atol=1e-7)


def test_continue_with_valid_sets(rng):
    X, y = _data(rng)
    Xv, yv = _data(rng, n=400)
    first = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5)
    evals = {}
    cont = lgb.train(
        PARAMS, lgb.Dataset(X, label=y), num_boost_round=5,
        init_model=first,
        valid_sets=[lgb.Dataset(Xv, label=yv, reference=None)],
        valid_names=["v"],
        callbacks=[lgb.record_evaluation(evals)])
    # recorded valid metric must equal a fresh evaluation of the full
    # 10-tree model on the valid set (scores were seeded correctly)
    final = evals["v"]["l2"][-1]
    direct = float(np.mean((cont.predict(Xv) - yv) ** 2))
    assert abs(final - direct) < 1e-5 * max(direct, 1.0)


def test_continue_multiclass(rng):
    X, _ = _data(rng)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbosity": -1, "min_data_in_leaf": 20}
    first = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    cont = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3,
                     init_model=first)
    assert cont.num_trees() == 18   # 6 iters x 3 classes
    p = cont.predict(X[:100])
    assert p.shape == (100, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)


def test_cli_snapshot_freq(rng, tmp_path):
    from lightgbm_tpu.cli import main as cli_main
    X, y = _data(rng, n=600)
    data_path = tmp_path / "train.csv"
    header = ",".join(["label"] + [f"f{i}" for i in range(X.shape[1])])
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",",
               header=header, comments="")
    out = tmp_path / "model.txt"
    cli_main(["task=train", f"data={data_path}", "header=true",
              "label_column=name:label", "objective=regression",
              "num_iterations=7", "snapshot_freq=3", "num_leaves=7",
              "verbosity=-1", f"output_model={out}"])
    assert os.path.exists(out)
    assert os.path.exists(str(out) + ".snapshot_iter_3")
    assert os.path.exists(str(out) + ".snapshot_iter_6")
    assert not os.path.exists(str(out) + ".snapshot_iter_7")
    # a snapshot is a loadable model with the right tree count
    snap = lgb.Booster(model_file=str(out) + ".snapshot_iter_3")
    assert snap.num_trees() == 3


def test_cli_input_model_continues(rng, tmp_path):
    from lightgbm_tpu.cli import main as cli_main
    X, y = _data(rng, n=600)
    data_path = tmp_path / "train.csv"
    header = ",".join(["label"] + [f"f{i}" for i in range(X.shape[1])])
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",",
               header=header, comments="")
    m1 = tmp_path / "m1.txt"
    m2 = tmp_path / "m2.txt"
    common = ["task=train", f"data={data_path}", "header=true",
              "label_column=name:label", "objective=regression",
              "num_leaves=7", "verbosity=-1"]
    cli_main(common + ["num_iterations=4", f"output_model={m1}"])
    cli_main(common + ["num_iterations=3", f"input_model={m1}",
                       f"output_model={m2}"])
    bst = lgb.Booster(model_file=str(m2))
    assert bst.num_trees() == 7


def test_continue_dart(rng):
    """DART continuation: init trees are kept, never dropped, and training
    proceeds (reference: dart.hpp num_init_iteration_ offsets)."""
    X, y = _data(rng)
    params = dict(PARAMS, boosting="dart", drop_rate=0.5, drop_seed=7)
    first = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    p_head_before = first.predict(X[:100])
    cont = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=first)
    assert cont.num_trees() == 10
    # the head-only prediction equals the init model exactly: init trees
    # were never dropped/renormalized
    p_head_after = cont.predict(X[:100], num_iteration=5)
    np.testing.assert_allclose(p_head_after, p_head_before, rtol=1e-6)
    assert _l2(cont, X, y) < _l2(first, X, y)


def test_continue_rf_raises(rng):
    X, y = _data(rng)
    params = dict(PARAMS, boosting="rf", bagging_freq=1,
                  bagging_fraction=0.8)
    first = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    with pytest.raises(ValueError, match="boosting=rf"):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3,
                  init_model=first)


def test_rollback_invalidates_device_predict_cache(rng):
    """rollback + retrain restores the same model LENGTH with a different
    last tree; the stacked device-predict cache must not serve the stale
    arrays (advisor finding, round 2)."""
    X, y = _data(rng, n=5000)   # >= 4096 rows so the device path engages
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5)
    g = bst._gbdt
    p1 = bst.predict(X)          # populates the stacked cache
    g.rollback_one_iter()
    # retrain on custom (perturbed) gradients so the replacement tree
    # genuinely differs from the rolled-back one (deterministic
    # retraining would otherwise reproduce it exactly)
    resid = np.asarray(g.scores) - (y + 0.5 * X[:, 3])
    g.train_one_iter(resid.astype(np.float32),
                     np.ones_like(resid, dtype=np.float32))
    g._flush_pending()
    p2 = bst.predict(X)
    # oracle: per-tree host traversal
    host = np.zeros(len(X))
    for t in g.models:
        host += t.predict(X)
    np.testing.assert_allclose(p2, host, rtol=1e-4, atol=1e-5)
    assert not np.allclose(p1, p2)
