"""Tier-1 gate for the jaxlint suite (lightgbm_tpu/analysis/,
tools/jaxlint.py, jaxlint_baseline.json).

Positive direction: the repo must be CLEAN against its committed
baseline — no new Tier A findings, no stale pinned debt, every Tier B
compile-artifact budget honored (the same comparison ``tools/jaxlint.py
--check`` runs).

Negative direction (the guards must actually guard): a deliberately
injected JL001 host sync in ops/histogram.py and a while-body
copy-budget regression — the default subtraction path's REAL measured
body fed to the mega-kernel's zero-copy budget — must both fail the
comparison, plus per-rule detection tests for JL002/JL003/JL004/JL005
and the suppression pragma.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)

from lightgbm_tpu.analysis import astlint, baseline, conlint  # noqa: E402

BASELINE = baseline.load(os.path.join(REPO, "jaxlint_baseline.json"))


# ---------------------------------------------------------------------------
# Tier A vs the committed ratchet
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tier_a_counts():
    return astlint.finding_counts(astlint.lint_tree(REPO))


def test_baseline_is_committed():
    assert BASELINE.get("tier_a") is not None
    assert BASELINE.get("tier_b"), \
        "jaxlint_baseline.json must pin the tier B budgets"
    assert BASELINE.get("tier_c") is not None, \
        "jaxlint_baseline.json must carry the tier_c table"


def test_tier_c_clean_against_baseline():
    """The tier-C concurrency gate (full rule/fixture coverage lives
    in tests/test_conlint.py; this is the suite-level clean check)."""
    measured = conlint.finding_counts(conlint.lint_tree(REPO))
    problems = baseline.compare_tier_c(measured, BASELINE)
    assert not problems, "\n".join(p.render() for p in problems)


def test_tier_a_clean_against_baseline(tier_a_counts):
    problems = baseline.compare_tier_a(tier_a_counts, BASELINE)
    assert not problems, "\n".join(p.render() for p in problems)


def test_fixed_hot_path_syncs_stay_fixed(tier_a_counts):
    """The three JL001s fixed in this PR (balanced-bagging int(),
    NDCG/MAP per-bucket float() loops) must not come back — and must
    NOT be pinned in the baseline either."""
    for key in ("JL001:lightgbm_tpu/models/boosting.py:GBDT._bagging_mask",
                "JL001:lightgbm_tpu/models/metric.py:NDCGMetric.eval",
                "JL001:lightgbm_tpu/models/metric.py:MapMetric.eval"):
        assert tier_a_counts.get(key, 0) == 0, key
        assert BASELINE["tier_a"].get(key, 0) == 0, key


# ---------------------------------------------------------------------------
# Negative: injected JL001 in ops/histogram.py fails the check
# ---------------------------------------------------------------------------
def test_injected_host_sync_in_histogram_is_caught():
    path = os.path.join(REPO, "lightgbm_tpu", "ops", "histogram.py")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    bad = src + ("\n\ndef _injected(grad):\n"
                 "    return float(jnp.sum(grad))\n")
    findings = astlint.lint_source(bad, "lightgbm_tpu/ops/histogram.py")
    jl001 = [f for f in findings
             if f.rule == "JL001" and f.func == "_injected"]
    assert jl001, "the injected host sync must be flagged"
    counts = astlint.finding_counts(findings)
    problems = baseline.compare_tier_a(counts, BASELINE)
    assert any(p.kind == "new" and "histogram" in p.key
               for p in problems), problems


def test_clean_histogram_has_no_findings():
    path = os.path.join(REPO, "lightgbm_tpu", "ops", "histogram.py")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    assert astlint.lint_source(src, "lightgbm_tpu/ops/histogram.py") == []


# ---------------------------------------------------------------------------
# Per-rule detection (source snippets under hot-path virtual names)
# ---------------------------------------------------------------------------
def _rules(src, path="lightgbm_tpu/ops/x.py"):
    return sorted({f.rule for f in astlint.lint_source(src, path)})


def test_jl001_item_and_asarray():
    assert _rules("def f(a):\n    return a.item()\n") == ["JL001"]
    assert _rules(
        "import numpy as np, jax.numpy as jnp\n"
        "def f(a):\n    return np.asarray(jnp.exp(a))\n") == ["JL001"]
    assert _rules(
        "import jax\n"
        "def f(xs):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        out.append(jax.device_get(x))\n"
        "    return out\n") == ["JL001"]


def test_jl001_ignores_host_numpy():
    assert _rules(
        "import numpy as np\n"
        "def f(a):\n    return float(np.sum(a))\n") == []


def test_jl001_scoped_to_hot_modules():
    src = "import jax.numpy as jnp\ndef f(a):\n    return float(jnp.sum(a))\n"
    assert _rules(src, "lightgbm_tpu/models/serving.py") == ["JL001"]
    assert _rules(src, "lightgbm_tpu/utils/timer.py") == []


def test_jl002_jit_in_loop_and_immediate():
    assert _rules(
        "import jax\n"
        "def f(xs):\n"
        "    for x in xs:\n"
        "        g = jax.jit(lambda v: v + 1)\n") == ["JL002"]
    assert _rules(
        "import jax\n"
        "def f(x):\n    return jax.jit(lambda v: v + 1)(x)\n") == ["JL002"]


def test_jl002_unhashable_static_arg():
    src = ("import jax\n"
           "import functools\n"
           "@functools.partial(jax.jit, static_argnames=('cfg',))\n"
           "def k(x, cfg=None):\n    return x\n"
           "def f(x):\n    return k(x, cfg=[1, 2])\n")
    assert _rules(src) == ["JL002"]


def test_jl003_f64_outside_x64_scope():
    src = ("import numpy as np, jax.numpy as jnp\n"
           "def f(a):\n    return jnp.asarray(a, dtype=np.float64)\n")
    assert _rules(src) == ["JL003"]
    scoped = ("import jax, numpy as np, jax.numpy as jnp\n"
              "def f(a):\n"
              "    with jax.experimental.enable_x64():\n"
              "        return jnp.asarray(a, dtype=np.float64)\n")
    assert _rules(scoped) == []


def test_jl004_python_sized_carry():
    src = ("import jax\n"
           "def f(n, x):\n"
           "    return jax.lax.fori_loop(0, 8, lambda i, c: c,\n"
           "                             tuple(x for _ in range(n)))\n")
    assert _rules(src) == ["JL004"]
    ok = ("import jax, jax.numpy as jnp\n"
          "def f(x):\n"
          "    return jax.lax.fori_loop(0, 8, lambda i, c: c, (x, x))\n")
    assert _rules(ok) == []


def test_jl005_collective_under_rank_branch():
    src = ("from . import network\n"
           "def f(v):\n"
           "    if network.rank() == 0:\n"
           "        return network.global_sum(v)\n"
           "    return v\n")
    assert _rules(src, "lightgbm_tpu/parallel/x.py") == ["JL005"]
    # the ELSE arm of a rank conditional is entered by exactly the
    # complementary ranks — just as divergent
    in_else = ("from . import network\n"
               "def f(v, is_master):\n"
               "    if is_master:\n"
               "        return v\n"
               "    else:\n"
               "        return network.global_sum(v)\n")
    assert _rules(in_else, "lightgbm_tpu/parallel/x.py") == ["JL005"]
    # uniform conditions (process_count/num_machines) are not divergent
    ok = ("from . import network\n"
          "def f(v):\n"
          "    if network.num_machines() > 1:\n"
          "        return network.global_sum(v)\n"
          "    return v\n")
    assert _rules(ok, "lightgbm_tpu/parallel/x.py") == []


def test_pragma_suppresses():
    src = ("import jax.numpy as jnp\n"
           "def f(a):\n"
           "    return float(jnp.sum(a))  # jaxlint: ok=JL001 one "
           "sync to report the value\n")
    assert _rules(src) == []
    other = ("import jax.numpy as jnp\n"
             "def f(a):\n"
             "    return float(jnp.sum(a))  # jaxlint: ok=JL003\n")
    assert _rules(other) == ["JL001"], "pragma is rule-specific"


# ---------------------------------------------------------------------------
# Tier B budgets (compiles the entry points once, module scope)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tier_b_measured():
    from lightgbm_tpu.analysis import artifacts
    return artifacts.collect_tier_b()


def test_tier_b_budgets_hold(tier_b_measured):
    problems = baseline.compare_tier_b(tier_b_measured, BASELINE)
    assert not problems, "\n".join(p.render() for p in problems)


def test_tier_b_detector_sees_the_subtraction_copies(tier_b_measured):
    """The default path's two contextual hist-state copies must be
    visible to the detector, or the mega zero-copy budget proves
    nothing (mirrors test_hlo_guard.py)."""
    assert tier_b_measured["while_body.default"]["hist_state_copies"] == 2


def test_copy_budget_regression_is_caught(tier_b_measured):
    """Negative: feed the DEFAULT body's real measured counts to the
    MEGA body's zero-copy budget — the comparison must fail, proving a
    reintroduced hist-state carry would be caught."""
    regressed = {"while_body.mega": {
        "hist_state_copies":
            tier_b_measured["while_body.default"]["hist_state_copies"],
        "hist_state_shape_lines": 1,
        "copies": tier_b_measured["while_body.mega"]["copies"],
    }}
    problems = baseline.compare_tier_b(regressed, BASELINE)
    keys = {p.key for p in problems if p.kind == "budget"}
    assert "while_body.mega.hist_state_copies" in keys, problems
    assert "while_body.mega.hist_state_shape_lines" in keys, problems


def test_serving_budget_regression_is_caught():
    """Negative: a retrace per call must breach the serving budget."""
    regressed = {"serving.compiles": {"max_traces_per_bucket": 4,
                                      "buckets_with_retrace": 3}}
    problems = baseline.compare_tier_b(regressed, BASELINE)
    assert any(p.key == "serving.compiles.max_traces_per_bucket"
               and p.kind == "budget" for p in problems), problems


def test_stale_baseline_entry_fails_the_ratchet(tier_a_counts):
    """Fixing a pinned violation must force shrinking the baseline."""
    inflated = dict(BASELINE["tier_a"])
    inflated["JL001:lightgbm_tpu/ops/ghost.py:gone"] = 3
    problems = baseline.compare_tier_a(
        tier_a_counts, {"tier_a": inflated})
    assert any(p.kind == "stale" and "ghost" in p.key for p in problems)


# ---------------------------------------------------------------------------
# CLI contract: --check exit codes and --json line format
# ---------------------------------------------------------------------------
def test_cli_check_and_json():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         "--check", "--tier", "a", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    for ln in lines:
        rec = json.loads(ln)        # one machine-readable line each
        assert rec.get("tier") in ("A", "B") or "problem" in rec


def test_cli_check_fails_against_empty_baseline(tmp_path):
    """--check must exit non-zero when findings exceed the baseline
    (here: an empty one)."""
    bl = tmp_path / "empty_baseline.json"
    bl.write_text('{"version": 1, "tier_a": {}, "tier_b": {}}\n')
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         "--check", "--tier", "a", "--baseline", str(bl)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
