"""LIGHTGBM_TPU_DEBUG=1 invariant lane (analog of the reference's DEBUG
CheckSplit / CheckAllDataInLeaf, serial_tree_learner.h:174-176)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.boosting import debug_validate_record


def test_debug_validate_passes_on_real_trees(rng, monkeypatch):
    import lightgbm_tpu.models.boosting as B
    monkeypatch.setattr(B, "DEBUG_CHECKS", True)
    n = 3000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 + 0.2 * rng.normal(size=n)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    bst._gbdt._flush_pending()      # checks ran during materialization
    assert bst.num_trees() == 5


def test_debug_validate_catches_corruption():
    rec = {
        "node_left": np.asarray([~0, -1]), "node_right": np.asarray([1, ~2]),
        "leaf_value": np.asarray([0.1, 0.2, 0.3]),
        "leaf_start": np.asarray([100, 150, 180]),
        "leaf_cnt": np.asarray([50, 30, 20]),
    }
    rec["node_right"][0] = 1
    rec["node_left"][1] = ~1
    debug_validate_record(rec, 2, 100, 100)      # consistent: passes
    bad = dict(rec)
    bad["leaf_cnt"] = np.asarray([50, 30, 10])   # counts don't sum to N
    with pytest.raises(AssertionError):
        debug_validate_record(bad, 2, 100, 100)
    bad2 = dict(rec)
    bad2["leaf_value"] = np.asarray([0.1, np.nan, 0.3])
    with pytest.raises(AssertionError):
        debug_validate_record(bad2, 2, 100, 100)
