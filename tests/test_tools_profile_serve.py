"""Smoke for tools/profile_serve.py (PR-12 satellite): the serving-
plane load harness runs at tiny sizes, emits parseable JSON with
p50/p99/QPS/shed-rate, proves the concurrent compile-count invariant
in its own output, and drops a valid BENCH_obs v3 artifact whose
fingerprint_extra carries the tenant count + bucket grid.  In-process
to share the session's jit caches (like the other tool smokes)."""

import importlib.util
import json
import os

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "profile_serve", os.path.join(HERE, "tools", "profile_serve.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_profile_serve_smoke(capsys):
    tool = _load_tool()
    rc = tool.main(["--smoke", "--clients", "3", "--requests", "15",
                    "--trees", "4", "--train-rows", "1200"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "serve_load"
    d = payload["detail"]
    assert d["multi_traced"] == {}, f"retrace under load: {d}"
    assert d["served"] == d["submitted"] == 45
    assert d["shed_rate"] == 0.0
    assert d["p50_ms"] >= 0 and d["p99_ms"] >= d["p50_ms"]
    assert d["req_per_s"] > 0
    # coalescing actually happened: fewer dispatches than requests
    assert d["dispatches"] < d["submitted"]
    assert all(v == 1 for v in d["new_traces"].values())

    # BENCH_obs v3 artifact: valid, fingerprinted with the tenant count
    # + bucket grid extra (series identity), serve metrics present
    from lightgbm_tpu.obs import benchio
    with open(benchio.default_path()) as fh:
        doc = json.load(fh)
    assert benchio.validate_bench_obs(doc) == []
    assert doc["tool"] == "profile_serve"
    extra = doc["fingerprint"]["knobs"]["extra"]
    assert extra["tenants"] == 3 and extra["flush_rows"] == 256
    # the trajectory entry landed in the (session-scratch) store with
    # gateable metric names
    from lightgbm_tpu.obs import regress
    entries, _ = regress.read_history()
    mine = [e for e in entries if e["tool"] == "profile_serve"]
    assert mine and {"req_per_s", "p50_ms", "p99_ms",
                     "shed_rate"} <= set(mine[-1]["metrics"])
