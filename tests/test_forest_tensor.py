"""Layered dense traversal (ops/forest_tensor.py + the serving
engine's ``predict_kernel`` knob).

The contract under test: the f32 layered path is BIT-IDENTICAL to the
stacked while-loop oracle (ops/predict.py) — leaves integer-equal,
raw scores byte-equal — across the NaN/missing-default, categorical,
multiclass, iteration-slicing, empty-tree/single-leaf and
quantized-plane matrix; the bf16 leaf plane is a tolerance path; and
the layered pack really is quantized (u8/u16 planes) with no
data-dependent while loop (the jaxlint ``predict.layered`` budget pins
the lowered text; here we pin the semantics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import forest_tensor
from lightgbm_tpu.ops.predict import predict_leaf_binned

BASE = {"verbosity": -1, "min_data_in_leaf": 10, "metric": ""}
N, F = 4500, 8


def _matrix(seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(N, F))
    X[:, 5] = rng.randint(0, 12, size=N)      # categorical column
    X[::7, 2] = np.nan                        # NaN column
    signal = (X[:, 0] * 2 + np.sin(X[:, 1] * 2)
              + np.where(np.isin(X[:, 5], [2, 5, 7]), 1.5, -0.5)
              + np.nan_to_num(X[:, 2]))
    return X, signal


def _train(params, X, y, rounds=8):
    bst = lgb.train(dict(BASE, **params), lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    bst._gbdt._flush_pending()
    return bst


@pytest.fixture(scope="module")
def reg_pair():
    """The same regression forest served by both kernels (training is
    deterministic, so the two boosters hold bit-identical trees)."""
    X, signal = _matrix()
    y = signal + 0.1 * np.random.RandomState(1).normal(size=N)
    Xn = X[:, :5]
    lay = _train({"objective": "regression", "num_leaves": 31,
                  "predict_kernel": "layered"}, Xn, y)
    loop = _train({"objective": "regression", "num_leaves": 31,
                   "predict_kernel": "loop"}, Xn, y)
    return lay, loop, Xn.astype(np.float64)


@pytest.fixture(scope="module")
def cat_pair():
    """Binary + categorical splits + NaN column under both kernels."""
    X, signal = _matrix(11)
    y = (signal > np.quantile(signal, 0.7)).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 31,
         "categorical_feature": [5], "enable_bundle": False}
    lay = _train(dict(p, predict_kernel="layered"), X, y)
    loop = _train(dict(p, predict_kernel="loop"), X, y)
    return lay, loop, X.astype(np.float64)


@pytest.fixture(scope="module")
def mc_pair():
    X, signal = _matrix(13)
    y = np.digitize(signal, np.quantile(signal, [1 / 3, 2 / 3]))
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "categorical_feature": [5], "enable_bundle": False}
    lay = _train(dict(p, predict_kernel="layered"), X, y, rounds=5)
    loop = _train(dict(p, predict_kernel="loop"), X, y, rounds=5)
    return lay, loop, X.astype(np.float64)


# ---------------------------------------------------------------------------
# bit-parity matrix: layered vs loop oracle
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("pair", ["reg", "cat", "mc"])
def test_layered_raw_bit_identical(pair, reg_pair, cat_pair, mc_pair):
    lay, loop, X = {"reg": reg_pair, "cat": cat_pair,
                    "mc": mc_pair}[pair]
    a = np.asarray(lay.predict(X, raw_score=True))
    b = np.asarray(loop.predict(X, raw_score=True))
    assert lay._gbdt.serving._warm("insession"), \
        "layered engine must be serving"
    assert lay._gbdt.serving._kernel_for(
        lay._gbdt.serving._packs["insession"][1]) == "layered"
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("pair", ["reg", "cat", "mc"])
def test_layered_leaves_equal(pair, reg_pair, cat_pair, mc_pair):
    lay, loop, X = {"reg": reg_pair, "cat": cat_pair,
                    "mc": mc_pair}[pair]
    la = np.asarray(lay.predict(X[:700], pred_leaf=True))
    lb = np.asarray(loop.predict(X[:700], pred_leaf=True))
    np.testing.assert_array_equal(la, lb)


def test_layered_slicing_bit_identical(reg_pair):
    lay, loop, X = reg_pair
    for s, m in [(0, 3), (2, 3), (3, -1), (1, 100)]:
        a = np.asarray(lay.predict(X[:300], raw_score=True,
                                   start_iteration=s, num_iteration=m))
        b = np.asarray(loop.predict(X[:300], raw_score=True,
                                    start_iteration=s, num_iteration=m))
        np.testing.assert_array_equal(a, b)


def test_layered_early_stop_bit_identical(cat_pair):
    lay, loop, X = cat_pair
    kw = dict(raw_score=True, pred_early_stop=True,
              pred_early_stop_freq=3, pred_early_stop_margin=2.0)
    np.testing.assert_array_equal(
        np.asarray(lay.predict(X, **kw)),
        np.asarray(loop.predict(X, **kw)))


def test_layered_compile_counts_pinned(reg_pair):
    """The kernel swap must not change the pinned one-trace-per-
    (kind, bucket) contract."""
    lay, _, X = reg_pair
    eng = lay._gbdt.serving
    for n in (700, 600, 900):
        lay.predict(X[:n], raw_score=True)
        lay.predict(X[:n], pred_leaf=True)
    tr = eng.stats()["traces"]
    assert tr[("raw", 1024)] == 1, tr
    assert tr[("leaf", 1024)] == 1, tr


# ---------------------------------------------------------------------------
# kernel-level: quantized planes, empty/single-leaf trees
# ---------------------------------------------------------------------------
def test_quantized_plane_dtypes(reg_pair):
    lay, _, X = reg_pair
    pack = lay._gbdt.serving._packs["insession"][1]
    layers = pack["per_k"][0]["layers"]
    assert layers["flags8"].dtype == jnp.uint8
    assert layers["bins"].dtype == jnp.uint16
    assert layers["kids"].dtype in (jnp.int16, jnp.int32)
    assert pack["layers_depth"] is not None and pack["layers_depth"] > 0


def _stacked_forest_with_empty_tree():
    """Two trees: one real 1-split tree, one ZERO-node (single-leaf)
    tree — the stacked empty-tree guard matrix."""
    T, n = 2, 1
    host = {
        "col": np.zeros((T, n), np.int32),
        "bin_start": np.zeros((T, n), np.int32),
        "is_bundled": np.zeros((T, n), np.int32),
        "num_bin": np.full((T, n), 8, np.int32),
        "default_bin": np.zeros((T, n), np.int32),
        "missing_type": np.zeros((T, n), np.int32),
        "threshold": np.full((T, n), 3, np.int32),
        "default_left": np.zeros((T, n), np.int32),
        "left": np.full((T, n), -1, np.int32),    # ~leaf 0
        "right": np.full((T, n), -2, np.int32),   # ~leaf 1
        "num_nodes": np.asarray([1, 0], np.int32),
    }
    return host


def test_empty_and_single_leaf_trees_match_loop_oracle():
    host = _stacked_forest_with_empty_tree()
    layers = forest_tensor.pack_layered(host)
    assert layers is not None
    depth = layers.pop("max_depth")
    assert depth == 1
    binned = jnp.asarray(
        np.arange(8, dtype=np.int32).reshape(8, 1))     # (n, G=1)
    got = np.asarray(forest_tensor.predict_leaf_layered(
        binned, layers, depth))
    nodes = {k: jnp.asarray(v) for k, v in host.items()}
    want = np.asarray(jax.vmap(
        lambda nd: predict_leaf_binned(binned, nd))(nodes))
    np.testing.assert_array_equal(got, want)
    # the zero-node tree lands every row on leaf 0
    np.testing.assert_array_equal(got[1], np.zeros(8, np.int32))
    # bins 0..3 go left (leaf 0), 4..7 right (leaf 1)
    np.testing.assert_array_equal(got[0],
                                  (np.arange(8) > 3).astype(np.int32))


def test_all_empty_forest_is_leaf_zero():
    host = _stacked_forest_with_empty_tree()
    host["num_nodes"] = np.asarray([0, 0], np.int32)
    layers = forest_tensor.pack_layered(host)
    depth = layers.pop("max_depth")
    assert depth == 0
    binned = jnp.asarray(np.arange(4, dtype=np.int32).reshape(4, 1))
    got = np.asarray(forest_tensor.predict_leaf_layered(
        binned, layers, depth))
    np.testing.assert_array_equal(got, np.zeros((2, 4), np.int32))


def test_overdeep_forest_falls_back_to_loop(monkeypatch, reg_pair):
    """A forest past the unroll ceiling must refuse the layered pack
    (the engine then serves from the loop oracle)."""
    monkeypatch.setattr(forest_tensor, "MAX_UNROLL_DEPTH", 1)
    lay, _, X = reg_pair
    g = lay._gbdt
    eng = g.serving
    host = jax.device_get([(d["nodes"], d["leaf_value"])
                           for d in g.device_trees])
    stacked = {name: np.stack([h[0][name] for h in host])
               for name in host[0][0]}
    assert forest_tensor.pack_layered(stacked) is None
    # a fresh pack built under the ceiling serves loop-side
    eng.invalidate()
    pack = eng._pack("insession", eng._insession_pack)
    assert pack["layers_depth"] is None
    assert eng._kernel_for(pack) == "loop"
    out = lay.predict(X[:300], raw_score=True)
    ref = sum(t.predict(X[:300]) for t in g.models)
    np.testing.assert_allclose(np.asarray(out).reshape(-1), ref,
                               rtol=1e-6, atol=1e-6)
    # restore the layered pack for later tests
    monkeypatch.undo()
    eng.invalidate()
    eng._pack("insession", eng._insession_pack)


# ---------------------------------------------------------------------------
# bf16 leaf plane (opt-in tolerance path)
# ---------------------------------------------------------------------------
def test_bf16_leaf_plane_tolerance():
    X, signal = _matrix(17)
    y = signal + 0.1 * np.random.RandomState(3).normal(size=N)
    Xn = X[:, :5]
    f32 = _train({"objective": "regression", "num_leaves": 15}, Xn, y,
                 rounds=5)
    bf = _train({"objective": "regression", "num_leaves": 15,
                 "predict_bf16_leaves": True}, Xn, y, rounds=5)
    pack = bf._gbdt.serving._packs
    a = np.asarray(f32.predict(Xn, raw_score=True))
    b = np.asarray(bf.predict(Xn, raw_score=True))
    assert bf._gbdt.serving._warm("insession")
    deltas = bf._gbdt.serving._packs["insession"][1]["per_k"][0]["deltas"]
    assert deltas.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits: leaf values are O(1), 5 trees sum
    rel = np.max(np.abs(a - b) / (np.abs(a) + 1e-3))
    assert rel < 0.05, rel
    # leaves (integer traversal) stay exact — only values quantize
    np.testing.assert_array_equal(
        np.asarray(f32.predict(Xn[:200], pred_leaf=True)),
        np.asarray(bf.predict(Xn[:200], pred_leaf=True)))


def test_bf16_refit_keeps_dtype_and_zero_retrace():
    """The leaf-refresh fast path must preserve the bf16 plane dtype
    (an f32 refresh would change dtypes and re-trace)."""
    rng = np.random.RandomState(5)
    X = rng.normal(size=(4500, 5))
    y = X[:, 0] + 0.1 * rng.normal(size=4500)
    bst = _train({"objective": "regression", "num_leaves": 15,
                  "predict_bf16_leaves": True}, X, y, rounds=4)
    g = bst._gbdt
    bst.predict(X, raw_score=True)
    snap = g.serving.trace_snapshot()
    g.apply_refit_leaf_values(
        [np.asarray(t.leaf_value) * 0.5 for t in g.models])
    bst.predict(X, raw_score=True)
    assert g.serving.new_traces_since(snap) == {}, \
        "bf16 refit refresh must not re-trace"
    deltas = g.serving._packs["insession"][1]["per_k"][0]["deltas"]
    assert deltas.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# predict_kernel knob plumbing
# ---------------------------------------------------------------------------
def test_unknown_kernel_rejected():
    rng = np.random.RandomState(9)
    X = rng.normal(size=(4500, 4))
    y = X[:, 0] + 0.1 * rng.normal(size=4500)
    bst = _train({"objective": "regression", "num_leaves": 7,
                  "predict_kernel": "warp"}, X, y, rounds=2)
    with pytest.raises(lgb.LightGBMError, match="predict_kernel"):
        bst.predict(X, raw_score=True)


def test_forced_layered_on_ineligible_forest_warns_and_serves(
        monkeypatch):
    monkeypatch.setattr(forest_tensor, "MAX_UNROLL_DEPTH", 0)
    rng = np.random.RandomState(19)
    X = rng.normal(size=(4500, 4))
    y = X[:, 0] + 0.1 * rng.normal(size=4500)
    bst = _train({"objective": "regression", "num_leaves": 7,
                  "predict_kernel": "layered"}, X, y, rounds=2)
    out = np.asarray(bst.predict(X, raw_score=True))
    assert bst._gbdt.serving._warned_layered
    ref = sum(t.predict(X) for t in bst._gbdt.models)
    np.testing.assert_allclose(out.reshape(-1), ref, rtol=1e-6,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# multi-forest stacking (kernel level; the service path is covered in
# test_predict_engine.py)
# ---------------------------------------------------------------------------
def test_stack_forests_padded_slots_are_noops(reg_pair, mc_pair):
    lay, _, X = reg_pair
    mc, _, Xmc = mc_pair
    packs, deltas = [], []
    for bst in (lay,):
        g = bst._gbdt
        pack = g.serving._pack("insession",
                               g.serving._insession_pack)
        for pk in pack["per_k"]:
            hp = {k: np.asarray(v) for k, v in pk["layers"].items()}
            hp["max_depth"] = pack["layers_depth"]
            packs.append(hp)
            deltas.append(np.asarray(pk["deltas"], np.float32))
    # a second tiny forest forces tree/node padding of the first
    host = _stacked_forest_with_empty_tree()
    tiny = forest_tensor.pack_layered(host)
    td = tiny.pop("max_depth")
    tiny_np = {k: np.asarray(v) for k, v in tiny.items()}
    tiny_np["max_depth"] = td
    packs.append(tiny_np)
    deltas.append(np.asarray([[0.5, -0.5], [2.0, 0.0]], np.float32))
    stacked = forest_tensor.stack_forests(packs, deltas)
    assert stacked is not None
    depth = stacked.pop("max_depth")
    g = lay._gbdt
    binned0 = np.asarray(g.serving._bin(X[:64], False))
    G_max = max(binned0.shape[1], 1)
    binned_f = np.zeros((2, 64, G_max), binned0.dtype)
    binned_f[0, :, :binned0.shape[1]] = binned0
    binned_f[1, :, 0] = np.arange(64) % 8
    out = np.asarray(forest_tensor.predict_raw_layered_forests(
        jnp.asarray(binned_f), stacked, stacked["tree_mask"], depth))
    ref0 = np.asarray(lay.predict(X[:64], raw_score=True)) \
        - g.init_scores[0]
    np.testing.assert_allclose(out[0], ref0, rtol=0, atol=1e-6)
    bins = np.arange(64) % 8
    ref1 = np.where(bins > 3, -0.5, 0.5) + 2.0
    np.testing.assert_allclose(out[1], ref1, rtol=0, atol=0)


def test_loop_kernel_skips_layered_plane_build(reg_pair):
    """predict_kernel=loop must not build (or upload) layered planes
    the forced oracle can never read — they are ~45% extra resident
    pack bytes per model."""
    _, loop, X = reg_pair
    loop.predict(X, raw_score=True)            # warm: pack builds
    pack = loop._gbdt.serving._packs["insession"][1]
    assert pack["layers_depth"] is None
    assert all(pk["layers"] is None for pk in pack["per_k"])
