"""Cross-feature combination coverage (VERDICT r2 weak #9: the thin
spots that bite next are untested combinations)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def test_categorical_x_distributed(rng):
    """Categorical splits under the data-parallel learner must match the
    serial learner on the 8-device virtual mesh."""
    n = 4000
    Xc = rng.randint(0, 8, size=(n, 2)).astype(float)
    Xn = rng.normal(size=(n, 3))
    X = np.column_stack([Xc, Xn])
    y = (Xc[:, 0] == 3) * 2.0 + Xn[:, 0] + 0.1 * rng.normal(size=n)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 20, "metric": "",
            "min_data_per_group": 5}
    serial = lgb.train(base, lgb.Dataset(
        X, label=y, categorical_feature=[0, 1]), num_boost_round=8)
    dist = lgb.train(dict(base, tree_learner="data"), lgb.Dataset(
        X, label=y, categorical_feature=[0, 1]), num_boost_round=8)
    np.testing.assert_allclose(serial.predict(X[:500]),
                               dist.predict(X[:500]), rtol=1e-4, atol=1e-5)


def test_quantized_x_dart(rng):
    n = 3000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.2 * rng.normal(size=n)
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "num_leaves": 15, "verbosity": -1, "drop_rate": 0.3,
                     "use_quantized_grad": True, "num_grad_quant_bins": 8,
                     "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    p = bst.predict(X)
    assert np.isfinite(p).all()
    mse0 = float(np.mean((y - y.mean()) ** 2))
    assert float(np.mean((y - p) ** 2)) < 0.6 * mse0


def test_forced_splits_x_monotone(rng, tmp_path):
    n = 3000
    X = rng.normal(size=(n, 4))
    y = 2 * X[:, 0] + np.sin(2 * X[:, 1]) + 0.1 * rng.normal(size=n)
    forced = tmp_path / "forced.json"
    forced.write_text(json.dumps(
        {"feature": 1, "threshold": 0.0,
         "left": {"feature": 1, "threshold": -1.0}}))
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "metric": "",
                     "monotone_constraints": "1,0,0,0",
                     "monotone_constraints_method": "intermediate",
                     "forcedsplits_filename": str(forced)},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    # the forced root split on feature 1 actually happened
    d = bst.dump_model()
    root = d["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 1
    assert abs(root["threshold"]) < 0.25      # binned upper of 0.0
    # monotonicity along feature 0 holds
    probe = np.zeros((50, 4))
    probe[:, 0] = np.linspace(-2, 2, 50)
    p = bst.predict(probe)
    assert np.all(np.diff(p) >= -1e-6)


@pytest.mark.slow  # 9.5 s: tier-1 window trim (PR 14, per
# test_durations) — continuation-x-valid keeps its fast in-window
# representative in test_continuation_x_dart_x_valid; multiclass
# training rides test_fused_multiclass.py
def test_continuation_x_multiclass_x_valid(rng):
    n = 3000
    X = rng.normal(size=(n, 6))
    y = rng.randint(0, 3, size=n).astype(float)
    X[np.arange(n), y.astype(int)] += 2.0
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
              "verbosity": -1, "metric": "multi_logloss"}
    ev1 = {}
    first = lgb.train(params, lgb.Dataset(X[:2000], label=y[:2000]),
                      num_boost_round=5,
                      valid_sets=[lgb.Dataset(X[2000:], label=y[2000:])],
                      valid_names=["v"],
                      callbacks=[lgb.record_evaluation(ev1)])
    ev2 = {}
    cont = lgb.train(params, lgb.Dataset(X[:2000], label=y[:2000]),
                     num_boost_round=5, init_model=first,
                     valid_sets=[lgb.Dataset(X[2000:], label=y[2000:])],
                     valid_names=["v"],
                     callbacks=[lgb.record_evaluation(ev2)])
    assert cont.num_trees() == 30            # 10 iterations x 3 classes
    # the continued run keeps improving the valid metric
    assert ev2["v"]["multi_logloss"][-1] < ev1["v"]["multi_logloss"][-1]
    p = cont.predict(X[2000:])
    assert p.shape == (1000, 3)
    acc = float((np.argmax(p, axis=1) == y[2000:]).mean())
    assert acc > 0.7


def test_efb_x_distributed(rng):
    """EFB-bundled sparse features under the mesh data-parallel learner
    must match the serial learner (round-3's categorical x sharded bug
    class: combinations are where bugs land)."""
    n = 4000
    # mutually-exclusive sparse columns (exactly one nonzero per row):
    # EFB's zero-conflict rule bundles them into one group
    # low-cardinality values keep each feature's bin count small enough
    # for the 256-bin-per-group cap the TPU layout imposes on bundles
    Xs = np.zeros((n, 4))
    kcol = rng.randint(0, 4, size=n)
    Xs[np.arange(n), kcol] = rng.randint(1, 40, size=n).astype(float)
    Xd = rng.normal(size=(n, 2))
    X = np.column_stack([Xs, Xd])
    y = Xs[:, 0] * 2.0 + Xd[:, 0] + 0.1 * rng.normal(size=n)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 20, "metric": ""}
    ds = lgb.Dataset(X, label=y)
    ds.construct(base)
    assert any(len(g.feature_indices) > 1 for g in ds._inner.groups), \
        "fixture must actually bundle"
    serial = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=8)
    dist = lgb.train(dict(base, tree_learner="data"),
                     lgb.Dataset(X, label=y), num_boost_round=8)
    np.testing.assert_allclose(serial.predict(X[:500]),
                               dist.predict(X[:500]), rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # 9.1 s: tier-1 window trim (PR 14) — voting
# keeps fast in-window lanes in test_parallel.py, quantized in
# test_quantized.py; the cross combination stays covered here slow
def test_voting_x_quantized(rng):
    n = 4000
    X = rng.normal(size=(n, 8))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 2) + 0.2 * rng.normal(size=n)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "use_quantized_grad": True, "num_grad_quant_bins": 16,
            "metric": ""}
    serial = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    voting = lgb.train(dict(base, tree_learner="voting", top_k=4),
                       lgb.Dataset(X, label=y), num_boost_round=10)
    # voting elects a feature subset per leaf, so trees may differ from
    # serial; quality must stay comparable
    mse_s = float(np.mean((serial.predict(X) - y) ** 2))
    mse_v = float(np.mean((voting.predict(X) - y) ** 2))
    assert mse_v < max(2.0 * mse_s, 0.3 * np.var(y))


def test_forced_splits_x_categorical(rng, tmp_path):
    n = 3000
    Xc = rng.randint(0, 6, size=n).astype(float)
    Xn = rng.normal(size=(n, 3))
    X = np.column_stack([Xc, Xn])
    y = ((Xc == 2) | (Xc == 4)) * 2.0 + Xn[:, 0] + 0.1 * rng.normal(size=n)
    forced = {"feature": 1, "threshold": 0.0}
    fp = tmp_path / "forced.json"
    fp.write_text(json.dumps(forced))
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 10,
                     "min_data_per_group": 5, "metric": "",
                     "forcedsplits_filename": str(fp)},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=8)
    model = bst.dump_model()
    cats = 0
    for t in model["tree_info"]:
        root = t["tree_structure"]
        assert root["split_feature"] == 1          # forced root
        def walk(node):
            nonlocal cats
            if "split_feature" in node:
                if node.get("decision_type") == "==":
                    cats += 1
                walk(node["left_child"]); walk(node["right_child"])
        walk(root)
    assert cats > 0, "categorical splits must appear under the forced root"
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.3 * np.var(y)


def test_continuation_x_dart_x_valid(rng, tmp_path):
    """init_model continuation of a DART model with a valid set: the
    continued booster must extend the loaded trees, keep evaluating the
    valid set, and improve on it."""
    n = 3000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.2 * rng.normal(size=n)
    Xv = rng.normal(size=(800, 6))
    yv = Xv[:, 0] * 2 + np.sin(Xv[:, 1]) + 0.2 * rng.normal(size=800)
    params = {"objective": "regression", "boosting": "dart",
              "num_leaves": 15, "verbosity": -1, "drop_rate": 0.2,
              "metric": "l2"}
    ds = lgb.Dataset(X, label=y)
    b1 = lgb.train(params, ds, num_boost_round=8)
    mpath = tmp_path / "dart.txt"
    b1.save_model(str(mpath))
    evals = {}
    from lightgbm_tpu.callback import record_evaluation
    ds2 = lgb.Dataset(X, label=y)
    vs = lgb.Dataset(Xv, label=yv, reference=ds2)
    b2 = lgb.train(params, ds2, num_boost_round=8,
                   valid_sets=[vs], valid_names=["v"],
                   init_model=str(mpath),
                   callbacks=[record_evaluation(evals)])
    assert b2.num_trees() > b1.num_trees()
    curve = evals["v"]["l2"]
    assert len(curve) == 8
    mse_cont = float(np.mean((b2.predict(Xv) - yv) ** 2))
    mse_init = float(np.mean((b1.predict(Xv) - yv) ** 2))
    assert mse_cont <= mse_init * 1.05
