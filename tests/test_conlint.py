"""Tier-1 gate for jaxlint tier C (lightgbm_tpu/analysis/conlint.py +
analysis/schedule.py, tools/jaxlint.py --tier c).

Static direction: the threaded planes must be CLEAN against the
committed ``tier_c`` baseline table (goal state: empty — every
surviving site pragma-documented in code), and each rule CL001–CL004
must actually fire on an injected violation (fixture modules below),
including through the subprocess rc contract.

Dynamic direction: the seeded cooperative schedule explorer must
(a) reproduce the pre-fix torn-read shape on an UNFIXED fixture — the
regression-test form of the ServingService.stats()/counter races fixed
in this PR — and never on the fixed twin, (b) reproduce a 2-cycle
lock-order inversion as a deterministic deadlock, (c) validate the
continual runtime's "done flips LAST" handoff protocol (runtime.py's
background retrain holder) by provoking the inverted write order, and
(d) run the three real serving-plane drills deterministically: same
seed, byte-identical report.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)

from lightgbm_tpu.analysis import baseline, conlint  # noqa: E402
from lightgbm_tpu.analysis.schedule import (  # noqa: E402
    SCHEDULE_SCENARIOS, Scheduler, instrument_service, report_bytes,
    run_schedule_drill)

BASELINE = baseline.load(os.path.join(REPO, "jaxlint_baseline.json"))


# ---------------------------------------------------------------------------
# static half: the repo vs the committed ratchet
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tier_c_counts():
    return conlint.finding_counts(conlint.lint_tree(REPO))


def test_tier_c_baseline_is_committed():
    assert BASELINE.get("tier_c") is not None, \
        "jaxlint_baseline.json must carry the tier_c table"


def test_tier_c_clean_against_baseline(tier_c_counts):
    problems = baseline.compare_tier_c(tier_c_counts, BASELINE)
    assert not problems, "\n".join(p.render() for p in problems)


def test_fixed_serving_races_stay_fixed(tier_c_counts):
    """The CL001s fixed in this PR (lock-free counter writes on the
    dispatch path, the lock-free stats() publish) must not come back —
    and must NOT be pinned in the baseline either."""
    for qual in ("ServingService.submit", "ServingService.stats",
                 "ServingService._dispatch", "ServingService._complete",
                 "ServingService._fail_all"):
        key = f"CL001:lightgbm_tpu/serving/service.py:{qual}"
        assert tier_c_counts.get(key, 0) == 0, key
        assert BASELINE["tier_c"].get(key, 0) == 0, key


# ---------------------------------------------------------------------------
# static half: each rule fires on an injected violation
# ---------------------------------------------------------------------------
FX_PATH = "lightgbm_tpu/serving/_fixture.py"

FX_CL001 = '''
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {"a": 0}

    def hit(self):
        with self._lock:
            self.counters["a"] += 1

    def leak(self):
        self.counters["a"] += 1

    def stats(self):
        return dict(self.counters)
'''

FX_CL002 = '''
import threading

class AB:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def xy(self):
        with self._x:
            with self._y:
                pass

    def yx(self):
        with self._y:
            with self._x:
                pass
'''

FX_CL003 = '''
import time
import threading

class Stopper:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = None

    def stop(self):
        with self._lock:
            time.sleep(0.1)
            self._worker.join()
'''

FX_CL004 = '''
import threading

class Waiter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def take(self):
        with self._cv:
            self._cv.wait()
'''


def _rules(findings):
    return sorted({f.rule for f in findings})


def test_cl001_unguarded_write_and_publish_fire():
    fs = conlint.lint_source(FX_CL001, FX_PATH)
    assert _rules(fs) == ["CL001"]
    quals = sorted(f.func for f in fs)
    assert quals == ["Svc.leak", "Svc.stats"], quals
    kinds = {f.func: f.message for f in fs}
    assert "written" in kinds["Svc.leak"]
    assert "aggregate read" in kinds["Svc.stats"]


def test_cl002_two_cycle_inversion_fires():
    fs = conlint.lint_source(FX_CL002, FX_PATH)
    assert _rules(fs) == ["CL002"]
    # one finding per edge of the cycle
    assert len(fs) == 2
    assert {f.func for f in fs} == {"AB.xy", "AB.yx"}


def test_cl002_cross_class_cycle_fires():
    """service->registry->service through annotated attr types: the
    cross-class edge construction the real serving plane relies on."""
    src = '''
import threading

class Registry:
    def __init__(self, svc: Service):
        self._rlock = threading.Lock()
        self.svc = svc

    def publish(self):
        with self._rlock:
            self.svc.poke()

class Service:
    def __init__(self, registry: Registry):
        self._slock = threading.Lock()
        self.registry = registry

    def poke(self):
        with self._slock:
            pass

    def pump(self):
        with self._slock:
            self.registry.publish()
'''
    fs = conlint.lint_source(src, FX_PATH)
    assert "CL002" in _rules(fs), [f.render() for f in fs]


def test_cl003_blocking_under_lock_fires():
    fs = conlint.lint_source(FX_CL003, FX_PATH)
    assert _rules(fs) == ["CL003"]
    whats = sorted(f.message for f in fs)
    assert len(fs) == 2                  # sleep + join
    assert any("time.sleep" in w for w in whats)
    assert any(".join()" in w for w in whats)


def test_cl004_predicate_free_wait_fires():
    fs = conlint.lint_source(FX_CL004, FX_PATH)
    assert _rules(fs) == ["CL004"]
    # and the repaired form — wait inside a while — is clean
    fixed = FX_CL004.replace(
        "        with self._cv:\n            self._cv.wait()",
        "        with self._cv:\n            while self._worker:\n"
        "                self._cv.wait()")
    assert conlint.lint_source(fixed, FX_PATH) == []


def test_pragma_suppresses_exactly_its_rule():
    ok = FX_CL001.replace("return dict(self.counters)",
                          "return dict(self.counters)  # conlint: ok=CL001")
    fs = conlint.lint_source(ok, FX_PATH)
    assert sorted(f.func for f in fs) == ["Svc.leak"]
    # a pragma for a DIFFERENT rule must not suppress
    other = FX_CL001.replace("return dict(self.counters)",
                             "return dict(self.counters)  # conlint: ok=CL003")
    fs = conlint.lint_source(other, FX_PATH)
    assert "Svc.stats" in {f.func for f in fs}


def test_out_of_scope_paths_are_skipped():
    assert conlint.lint_source(FX_CL001,
                               "lightgbm_tpu/models/metric.py") == []


def test_caller_holds_lock_inheritance_stays_quiet():
    """Telemetry._event's contract: a private method whose every call
    site holds the lock is analyzed as holding it — no pragma needed."""
    src = '''
import threading

class Tel:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def record(self, ev):
        with self._lock:
            self._event(ev)

    def instant(self, ev):
        with self._lock:
            self._event(ev)

    def _event(self, ev):
        self.events.append(ev)
'''
    assert conlint.lint_source(src, FX_PATH) == []


# ---------------------------------------------------------------------------
# ratchet semantics
# ---------------------------------------------------------------------------
def test_ratchet_fails_on_new_and_stale_pins():
    measured = conlint.finding_counts(
        conlint.lint_source(FX_CL001, FX_PATH))
    # new finding vs an empty table
    probs = baseline.compare_tier_c(measured, {"tier_c": {}})
    assert probs and all(p.kind == "new" for p in probs)
    # exact pin: clean
    assert baseline.compare_tier_c(measured, {"tier_c": dict(measured)}) \
        == []
    # stale pin: a ghost key that no longer measures fails too
    stale = dict(measured)
    stale["CL001:lightgbm_tpu/serving/ghost.py:Ghost.stats"] = 1
    probs = baseline.compare_tier_c(measured, {"tier_c": stale})
    assert [p.kind for p in probs] == ["stale"]


# ---------------------------------------------------------------------------
# subprocess rc contract
# ---------------------------------------------------------------------------
def _jaxlint(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         *argv],
        capture_output=True, text=True, timeout=300)


def test_cli_tier_c_clean_on_repo():
    r = _jaxlint("--tier", "c", "--check")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_tier_c_fails_on_injected_fixture_tree(tmp_path):
    pkg = tmp_path / "lightgbm_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        FX_CL001 + FX_CL002 + FX_CL003 + FX_CL004)
    r = _jaxlint("--tier", "c", "--check", "--json",
                 "--root", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    recs = [json.loads(ln) for ln in r.stdout.splitlines() if ln]
    rules = {rec["rule"] for rec in recs if rec.get("tier") == "C"}
    assert rules == {"CL001", "CL002", "CL003", "CL004"}, rules
    assert all(rec.get("tier") == "C" or "problem" in rec
               for rec in recs)


def test_cli_tier_c_fails_on_stale_pin(tmp_path):
    bl = {"version": 1, "tier_a": {}, "tier_b": {}, "tier_c":
          {"CL001:lightgbm_tpu/serving/ghost.py:Ghost.stats": 1}}
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(bl))
    r = _jaxlint("--tier", "c", "--check", "--baseline", str(path))
    assert r.returncode == 1
    assert "stale" in r.stdout


# ---------------------------------------------------------------------------
# dynamic half: scheduler fixtures (regression form of the fixed races)
# ---------------------------------------------------------------------------
FX_TORN = '''
import threading

class MiniService:
    """The pre-fix ServingService shape: counters written lock-free on
    the serve path, published lock-free by stats()."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {"submitted": 0, "served": 0}

    def reset(self):
        with self._lock:
            self.counters = {"submitted": 0, "served": 0}

    def tick(self):
        self.counters["submitted"] += 1
        self.counters["served"] += 1

    def stats(self):
        return dict(self.counters)
'''

FX_TORN_FIXED = '''
import threading

class MiniService:
    """The post-fix shape: every write and the publish hold the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {"submitted": 0, "served": 0}

    def reset(self):
        with self._lock:
            self.counters = {"submitted": 0, "served": 0}

    def tick(self):
        with self._lock:
            self.counters["submitted"] += 1
            self.counters["served"] += 1

    def stats(self):
        with self._lock:
            return dict(self.counters)
'''


def _mini(src, filename):
    ns = {}
    exec(compile(src, filename, "exec"), ns)   # noqa: S102 — fixture
    return ns["MiniService"]()


def _run_torn(src, seed, filename):
    """One seeded run: a writer ticking the invariant-coupled counter
    pair against an atomic reader; returns (torn, schedule)."""
    findings = conlint.lint_source(src, FX_PATH)
    sched = Scheduler(seed=seed)
    svc = _mini(src, filename)
    svc._lock = sched.lock("mini._lock")
    seen = []

    def writer():
        for _ in range(3):
            svc.tick()

    def reader():
        for _ in range(2):
            seen.append(svc.stats())

    sched.spawn("writer", writer)
    sched.spawn("reader", reader)
    sched.watch_findings(findings, filename)
    sched.run()
    sched.check()
    torn = any(s["submitted"] != s["served"] for s in seen)
    return torn, list(sched.schedule)


def test_explorer_reproduces_prefix_torn_read():
    """The static pass finds the CL001 lines, the explorer interleaves
    at exactly those lines, and SOME seed exposes the torn pair — on
    the unfixed fixture only.  This is the regression test for the
    stats()/counter races fixed in this PR."""
    findings = conlint.lint_source(FX_TORN, FX_PATH)
    assert {f.rule for f in findings} == {"CL001"}, \
        [f.render() for f in findings]
    seeds = range(30)
    torn_seeds = [s for s in seeds
                  if _run_torn(FX_TORN, s, "<fx-torn>")[0]]
    assert torn_seeds, "no seed in range(30) provoked the torn read"
    # the fixed twin is CL001-clean AND never torn on the same seeds
    assert conlint.lint_source(FX_TORN_FIXED, FX_PATH) == []
    for s in torn_seeds[:5]:
        torn, _ = _run_torn(FX_TORN_FIXED, s, "<fx-torn-fixed>")
        assert not torn, f"fixed fixture torn at seed {s}"
    # determinism: the provoking seed replays the identical schedule
    s = torn_seeds[0]
    a = _run_torn(FX_TORN, s, "<fx-torn>")
    b = _run_torn(FX_TORN, s, "<fx-torn>")
    assert a == b


def _run_inversion(seed):
    sched = Scheduler(seed=seed)
    x = sched.lock("X")
    y = sched.lock("Y")

    def xy():
        with x:
            with y:
                pass

    def yx():
        with y:
            with x:
                pass

    sched.spawn("xy", xy)
    sched.spawn("yx", yx)
    sched.run()
    return sched


def test_explorer_reproduces_lock_order_inversion_deadlock():
    """The dynamic form of CL002: opposite acquisition order deadlocks
    under some schedule, deterministically per seed."""
    dead = [s for s in range(20)
            if _run_inversion(s).deadlock is not None]
    assert dead, "no seed in range(20) deadlocked the 2-cycle"
    s = dead[0]
    a, b = _run_inversion(s), _run_inversion(s)
    assert a.deadlock == b.deadlock
    assert a.schedule == b.schedule
    # the deadlock report names both locks (the wait-for cycle)
    assert set(a.deadlock["blocked"].values()) == {"X", "Y"}


FX_HANDOFF = '''
class Handoff:
    """Replica of continual/runtime.py's background-retrain holder
    protocol: one writer, lock-free dict stores, done flips LAST."""

    def __init__(self):
        self.holder = {"done": False}

    def worker_good(self):
        self.holder["result"] = 42
        self.holder["done"] = True

    def worker_bad(self):
        self.holder["done"] = True
        self.holder["result"] = 42

    def poll(self):
        if self.holder.get("done"):
            return self.holder.get("result")
        return "pending"
'''


def _run_handoff(worker_name, seed):
    filename = f"<fx-handoff-{worker_name}>"
    ns = {}
    exec(compile(FX_HANDOFF, filename, "exec"), ns)  # noqa: S102
    h = ns["Handoff"]()
    sched = Scheduler(seed=seed)
    lines = [i for i, ln in enumerate(FX_HANDOFF.splitlines(), 1)
             if 'self.holder["' in ln]
    sched.watch_lines(filename, lines)
    polled = []

    def poller():
        for _ in range(4):
            polled.append(h.poll())

    sched.spawn("worker", getattr(h, worker_name))
    sched.spawn("poller", poller)
    sched.run()
    sched.check()
    return polled


def test_handoff_done_flips_last_protocol():
    """runtime.py:~548's documented invariant, replayed under permuted
    interleavings: writing done BEFORE result lets a poll read a
    missing result; the real order never does."""
    bad_seeds = [s for s in range(30)
                 if None in _run_handoff("worker_bad", s)]
    assert bad_seeds, "inverted write order never produced a torn poll"
    for s in bad_seeds[:5]:
        got = _run_handoff("worker_good", s)
        assert None not in got, (s, got)
        assert all(g in ("pending", 42) for g in got)


# ---------------------------------------------------------------------------
# dynamic half: real serving-plane drills
# ---------------------------------------------------------------------------
def test_schedule_drills_fixed_seed():
    for scenario in SCHEDULE_SCENARIOS:
        rep = run_schedule_drill(scenario, seed=1)
        assert rep["deadlock"] is None
        assert all(m in ("v1", "v2") for m in rep["matched"]), rep


def test_schedule_drill_byte_identical_reports():
    a = run_schedule_drill("publish_pump", seed=3)
    b = run_schedule_drill("publish_pump", seed=3)
    assert report_bytes(a) == report_bytes(b)


@pytest.mark.slow
def test_schedule_drill_seed_sweep():
    """Wider interleaving search (out of the tier-1 window): every
    scenario, many seeds, every invariant asserted inside the drill."""
    for scenario in SCHEDULE_SCENARIOS:
        for seed in range(12):
            rep = run_schedule_drill(scenario, seed=seed)
            assert rep["deadlock"] is None
