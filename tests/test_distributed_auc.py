"""Exact-global distributed AUC (PR-3, round-6 verdict ask #5).

``distributed_exact_auc=true`` gathers (score, label, weight) rows
across ranks and evaluates the tie-aware AUC over the full dataset —
exact under data-parallel row sharding, where the default per-rank
weighted mean (metric.py _rank_mean) is an explicit approximation.

The 8-rank group is emulated over the suite's 8 virtual devices by
sharding one dataset 8 ways and faking the network facade's
num_machines/global_concat with the full shard set, mirroring how
rank-sharded metrics see their local rows."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.models import metric as metric_mod
from lightgbm_tpu.models.metric import AUCMetric, _weighted_auc
from lightgbm_tpu.parallel import network
from lightgbm_tpu.parallel.distributed import rank_shard_indices

N_RANKS = 8


def _make(rng, n=4003, weighted=True):
    score = rng.normal(size=n)
    label = (rng.rand(n) < 1 / (1 + np.exp(-score
                                           + 0.5 * rng.normal(size=n)))
             ).astype(np.float64)
    # duplicate scores exercise the tie-handling arm
    score[:n // 10] = np.round(score[:n // 10], 1)
    weight = rng.uniform(0.1, 3.0, size=n) if weighted else None
    if weight is not None:
        # Metadata stores weights as f32 (reference label_t); the
        # exactness claim is vs single-device eval of the SAME stored
        # data, so quantize the fixture identically
        weight = weight.astype(np.float32).astype(np.float64)
    return score, label, weight


def _fake_network(monkeypatch, shards):
    """Patch the facade: 8 machines; global_concat returns the full
    concatenation by matching the caller's local shard."""
    monkeypatch.setattr(network, "num_machines", lambda: N_RANKS)

    def fake_concat(local):
        local = np.asarray(local)
        for quantity in shards.values():
            for piece in quantity:
                if piece.shape == local.shape and np.array_equal(
                        piece, local, equal_nan=True):
                    return np.concatenate(quantity, axis=0)
        raise AssertionError("global_concat got an unknown shard")

    monkeypatch.setattr(network, "global_concat", fake_concat)
    # the default path's weighted mean uses global_sum over pairs
    monkeypatch.setattr(
        network, "global_sum",
        lambda vals: np.asarray(vals, dtype=np.float64) * N_RANKS)


@pytest.mark.parametrize("weighted", [False, True])
def test_exact_auc_equals_single_device(rng, monkeypatch, weighted):
    import jax
    import jax.numpy as jnp
    score, label, weight = _make(rng, weighted=weighted)
    # the f64 single-device reference — the metric's exact path also
    # evaluates under x64 (f32 cumsums would void the 1e-12 claim)
    with jax.experimental.enable_x64():
        exact_single = float(_weighted_auc(
            jnp.asarray(score), jnp.asarray(label),
            jnp.asarray(weight) if weight is not None else None))

    idx = [rank_shard_indices(len(score), r, N_RANKS)
           for r in range(N_RANKS)]
    shards = {
        "score": [score[i] for i in idx],
        "label": [label[i] for i in idx],
        "weight": [(weight[i] if weight is not None
                    else np.ones(len(i))) for i in idx],
    }
    _fake_network(monkeypatch, shards)
    cfg = Config({"objective": "binary", "metric": "auc",
                  "distributed_exact_auc": True})
    per_rank = []
    for r in range(N_RANKS):
        m = AUCMetric(cfg)
        meta = Metadata(len(idx[r]))
        meta.set_label(label[idx[r]])
        if weight is not None:
            meta.set_weight(weight[idx[r]])
        m.init(meta)
        (_, val), = m.eval(score[idx[r]], None)
        per_rank.append(val)
    # every rank reports the SAME value, equal to single-device exact
    assert max(per_rank) - min(per_rank) < 1e-15
    assert abs(per_rank[0] - exact_single) < 1e-12


def test_default_stays_warned_weighted_mean(rng, monkeypatch):
    """Without the option the approximation (with its one-time warning)
    is unchanged — per-rank AUC weighted by sum_weight."""
    score, label, _ = _make(rng, n=1600, weighted=False)
    idx = [rank_shard_indices(len(score), r, N_RANKS)
           for r in range(N_RANKS)]
    shards = {"score": [score[i] for i in idx],
              "label": [label[i] for i in idx],
              "weight": [np.ones(len(i)) for i in idx]}
    _fake_network(monkeypatch, shards)
    monkeypatch.setattr(metric_mod, "_RANK_MEAN_WARNED", False)
    cfg = Config({"objective": "binary", "metric": "auc"})
    m = AUCMetric(cfg)
    meta = Metadata(len(idx[0]))
    meta.set_label(label[idx[0]])
    m.init(meta)
    import jax.numpy as jnp
    (_, val), = m.eval(score[idx[0]], None)
    local = float(_weighted_auc(jnp.asarray(score[idx[0]]),
                                jnp.asarray(label[idx[0]]), None))
    # the fake global_sum scales num and den alike -> rank-0 mean
    # equals its local AUC here; the point is the exact path was NOT
    # taken and the approximation warning fired
    assert abs(val - local) < 1e-12
    assert metric_mod._RANK_MEAN_WARNED


def test_global_concat_single_process_identity(rng):
    x = rng.normal(size=(17, 2))
    np.testing.assert_array_equal(network.global_concat(x), x)
