"""Arrow ingestion tests (reference model:
tests/python_package_test/test_arrow.py).

pyarrow is not bundled in every image, so there are two lanes:
  * real-pyarrow tests, skipped when pyarrow is unavailable;
  * duck-typed stand-in objects that exercise the same detection and
    conversion paths `lightgbm_tpu.basic` uses for arrow data.
"""

import sys
import types

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import (_arrow_1d_to_numpy, _arrow_table_to_matrix,
                                _is_arrow, _to_matrix)

try:
    import pyarrow as pa
    HAS_PA = True
except ImportError:
    pa = None
    HAS_PA = False


# ---------------------------------------------------------------------------
# duck-typed stand-ins living in a fake "pyarrow" module namespace
# ---------------------------------------------------------------------------

class _FakeColumn:
    __module__ = "pyarrow.lib"

    def __init__(self, values):
        self._v = np.asarray(values, dtype=np.float64)

    def cast(self, *_a, **_k):
        raise RuntimeError("no real pyarrow")   # force the to_pandas branch

    def to_pandas(self):
        return self._v


class _FakeTable:
    __module__ = "pyarrow.lib"

    def __init__(self, cols, names):
        self._cols = [_FakeColumn(c) for c in cols]
        self.column_names = list(names)

    def column(self, i):
        return self._cols[i]


def _make_data(n=400, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def test_fake_arrow_detection():
    X, y = _make_data()
    t = _FakeTable([X[:, i] for i in range(4)], ["a", "b", "c", "d"])
    assert _is_arrow(t)
    assert not _is_arrow(X)
    mat, names = _arrow_table_to_matrix(t)
    np.testing.assert_allclose(mat, X)
    assert names == ["a", "b", "c", "d"]
    np.testing.assert_allclose(_arrow_1d_to_numpy(_FakeColumn(y)), y)
    np.testing.assert_allclose(_to_matrix(t), X)


def test_fake_arrow_train_predict():
    X, y = _make_data()
    t = _FakeTable([X[:, i] for i in range(4)], ["f1", "f2", "f3", "f4"])
    ds = lgb.Dataset(t, label=_FakeColumn(y))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=10)
    assert bst.feature_name() == ["f1", "f2", "f3", "f4"]
    pred_arrow = bst.predict(_FakeTable([X[:, i] for i in range(4)],
                                        ["f1", "f2", "f3", "f4"]))
    pred_np = bst.predict(X)
    np.testing.assert_allclose(pred_arrow, pred_np)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, pred_np) > 0.85


@pytest.mark.skipif(not HAS_PA, reason="pyarrow not installed")
def test_real_arrow_train():
    X, y = _make_data()
    table = pa.table({f"f{i}": X[:, i] for i in range(4)})
    ds = lgb.Dataset(table, label=pa.chunked_array([y]))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=10)
    np.testing.assert_allclose(bst.predict(table), bst.predict(X))
