"""Binning unit tests vs NumPy oracles (reference semantics: src/io/bin.cpp)."""

import numpy as np
import pytest

from lightgbm_tpu.ops.binning import (BIN_CATEGORICAL, MISSING_NAN,
                                      MISSING_NONE, MISSING_ZERO, BinMapper,
                                      greedy_find_bin)


def test_greedy_find_bin_few_distinct():
    vals = [1.0, 2.0, 3.0]
    counts = [10, 10, 10]
    bounds = greedy_find_bin(vals, counts, 10, 30, 1)
    assert bounds[-1] == np.inf
    assert len(bounds) == 3
    # boundaries at midpoints
    assert 1.0 < bounds[0] < 2.0
    assert 2.0 < bounds[1] < 3.0


def test_greedy_find_bin_min_data():
    vals = [1.0, 2.0, 3.0, 4.0]
    counts = [1, 1, 1, 100]
    bounds = greedy_find_bin(vals, counts, 10, 103, 3)
    # first boundary only after accumulating >= 3
    assert len(bounds) == 2


def test_bin_mapper_roundtrip():
    rng = np.random.RandomState(0)
    vals = rng.normal(size=5000)
    bm = BinMapper()
    bm.find_bin(vals, total_sample_cnt=5000, max_bin=255)
    assert bm.missing_type == MISSING_NONE
    assert 2 <= bm.num_bin <= 255
    bins = bm.values_to_bins(vals)
    # every value maps into the bin whose upper bound is the first >= value
    for v, b in zip(vals[:200], bins[:200]):
        assert v <= bm.bin_upper_bound[b]
        if b > 0:
            assert v > bm.bin_upper_bound[b - 1]


def test_bin_mapper_nan_missing():
    rng = np.random.RandomState(1)
    vals = rng.normal(size=1000)
    vals[::7] = np.nan
    bm = BinMapper()
    bm.find_bin(vals, total_sample_cnt=1000, max_bin=64)
    assert bm.missing_type == MISSING_NAN
    bins = bm.values_to_bins(vals)
    assert (bins[::7] == bm.num_bin - 1).all()


def test_bin_mapper_zero_as_missing():
    rng = np.random.RandomState(2)
    vals = rng.normal(size=1000)
    vals[::3] = 0.0
    bm = BinMapper()
    nonzero = vals[np.abs(vals) > 1e-35]
    bm.find_bin(nonzero, total_sample_cnt=1000, max_bin=64, zero_as_missing=True)
    assert bm.missing_type in (MISSING_ZERO, MISSING_NONE)
    bins = bm.values_to_bins(vals)
    assert (bins[::3] == bm.default_bin).all()


def test_bin_mapper_categorical():
    rng = np.random.RandomState(3)
    vals = rng.choice([0, 1, 2, 5, 9], size=2000, p=[0.4, 0.3, 0.2, 0.05, 0.05])
    bm = BinMapper()
    bm.find_bin(vals.astype(np.float64), total_sample_cnt=2000, max_bin=32,
                bin_type=BIN_CATEGORICAL)
    assert bm.bin_type == BIN_CATEGORICAL
    # most frequent category gets bin 1
    assert bm.bin_2_categorical[1] == 0
    bins = bm.values_to_bins(vals.astype(np.float64))
    assert (bins[vals == 0] == 1).all()


def test_trivial_feature():
    bm = BinMapper()
    bm.find_bin(np.zeros(0), total_sample_cnt=100, max_bin=255)
    assert bm.is_trivial


def test_efb_bundles_one_hot_blocks(rng):
    """Full EFB: mutually exclusive one-hot columns (sparse_rate ~0.75,
    below the old 0.8-only policy) bundle into few groups while dense
    columns stay singletons, and predictions match an unbundled model
    (reference: Dataset::FindGroups over ALL features, dataset.cpp:60)."""
    import lightgbm_tpu as lgb
    n = 4000
    codes = rng.randint(0, 4, size=n)
    onehot = np.eye(4)[codes]                      # 4 exclusive columns
    dense = rng.normal(size=(n, 3))
    X = np.column_stack([onehot, dense])
    y = codes * 1.0 + dense[:, 0] + 0.1 * rng.normal(size=n)

    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 20, "metric": ""}
    ds = lgb.Dataset(X, label=y)
    ds.construct(params)
    inner = ds._inner
    # 4 exclusive one-hots -> 1 shared group; 3 dense singletons
    assert inner.num_groups <= 1 + 3, [g.feature_indices
                                       for g in inner.groups]
    assert any(len(g.feature_indices) >= 4 for g in inner.groups)

    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=15)
    # unbundled oracle: disable bundling via enable_bundle=false
    bst0 = lgb.train(dict(params, enable_bundle=False),
                     lgb.Dataset(X, label=y), num_boost_round=15)
    p, p0 = bst.predict(X), bst0.predict(X)
    mse = float(np.mean((y - p) ** 2))
    mse0 = float(np.mean((y - p0) ** 2))
    assert mse < mse0 * 1.2 + 1e-6      # bundling does not hurt quality
