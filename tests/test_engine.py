"""End-to-end training tests (reference model: tests/python_package_test/test_engine.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_regression(n=500, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.1 * rng.normal(size=n)
    return X, y


def make_binary(n=500, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 2.0 + X[:, 1] - X[:, 2]
    y = (logit + rng.normal(size=n) * 0.5 > 0).astype(np.float64)
    return X, y


def test_regression_l2_learns():
    X, y = make_regression()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "learning_rate": 0.1, "min_data_in_leaf": 5,
                     "verbosity": -1}, ds, num_boost_round=30)
    pred = bst.predict(X)
    mse0 = np.mean((y - y.mean()) ** 2)
    mse = np.mean((y - pred) ** 2)
    assert mse < 0.3 * mse0


def test_binary_learns():
    X, y = make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=30)
    pred = bst.predict(X)
    assert pred.min() >= 0 and pred.max() <= 1
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.85


def test_prediction_consistency_in_and_out_of_training():
    """Device traversal scores must match host-tree raw predictions."""
    X, y = make_regression(300, 5)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=10)
    raw = bst.predict(X, raw_score=True)
    train_scores = np.asarray(bst._gbdt.scores)
    np.testing.assert_allclose(raw, train_scores, rtol=1e-4, atol=1e-4)


def test_early_stopping():
    X, y = make_regression(400, 8, seed=1)
    Xv, yv = make_regression(200, 8, seed=2)
    ds = lgb.Dataset(X, label=y)
    vs = lgb.Dataset(Xv, label=yv, reference=ds)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "metric": "l2"},
                    ds, num_boost_round=200, valid_sets=[vs],
                    callbacks=[lgb.early_stopping(5, verbose=False)])
    assert bst.best_iteration > 0
    assert bst.best_iteration <= 200


def test_model_save_load_roundtrip(tmp_path):
    X, y = make_binary(300, 6)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=5)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    p1 = bst.predict(X, raw_score=True)
    p2 = bst2.predict(X, raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-10)


def test_multiclass():
    rng = np.random.RandomState(5)
    n = 600
    X = rng.normal(size=(n, 6))
    y = np.argmax(X[:, :3] + rng.normal(size=(n, 3)) * 0.3, axis=1).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "verbosity": -1}, ds, num_boost_round=20)
    pred = bst.predict(X)
    assert pred.shape == (n, 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    acc = np.mean(np.argmax(pred, axis=1) == y)
    assert acc > 0.8


def test_bagging_and_feature_fraction():
    X, y = make_regression(600, 12, seed=3)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "bagging_fraction": 0.6, "bagging_freq": 1,
                     "feature_fraction": 0.7, "min_data_in_leaf": 5,
                     "verbosity": -1}, ds, num_boost_round=30)
    pred = bst.predict(X)
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - pred) ** 2) < 0.5 * mse0


def test_goss():
    X, y = make_regression(800, 10, seed=4)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "data_sample_strategy": "goss", "verbosity": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=30)
    pred = bst.predict(X)
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - pred) ** 2) < 0.5 * mse0


def test_l1_objective_renewal():
    X, y = make_regression(400, 8, seed=6)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression_l1", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=30)
    pred = bst.predict(X)
    mae0 = np.mean(np.abs(y - np.median(y)))
    assert np.mean(np.abs(y - pred)) < 0.7 * mae0


def test_constant_dataset_trains_stub_trees():
    """Zero usable features: training must produce constant predictions
    (reference: BoostFromAverage with no splittable features)."""
    X = np.zeros((50, 2))
    y = np.full(50, 3.0)
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=2)
    assert np.allclose(bst.predict(X), 3.0)


def test_path_smooth_regularizes():
    """path_smooth shrinks leaf outputs toward the parent: predictions get
    smoother (lower variance) but the model still learns
    (reference: CalculateSplittedLeafOutput smoothing arm)."""
    X, y = make_regression(600, 6, seed=5)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, label=y), 20)
    b1 = lgb.train({**base, "path_smooth": 50.0},
                   lgb.Dataset(X, label=y), 20)
    mse0 = np.mean((y - b0.predict(X)) ** 2)
    mse1 = np.mean((y - b1.predict(X)) ** 2)
    # smoothing trades a bit of train fit for regularization
    assert mse1 > mse0
    assert mse1 < 0.4 * np.var(y)


def test_fused_lag_pipeline_consistency():
    """Without valid sets the fused path lags tree materialization by one
    iteration; every model consumer must still see all trees, and stopping
    at no-more-splits must not duplicate stub trees."""
    X, y = make_regression(400, 5, seed=11)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "min_data_in_leaf": 10, "verbosity": -1}, ds, 12)
    assert bst.num_trees() == 12
    assert len(bst.dump_model()["tree_info"]) == 12
    # exhaustion: tiny data + huge min_data stops early without stub spam
    Xs, ys = make_regression(40, 3, seed=12)
    bst2 = lgb.train({"objective": "regression", "num_leaves": 31,
                      "min_data_in_leaf": 35, "verbosity": -1},
                     lgb.Dataset(Xs, label=ys), 20)
    infos = bst2.dump_model()["tree_info"]
    stubs = sum(1 for t in infos if t["num_leaves"] <= 1)
    assert stubs <= 1, f"{stubs} stub trees"
