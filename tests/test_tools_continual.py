"""Tier-1 lanes for the continual-runtime tooling (ISSUE-6 satellite):
`tools/ab_bench.py --drift` must assert rollback-within-N + last-good
serving parity end-to-end, and `tools/profile_continual.py --smoke`
must emit its JSON report with every drill invariant green.  The
profiler runs in-process to share the session's jit caches (the
profile_predict lane's trick); ab_bench runs as a real subprocess —
it is the operator-facing CI entry point and its exit code is the
contract.
"""

import importlib.util
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(HERE, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_profile_continual_smoke(capsys):
    tool = _load_tool("profile_continual")
    rc = tool.main(["--smoke", "--rows", "256", "--ticks", "4"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert rc == 0
    payload = json.loads(out)
    assert payload["metric"] == "continual"
    detail = payload["detail"]
    # steady-state ticks never retrace: kinds compile exactly once
    assert all(v == 1 for v in detail["tick"]["trace_counts"].values())
    assert detail["tick"]["tick_ms"] > 0
    d = detail["drills"]
    assert d["swap"]["detected_within_window"]
    assert d["swap"]["one_trace_per_key"]
    assert d["degrade"]["still_serving"]
    assert d["rollback"]["pre_post_identical"]


def test_ab_bench_drift_lane(tmp_path):
    obs_path = str(tmp_path / "BENCH_obs.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=HERE,
               BENCH_OBS_PATH=obs_path)
    out = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "ab_bench.py"),
         "--drift", "--drift-rows", "192", "--rollback-within", "3"],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    line = [ln for ln in out.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["detected_within_window"] is True
    assert rec["one_trace_per_key"] is True
    assert rec["rollback_ok"] is True, \
        f"rollback fired after {rec['rollback_delay_ticks']} ticks"
    assert rec["post_rollback_parity"] is True
    assert rec["swap_latency_s"] > 0
    # ISSUE-9: the health lane rode along — the planted single-feature
    # covariate shift must be attributed #1
    assert rec["health"]["planted_rank"] == 1, rec["health"]
    assert rec["health"]["skew_top"][0]["feature"] == \
        rec["health"]["planted_feature"]
    # ISSUE-8 satellite: the machine-readable perf artifact rides along
    # (schema v3 since ISSUE-11: hardware fingerprint + aborted flag)
    with open(obs_path) as fh:
        art = json.load(fh)
    assert art["schema"] == "lightgbm-tpu/bench-obs/v3"
    assert art["tool"] == "ab_bench.drift"
    assert art["aborted"] is False
    assert art["fingerprint"]["backend"] == "cpu"
    assert art["timings"]["rollback_ok"] is True
    assert art["health"]["planted_rank"] == 1
    assert any(k.startswith("serving.") for k in art["compile_counts"])
    assert art["memory_peaks"]["owners"]
    from lightgbm_tpu.obs import benchio
    assert benchio.validate_bench_obs(art) == []
