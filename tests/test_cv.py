"""cv() parity with the reference engine
(/root/reference/python-package/lightgbm/engine.py:580-744): fpreproc,
eval_train_metric, sklearn splitter folds, ranking group-aware folds."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

BASE = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "metric": "binary_logloss", "min_data_in_leaf": 10}


def _bin_data(rng, n=1200, f=6):
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 0)
    return X, y.astype(np.float64)


@pytest.mark.slow  # 7.5 s: tier-1 window trim (PR 12, per
# test_durations.json); test_engine.py keeps the fast in-window
# training-metric representative (is_provide_training_metric) and
# test_cv_fpreproc_applied_per_fold keeps the cv-series shape cover
def test_cv_eval_train_metric(rng):
    """eval_train_metric=True adds `train <metric>-mean` series
    (reference: engine.py cv eval_train_metric arm)."""
    X, y = _bin_data(rng)
    res = lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=8,
                 nfold=3, eval_train_metric=True, seed=4)
    assert "train binary_logloss-mean" in res
    assert "valid binary_logloss-mean" in res
    assert len(res["train binary_logloss-mean"]) == 8
    # train loss should be below valid loss by the end (it's fitted)
    assert res["train binary_logloss-mean"][-1] <= \
        res["valid binary_logloss-mean"][-1] + 1e-6


def test_cv_fpreproc_applied_per_fold(rng):
    """fpreproc mutates each fold's sets/params before training
    (reference: engine.py:553-556)."""
    X, y = _bin_data(rng)
    calls = []

    def fpreproc(dtrain, dtest, params):
        calls.append((dtrain.num_data(), dtest.num_data()))
        params = dict(params, learning_rate=0.5)
        return dtrain, dtest, params

    res = lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=5,
                 nfold=3, fpreproc=fpreproc, seed=4,
                 return_cvbooster=True)
    assert len(calls) == 3
    assert all(tr + te == len(y) for tr, te in calls)
    # the params hook took effect on the fold boosters
    for bst in res["cvbooster"].boosters:
        assert bst.config.learning_rate == pytest.approx(0.5)


@pytest.mark.slow  # 7.1 s: tier-1 window trim (PR 12, per
# test_durations.json); test_cv_sklearn_groupkfold_ranking keeps the
# fast in-window sklearn-splitter representative
def test_cv_sklearn_splitter_folds(rng):
    """A scikit-learn splitter object drives the folds
    (reference: engine.py:507-517 hasattr(folds, 'split'))."""
    from sklearn.model_selection import KFold
    X, y = _bin_data(rng)
    res = lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=6,
                 folds=KFold(n_splits=4, shuffle=True, random_state=0))
    assert len(res["valid binary_logloss-mean"]) == 6
    # 4 folds -> stdv series exists and is finite
    assert np.isfinite(res["valid binary_logloss-stdv"]).all()
    # a non-iterable non-splitter raises like the reference
    with pytest.raises(AttributeError, match="folds should be"):
        lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=2, folds=3)


def test_cv_ranking_group_aware(rng):
    """lambdarank cv splits by whole query groups (reference:
    engine.py:525-532 group_kfold path): every fold's booster must see
    intact query groups summing to the fold's rows."""
    nq, qsize = 60, 8
    n = nq * qsize
    X = rng.normal(size=(n, 5))
    rel = (X[:, 0] + 0.2 * rng.normal(size=n))
    y = np.digitize(rel, np.quantile(rel, [0.5, 0.8])).astype(np.float64)
    group = np.full(nq, qsize)
    params = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
              "metric": "ndcg", "eval_at": "3", "min_data_in_leaf": 5}
    res = lgb.cv(params, lgb.Dataset(X, label=y, group=group),
                 num_boost_round=6, nfold=3, seed=7,
                 return_cvbooster=True)
    assert "valid ndcg@3-mean" in res
    assert len(res["valid ndcg@3-mean"]) == 6
    for bst in res["cvbooster"].boosters:
        g = np.asarray(bst.train_set.group)
        # groups kept whole: each fold's train groups are full-size
        assert (g == qsize).all()
        assert g.sum() == bst.train_set.num_data()
    # ndcg improves over training
    assert res["valid ndcg@3-mean"][-1] >= res["valid ndcg@3-mean"][0] - 1e-9


@pytest.mark.slow  # 12.3 s: tier-1 window trim (PR 14, per
# test_durations.json) — group-aware ranking CV keeps its fast
# in-window representative in test_cv_ranking_group_aware
def test_cv_sklearn_groupkfold_ranking(rng):
    """GroupKFold passed explicitly receives the flattened query ids as
    groups (reference: engine.py:509-516)."""
    from sklearn.model_selection import GroupKFold
    nq, qsize = 40, 6
    n = nq * qsize
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    group = np.full(nq, qsize)
    params = {"objective": "lambdarank", "num_leaves": 7, "verbosity": -1,
              "metric": "ndcg", "eval_at": "2", "min_data_in_leaf": 5}
    res = lgb.cv(params, lgb.Dataset(X, label=y, group=group),
                 num_boost_round=3,
                 folds=GroupKFold(n_splits=4), return_cvbooster=True)
    assert len(res["valid ndcg@2-mean"]) == 3
    for bst in res["cvbooster"].boosters:
        g = np.asarray(bst.train_set.group)
        assert (g == qsize).all()


@pytest.mark.slow  # 21 s (50 rounds x 3 folds): the single slowest test
# of the slowest non-slow lane — out of the 870 s tier-1 window so the
# ~40 s of lanes past the old cutoff run instead (test_durations.json
# artifact, ISSUE-9); still covered by full/slow runs
def test_cv_early_stopping_and_callbacks(rng):
    """cv honors callbacks (log_evaluation cadence) and early stopping
    sets best_iteration on the returned CVBooster."""
    X, y = _bin_data(rng, n=800)
    seen = []

    def spy(env):
        seen.append((env.iteration,
                     [e[1] for e in env.evaluation_result_list]))

    res = lgb.cv(dict(BASE, early_stopping_round=3),
                 lgb.Dataset(X, label=y), num_boost_round=50, nfold=3,
                 seed=4, callbacks=[spy], return_cvbooster=True)
    assert seen and seen[0][1] == ["valid binary_logloss"]
    cvb = res["cvbooster"]
    assert 1 <= cvb.best_iteration <= 50
    # reference semantics: series truncated to best_iteration, fold
    # boosters stamped (engine.py:843-848)
    if cvb.best_iteration < 50:
        assert len(res["valid binary_logloss-mean"]) == cvb.best_iteration
        assert all(b.best_iteration == cvb.best_iteration
                   for b in cvb.boosters)


@pytest.mark.slow  # 17.3 s: tier-1 window trim (PR 14) — init_model
# continuation keeps fast in-window representatives in
# test_continue.py; the cv()-level plumbing stays covered here slow
def test_cv_init_model_continues(rng, tmp_path):
    """cv(init_model=...) seeds every fold (and its valid scores) from
    the model, like train(); starting from a trained model must not be
    worse than a cold start at the same added rounds."""
    X, y = _bin_data(rng)
    f = str(tmp_path / "warm.txt")
    lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=12) \
        .save_model(f)
    warm = lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=3,
                  nfold=3, seed=4, init_model=f)
    cold = lgb.cv(BASE, lgb.Dataset(X, label=y), num_boost_round=3,
                  nfold=3, seed=4)
    assert warm["valid binary_logloss-mean"][-1] < \
        cold["valid binary_logloss-mean"][-1]
