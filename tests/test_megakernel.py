"""End-to-end coverage for the split mega-kernel path (tpu_megakernel):
the Pallas program (run through the interpreter off-TPU) must build
BIT-IDENTICAL trees to its XLA oracle formulation, the oracle itself
must agree numerically with the default subtraction path, and every
unsupported route must fall back cleanly at learner init.

The mega path's histogram chunk grid is the parent cover (not the
children's own ranges), so mega trees are bit-identical to the mega XLA
oracle but only NUMERICALLY equivalent to the subtraction-path trees —
the assertions below encode exactly that contract.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(seed=5, n=1200, f=6):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * np.sin(X[:, 1] * 2)
         + 0.4 * rng.randn(n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "min_data_in_leaf": 20, "tpu_row_chunk": 256}


def _train(X, y, nbr=2, **kw):
    return lgb.train({**BASE, **kw}, lgb.Dataset(X, label=y),
                     num_boost_round=nbr)


def _trees(bst):
    """Model text minus the [param] dump (params legitimately differ
    between the arms; the TREES must not)."""
    return [ln for ln in bst.model_to_string().splitlines()
            if not ln.startswith("[")]


def test_mega_xla_matches_default_path_numerically():
    """The oracle formulation is the same math as the subtraction path
    up to f32 summation grouping: predictions agree to float noise."""
    X, y = _data()
    b0 = _train(X, y, nbr=5)
    b1 = _train(X, y, nbr=5, tpu_megakernel="xla")
    assert b0._gbdt.learner._use_mega is None       # CPU auto: off
    assert b1._gbdt.learner._use_mega == "xla"
    d = float(np.abs(b0.predict(X[:400]) - b1.predict(X[:400])).max())
    assert d < 1e-4, d


@pytest.mark.parametrize("extra", [
    {},
    # 13 s each (interpreter-mode training): tier-1 window offenders
    # per test_durations.json; the plain case stays as the fast
    # in-window representative of the interpret-mega lane, the
    # sampling/quantized variants keep full coverage in the slow lane
    pytest.param({"bagging_fraction": 0.6, "bagging_freq": 1},
                 marks=pytest.mark.slow),
    pytest.param({"data_sample_strategy": "goss"},
                 marks=pytest.mark.slow),
    pytest.param({"use_quantized_grad": True},
                 marks=pytest.mark.slow),
])
def test_mega_interpret_bitexact_vs_oracle(extra):
    """The acceptance contract: mega-kernel (interpret mode on CPU)
    trees bit-identical to the XLA oracle at L=31, including
    bagging/GOSS masks and quantized integer gradient carriers.

    BOTH arms run with tpu_kernel_interpret=True so partition and split
    search use the identical implementations and the comparison isolates
    exactly the mega-kernel's fused histogram semantics.  (On CPU the
    Pallas pair-search and the XLA vmapped search differ by last-ulp
    gemm rounding — an implementation-lane difference the TPU MXU does
    not have — so mixing search implementations across arms is not a
    valid bit-exactness comparison.)"""
    X, y = _data(seed=11, n=900)
    kw = {"num_leaves": 31, "tpu_kernel_interpret": True, **extra}
    bx = _train(X, y, tpu_megakernel="xla", **kw)
    bp = _train(X, y, tpu_megakernel="pallas", **kw)
    lr = bp._gbdt.learner
    assert lr._use_mega == "pallas" and lr._use_pallas_part
    assert bx._gbdt.learner._use_mega == "xla"
    assert _trees(bx) == _trees(bp)
    d = np.abs(bx.predict(X[:300]) - bp.predict(X[:300])).max()
    assert float(d) == 0.0


@pytest.mark.slow  # 12.4 s: tier-1 window offender per
# test_durations.json; kernel-level radix-4 interpret coverage stays
# fast in tests/test_pallas_interpret.py
def test_mega_interpret_radix4_bitexact():
    """The radix-4 compaction network changes the instruction schedule,
    never the layout: mega trees stay bit-identical to the oracle."""
    X, y = _data(seed=13, n=900)
    bx = _train(X, y, tpu_megakernel="xla", tpu_kernel_interpret=True)
    bp = _train(X, y, tpu_megakernel="pallas", tpu_kernel_interpret=True,
                tpu_compact_radix=True)
    assert bp._gbdt.learner._compact_radix
    assert _trees(bx) == _trees(bp)


@pytest.mark.slow
def test_mega_interpret_bitexact_L255():
    """The L=255 geometry of the acceptance contract (slow: interpret
    mode pays per-split interpreter cost across a deep leaf-wise tree)."""
    X, y = _data(seed=17, n=3000, f=8)
    kw = {"num_leaves": 255, "min_data_in_leaf": 10,
          "tpu_kernel_interpret": True}
    bx = _train(X, y, nbr=1, tpu_megakernel="xla", **kw)
    bp = _train(X, y, nbr=1, tpu_megakernel="pallas", **kw)
    assert bp._gbdt.learner._use_mega == "pallas"
    assert _trees(bx) == _trees(bp)


def test_nonmega_interpret_kernels_structural():
    """The pre-existing kernel stack (partition + pair-search +
    flat-hist RMW) run through the interpreter must reproduce the pure
    XLA path's tree STRUCTURE and agree numerically — the off-TPU lane
    for the kernels the TPU selfcheck exercises on device.  (Bitwise
    equality holds on the TPU MXU but not across CPU gemm shapes: the
    pair-search kernel and the XLA search stack their prefix matmuls
    differently, which rounds differently under Eigen.)"""
    X, y = _data(seed=19)
    bx = _train(X, y, tpu_megakernel="off")
    bi = _train(X, y, tpu_megakernel="off", tpu_kernel_interpret=True)
    lr = bi._gbdt.learner
    assert (lr._use_pallas_part and lr._use_pallas_search
            and lr._use_flat_hist)
    struct = ("split_feature=", "threshold=", "left_child=",
              "right_child=", "num_leaves=", "decision_type=")
    sx = [ln for ln in _trees(bx) if ln.startswith(struct)]
    si = [ln for ln in _trees(bi) if ln.startswith(struct)]
    assert sx == si
    d = float(np.abs(bx.predict(X[:300]) - bi.predict(X[:300])).max())
    assert d < 1e-5, d


def test_mega_fallback_routes_clean_at_init():
    """Unsupported routes must fall back to the current split path at
    learner init (no mid-train surprises): categorical features, u16
    bins (max_bin > 256), cegb-lazy payloads, forced splits."""
    X, y = _data(n=800)
    # categorical
    Xc = X.copy()
    Xc[:, 3] = np.random.RandomState(0).randint(0, 5, len(Xc))
    bc = lgb.train({**BASE, "tpu_megakernel": "xla",
                    "categorical_feature": [3]},
                   lgb.Dataset(Xc, label=y, categorical_feature=[3]),
                   num_boost_round=2)
    assert bc._gbdt.learner._use_mega is None
    # u16 bins
    b16 = _train(X, y, tpu_megakernel="xla", max_bin=300)
    assert b16._gbdt.learner._use_mega is None
    assert b16._gbdt.learner.B > 256
    # cegb-lazy
    lazy = ",".join(["0.1"] * X.shape[1])
    bl = _train(X, y, tpu_megakernel="xla",
                cegb_penalty_feature_lazy=lazy)
    assert bl._gbdt.learner._use_mega is None
    # forced splits
    import json
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fh:
        json.dump({"feature": 0, "threshold": 0.0}, fh)
        fname = fh.name
    try:
        bf = _train(X, y, tpu_megakernel="xla",
                    forcedsplits_filename=fname)
    finally:
        os.remove(fname)
    assert bf._gbdt.learner._use_mega is None
    # every fallback still trains a usable model
    for b in (bc, b16, bl, bf):
        assert np.isfinite(b.predict(X[:50])).all()


def test_mega_off_and_unknown_modes():
    X, y = _data(n=600)
    boff = _train(X, y, tpu_megakernel="off")
    assert boff._gbdt.learner._use_mega is None
    bauto = _train(X, y)            # auto on CPU without interpret: off
    assert bauto._gbdt.learner._use_mega is None
