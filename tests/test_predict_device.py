"""Device-side batch prediction and batched TreeSHAP.

The device path bins rows with the training mappers and traverses all
trees in one jitted vmap; for in-session trees this is EXACT in bin
space, so it must agree with the host double-precision tree walk to
float32-summation tolerance.  Batched TreeSHAP must match the per-row
recursion bit-for-bit (same arithmetic, vectorized) and satisfy the
additivity property (sum of contributions == raw prediction).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(rng, n=6000, f=8):
    X = rng.normal(size=(n, f))
    X[::13, 2] = np.nan
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 2) +
         np.nan_to_num(X[:, 2]) * 0.5 + 0.2 * rng.normal(size=n))
    return X, y


def test_device_predict_matches_host(rng):
    X, y = _data(rng)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    g = bst._gbdt
    p_dev = g.predict_raw(X)                       # n >= 4096: device path
    # force the host path by hiding the device trees
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    p_host = g.predict_raw(X)
    g.device_trees = saved
    np.testing.assert_allclose(p_dev, p_host, rtol=2e-6, atol=2e-6)
    # slicing start/num_iteration goes through the same path
    p_dev5 = g.predict_raw(X, start_iteration=5, num_iteration=5)
    g.device_trees = [None] * len(saved)
    p_host5 = g.predict_raw(X, start_iteration=5, num_iteration=5)
    g.device_trees = saved
    np.testing.assert_allclose(p_dev5, p_host5, rtol=2e-6, atol=2e-6)


def test_device_predict_multiclass(rng):
    X, yr = _data(rng)
    y = np.digitize(yr, np.quantile(yr, [0.4, 0.8]))
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1,
                     "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    g = bst._gbdt
    p_dev = g.predict_raw(X)
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    p_host = g.predict_raw(X)
    g.device_trees = saved
    np.testing.assert_allclose(p_dev, p_host, rtol=2e-6, atol=2e-6)


def test_shap_batch_matches_scalar_recursion(rng):
    from lightgbm_tpu.models import shap as shap_mod
    X, y = _data(rng, n=300)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 10},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    g = bst._gbdt
    g._flush_pending()
    data = np.asarray(X[:40], np.float64)
    nfeat = g.max_feature_idx + 1
    for tree in g.models:
        batch_phi = np.zeros((len(data), nfeat + 1))
        shap_mod._tree_shap_batch(tree, data, batch_phi)
        parent = [shap_mod._PathElement()
                  for _ in range(tree.num_leaves + 3)]
        for r in range(len(data)):
            phi = np.zeros(nfeat + 1)
            shap_mod._tree_shap(tree, data[r], phi, 0, 0, parent,
                                1.0, 1.0, -1)
            np.testing.assert_allclose(batch_phi[r], phi,
                                       rtol=1e-9, atol=1e-12)


def test_shap_additivity(rng):
    X, y = _data(rng, n=500)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 10},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    contrib = bst.predict(X[:100], pred_contrib=True)
    raw = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-5, atol=1e-5)


def test_device_predict_categorical_oov(rng):
    """Categorical-split trees predict on device via the OOV-sentinel
    bin: unseen categories and NaN fall to the RIGHT child like the
    reference's raw-value CategoricalDecision (tree.h), matching the
    host walk exactly."""
    n = 6000
    X = rng.normal(size=(n, 5))
    X[:, 1] = rng.randint(0, 12, size=n)           # categorical
    y = (X[:, 0] + np.where(np.isin(X[:, 1], [2, 3, 7]), 2.0, -1.0)
         + 0.1 * rng.normal(size=n))
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 20,
                     "categorical_feature": [1], "enable_bundle": False},
                    lgb.Dataset(X, label=y), num_boost_round=12)
    g = bst._gbdt
    g._flush_pending()
    assert any(d["has_cat_split"] for d in g.device_trees), \
        "fixture must produce categorical splits"
    # OOV categories (99, -5) and NaN in the categorical column
    Xq = X.copy()
    Xq[::7, 1] = 99.0
    Xq[1::7, 1] = -5.0
    Xq[2::7, 1] = np.nan
    p_dev = g._predict_raw_device(Xq, 0, 12)
    assert p_dev is not None, "categorical device path must engage"
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    p_host = g.predict_raw(Xq)
    g.device_trees = saved
    np.testing.assert_allclose(p_dev[:, 0], np.asarray(p_host),
                               rtol=2e-6, atol=2e-6)


def test_device_predict_small_batch_warm_cache(rng):
    X, y = _data(rng)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 20},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    g = bst._gbdt
    g._flush_pending()
    small = X[:64]
    # cold cache: small batches decline the device path
    assert not g.serving._warm("insession")
    assert g._predict_raw_device(small, 0, 10) is None
    # a big batch warms the cache; the SAME compiled traversal then
    # serves small batches
    assert g._predict_raw_device(X, 0, 10) is not None
    p_small = g._predict_raw_device(small, 0, 10)
    assert p_small is not None
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    p_host = g.predict_raw(small)
    g.device_trees = saved
    np.testing.assert_allclose(p_small[:, 0], np.asarray(p_host),
                               rtol=2e-6, atol=2e-6)
