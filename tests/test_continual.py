"""Continual-training runtime (lightgbm_tpu/continual/).

Covers the ISSUE-6 acceptance surface end-to-end through the
deterministic drift harness: inject drift at tick T -> regression
detected within the window -> retrain kicked off with retry/backoff
(killed once mid-retrain, resumed from checkpoint) -> guarded atomic
swap with at most one compile per (kind, bucket) -> kill-every-attempt
degrades gracefully to the last-good pack -> forced post-swap
regression rolls back with predictions bit-identical to the pre-swap
pack.  Plus the unit surface: seeded backoff replay, windowed
regression detection, swap gating, NaN-burst refit guarding, and
zero-retrace steady-state ticks.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.continual import (ContinualBooster, DriftSpec,
                                    DriftStream, run_drift_drill)
from lightgbm_tpu.continual.drift import _DRILL_PARAMS
from lightgbm_tpu.continual.runtime import TickReport, tick_metric
from lightgbm_tpu.robustness.retry import (ManualClock, backoff_schedule,
                                           retry_with_backoff)
from lightgbm_tpu.utils.log import LightGBMError


def _tiny_cb(**overrides):
    """A small ContinualBooster on a stable synthetic stream."""
    p = dict(_DRILL_PARAMS)
    p.update({"num_iterations": 8, "num_leaves": 7}, **overrides)
    warm = DriftStream(num_features=5, rows=512, seed=21)
    X0, y0 = warm.batch(0)
    return ContinualBooster(p, X0, y0), DriftStream(
        num_features=5, rows=128, seed=22)


# ---------------------------------------------------------------------------
# end-to-end drift drills (the acceptance-criteria scenarios)
# ---------------------------------------------------------------------------
def test_swap_drill_end_to_end(tmp_path):
    """Covariate shift at tick 4: detection within the window, the
    retrain killed once mid-flight and RESUMED from its checkpoint on
    the retry, a guarded hot-swap costing at most one compile per
    (kind, bucket), and metric recovery on the post-swap ticks."""
    rep = run_drift_drill("swap", rows=192, drift_at=4, post_ticks=5,
                          checkpoint_dir=str(tmp_path))
    assert rep["detected_within_window"], rep
    assert rep["swap_tick"] is not None
    # killed once -> exactly 2 attempts, the second resuming bit-exact
    assert rep["retrain_attempts"] == 2
    assert rep["one_trace_per_key"], rep["swap_new_traces"]
    assert rep["swap_new_traces"], "swap must warm the candidate's pack"
    assert rep["metric_recovered"]
    assert rep["final_generation"] == 1


def test_degrade_drill_serves_last_good():
    """Every retrain attempt dies (no checkpoints): retry exhaustion
    must degrade gracefully — the last-good model keeps serving and no
    swap ever happens."""
    rep = run_drift_drill("degrade", rows=192, drift_at=4, post_ticks=5)
    assert rep["detected_within_window"]
    assert rep["degrade_tick"] is not None
    assert rep["swap_tick"] is None
    assert rep["still_serving"]
    assert rep["generation"] == 0


def test_rollback_drill_bit_identical():
    """A deliberately bad candidate force-swapped in: the watchdog must
    roll back within the rollback window, and post-rollback predictions
    must be BIT-identical to the pre-swap pack (the restored booster's
    engine kept its own packs under its own mutation-counter keys)."""
    rep = run_drift_drill("rollback", rows=192, drift_at=3, post_ticks=5)
    assert rep["rollback_within"], rep
    assert rep["pre_post_identical"], \
        "post-rollback serving differs from the pre-swap pack"


# ---------------------------------------------------------------------------
# seeded retry/backoff (satellite: deterministic replays)
# ---------------------------------------------------------------------------
def test_backoff_schedule_is_pure():
    a = backoff_schedule(5, base_delay=0.5, max_delay=4.0, jitter=0.3,
                         seed=11)
    b = backoff_schedule(5, base_delay=0.5, max_delay=4.0, jitter=0.3,
                         seed=11)
    assert a == b, "same arguments must replay the same delays"
    c = backoff_schedule(5, base_delay=0.5, max_delay=4.0, jitter=0.3,
                         seed=12)
    assert a != c, "jitter must depend on the seed"
    plain = backoff_schedule(5, base_delay=0.5, max_delay=4.0)
    assert plain == [0.5, 1.0, 2.0, 4.0, 4.0]
    assert all(x >= y for x, y in zip(a, plain)), \
        "jitter only ever lengthens the capped exponential delay"


def test_retry_replays_identical_sleeps():
    """Two failing runs with the same policy sleep the identical
    sequence — the property kill+resume fault drills rely on."""
    def run_once():
        clk = ManualClock()
        slept = []

        def sleep(d):
            slept.append(d)
            clk.sleep(d)

        def boom():
            raise RuntimeError("transient")

        with pytest.raises(LightGBMError):
            retry_with_backoff(boom, attempts=4, base_delay=0.1,
                               jitter=0.5, seed=7, sleep=sleep, clock=clk,
                               describe="replay probe")
        return slept, clk.now

    s1, t1 = run_once()
    s2, t2 = run_once()
    assert s1 == s2 and t1 == t2
    assert len(s1) == 3                       # no sleep after the last


def test_retry_deadline_uses_injected_clock():
    """The out-of-budget decision reads the injected clock, so a stubbed
    sleep plus ManualClock makes the deadline cut-off deterministic."""
    clk = ManualClock()
    calls = []

    def fail():
        calls.append(1)
        raise RuntimeError("nope")

    with pytest.raises(LightGBMError, match="deadline|attempt"):
        retry_with_backoff(fail, attempts=10, base_delay=1.0,
                           max_delay=1.0, deadline=2.5, seed=0,
                           sleep=clk.sleep, clock=clk,
                           describe="deadline probe")
    # delays of 1s each: attempts at t=0,1,2; the next delay would end
    # at 3.0 > 2.5, so exactly 3 attempts run — every time
    assert len(calls) == 3


# ---------------------------------------------------------------------------
# windowed regression detection + swap gate (unit surface)
# ---------------------------------------------------------------------------
def test_windowed_regression_detection():
    cb, _ = _tiny_cb(continual_window=3, continual_metric_threshold=0.2)
    cb.history = [1.0] * 6
    assert not cb._regressed()
    cb.history = [1.0] * 3 + [1.15] * 3       # within threshold
    assert not cb._regressed()
    cb.history = [1.0] * 3 + [1.3] * 3        # beyond threshold
    assert cb._regressed()


def test_swap_gate_rejects_worse_candidate():
    """A retrain over a poisoned buffer must not replace a healthy
    model: the gate compares candidate vs served on the gate batch."""
    cb, stream = _tiny_cb()
    for t in range(2):
        cb.tick(*stream.batch(t))
    served = cb.booster
    Xb = np.random.RandomState(5).normal(size=(64, 5))
    bad = lgb.train({"objective": "regression", "verbosity": -1,
                     "num_leaves": 3, "metric": ""},
                    lgb.Dataset(Xb, label=-50.0 * np.ones(64)),
                    num_boost_round=1)
    r = TickReport(tick=cb.tick_no)
    cb._gate_and_swap(bad, r)
    assert r.swap_rejected and not r.swapped
    assert cb.booster is served, "a rejected candidate must not install"
    assert cb.generation == 0


def test_nan_burst_tick_guard_skips_refit():
    """A NaN-burst tick (poisoned upstream join) must not poison the
    served model: with nonfinite_policy=skip_iteration the refit drops
    every iteration and serving stays finite and unchanged."""
    spec = DriftSpec(nan_burst_at=1, nan_burst_ticks=1, nan_fraction=0.5)
    cb, _ = _tiny_cb(nonfinite_policy="skip_iteration")
    stream = DriftStream(num_features=5, rows=128, seed=23, spec=spec)
    cb.tick(*stream.batch(0))
    Xp = stream.batch(2)[0]
    before = cb.predict(Xp, raw_score=True)
    r = cb.tick(*stream.batch(1))             # the burst tick
    assert r.refit_applied and r.refit_skipped
    after = cb.predict(Xp, raw_score=True)
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    assert np.isfinite(np.asarray(after)).all()
    # the NaN tick metric must not enter the detection history: one
    # NaN would blind the windowed mean for 2*W ticks and disarm a
    # watchdog whose baseline captured it
    assert np.isfinite(cb.history).all()
    assert len(cb.history) == 1               # tick 0 only


def test_steady_state_ticks_add_no_retraces():
    """After the first tick settles the per-kind compiles, further
    ticks must add ZERO serving retraces: the in-place refit rides the
    engine's leaf-refresh fast path (delta re-transfer, no re-pack)."""
    cb, stream = _tiny_cb()
    cb.tick(*stream.batch(0))
    snap = cb.serving_engine.trace_snapshot()
    before_pred = cb.predict(stream.batch(9)[0], raw_score=True)
    for t in range(1, 4):
        r = cb.tick(*stream.batch(t))
        assert r.refit_applied
    assert cb.serving_engine.new_traces_since(snap) == {}
    # and the refits really changed the served model (same shapes,
    # fresh leaf values through the fast path)
    after_pred = cb.predict(stream.batch(9)[0], raw_score=True)
    assert not np.array_equal(np.asarray(before_pred),
                              np.asarray(after_pred))


def test_background_retrain_lands_at_a_later_tick():
    """background=True: the retrain runs off the tick thread over a
    buffer SNAPSHOT (the live deque keeps growing underneath it), and
    a later tick polls the finished candidate and swaps it in."""
    spec = DriftSpec(covariate_shift_at=2)
    p = dict(_DRILL_PARAMS)
    p.update({"num_iterations": 8, "num_leaves": 7,
              "continual_window": 2, "continual_retrain_rounds": 8})
    warm = DriftStream(num_features=5, rows=512, seed=41)
    X0, y0 = warm.batch(0)
    cb = ContinualBooster(p, X0, y0, background=True)
    stream = DriftStream(num_features=5, rows=128, seed=42, spec=spec)
    started = swapped = None
    for t in range(14):
        r = cb.tick(*stream.batch(t))
        if r.drift_detected and started is None:
            started = t
        if r.swapped and swapped is None:
            swapped = t
            assert r.retrain_attempts >= 1    # published before "done"
            break
        if cb._bg is not None:                # retrain still in flight
            cb._bg["thread"].join(timeout=60)
    assert started is not None, "drift never detected"
    assert swapped is not None and swapped > started, \
        "background retrain must land at a LATER tick than detection"
    assert cb.generation == 1


def test_drift_stream_batches_are_pure():
    """batch(t) is a pure function of (seed, t): replaying any tick in
    isolation reproduces it bit-exact, out of order."""
    spec = DriftSpec(covariate_shift_at=3, nan_burst_at=5)
    s1 = DriftStream(num_features=4, rows=64, seed=31, spec=spec)
    s2 = DriftStream(num_features=4, rows=64, seed=31, spec=spec)
    for t in (6, 0, 5, 3):
        X1, y1 = s1.batch(t)
        X2, y2 = s2.batch(t)
        np.testing.assert_array_equal(X1, X2)
        np.testing.assert_array_equal(y1, y2)
    # the shift applies exactly from covariate_shift_at onward
    np.testing.assert_array_equal(
        s1.batch(4)[0], DriftStream(num_features=4, rows=64, seed=31,
                                    spec=DriftSpec()).batch(4)[0] + 2.5)


def test_tick_metric_matches_objective():
    y = np.array([0.0, 1.0, 1.0, 0.0])
    raw = np.array([-2.0, 1.5, 0.5, -0.1])
    p = 1.0 / (1.0 + np.exp(-raw))
    want = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert tick_metric("binary_logloss", y, raw) == pytest.approx(want)
    assert tick_metric("l2", y, raw) == pytest.approx(
        np.mean((raw - y) ** 2))
    with pytest.raises(LightGBMError, match="continual_metric"):
        tick_metric("bogus", y, raw)


# ---------------------------------------------------------------------------
# ISSUE-8 satellites: serving-only guard + retrain-in-flight status
# ---------------------------------------------------------------------------
def test_update_after_inplace_refit_raises():
    """PR-6 known hazard, now a loud error: refit(inplace=True) makes a
    booster serving-only (its training scores no longer match the
    model), so update() must refuse instead of silently training on
    stale state."""
    rng = np.random.RandomState(13)
    X = rng.normal(size=(512, 5))
    y = X @ rng.normal(size=5) + 0.1 * rng.normal(size=512)
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "num_leaves": 7, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    bst.update()                              # trainable before the refit
    out = bst.refit(X, -y, decay_rate=0.0, inplace=True)
    assert out is bst
    with pytest.raises(LightGBMError, match="serving-only"):
        bst.update()
    # serving still works; only training is closed
    assert np.isfinite(bst.predict(X[:8])).all()
    # the OUT-OF-PLACE refit leaves the original booster trainable
    bst2 = lgb.train({"objective": "regression", "verbosity": -1,
                      "num_leaves": 7, "metric": ""},
                     lgb.Dataset(X, label=y), num_boost_round=3)
    bst2.refit(X, -y, decay_rate=0.0)
    bst2.update()


def test_background_retrain_status_transitions():
    """ContinualBooster(background=True).status() exposes the retrain
    in flight BETWEEN ticks — idle -> retraining (live attempt count,
    here including one killed attempt) -> awaiting-gate -> idle after
    the swap lands — instead of being observable only at the next
    tick's poll."""
    import threading

    p = dict(_DRILL_PARAMS)
    p.update({"num_iterations": 6, "num_leaves": 7,
              "continual_retrain_attempts": 3,
              "continual_backoff_base": 0.001})
    warm = DriftStream(num_features=5, rows=512, seed=51)
    X0, y0 = warm.batch(0)
    cb = ContinualBooster(p, X0, y0, background=True,
                          sleep=lambda d: None)
    stream = DriftStream(num_features=5, rows=128, seed=52)
    assert cb.status() == {"state": "idle", "attempts": 0,
                           "generation": 0}

    cb.tick(*stream.batch(0))                 # arms the gate batch
    started = threading.Event()
    release = threading.Event()

    def fake_retrain(tag, attempt_state, batches):
        # attempt 1 dies (the kill-mid-retrain drill shape); attempt 2
        # blocks until the test has observed the live status, then
        # builds a real candidate
        attempt_state["n"] += 1
        if attempt_state["n"] == 1:
            started.set()
            raise RuntimeError("killed mid-retrain (drill)")
        release.wait(60)
        Xs = np.concatenate([b[0] for b in batches], axis=0)
        ys = np.concatenate([np.asarray(b[1]) for b in batches], axis=0)
        return lgb.train({"objective": "regression", "verbosity": -1,
                          "num_leaves": 7, "metric": ""},
                         lgb.Dataset(Xs, label=ys), num_boost_round=4)

    cb._retrain_once = fake_retrain
    r = TickReport(tick=cb.tick_no)
    cb._start_retrain(r)
    assert started.wait(60), "background retrain never started"
    st = cb.status()
    assert st["state"] == "retraining" and st["attempts"] >= 1
    release.set()
    cb._bg["thread"].join(60)
    st = cb.status()
    assert st == {"state": "awaiting-gate", "attempts": 2,
                  "generation": 0}
    r2 = cb.tick(*stream.batch(1))            # polls + gates + swaps
    assert r2.retrain_completed and r2.retrain_attempts == 2
    assert cb.status() == {"state": "idle", "attempts": 0,
                           "generation": cb.generation}
    assert cb.generation == 1 or r2.swap_rejected
