"""Production serving plane (lightgbm_tpu/serving/).

The PR-12 acceptance gates: (1) concurrent single-row clients coalesce
into the engine's existing power-of-two buckets with EXACTLY the
per-(kind, bucket) compile counts the serial path produces — including
during a hot-swap under load — and with no interleaved-pack corruption
(every ticket's rows answer with that row's own prediction); (2) the
breaker / deadline / queue-flood drills are deterministic under
injected clocks: same seed, identical trip ticks, shed counts and
recovery sequence; (3) registry rollback is bit-identical and pack
eviction by memory budget costs a re-pack, never a re-compile.
"""

import json
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.robustness import faultinject
from lightgbm_tpu.robustness.retry import ManualClock
from lightgbm_tpu.serving import (ModelRegistry, ServingService,
                                  run_serve_drill)
from lightgbm_tpu.serving.admission import TokenBucket
from lightgbm_tpu.serving.drill import DRILL_SCENARIOS

BASE = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
        "metric": "", "min_data_in_leaf": 5, "seed": 11}
N, F = 500, 5


def _train(seed=11, rounds=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(N, F))
    y = X[:, 0] + 0.5 * np.sin(X[:, 1]) + 0.1 * rng.normal(size=N)
    bst = lgb.train(dict(BASE, seed=seed), lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    bst._gbdt._flush_pending()
    return bst, X


# ---------------------------------------------------------------------------
# Acceptance 1: coalesced concurrent traffic == serial compile counts
# ---------------------------------------------------------------------------
def test_coalesced_compile_counts_match_serial_path():
    """32 threads of single-row clients through the micro-batcher must
    trace exactly what ONE serial 256-row predict traces per (kind,
    bucket) — no retrace storm — and every client gets its own row's
    answer (no interleaved-pack corruption)."""
    # two identical trainings (same seed -> same trees): one serves the
    # serial baseline, one serves through the service
    serial, X = _train()
    served, _ = _train()
    np.testing.assert_array_equal(
        np.asarray(serial.predict(X, raw_score=True)),
        np.asarray(served.predict(X, raw_score=True)))

    # serial baseline: warmed exactly like a published model (the
    # registry lifts the cold-row gate via mark_rewarm + gate predict)
    eng_serial = serial._gbdt.serving
    eng_serial.mark_rewarm(("insession", "loaded"))
    serial.predict(X, raw_score=True)
    base = dict(eng_serial.trace_counts)
    serial.predict(X[:256], raw_score=True)
    serial.predict(X[:256], pred_leaf=True)
    serial.predict(X[:256], pred_contrib=True)
    serial_traces = {k: v - base.get(k, 0)
                     for k, v in eng_serial.trace_counts.items()
                     if v - base.get(k, 0) > 0}

    # service side: same warmth, then 32 threads x 8 single-row submits
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=256, max_delay=10.0,
                         queue_depth=1024)
    reg.publish("m", served, gate_rows=X)     # same warm-up as baseline
    eng = served._gbdt.serving
    base_svc = dict(eng.trace_counts)
    tickets = {}

    def client(i):
        mine = []
        for j in range(8):
            ridx = (i * 8 + j) % 256
            for kind in ("raw", "leaf", "contrib"):
                mine.append((ridx, kind,
                             svc.submit(X[ridx].reshape(1, -1),
                                        model="m", kind=kind,
                                        tenant=f"t{i % 4}")))
        tickets[i] = mine

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # all 256 rows per kind are pending; flush them bucket-by-bucket
    svc.pump(force=True)
    svc_traces = {k: v - base_svc.get(k, 0)
                  for k, v in eng.trace_counts.items()
                  if v - base_svc.get(k, 0) > 0}
    assert svc_traces == serial_traces, (svc_traces, serial_traces)
    # one dispatch per flushed bucket: 256 rows per kind at
    # flush_rows=256 is exactly one batch per kind lane
    assert svc.counters["dispatches"] == 3
    assert svc.counters["shed"] == 0

    # no interleaved-pack corruption: each ticket answers ITS row
    want_raw = np.asarray(serial.predict(X[:256], raw_score=True))
    want_leaf = np.asarray(serial.predict(X[:256], pred_leaf=True))
    want_con = np.asarray(serial.predict(X[:256], pred_contrib=True))
    for mine in tickets.values():
        for ridx, kind, t in mine:
            assert t.status == "ok", (t.status, t.reason)
            got = np.asarray(t.result)
            if kind == "raw":
                np.testing.assert_allclose(
                    got.reshape(-1), want_raw[ridx].reshape(-1),
                    rtol=0, atol=0)
            elif kind == "leaf":
                np.testing.assert_array_equal(
                    got.reshape(-1), want_leaf[ridx].reshape(-1))
            else:
                np.testing.assert_allclose(
                    got.reshape(-1), want_con[ridx].reshape(-1),
                    rtol=0, atol=1e-12)


def test_live_worker_no_retrace_storm():
    """With the async worker flushing by its own cadence, arbitrary
    coalesced sizes must still land in at most the flush bucket's
    power-of-two buckets, each traced exactly once."""
    bst, X = _train(seed=23)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=256, max_delay=0.002,
                         queue_depth=1024)
    reg.publish("m", bst, gate_rows=X)
    eng = bst._gbdt.serving
    base = dict(eng.trace_counts)
    svc.start()
    try:
        oks = []

        def client(i):
            ts = [svc.submit(X[(i * 16 + j) % N].reshape(1, -1),
                             model="m") for j in range(16)]
            for t in ts:
                assert t.wait(30.0)
            oks.append(all(t.status == "ok" for t in ts))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        svc.stop()
    assert all(oks)
    new = {k: v - base.get(k, 0) for k, v in eng.trace_counts.items()
           if v - base.get(k, 0) > 0}
    assert set(k[1] for k in new) <= {128, 256}, new
    assert all(v == 1 for v in new.values()), new


def test_hot_swap_under_live_load_zero_retraces():
    """A publish landing while threads hammer the name: in-flight
    requests finish on whichever version they were dispatched against,
    the outgoing engine never re-traces, the incoming engine warms with
    at most one compile per (kind, bucket)."""
    v1, X = _train(seed=31)
    v2, _ = _train(seed=32, rounds=7)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=128, max_delay=0.002,
                         queue_depth=4096)
    reg.publish("m", v1, gate_rows=X[:128])
    eng1 = v1._gbdt.serving
    want1 = np.asarray(v1.predict(X, raw_score=True)).reshape(-1)
    want2 = np.asarray(v2.predict(X, raw_score=True)).reshape(-1)
    assert not np.allclose(want1, want2)
    snap1 = dict(eng1.trace_counts)
    svc.start()
    stop = threading.Event()
    bad = []

    def client(i):
        j = 0
        while not stop.is_set() or j < 8:
            ridx = (i * 37 + j) % N
            t = svc.submit(X[ridx].reshape(1, -1), model="m")
            if not t.wait(30.0) or t.status != "ok":
                bad.append((i, j, t.status, t.reason))
                break
            got = float(np.asarray(t.result).reshape(-1)[0])
            # f32 device accumulation vs the f64 host oracle: ~1e-7
            if not (abs(got - want1[ridx]) < 1e-5
                    or abs(got - want2[ridx]) < 1e-5):
                bad.append((i, j, "corrupt", got))
                break
            j += 1
            if j > 400:
                break

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    try:
        for t in threads:
            t.start()
        rep = reg.publish("m", v2, gate_rows=X[:128])   # swap mid-load
        stop.set()
        for t in threads:
            t.join(60.0)
    finally:
        stop.set()
        svc.stop()
    assert not bad, bad[:5]
    assert all(v <= 1 for v in rep["warm_traces"].values()), rep
    # the outgoing engine served concurrent traffic from its existing
    # programs throughout — including while the swap was landing
    new1 = {k: v - snap1.get(k, 0) for k, v in eng1.trace_counts.items()
            if v - snap1.get(k, 0) > 0}
    assert new1 == {}, new1
    eng2 = v2._gbdt.serving
    assert all(v == 1 for v in eng2.trace_counts.values()), \
        eng2.trace_counts


# ---------------------------------------------------------------------------
# Acceptance 2: deterministic drills (same seed -> identical reports)
# ---------------------------------------------------------------------------
# tier-1 window trim (PR 13): replay-determinism is ONE property of
# the shared ManualClock drill machinery — [flood] is the fast
# in-window representative; the other scenarios' behavior keeps its
# own dedicated in-window test below, and their replay lanes run in
# the slow tier
@pytest.mark.parametrize("scenario", [
    pytest.param(s, marks=pytest.mark.slow) if s != "flood"
    else s for s in DRILL_SCENARIOS])
def test_drills_replay_bit_identically(scenario):
    r1 = run_serve_drill(scenario, seed=3)
    r2 = run_serve_drill(scenario, seed=3)
    assert json.dumps(r1, sort_keys=True, default=str) == \
        json.dumps(r2, sort_keys=True, default=str)


def test_breaker_drill_trip_and_recovery_sequence():
    r = run_serve_drill("breaker", seed=3)
    assert r["trip_tick"] is not None
    assert r["recovery_tick"] is not None
    assert r["recovery_tick"] > r["trip_tick"]
    assert r["trip_count"] == 1
    assert r["final_state"] == "closed"
    # fail-fast never happened silently: while open, traffic degraded
    # to the last-good version instead of erroring
    assert r["fallback_served"] > 0
    # pre-trip consecutive failures error; the trip itself degrades
    assert r["errors"] == 2
    # the breaker's own event log tells the whole story, in order
    assert [e["event"] for e in r["breaker_events"]] == \
        ["tripped", "probe", "reopened", "probe", "recovered"]


def test_deadline_drill_sheds_before_dispatch_never_after():
    r = run_serve_drill("deadline", seed=5)
    assert r["shed"] == 2 and r["shed_reasons"] == {"deadline": 2}
    assert r["served"] == 3
    # the invariant with teeth: nothing served outlived its budget
    assert r["dispatched_expired"] == 0
    statuses = [t["status"] for t in r["tickets"]]
    assert statuses == ["ok", "shed", "ok", "shed", "ok"]


def test_queue_flood_drill_bounded_depth_and_shed_order():
    r = run_serve_drill("flood", seed=7)
    assert r["bounded"] and r["max_depth_seen"] <= r["queue_depth"]
    assert r["shed_total"] == r["flood"]["count"] - r["served"]
    assert r["shed_order"], "a flood past the bound must shed"
    # the ladder sheds explanatory kinds for decision kinds: no raw
    # request was shed to make room (raw is the top class), and every
    # ladder eviction removed a lower class than the arrival that
    # caused it
    assert all(reason in ("queue_full", "degraded")
               for _, _, reason in r["shed_order"])
    assert "contrib" not in r["survivor_kinds"] or \
        all(k == "contrib" for _, k, _ in r["shed_order"])


# ---------------------------------------------------------------------------
# registry: rollback bit-identity, pack eviction by budget
# ---------------------------------------------------------------------------
def test_registry_rollback_bit_identical_and_versions():
    v1, X = _train(seed=41)
    v2, _ = _train(seed=42, rounds=6)
    reg = ModelRegistry()
    reg.publish("m", v1, gate_rows=X[:128])
    p1 = np.asarray(reg.get("m").predict(X, raw_score=True))
    reg.publish("m", v2, gate_rows=X[:128])
    p2 = np.asarray(reg.get("m").predict(X, raw_score=True))
    assert not np.allclose(p1, p2)
    assert reg.version("m") == 2
    assert reg.rollback("m")
    p1b = np.asarray(reg.get("m").predict(X, raw_score=True))
    np.testing.assert_array_equal(p1b, p1)   # bit-identical
    assert reg.version("m") == 3
    assert not reg.rollback("m"), "previous was consumed by rollback"


def test_registry_pack_budget_evicts_lru_without_recompiling():
    v1, X = _train(seed=51)
    v2, _ = _train(seed=52)
    reg = ModelRegistry(pack_budget_bytes=1)     # everything over budget
    reg.publish("a", v1, gate_rows=X[:128])
    ref = np.asarray(reg.get("a").predict(X[:100], raw_score=True))
    eng1 = v1._gbdt.serving
    traces = dict(eng1.trace_counts)
    assert eng1.stats()["packs"], "publish must warm packs"
    reg.publish("b", v2, gate_rows=X[:128])      # a is now LRU: evicted
    assert reg.evictions >= 1
    assert eng1.stats()["packs"] == [], "a's packs must be evicted"
    # next use re-packs lazily and answers identically with ZERO new
    # compiles (the engine's jit cache survives invalidation)
    out = np.asarray(reg.get("a").predict(X[:100], raw_score=True))
    np.testing.assert_array_equal(out, ref)
    assert dict(eng1.trace_counts) == traces
    assert eng1.stats()["packs"], "re-pack must have happened"


# ---------------------------------------------------------------------------
# admission primitives
# ---------------------------------------------------------------------------
def test_publish_resets_a_tripped_breaker():
    """A hot-swap installs a DIFFERENT forest: the broken version's
    open breaker (and its climbing backoff ladder) must not keep the
    fixed model on the stale fallback until the next scheduled
    probe."""
    clock = ManualClock()
    v1, X = _train(seed=97)
    v2, _ = _train(seed=98, rounds=6)
    reg = ModelRegistry(clock=clock)
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         breaker_threshold=2, breaker_base=100.0,
                         clock=clock)
    reg.publish("m", v1, gate_rows=X[:64])
    with faultinject.injected(fail_predict_model="m",
                              fail_predict_times=2):
        for i in range(2):
            svc.submit(X[i].reshape(1, -1), model="m")
            svc.pump(force=True)
    assert svc.breakers["m"].state == "open"
    # operator publishes the fixed version: served immediately — no
    # waiting out the 100s backoff, no stale-fallback traffic
    reg.publish("m", v2, gate_rows=X[:64])
    t = svc.submit(X[0].reshape(1, -1), model="m")
    svc.pump(force=True)
    assert t.status == "ok" and t.reason is None
    assert svc.breakers["m"].state == "closed"


def test_breaker_probe_inconclusive_returns_the_token():
    """A malformed probe batch carries no verdict on the model: the
    probe token must come back so a later dispatch can probe again —
    otherwise the breaker waits forever on an outcome that never
    arrives."""
    from lightgbm_tpu.serving.admission import CircuitBreaker
    clock = ManualClock()
    br = CircuitBreaker(threshold=1, base_delay=0.1, clock=clock)
    br.record_failure()                      # trips
    assert br.state == "open"
    clock.sleep(0.2)
    assert br.allow() == "probe"
    br.probe_inconclusive()                  # malformed probe batch
    assert br.state == "open"
    assert br.allow() == "probe", "the token must be reissuable"
    br.record_success()
    assert br.state == "closed"


def test_token_bucket_rate_limit_deterministic():
    clock = ManualClock()
    tb = TokenBucket(rate=1.0, burst=2.0, clock=clock)
    assert tb.allow() and tb.allow() and not tb.allow()
    clock.sleep(1.0)
    assert tb.allow() and not tb.allow()


def test_service_rate_limit_sheds_at_submit():
    clock = ManualClock()
    bst, X = _train(seed=61)
    reg = ModelRegistry(clock=clock)
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         rate=1.0, burst=2.0, clock=clock)
    reg.publish("m", bst, gate_rows=X[:64])
    t1 = svc.submit(X[0].reshape(1, -1), model="m")
    t2 = svc.submit(X[1].reshape(1, -1), model="m")
    t3 = svc.submit(X[2].reshape(1, -1), model="m")
    assert t3.status == "shed" and t3.reason == "ratelimit"
    clock.sleep(2.0)
    t4 = svc.submit(X[3].reshape(1, -1), model="m")
    svc.pump(force=True)
    assert t1.status == t2.status == t4.status == "ok"
    assert svc.stats()["shed_rate"] == 0.25


def test_unknown_model_and_kind_errors():
    bst, X = _train(seed=71)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0)
    reg.publish("m", bst, gate_rows=X[:64])
    t = svc.submit(X[0].reshape(1, -1), model="nope")
    svc.pump(force=True)
    assert t.status == "error" and t.reason == "unknown_model"
    with pytest.raises(lgb.LightGBMError):
        svc.submit(X[0].reshape(1, -1), model="m", kind="banana")
    # malformed shapes are rejected at the door (HTTP maps to 400),
    # never dispatched — a 3-d array must not charge the breaker
    with pytest.raises(lgb.LightGBMError, match="2-d"):
        svc.submit(X[:2].reshape(2, F, 1), model="m")
    with pytest.raises(lgb.LightGBMError, match="non-empty"):
        svc.submit(np.zeros((0, F)), model="m")
    svc.max_request_rows = 8
    with pytest.raises(lgb.LightGBMError, match="serve_max_request_rows"):
        svc.submit(X[:9], model="m")


# ---------------------------------------------------------------------------
# HTTP front end: one round trip through every endpoint
# ---------------------------------------------------------------------------
def test_http_endpoints_round_trip(tmp_path):
    import urllib.error
    import urllib.request

    from lightgbm_tpu.serving.httpd import serve_in_background, shutdown_server
    v1, X = _train(seed=81)
    v2, _ = _train(seed=82, rounds=6)
    path2 = str(tmp_path / "v2.txt")
    v2.save_model(path2)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=128, max_delay=0.002)
    reg.publish("default", v1, gate_rows=X[:128])
    server, th = serve_in_background(svc, port=0)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"

    def post(route, doc):
        req = urllib.request.Request(
            url + route, data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        out = post("/v1/predict", {"rows": [X[0].tolist()]})
        assert out["status"] == "ok"
        want = float(np.asarray(
            v1.predict(X[0].reshape(1, -1),
                       raw_score=True)).reshape(-1)[0])
        assert abs(out["predictions"][0] - want) < 1e-9
        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["ok"] and h["models"] == ["default"]
        # hot-swap through the API, then roll it back
        pub = post("/v1/models/default/publish", {"model_file": path2})
        assert pub["version"] == 2
        assert all(v <= 1 for v in pub["warm_traces"].values())
        out2 = post("/v1/predict", {"rows": [X[0].tolist()]})
        assert abs(out2["predictions"][0] - want) > 1e-12
        rb = post("/v1/models/default/rollback", {})
        assert rb["rolled_back"]
        out3 = post("/v1/predict", {"rows": [X[0].tolist()]})
        assert abs(out3["predictions"][0] - want) < 1e-9
        with urllib.request.urlopen(url + "/stats", timeout=10) as r:
            st = json.loads(r.read())
        assert st["counters"]["served"] >= 3
        assert "default.raw" in st["latency"]
        # an unknown model 404s; a bad kind is the client's bug -> 400
        try:
            post("/v1/predict", {"rows": [X[0].tolist()],
                                 "model": "nope"})
            raise AssertionError("unknown model must 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
        try:
            post("/v1/predict", {"rows": [X[0].tolist()],
                                 "kind": "banana"})
            raise AssertionError("unknown kind must 400")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        # deadline-bounded, lock-free teardown (conlint CL003 contract)
        clean = shutdown_server(server, th, svc)
    assert clean, "HTTP serve thread failed to exit inside the deadline"


def test_http_admin_token_gates_operator_endpoints(tmp_path):
    """With serve_admin_token configured, publish/rollback demand the
    X-Admin-Token header — a reachable port is not an operator
    credential."""
    import urllib.error
    import urllib.request

    from lightgbm_tpu.serving.httpd import make_server
    v1, X = _train(seed=83)
    p1 = str(tmp_path / "m.txt")
    v1.save_model(p1)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=128, max_delay=0.002)
    reg.publish("default", v1, gate_rows=X[:128])
    svc.start()
    server = make_server(svc, port=0, admin_token="sesame")
    import threading as _t
    _t.Thread(target=server.serve_forever, daemon=True).start()
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}/v1/models/default/publish"
    body = json.dumps({"model_file": p1}).encode()
    try:
        try:
            urllib.request.urlopen(urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"}),
                timeout=10)
            raise AssertionError("tokenless publish must 403")
        except urllib.error.HTTPError as exc:
            assert exc.code == 403
        with urllib.request.urlopen(urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json",
                         "X-Admin-Token": "sesame"}), timeout=10) as r:
            assert json.loads(r.read())["version"] == 2
    finally:
        from lightgbm_tpu.serving.httpd import shutdown_server
        shutdown_server(server, service=svc)


def test_wrong_width_requests_rejected_never_trip_breaker():
    """A client sending the wrong feature count is rejected at submit
    (structurally, against the model's num_feature — the HTTP layer
    maps it to 400): it can neither crash a healthy coalesced batch
    nor charge the model's breaker, and well-formed requests in the
    same pump answer fine."""
    bst, X = _train(seed=95)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         breaker_threshold=1)
    reg.publish("m", bst, gate_rows=X[:64])
    good = [svc.submit(X[i].reshape(1, -1), model="m")
            for i in range(3)]
    for _ in range(3):
        with pytest.raises(lgb.LightGBMError, match="features"):
            svc.submit(np.zeros((1, F + 2)), model="m")
    svc.pump(force=True)
    assert all(t.status == "ok" for t in good), \
        [(t.status, t.reason) for t in good]
    # even at breaker_threshold=1, client faults never tripped it
    assert svc.breakers["m"].state == "closed"
    assert svc.breakers["m"].trip_count == 0


def test_serve_config_wiring(tmp_path):
    """The CLI task=serve path: serve_* params build the registry +
    service, serve_models loads and warm-publishes each entry."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving.httpd import (build_from_config,
                                            load_models_from_config)
    v1, X = _train(seed=91)
    p1 = str(tmp_path / "m1.txt")
    v1.save_model(p1)
    cfg = Config({"task": "serve", "serve_models": f"alpha={p1}",
                  "serve_flush_rows": 128, "serve_flush_ms": 1.0,
                  "serve_queue_depth": 32, "serve_rate_limit": 0,
                  "serve_breaker_threshold": 2,
                  "serve_default_deadline_ms": 500.0,
                  "serve_pack_budget_mb": 64.0, "verbosity": -1})
    reg, svc = build_from_config(cfg)
    assert reg.pack_budget_bytes == 64_000_000
    assert svc.batcher.flush_rows == 128
    assert svc.default_deadline == 0.5
    load_models_from_config(reg, cfg)
    assert reg.names() == ["alpha"]
    t = svc.submit(X[0].reshape(1, -1), model="alpha")
    svc.pump(force=True)
    assert t.status == "ok"
    want = np.asarray(v1.predict(X[0].reshape(1, -1),
                                 raw_score=True)).reshape(-1)
    np.testing.assert_allclose(np.asarray(t.result).reshape(-1), want,
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# fault injectors (robustness/faultinject.py serve extensions)
# ---------------------------------------------------------------------------
def test_faultinject_serve_injectors_contract():
    with faultinject.injected(fail_predict_model="m",
                              fail_predict_times=2):
        faultinject.maybe_fail_predict("other")     # no match: silent
        with pytest.raises(faultinject.InjectedPredictError):
            faultinject.maybe_fail_predict("m")
        with pytest.raises(faultinject.InjectedPredictError):
            faultinject.maybe_fail_predict("m")
        faultinject.maybe_fail_predict("m")         # exhausted: silent
    with faultinject.injected(slow_predict_model=None,
                              slow_predict_seconds=0.5,
                              slow_predict_times=1):
        assert faultinject.maybe_slow_predict("anything") == 0.5
        assert faultinject.maybe_slow_predict("anything") == 0.0
    with faultinject.injected(flood_tenant="t", flood_requests=9):
        assert faultinject.take_flood() == ("t", 9)
        assert faultinject.take_flood() is None     # one-shot
    assert faultinject.take_flood() is None         # cleared


def test_per_tenant_latency_in_stats_and_prometheus():
    """ROADMAP item 1a slice: the admission layer's tenant id reaches
    (1) /stats as exact per-tenant p50/p99 (telemetry may be off) and
    (2) with a telemetry session on, the `serve.tenant.<t>.<kind>`
    span histograms the Prometheus export carries."""
    from lightgbm_tpu.obs import telemetry as obs
    from lightgbm_tpu.obs.exporters import prometheus_text

    bst, X = _train()
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         queue_depth=1024)
    reg.publish("m", bst, gate_rows=X)
    obs.get().reset(mode="counters")
    try:
        for tenant, lo in (("web", 0), ("app", 8)):
            for i in range(4):
                svc.submit(X[lo + i].reshape(1, -1), model="m",
                           kind="raw", tenant=tenant)
        svc.pump(force=True)
        stats = svc.stats()
        tl = stats["tenant_latency"]
        assert set(tl) == {"web", "app"}
        for t in ("web", "app"):
            assert tl[t]["count"] == 4
            assert tl[t]["p99_s"] >= tl[t]["p50_s"] >= 0.0
        # the dispatch span carries the tenant when a lane is
        # single-tenant... here both lanes coalesced into one batch:
        # per-tenant exactness lives in the _complete samples
        rep = obs.get().report()
        spans = {k: v for k, v in rep["spans"].items()
                 if k.startswith("serve.tenant.")}
        assert set(spans) == {"serve.tenant.web.raw",
                              "serve.tenant.app.raw"}, spans
        assert all(v["count"] == 4 for v in spans.values())
        text = prometheus_text(obs.get())
        assert 'serve_tenant_web_raw' in text.replace(".", "_")
    finally:
        obs.get().reset(mode="off")


def test_cohort_fault_degrades_without_spending_injection_budget():
    """Review fix (PR 13): the cohort pre-check probes armed faults
    NON-destructively (`predict_fault_armed`), so N armed failures
    record N per-model breaker failures with cohort lanes on — the
    wave degrades to the per-model path and the breaker trips after
    exactly `threshold` waves, same as serve_cohort=False.  Every
    drained ticket still answers (nothing stranded)."""
    (b0, X0), (b1, X1) = _train(seed=41), _train(seed=43)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         queue_depth=1024, cohort=True,
                         breaker_threshold=2)
    reg.publish("a", b0, gate_rows=X0)
    reg.publish("b", b1, gate_rows=X1)
    with faultinject.injected(fail_predict_model="a",
                              fail_predict_times=2):
        for _ in range(2):
            ta = svc.submit(X0[:8], model="a", kind="raw", tenant="a")
            tb = svc.submit(X1[:8], model="b", kind="raw", tenant="b")
            svc.pump(force=True)
            assert tb.status == "ok"
            assert ta.status == "error", (ta.status, ta.reason)
        assert svc.counters["cohort_dispatches"] == 0
        assert svc.breakers["a"].state == "open"
    # budget exhausted + breaker open: "a" is excluded from waves, a
    # 1-model remainder is below cohort_min, so "b" serves per-model
    tb = svc.submit(X1[:8], model="b", kind="raw", tenant="b")
    svc.pump(force=True)
    assert tb.status == "ok"

    # successful cohort dispatches RESET consecutive-failure counts: a
    # stray failure must not accumulate across cohort successes
    svc2 = ServingService(reg, flush_rows=64, max_delay=10.0,
                          queue_depth=1024, cohort=True,
                          breaker_threshold=2)
    with faultinject.injected(fail_predict_model="a",
                              fail_predict_times=1):
        svc2.submit(X0[:8], model="a", kind="raw")
        svc2.submit(X1[:8], model="b", kind="raw")
        svc2.pump(force=True)                 # one failure recorded
    assert svc2.breakers["a"].consecutive_failures == 1
    svc2.submit(X0[:8], model="a", kind="raw")
    svc2.submit(X1[:8], model="b", kind="raw")
    assert svc2.pump(force=True) == 1         # clean cohort wave
    assert svc2.counters["cohort_dispatches"] == 1
    assert svc2.breakers["a"].consecutive_failures == 0
