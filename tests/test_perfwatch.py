"""Perf observatory (ISSUE-11): the BENCH_history.jsonl trajectory
store, the hardware/config fingerprint, the noise-aware regression
gate and the ``tools/perfwatch.py`` CLI on top.

Covers the acceptance contract — the gate flags a planted 3x slowdown
(rc != 0) and passes identical re-runs clean (rc == 0) — plus the
concurrency/corruption envelope of an append-only store: torn-file
recovery, two writers interleaving, and the v2 -> v3 BENCH_obs schema
round-trip through ``validate_bench_obs``.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

from lightgbm_tpu.obs import benchio, regress

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_perfwatch():
    spec = importlib.util.spec_from_file_location(
        "perfwatch", os.path.join(HERE, "tools", "perfwatch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def hist(tmp_path):
    return str(tmp_path / "BENCH_history.jsonl")


def _seed(hist_path, values, tool="t", metric="per_iter_s", config=None):
    for v in values:
        regress.append_entry(tool, {metric: v}, config=config,
                             path=hist_path)


# ---------------------------------------------------------------------------
# fingerprint + schema v3
# ---------------------------------------------------------------------------
def test_fingerprint_stable_and_shape_banded():
    cfg = {"num_leaves": 63, "tpu_row_chunk": 4096, "seed": 7,
           "verbosity": -1}
    a = regress.fingerprint(cfg, rows=70_000, features=28)
    b = regress.fingerprint(cfg, rows=100_000, features=28)
    assert regress.fingerprint_key(a) == regress.fingerprint_key(b), \
        "70k and 100k rows share the 2^17 band"
    c = regress.fingerprint(cfg, rows=200_000, features=28)
    assert regress.fingerprint_key(a) != regress.fingerprint_key(c), \
        "a different shape band must fork the series"
    d = regress.fingerprint({**cfg, "tpu_row_chunk": 512}, rows=70_000,
                            features=28)
    assert regress.fingerprint_key(a) != regress.fingerprint_key(d), \
        "a perf-relevant knob must fork the series"
    e = regress.fingerprint({**cfg, "seed": 99}, rows=70_000,
                            features=28)
    assert regress.fingerprint_key(a) == regress.fingerprint_key(e), \
        "perf-irrelevant params must NOT fork the series"
    # the live identity is honest about this host
    assert a["cpu_count"] == os.cpu_count()
    assert a["backend"] == "cpu"
    assert a["device_count"] >= 1


def test_fingerprint_knob_alias_and_extra():
    # bench.py/ab_bench.py record "leaves": it must fork the series
    # exactly like "num_leaves" would
    f63 = regress.fingerprint({"leaves": 63}, rows=1000)
    f255 = regress.fingerprint({"leaves": 255}, rows=1000)
    assert f63["knobs"]["num_leaves"] == 63
    assert regress.fingerprint_key(f63) != regress.fingerprint_key(f255)
    assert regress.fingerprint_key(f63) == regress.fingerprint_key(
        regress.fingerprint({"num_leaves": 63}, rows=1000))
    # experiment parameters (ab_bench per-arm overrides, frontier K)
    # fork via `extra`
    e1 = regress.fingerprint({}, rows=1000,
                             extra={"b": {"tpu_megakernel": "xla"}})
    e2 = regress.fingerprint({}, rows=1000,
                             extra={"b": {"tpu_row_chunk": 512}})
    assert regress.fingerprint_key(e1) != regress.fingerprint_key(e2)


def test_bench_obs_v3_roundtrip_and_trajectory(tmp_path, hist):
    obs_path = str(tmp_path / "BENCH_obs.json")
    out = benchio.write_bench_obs(
        "unit_bench", {"rows": 5000, "features": 10, "num_leaves": 31},
        {"per_iter_s": 0.25, "note": "x"},
        metrics={"per_iter_s": 0.25}, rows=5000, features=10,
        path=obs_path, history_path=hist)
    doc = json.load(open(out))
    assert doc["schema"] == benchio.SCHEMA
    assert benchio.validate_bench_obs(doc) == []
    assert doc["aborted"] is False
    assert doc["fingerprint"]["shape_band"]["rows"] == "2^13"
    entries, skipped = regress.read_history(hist)
    assert skipped == 0 and len(entries) == 1
    ent = entries[0]
    assert ent["metrics"] == {"per_iter_s": 0.25}
    assert ent["fingerprint_key"] == regress.fingerprint_key(
        doc["fingerprint"])


def test_v2_documents_still_validate():
    v2 = {"schema": benchio.SCHEMA_V2, "tool": "bench", "config": {},
          "timings": {}, "compile_counts": {}, "memory_peaks": {},
          "health": None}
    assert benchio.validate_bench_obs(v2) == []
    # v3 without a fingerprint is NOT valid
    v3 = dict(v2, schema=benchio.SCHEMA)
    assert any("fingerprint" in p
               for p in benchio.validate_bench_obs(v3))
    v3["fingerprint"] = regress.fingerprint({})
    v3["aborted"] = True          # the validator accepts aborted docs
    assert benchio.validate_bench_obs(v3) == []
    assert any("schema" in p for p in benchio.validate_bench_obs(
        {"schema": "lightgbm-tpu/bench-obs/v1"}))


# ---------------------------------------------------------------------------
# store robustness: torn files, concurrent writers
# ---------------------------------------------------------------------------
def test_torn_file_recovery(hist):
    _seed(hist, [1.0, 1.1, 0.9])
    # a writer died mid-record: half a JSON object, no trailing newline
    with open(hist, "a") as fh:
        fh.write('{"schema": "lightgbm-tpu/bench-history/v1", "tool"')
    entries, skipped = regress.read_history(hist)
    assert len(entries) == 3 and skipped == 1
    # the next append detaches itself from the torn tail and survives
    regress.append_entry("t", {"per_iter_s": 1.05}, path=hist)
    entries, skipped = regress.read_history(hist)
    assert len(entries) == 4 and skipped == 1
    assert entries[-1]["metrics"]["per_iter_s"] == 1.05


def test_foreign_and_blank_lines_skipped(hist):
    _seed(hist, [2.0])
    with open(hist, "a") as fh:
        fh.write("\n")
        fh.write('{"schema": "something-else", "metrics": {}}\n')
        fh.write("not json at all\n")
    entries, skipped = regress.read_history(hist)
    assert len(entries) == 1 and skipped == 2


def test_concurrent_appends_interleave_whole_lines(hist):
    n_writers, per = 4, 40

    def writer(i):
        for j in range(per):
            regress.append_entry(f"w{i}", {"wall_s": float(j)},
                                 path=hist)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries, skipped = regress.read_history(hist)
    assert skipped == 0, "interleaved appends must never splice lines"
    assert len(entries) == n_writers * per
    per_tool = {}
    for e in entries:
        per_tool.setdefault(e["tool"], []).append(e["metrics"]["wall_s"])
    # each writer's own records kept their order (O_APPEND semantics)
    assert all(v == sorted(v) and len(v) == per
               for v in per_tool.values())


# ---------------------------------------------------------------------------
# the noise-aware detector
# ---------------------------------------------------------------------------
def test_detector_warmup_never_flags(hist):
    _seed(hist, [1.0, 10.0])      # 10x jump, but only 1 prior sample
    findings = regress.evaluate(*_read(hist))
    assert [f.status for f in findings] == ["warmup"]
    assert not regress.regressions(findings)


def _read(hist_path):
    entries, _ = regress.read_history(hist_path)
    return (entries,)


def test_detector_noise_band_and_planted_slowdown(hist):
    _seed(hist, [1.0, 1.02, 0.98, 1.01])
    # within the floor: ok
    regress.append_entry("t", {"per_iter_s": 1.05}, path=hist)
    findings = regress.evaluate(*_read(hist))
    assert [f.status for f in findings] == ["ok"]
    # 3x: REGRESSED
    regress.append_entry("t", {"per_iter_s": 3.0}, path=hist)
    findings = regress.evaluate(*_read(hist))
    assert [f.status for f in findings] == ["REGRESSED"]
    assert len(regress.regressions(findings)) == 1
    # the paired statistic is the median/MAD of the priors
    f = findings[0]
    assert f.median == pytest.approx(1.01, abs=0.02)
    assert f.n_prior == 5


def test_detector_direction_throughput_and_aborted(hist):
    # throughput metric: LOWER is worse
    for v in (100.0, 101.0, 99.0, 100.5):
        regress.append_entry("t", {"contrib_rows_per_s": v}, path=hist)
    regress.append_entry("t", {"contrib_rows_per_s": 30.0}, path=hist)
    findings = regress.evaluate(*_read(hist))
    assert [f.status for f in findings] == ["REGRESSED"]
    # an aborted entry is kept in the file but excluded from the series
    regress.append_entry("t", {"contrib_rows_per_s": 1.0}, path=hist,
                         aborted=True)
    entries, _ = regress.read_history(hist)
    assert entries[-1]["aborted"] is True
    assert regress.evaluate(entries)[0].value == 30.0
    # unknown-direction metrics report but never gate
    for v in (5.0, 5.0, 5.0, 5.0, 50.0):
        regress.append_entry("t2", {"detect_tick": v}, path=hist)
    f2 = [f for f in regress.evaluate(*_read(hist))
          if f.metric == "detect_tick"][0]
    assert f2.status == "ungated" and not f2.regressed
    # zero-centered signed deltas (ab_bench paired_delta_s): the
    # relative floor vanishes at median ~0, so sub-millisecond jitter
    # would gate — delta metrics must never gate
    for v in (-0.0002, 0.0001, 0.0003, -0.0001, 0.002):
        regress.append_entry("t3", {"paired_delta_s": v}, path=hist)
    f3 = [f for f in regress.evaluate(*_read(hist))
          if f.metric == "paired_delta_s"][0]
    assert f3.status == "ungated" and not f3.regressed


def test_different_fingerprints_never_compared(hist):
    # 4 fast runs in one shape band, then a "slow" run in another band:
    # series are split by fingerprint, so nothing can regress
    for v in (1.0, 1.0, 1.0, 1.0):
        regress.append_entry("t", {"per_iter_s": v},
                             config={"rows": 1000}, path=hist)
    regress.append_entry("t", {"per_iter_s": 9.0},
                         config={"rows": 10_000_000}, path=hist)
    findings = regress.evaluate(*_read(hist))
    by_status = sorted(f.status for f in findings)
    assert by_status == ["ok", "warmup"]


# ---------------------------------------------------------------------------
# the CLI gate: rc contract + drill (acceptance)
# ---------------------------------------------------------------------------
def test_check_rc_contract_in_process(hist, capsys):
    pw = _load_perfwatch()
    _seed(hist, [1.0, 1.0, 1.0, 1.0])
    assert pw.main(["check", "--history", hist]) == 0
    # planted 3x slowdown -> rc != 0
    regress.append_entry("t", {"per_iter_s": 3.0}, path=hist)
    assert pw.main(["check", "--history", hist]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "per_iter_s" in out
    # a re-run of identical measurements passes clean again
    regress.append_entry("t", {"per_iter_s": 1.0}, path=hist)
    assert pw.main(["check", "--history", hist]) == 0


def test_drill_in_process(hist, capsys):
    pw = _load_perfwatch()
    assert pw.main(["drill", "--history", hist]) == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    rep = json.loads(last)
    assert rep["detected"] is True
    assert rep["clean_rc"] == 0 and rep["planted_rc"] != 0 \
        and rep["rerun_rc"] == 0
    # the drill's measurements came from the injected clock, not the
    # host: every baseline sample is exactly dt
    entries, _ = regress.read_history(hist)
    assert entries[0]["metrics"]["wall_s"] == pytest.approx(0.1)
    import time
    assert regress._CLOCK is time.perf_counter       # restored


def test_drill_scoped_to_own_series_on_shared_store(hist, capsys):
    """A pre-existing regression in an UNRELATED series must neither
    fail the drill nor be masked by it: the drill's internal checks
    are scoped to its own perfwatch.drill entries."""
    pw = _load_perfwatch()
    _seed(hist, [1.0, 1.0, 1.0, 1.0, 5.0], tool="bench")   # regressed
    assert pw.main(["check", "--history", hist]) == 1
    assert pw.main(["drill", "--history", hist]) == 0
    capsys.readouterr()
    # the shared store still gates its own regression afterwards,
    # and scoping by tool isolates the clean drill series
    assert pw.main(["check", "--history", hist]) == 1
    assert pw.main(["check", "--history", hist, "--tool",
                    "perfwatch.drill"]) == 0
    # a typo'd --tool must fail loudly, not gate nothing with rc 0
    assert pw.main(["check", "--history", hist, "--tool",
                    "no_such_tool"]) == 2


def test_report_renders_trajectory(hist, capsys):
    pw = _load_perfwatch()
    _seed(hist, [1.0, 1.1, 0.9], tool="bench")
    assert pw.main(["report", "--history", hist]) == 0
    out = capsys.readouterr().out
    assert "bench/per_iter_s" in out and "n=3" in out
    assert pw.main(["report", "--history", hist, "--tool",
                    "nonexistent"]) == 0


def test_drill_cli_subprocess():
    """The tier-1 smoke of the acceptance contract through the real
    entry point: plants a 3x slowdown via clock injection, asserts
    detection (rc != 0 inside), exits 0 only when the whole contract
    holds."""
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "perfwatch.py"),
         "drill", "--scale", "3.0"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["detected"] is True and rep["ok"] is True
    assert rep["clean_rc"] == 0 and rep["planted_rc"] != 0


# ---------------------------------------------------------------------------
# export-on-failure + producer wiring
# ---------------------------------------------------------------------------
def test_abort_guard_emits_artifact_on_failure(tmp_path, hist):
    obs_path = str(tmp_path / "BENCH_obs.json")
    with pytest.raises(SystemExit):
        with benchio.abort_guard("unit_bench", {"rows": 100},
                                 path=obs_path, history_path=hist):
            raise SystemExit("measured tool died")
    doc = json.load(open(obs_path))
    assert doc["aborted"] is True
    assert "measured tool died" in doc["timings"]["error"]
    assert benchio.validate_bench_obs(doc) == []
    entries, _ = regress.read_history(hist)
    assert len(entries) == 1 and entries[0]["aborted"] is True


def test_abort_guard_keeps_real_artifact_on_late_failure(tmp_path,
                                                         hist):
    """A lane that measured, wrote its artifact and THEN failed its
    assertion must keep the real (non-aborted) artifact — the
    measurement finished; the gate didn't."""
    obs_path = str(tmp_path / "BENCH_obs.json")
    with pytest.raises(SystemExit):
        with benchio.abort_guard("unit_bench", {"rows": 100},
                                 path=obs_path,
                                 history_path=hist) as guard:
            guard.write({"per_iter_s": 0.5})
            raise SystemExit("assertion after the artifact")
    doc = json.load(open(obs_path))
    assert doc["aborted"] is False
    assert doc["timings"] == {"per_iter_s": 0.5}


def test_profile_tools_append_fingerprinted_entries(tmp_path,
                                                    monkeypatch,
                                                    capsys):
    """profile_construct --smoke (cheap at tiny sizes) appends a v3
    fingerprinted trajectory entry — the producer-wiring acceptance
    lane that is feasible in-window (bench.py/ab_bench wiring runs the
    identical guard.write path and is pinned by the committed seed
    trajectory)."""
    hist = str(tmp_path / "h.jsonl")
    monkeypatch.setenv("BENCH_HISTORY_PATH", hist)
    monkeypatch.setenv("BENCH_OBS_PATH", str(tmp_path / "obs.json"))
    spec = importlib.util.spec_from_file_location(
        "profile_construct", os.path.join(HERE, "tools",
                                          "profile_construct.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--rows", "4000", "--features", "6"]) == 0
    capsys.readouterr()
    entries, skipped = regress.read_history(hist)
    assert skipped == 0 and len(entries) == 1
    ent = entries[0]
    assert ent["tool"] == "profile_construct"
    assert ent["metrics"]["vectorized_s"] > 0
    assert ent["fingerprint"]["shape_band"]["rows"] == "2^12"
    doc = json.load(open(tmp_path / "obs.json"))
    assert benchio.validate_bench_obs(doc) == []


def test_committed_seed_trajectory_is_valid_and_covers_producers():
    """The repo's bench trajectory is non-empty (ISSUE-11 satellite):
    the committed BENCH_history.jsonl parses clean, every entry carries
    a v3 fingerprint, and the acceptance producers — bench.py,
    ab_bench, and at least two profile_* tools — have real entries."""
    path = os.path.join(HERE, "BENCH_history.jsonl")
    entries, skipped = regress.read_history(path)
    assert skipped == 0, "committed trajectory must parse clean"
    assert entries, "committed trajectory must be non-empty"
    tools = {e["tool"] for e in entries}
    assert "bench" in tools
    assert any(t.startswith("ab_bench") for t in tools)
    assert len([t for t in tools if t.startswith("profile_")]) >= 2
    for e in entries:
        assert e["fingerprint_key"] == regress.fingerprint_key(
            e["fingerprint"])
        assert e["metrics"], "seed entries must carry metrics"
    # report renders it, and the gate runs clean on the seed
    text = regress.render_report(entries)
    assert "bench/" in text
    assert not regress.regressions(regress.evaluate(entries))


def test_metric_direction_memory_suffixes_gate_higher_worse():
    """Memory metrics (footprint in MB / RSS / bytes) are higher-worse
    and must gate even when their name contains "delta" — a
    "train_rss_delta_mb" is a bounded footprint measurement, not a
    signed near-zero A/B difference (those keep direction 0)."""
    from lightgbm_tpu.obs.regress import metric_direction
    assert metric_direction("train_rss_delta_mb") == 1
    assert metric_direction("rss_delta_mb") == 1
    assert metric_direction("peak_rss_kb") == 1
    assert metric_direction("vm_rss") == 1
    assert metric_direction("dedup_device_bytes") == 1
    # unchanged pre-existing behaviors
    assert metric_direction("paired_delta_s") == 0      # signed A/B diff
    assert metric_direction("train_s") == 1
    assert metric_direction("rows_per_s") == -1
    assert metric_direction("binned_residents") == 0    # unknown name
