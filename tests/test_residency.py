"""Single-copy binned residency (ISSUE 18).

The fused trainer must train at ~1x the binned footprint: it ADOPTS the
learner/ingest master buffer into the physical carrier (XLA donation
aliases, never copies), updates it in place every iteration, and retires
every other binned-footprint reference.  Reading scores or resuming
training converts the physical layout back into a carrier instead of
dropping it; anything that later needs pristine bins (a second booster
on the shared dataset, host recovery) rebuilds them bit-identically by
unpermuting the live carrier.  The HBM ledger attributes the surviving
resident and deduplicates aliased buffers.
"""

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.obs import memory as obs_memory

BASE = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
        "min_data_in_leaf": 5, "metric": ""}


def _data(n=1200, f=8, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    X[rng.rand(n) < 0.05, 2] = np.nan
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.1 * rng.normal(size=n)
    return X, y


class _Seq(lgb.Sequence):
    def __init__(self, mat, batch_size=211):
        self._m = mat
        self.batch_size = batch_size

    def __getitem__(self, idx):
        return self._m[idx]

    def __len__(self):
        return len(self._m)


def _tree_part(model_str: str) -> str:
    head, sep, tail = model_str.partition("parameters:")
    return head


def test_fused_adoption_single_resident():
    """After the first fused iteration the physical carrier IS the
    learner's master buffer (same device pointer — donation aliased, not
    copied), the step updates it in place, learner/ingest references are
    retired, and the ledger attributes the carrier's bytes."""
    X, y = _data()
    bst = lgb.Booster(dict(BASE), lgb.Dataset(X, label=y))
    g = bst._gbdt
    lr = g.learner
    p0 = lr._part0
    assert p0 is not None
    ptr0 = p0.unsafe_buffer_pointer()

    bst.update()
    assert g._phys is not None, "fused path must engage on this config"
    pb = g._phys[0]
    assert pb.unsafe_buffer_pointer() == ptr0, \
        "adoption must alias the donated master buffer, not copy it"
    ptr1 = pb.unsafe_buffer_pointer()

    bst.update()
    assert g._phys[0].unsafe_buffer_pointer() == ptr1, \
        "the donated fused step must update the bins in place"

    # every other binned-footprint reference is retired
    assert lr._part0 is None
    ing = getattr(lr, "_ingest", None)
    residents = 1
    for cand in (getattr(ing, "buffer", None), lr._part0):
        if cand is not None and not cand.is_deleted():
            residents += 1
    assert residents == 1

    st = obs_memory.snapshot()["owners"].get("train.state", {})
    assert st.get("device_unique_bytes", 0) >= int(g._phys[0].nbytes), \
        "the ledger must attribute the adopted carrier to train.state"


def test_scores_read_resume_parity():
    """Reading scores mid-training converts the physical layout into the
    carrier (it must NOT destroy the only binned copy); resuming trains
    structurally identical trees to an uninterrupted run.  Leaf values
    may drift at float-summation level: the resume re-inits from the
    identity row layout while an uninterrupted run keeps the permuted
    layout, so reductions reorder (pre-existing fused re-init behavior,
    same before and after single-copy residency)."""
    X, y = _data(seed=7)

    bst_a = lgb.Booster(dict(BASE), lgb.Dataset(X, label=y))
    for _ in range(4):
        bst_a.update()

    bst_b = lgb.Booster(dict(BASE), lgb.Dataset(X, label=y))
    for _ in range(2):
        bst_b.update()
    s = bst_b._gbdt.scores                 # forces physical -> carrier
    assert np.isfinite(np.asarray(s)).all()
    assert bst_b._gbdt._phys_carrier is not None
    for _ in range(2):
        bst_b.update()

    keep = ("split_feature=", "threshold=", "left_child=", "right_child=",
            "decision_type=", "num_leaves=", "leaf_count=")

    def _structure(bst):
        return [ln for ln in bst.model_to_string().splitlines()
                if ln.startswith(keep)]

    assert _structure(bst_b) == _structure(bst_a)
    np.testing.assert_allclose(bst_b.predict(X), bst_a.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_second_booster_recovers_pristine_bins():
    """A second booster on a dataset whose buffer was ADOPTED by a first
    booster must recover pristine bins from the live (permuted) carrier
    and train bit-identically to a booster on a fresh dataset."""
    X, y = _data(seed=9)
    ds = lgb.Dataset(X, label=y)

    bst1 = lgb.Booster(dict(BASE), ds)
    for _ in range(2):
        bst1.update()

    bst2 = lgb.Booster(dict(BASE), ds)      # shares the adopted dataset
    for _ in range(2):
        bst2.update()

    bst_ref = lgb.Booster(dict(BASE), lgb.Dataset(X, label=y))
    for _ in range(2):
        bst_ref.update()

    ref = _tree_part(bst_ref.model_to_string())
    assert _tree_part(bst1.model_to_string()) == ref
    assert _tree_part(bst2.model_to_string()) == ref


def test_refit_after_adoption_bit_identity():
    """refit() needs the original bins after the trainer adopted (and
    permuted) the only binned copy — the traversal must read the live
    carrier, bit-matching a refit from a never-adopted resident arm."""
    X, y = _data(seed=11)
    y2 = y + 0.25

    p = dict(BASE, num_iterations=3)
    m_stream = lgb.train(dict(p, bin_construct_mode="sketch"),
                         lgb.Dataset([_Seq(X)], label=y))
    m_res = lgb.train(p, lgb.Dataset(X, label=y))
    r_stream = m_stream.refit(X, y2)
    r_res = m_res.refit(X, y2)
    assert (_tree_part(r_stream.model_to_string())
            == _tree_part(r_res.model_to_string()))


@pytest.mark.parametrize("extra", [
    {"objective": "regression_l1"},          # leaf renewal traverses train
    {"linear_tree": True, "min_data_in_leaf": 20, "num_leaves": 7},
])
def test_streaming_train_traversal_parity(extra):
    """Objectives whose training loop traverses the train data (l1 leaf
    renewal, linear leaf fitting) must bit-match the resident-matrix arm
    when the only binned copy is the adopted streaming carrier."""
    X, y = _data(seed=13)
    p = dict(BASE, num_iterations=4, **extra)
    m_res = lgb.train(dict(p, bin_construct_mode="exact"),
                      lgb.Dataset(X, label=y))
    m_stream = lgb.train(dict(p, bin_construct_mode="sketch"),
                         lgb.Dataset([_Seq(X)], label=y))
    assert (_tree_part(m_stream.model_to_string())
            == _tree_part(m_res.model_to_string()))


def test_host_binned_recovery_streams_in_blocks(monkeypatch):
    """host_binned() recovery after adoption stages bounded row blocks
    (one (G, block_rows) device slab at a time), never the full matrix,
    and bit-matches the resident reference."""
    X, y = _data(n=2400, seed=15)
    ref = BinnedDataset.from_matrix(
        X, Config({"verbosity": -1, "bin_construct_mode": "exact"}),
        label=y).host_binned()

    params = dict(BASE, bin_construct_mode="sketch")
    d = lgb.Dataset([_Seq(X, 173)], label=y, params=params)
    d.construct(params)
    ds = d._inner
    assert ds.device_ingest is not None and ds.binned is None

    # adopt the ingest buffer so host_binned must go through carrier
    # recovery first (the interesting path); keep the booster alive —
    # its live carrier is what the recovery callback unpermutes
    bst = lgb.Booster(params, d)
    bst.update()
    di = ds.device_ingest
    assert di.buffer is None, "training must have adopted the buffer"
    block = 256
    staged = []
    real_get = jax.device_get

    def spy(x, *a, **k):
        if hasattr(x, "nbytes"):
            staged.append(int(x.nbytes))
        return real_get(x, *a, **k)

    monkeypatch.setattr(jax, "device_get", spy)
    out = di.host_binned(block_rows=block)
    monkeypatch.undo()

    np.testing.assert_array_equal(out, ref)
    bound = di.G * block * np.dtype(di.dtype).itemsize
    assert staged, "blocked recovery must stage through device_get"
    assert max(staged) <= bound, (max(staged), bound)
    assert len(staged) >= -(-di.N // block)


def test_ledger_dedup_counts_aliased_buffers_once():
    """The HBM ledger's dedup accounting: the same device buffer
    registered under two owners contributes once to dedup_device_bytes,
    and each owner's device_unique_bytes reflects first-attribution in
    deterministic (sorted owner name) order."""
    import jax.numpy as jnp

    arr = jnp.arange(4096, dtype=jnp.int32)

    class _Holder:
        pass

    a, b = _Holder(), _Holder()
    a.x = arr
    b.x = arr
    obs_memory.register("ztest.alias_a", a, lambda o: [o.x])
    obs_memory.register("ztest.alias_b", b, lambda o: [o.x])
    try:
        snap = obs_memory.snapshot()
        oa = snap["owners"]["ztest.alias_a"]
        ob = snap["owners"]["ztest.alias_b"]
        nb = int(arr.nbytes)
        assert oa["device_bytes"] == nb and ob["device_bytes"] == nb
        # sorted order: alias_a attributes the buffer, alias_b sees 0
        assert oa["device_unique_bytes"] == nb
        assert ob["device_unique_bytes"] == 0
        assert snap["dedup_device_bytes"] <= sum(
            o["device_bytes"] for o in snap["owners"].values())
    finally:
        del a, b
