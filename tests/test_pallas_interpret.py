"""Off-TPU correctness lane for the Pallas kernels via the interpreter
(VERDICT r2 #10: Pallas correctness must not depend on TPU availability).
Small shapes — the interpreter is slow."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)
from lightgbm_tpu.ops import split as so
from lightgbm_tpu.ops.split_pallas import best_split_pair_pallas
from lightgbm_tpu.ops.split_megakernel_pallas import (
    both_children_hist_xla, split_megakernel_pallas, unpack_hist4)


def _oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl):
    pb = pb.copy()
    pg = pg.copy()
    colv = pb[col, start:start + cnt].astype(np.int32)
    fb_raw = colv - bstart
    in_r = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = np.where(isb == 1, np.where(in_r, fb_raw, dbin), colv)
    if mtype == 1:
        miss = fb == dbin
    elif mtype == 2:
        miss = fb == nb - 1
    else:
        miss = np.zeros_like(fb, bool)
    gl = np.where(miss, dl != 0, fb <= thr)
    order = np.concatenate([np.where(gl)[0], np.where(~gl)[0]]) + start
    pb[:, start:start + cnt] = pb[:, order]
    pg[:, start:start + cnt] = pg[:, order]
    return pb, pg, int(gl.sum())


@pytest.mark.parametrize("trial", [0, 1, 2])
def test_partition_kernel_interpreted(trial):
    C, G32 = 256, 32
    Np = 8 * C
    rng = np.random.RandomState(trial)
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 4 * C))
    cnt = int(rng.randint(0, 3 * C))
    col = int(rng.randint(0, 28))
    nb = int(rng.randint(10, 250))
    mtype = int(rng.randint(0, 3))
    dbin = int(rng.randint(0, nb))
    thr = int(rng.randint(0, nb))
    dl = int(rng.rand() < 0.5)
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, dbin,
                            mtype, thr, dl)
    sc = make_scalars(start, cnt, col, 0, 0, nb, dbin, mtype, thr, dl)
    rpb, rpg, _, rnl = partition_leaf_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
        row_chunk=C, interpret=True)
    assert int(np.asarray(rnl)[0, 0]) == enl
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(
        np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))


def test_split_kernel_interpreted():
    rng = np.random.RandomState(3)
    F, BF = 7, 31
    num_bin = rng.randint(3, BF + 1, size=F).astype(np.int32)
    missing = rng.randint(0, 3, size=F).astype(np.int32)
    dflt = np.where(missing == 1, rng.randint(0, 3, size=F), 0).astype(np.int32)
    ctx = so.SplitContext(jnp.asarray(num_bin), jnp.asarray(missing),
                          jnp.asarray(dflt), jnp.zeros(F, jnp.int32),
                          jnp.arange(F, dtype=jnp.int32))
    half = np.zeros((F, 8), np.int32)
    half[:, 0] = num_bin
    half[:, 1] = missing
    half[:, 2] = dflt
    fmeta = jnp.asarray(np.concatenate([half, half]))
    hists, infos, refs = [], [], []
    for c in range(2):
        hist = np.zeros((F, BF, 2), np.float32)
        for f in range(F):
            hist[f, :num_bin[f], 0] = rng.normal(size=num_bin[f])
            hist[f, :num_bin[f], 1] = rng.uniform(0.01, 2.0,
                                                  size=num_bin[f])
        sum_g = float(hist[0, :, 0].sum())
        sum_h = float(hist[0, :, 1].sum())
        cnt = 1000 + 200 * c
        mask = rng.rand(F) > 0.2
        refs.append(so.find_best_split_fast(
            jnp.asarray(hist), ctx, jnp.float32(sum_g),
            jnp.float32(sum_h), jnp.int32(cnt), 0.0, 1e-3, 0.0, 0.0,
            5, 1e-3, jnp.asarray(mask)))
        hists.append(hist)
        info = np.zeros((F, 8), np.float32)
        info[:, 0] = sum_g
        info[:, 1] = sum_h
        info[:, 2] = cnt
        info[:, 3] = 1.0
        info[:, 4] = mask
        infos.append(info)
    hg = jnp.asarray(np.concatenate([hists[0][..., 0], hists[1][..., 0]]))
    hh = jnp.asarray(np.concatenate([hists[0][..., 1], hists[1][..., 1]]))
    tile = np.asarray(best_split_pair_pallas(
        hg, hh, fmeta, jnp.asarray(np.concatenate(infos)),
        l1=0.0, l2=1e-3, max_delta_step=0.0, min_gain_to_split=0.0,
        min_data_in_leaf=5, min_sum_hessian=1e-3, max_depth=0,
        interpret=True))
    for c, ref in enumerate(refs):
        row = tile[c]
        assert row[1:2].view(np.int32)[0] == int(ref.feature)
        assert row[2:3].view(np.int32)[0] == int(ref.threshold)
        np.testing.assert_allclose(row[0], float(ref.gain),
                                   rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("trial", [0, 1])
def test_partition_kernel_radix4_interpreted(trial):
    """The radix-4 compaction network must produce the identical stable
    partition layout as the binary network (trial 1 adds pack_rowid)."""
    C, G32, G = 256, 32, 28
    Np = 8 * C
    rng = np.random.RandomState(40 + trial)
    pack = trial == 1
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    if pack:
        pb[G:] = 0
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 4 * C))
    cnt = int(rng.randint(1, 3 * C))
    col = int(rng.randint(0, G))
    nb = int(rng.randint(10, 250))
    thr = int(rng.randint(0, nb))
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, 0, 0, thr, 1)
    sc = make_scalars(start, cnt, col, 0, 0, nb, 0, 0, thr, 1)
    rpb, rpg, _, rnl = partition_leaf_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
        row_chunk=C, pack_rowid=pack, compact_radix=True, interpret=True)
    assert int(np.asarray(rnl)[0, 0]) == enl
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(
        np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))


@pytest.mark.parametrize("trial,radix", [(0, False), (1, True)])
def test_megakernel_interpreted(trial, radix):
    """Mega-kernel: the partition must match the NumPy oracle bit-exact
    AND the both-children histogram accumulator must match the XLA
    oracle (both_children_hist_xla) bit-exact — the same chunk grid and
    accumulation math by construction."""
    C, G32, G, B = 256, 32, 28, 255
    Np = 8 * C
    rng = np.random.RandomState(60 + trial)
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 4 * C))
    cnt = int(rng.randint(1, 3 * C))
    col = int(rng.randint(0, G))
    nb = int(rng.randint(10, 250))
    mtype = int(rng.randint(0, 3))
    dbin = int(rng.randint(0, nb))
    thr = int(rng.randint(0, nb))
    dl = int(rng.rand() < 0.5)
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, dbin,
                            mtype, thr, dl)
    sc = make_scalars(start, cnt, col, 0, 0, nb, dbin, mtype, thr, dl)
    rpb, rpg, _, rnl, acc = split_megakernel_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
        row_chunk=C, num_bins=B, num_groups=G, compact_radix=radix,
        interpret=True)
    assert int(np.asarray(rnl)[0, 0]) == enl
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(
        np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))
    acc_oracle = both_children_hist_xla(
        jnp.asarray(pb), jnp.asarray(pg), jnp.int32(start), jnp.int32(cnt),
        jnp.int32(col),
        tuple(jnp.int32(v) for v in (0, 0, nb, dbin, mtype, thr, dl)),
        row_chunk=C, num_bins=B, num_groups=G)
    np.testing.assert_array_equal(np.asarray(acc), np.asarray(acc_oracle))
    # independent NumPy reference for the histogram VALUES (allclose:
    # different summation order than the f32 matmul accumulation)
    colv = pb[col, start:start + cnt].astype(np.int32)
    if mtype == 1:
        miss = colv == dbin
    elif mtype == 2:
        miss = colv == nb - 1
    else:
        miss = np.zeros_like(colv, bool)
    gl = np.where(miss, dl != 0, colv <= thr)
    hl_g, hl_h, hr_g, hr_h = [np.asarray(x) for x in unpack_hist4(acc, B)]
    gseg = pg[0, start:start + cnt].astype(np.float64)
    hseg = pg[1, start:start + cnt].astype(np.float64)
    for gi in (0, col, G - 1):
        binseg = pb[gi, start:start + cnt]
        for side, (eg, eh) in ((gl, (hl_g, hl_h)), (~gl, (hr_g, hr_h))):
            refg = np.zeros(256)
            refh = np.zeros(256)
            np.add.at(refg, binseg[side], gseg[side])
            np.add.at(refh, binseg[side], hseg[side])
            np.testing.assert_allclose(eg[gi], refg, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(eh[gi], refh, rtol=1e-4, atol=1e-4)


def test_megakernel_zero_count_interpreted():
    """cnt == 0 (the trash-slot iteration): no rows move, the left count
    clamps to 0 and the histogram accumulator is all-zero."""
    C, G32, G, B = 256, 32, 28, 255
    Np = 8 * C
    rng = np.random.RandomState(99)
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    sc = make_scalars(3 * C + 17, 0, 5, 0, 0, 200, 0, 0, 100, 0)
    rpb, rpg, _, rnl, acc = split_megakernel_pallas(
        jnp.asarray(pb), jnp.asarray(pg),
        jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
        row_chunk=C, num_bins=B, num_groups=G, interpret=True)
    assert int(np.asarray(rnl)[0, 0]) == 0
    np.testing.assert_array_equal(np.asarray(rpb), pb)
    np.testing.assert_array_equal(np.asarray(rpg)[:3], pg[:3])
    assert not np.asarray(acc).any()


@pytest.mark.parametrize("trial", [0, 1])
def test_partition_kernel_pack_rowid_interpreted(trial):
    """pack_rowid rides ghi row 2 inside the spare packed-bin bytes;
    HBM layout must be unchanged (pad bin rows zero, rowid row exact)."""
    C, G32, G = 256, 32, 28
    Np = 8 * C
    rng = np.random.RandomState(100 + trial)
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pb[G:] = 0                     # pad rows zero: the dataset invariant
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 4 * C))
    cnt = int(rng.randint(1, 3 * C))
    col = int(rng.randint(0, G))
    nb = int(rng.randint(10, 250))
    thr = int(rng.randint(0, nb))
    epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, 0, 0, thr, 0)
    sc = make_scalars(start, cnt, col, 0, 0, nb, 0, 0, thr, 0)
    for ghi_live in (3, 5):
        rpb, rpg, _, rnl = partition_leaf_pallas(
            jnp.asarray(pb), jnp.asarray(pg),
            jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
            row_chunk=C, ghi_live=ghi_live, pack_rowid=True,
            interpret=True)
        assert int(np.asarray(rnl)[0, 0]) == enl
        np.testing.assert_array_equal(np.asarray(rpb), epb)
        np.testing.assert_array_equal(
            np.asarray(rpg)[:ghi_live].view(np.int32),
            epg[:ghi_live].view(np.int32))
