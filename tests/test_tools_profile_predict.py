"""Smoke for tools/profile_predict.py (PR-3 satellite): the serving
throughput harness runs at tiny sizes, emits parseable JSON, proves the
compile-count invariant (one trace per kind x bucket x depth-group),
and pins device SHAP parity against the host recursion in its own
output.  Runs in-process to share the session's jit caches (a
subprocess would pay ~20 s of import+compile for the same cover)."""

import importlib.util
import json
import os
import sys


HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "profile_predict", os.path.join(HERE, "tools",
                                        "profile_predict.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_profile_predict_smoke(capsys):
    tool = _load_tool()
    rc = tool.main(["--smoke", "--rows", "1200", "--trees", "4",
                    "--cohort", "2"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "predict_serving"
    detail = payload["detail"]
    assert detail["multi_traced"] == {}, \
        f"retrace detected: {detail['multi_traced']}"
    assert detail["grid"], "grid must not be empty"
    row = detail["grid"][0]
    assert row["raw_warm_s"] >= 0 and row["contrib_warm_s"] >= 0
    assert row["host_parity_max_abs"] < 1e-10
    # every traced (kind, bucket) was called at least once yet traced
    # exactly once
    assert all(v == 1 for v in detail["traces"].values())
    # PR-13 lanes: the layered-vs-loop A/B is bit-exact with its own
    # engines' compile counts pinned, and the 2-model cohort wave cost
    # ONE dispatch with the cohort program traced exactly once
    ab = detail["kernel_ab"]
    assert ab["bit_parity_max_abs"] == 0.0
    assert ab["multi_traced"] == {}
    assert ab["grid"] and all(
        g["layered_rows_trees_per_s"] > 0 for g in ab["grid"])
    co = detail["cohort"]
    assert co["violations"] == [], co["violations"]
    assert co["cohort_traces"] == {"cohort_raw@128": 1}
