"""Test configuration: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's distributed-without-cluster testing strategy
(tests/distributed/_test_distributed.py spawns N localhost processes); here N
virtual XLA host devices stand in for N TPU chips.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic per-session compilation cache: the machine-shared default cache
# can contain executables AOT-compiled elsewhere (via the TPU tunnel's
# compile helper) whose CPU lowering differs from — and in some entries
# numerically corrupts — locally-compiled code.  A fresh dir keeps every
# process of this test session (pytest + CLI subprocesses) consistent.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      tempfile.mkdtemp(prefix="jax-cache-tests-"))
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# Site plugins (e.g. a TPU tunnel) may have force-registered themselves and
# overridden jax_platforms; pin CPU explicitly so tests never touch hardware.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(42)
