"""Test configuration: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's distributed-without-cluster testing strategy
(tests/distributed/_test_distributed.py spawns N localhost processes); here N
virtual XLA host devices stand in for N TPU chips.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic per-session compilation cache: the machine-shared default cache
# can contain executables AOT-compiled elsewhere (via the TPU tunnel's
# compile helper) whose CPU lowering differs from — and in some entries
# numerically corrupts — locally-compiled code.  A fresh dir keeps every
# process of this test session (pytest + CLI subprocesses) consistent.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      tempfile.mkdtemp(prefix="jax-cache-tests-"))
# Hermetic perf-trajectory store: tests (and every CLI subprocess they
# spawn — ab_bench/profile_* smokes inherit the env) must append their
# BENCH_obs/BENCH_history entries to a per-session scratch store, never
# to the committed repo-root BENCH_history.jsonl; real bench rounds run
# outside pytest and keep the default path.  Force-set, not setdefault:
# an operator with $BENCH_HISTORY_PATH exported for a bench round must
# not have a pytest run pollute that store with smoke-sized samples.
_OBS_SCRATCH = tempfile.mkdtemp(prefix="bench-obs-tests-")
os.environ["BENCH_HISTORY_PATH"] = os.path.join(_OBS_SCRATCH,
                                                "BENCH_history.jsonl")
os.environ["BENCH_OBS_PATH"] = os.path.join(_OBS_SCRATCH,
                                            "BENCH_obs.json")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# Site plugins (e.g. a TPU tunnel) may have force-registered themselves and
# overridden jax_platforms; pin CPU explicitly so tests never touch hardware.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# ---------------------------------------------------------------------------
# Test-suite observability: per-file duration artifact.
#
# The full suite overruns the 870 s tier-1 window on the 2-core host
# (ROADMAP), so which lanes eat the window is operational data — every
# run drops a JSON artifact mapping test file -> {wall seconds, tests}
# so slow lanes can be found (and split/slow-marked) without rerunning
# under a profiler.  Path: $TEST_DURATIONS_OUT, else
# test_durations.json next to the rootdir (gitignored).
# ---------------------------------------------------------------------------
_DURATIONS: dict = {}
_SESSION_T0 = None


def pytest_sessionstart(session):
    global _SESSION_T0
    import time
    _SESSION_T0 = time.time()


def pytest_runtest_logreport(report):
    # setup + call + teardown all bill to the test's file: the window is
    # spent on wall-clock, not on call phases alone
    fname = report.nodeid.split("::", 1)[0]
    ent = _DURATIONS.setdefault(fname, {"seconds": 0.0, "tests": 0,
                                        "failed": 0})
    ent["seconds"] += float(getattr(report, "duration", 0.0) or 0.0)
    if report.when == "call":
        ent["tests"] += 1
        if report.failed:
            ent["failed"] += 1


def pytest_sessionfinish(session, exitstatus):
    import json
    import time
    if not _DURATIONS:
        return
    out = os.environ.get("TEST_DURATIONS_OUT")
    if out is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = os.path.join(root, "test_durations.json")
    doc = {
        "wall_s": round(time.time() - _SESSION_T0, 2)
        if _SESSION_T0 else None,
        "files": {f: {"seconds": round(v["seconds"], 2),
                      "tests": v["tests"], "failed": v["failed"]}
                  for f, v in sorted(_DURATIONS.items(),
                                     key=lambda kv: -kv[1]["seconds"])},
    }
    try:
        tmp = out + f".tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, out)
    except OSError:
        pass
    # the same numbers also land as one perfwatch trajectory entry —
    # in a PERSISTENT side store (gitignored, like test_durations.json
    # itself: $TEST_HISTORY_OUT, else BENCH_history_tests.jsonl at the
    # rootdir), NOT the per-session scratch BENCH_HISTORY_PATH above,
    # so the "pytest" series accumulates across sessions and
    # `perfwatch check --history BENCH_history_tests.jsonl` can gate
    # suite wall-clock and per-file lane costs (`_s`-suffixed = gated
    # time-like metrics); the test-count shape band keeps single-file
    # runs and full-suite runs in separate series
    try:
        from lightgbm_tpu.obs import regress
        hist_out = os.environ.get("TEST_HISTORY_OUT") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_history_tests.jsonl")
        n_tests = sum(v["tests"] for v in _DURATIONS.values())
        metrics = {"wall_s": doc["wall_s"] or 0.0}
        metrics.update({f + "_s": v["seconds"]
                        for f, v in _DURATIONS.items()})
        # a failed or cut-short session (pytest -x, ctrl-C, collection
        # errors) has fast-but-bogus wall numbers: record it aborted so
        # the detector excludes it (regress.py contract), same as every
        # abort_guard producer
        regress.append_entry(
            "pytest", metrics,
            config={"files": len(_DURATIONS), "tests": n_tests},
            rows=n_tests, aborted=bool(exitstatus), path=hist_out)
    except Exception:
        pass                  # a failed append must never fail the run


def pytest_configure(config):
    # the tier-1 runner deselects with -m 'not slow' (ROADMAP);
    # registering the marker kills the per-test unknown-mark warning
    # and lets --strict-markers catch a typo'd trim mark that would
    # silently keep a slow test inside the 870 s window
    config.addinivalue_line(
        "markers",
        "slow: out-of-window lanes (tier-1 runs -m 'not slow'); each "
        "trim keeps a named fast in-window representative")


@pytest.fixture
def rng():
    return np.random.RandomState(42)
