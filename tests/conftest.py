"""Test configuration: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's distributed-without-cluster testing strategy
(tests/distributed/_test_distributed.py spawns N localhost processes); here N
virtual XLA host devices stand in for N TPU chips.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# Site plugins (e.g. a TPU tunnel) may have force-registered themselves and
# overridden jax_platforms; pin CPU explicitly so tests never touch hardware.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(42)
