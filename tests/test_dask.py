"""Dask wrapper tests (reference model: tests/python_package_test/test_dask.py).

dask is not bundled in this image, so the orchestration logic is exercised
with lightweight fakes implementing the small client/collection surface the
wrapper uses; real-dask tests run when dask.distributed is installed.
"""

import numpy as np
import pytest

import lightgbm_tpu.dask as lgb_dask
from lightgbm_tpu.dask import (DASK_INSTALLED, DaskLGBMClassifier,
                               DaskLGBMRegressor, _concat_parts)


def test_import_without_dask_and_clear_error():
    est = DaskLGBMRegressor(n_estimators=5)
    if not DASK_INSTALLED:
        with pytest.raises(ImportError, match="dask"):
            est.fit(object(), object())


def test_concat_parts():
    a = np.arange(6).reshape(3, 2)
    b = np.arange(6, 12).reshape(3, 2)
    out = _concat_parts([a, b])
    assert out.shape == (6, 2)
    v = _concat_parts([np.arange(3), np.arange(3, 5)])
    np.testing.assert_array_equal(v, np.arange(5))


class _FakeFuture:
    def __init__(self, value, key, worker):
        self._v = value
        self.key = key
        self.worker = worker

    def result(self):
        return self._v


class _FakeClient:
    def __init__(self, nparts):
        self.nparts = nparts

    def compute(self, parts):
        return [_FakeFuture(p._value, f"k{i}", f"w{i % 2}")
                for i, p in enumerate(parts)]

    def who_has(self, futures):
        return {f.key: (f.worker,) for f in futures}

    def scheduler_info(self):
        return {"workers": {"w0": {}, "w1": {}}}


class _FakeDelayed:
    def __init__(self, value):
        self._value = value


class _FakeArray:
    """Duck-types the slice of the dask.array API the wrapper touches."""

    def __init__(self, arr, nparts=4):
        self._arr = np.asarray(arr)
        self.dask = {}
        self.ndim = self._arr.ndim
        self._parts = np.array_split(self._arr, nparts, axis=0)

    def to_delayed(self):
        return np.asarray([_FakeDelayed(p) for p in self._parts],
                          dtype=object)

    def compute(self):
        return self._arr

    def map_blocks(self, fn, **_kwargs):
        return np.concatenate([np.asarray(fn(p)).reshape(-1)
                               for p in self._parts])


@pytest.fixture
def fake_dask(monkeypatch):
    monkeypatch.setattr(lgb_dask, "DASK_INSTALLED", True)
    monkeypatch.setattr(lgb_dask, "default_client", lambda: _FakeClient(4))
    monkeypatch.setattr(lgb_dask, "wait", lambda futures: None)


def _make_data(n=600, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int32)
    return X, y


def test_fake_dask_classifier_roundtrip(fake_dask):
    X, y = _make_data()
    dX, dy = _FakeArray(X), _FakeArray(y)
    est = DaskLGBMClassifier(n_estimators=10, num_leaves=15, verbosity=-1)
    est.fit(dX, dy, client=_FakeClient(4), distributed=False)
    pred = est.predict(_FakeArray(X))
    assert pred.shape == (len(y),)
    assert np.mean(pred == y) > 0.9
    # to_local returns a plain estimator that predicts identically
    local = est.to_local()
    np.testing.assert_allclose(local.predict(X), pred)


def test_fake_dask_regressor(fake_dask):
    X, y = _make_data()
    yr = X[:, 0] * 2.0 + X[:, 2]
    est = DaskLGBMRegressor(n_estimators=15, num_leaves=15, verbosity=-1)
    est.fit(_FakeArray(X), _FakeArray(yr), client=_FakeClient(4),
            distributed=False)
    pred = est.predict(_FakeArray(X))
    assert np.mean((pred - yr) ** 2) < 0.3 * np.var(yr)


import os as _os

_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


class _FakeDistClient(_FakeClient):
    """Fake client whose submit() runs `_train_part` ranks in REAL
    subprocesses (each becomes a jax.distributed process), so the
    per-worker data plane is exercised end-to-end without dask: the
    client process never touches partition contents.  Worker names are
    real host:port addresses so the coordinator derivation works."""

    WORKERS = ("tcp://127.0.0.1:40101", "tcp://127.0.0.1:40102")

    def __init__(self, nparts, tmp_path):
        super().__init__(nparts)
        self.tmp = tmp_path

    def compute(self, parts):
        return [_FakeFuture(p._value, f"k{i}",
                            self.WORKERS[i % len(self.WORKERS)])
                for i, p in enumerate(parts)]

    def scheduler_info(self):
        return {"workers": {w: {} for w in self.WORKERS}}

    def submit(self, fn, *args, workers=None, allow_other_workers=None,
               pure=None, **kw):
        import lightgbm_tpu.dask as mod
        if fn is not mod._train_part:
            # small helper submissions (per-part uniques) run inline
            val = fn(*[a.result() if isinstance(a, _FakeFuture) else a
                       for a in args])
            return _FakeFuture(val, f"inline-{id(val)}", None)
        import pickle
        import subprocess
        import sys

        def resolve(a):
            if isinstance(a, list):
                return [x.result() if isinstance(x, _FakeFuture) else x
                        for x in a]
            return a.result() if isinstance(a, _FakeFuture) else a

        rank = args[7]
        argfile = self.tmp / f"rank{rank}.pkl"
        outfile = self.tmp / f"rank{rank}.out.pkl"
        with open(argfile, "wb") as f:
            pickle.dump([resolve(a) for a in args], f)
        code = (
            "import os, pickle, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "os.environ.pop('XLA_FLAGS', None)\n"
            "import tempfile\n"
            "os.environ['JAX_COMPILATION_CACHE_DIR'] = "
            "tempfile.mkdtemp(prefix='jax-dask-')\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            f"args = pickle.load(open({str(argfile)!r}, 'rb'))\n"
            # initialize BEFORE the package import can touch the backend
            "jax.distributed.initialize(coordinator_address=args[9],\n"
            "    num_processes=args[8], process_id=args[7])\n"
            f"sys.path.insert(0, {_REPO_ROOT!r})\n"
            "from lightgbm_tpu.dask import _train_part\n"
            "out = _train_part(*args)\n"
            f"pickle.dump(out, open({str(outfile)!r}, 'wb'))\n")
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        fut = _FakeFuture(None, f"train{rank}", workers[0])
        fut._proc, fut._outfile = p, outfile
        return fut

    def gather(self, futures):
        import pickle
        out = []
        for f in futures:
            if getattr(f, "_proc", None) is not None:
                log = f._proc.communicate(timeout=900)[0].decode()
                assert f._proc.returncode == 0, log[-3000:]
                out.append(pickle.load(open(f._outfile, "rb")))
            else:
                out.append(f.result())
        return out


def test_fake_dask_distributed_per_worker_plane(fake_dask, tmp_path):
    """The distributed fit path: partitions stay on their workers, each
    worker trains as a jax.distributed rank (a real 2-process run via
    the subprocess-backed fake), and the client only ever receives the
    model text."""
    X, y = _make_data(n=1200)
    port = 12600 + _os.getpid() % 300
    est = DaskLGBMClassifier(n_estimators=10, num_leaves=15, verbosity=-1,
                             min_child_samples=5, local_listen_port=port)
    client = _FakeDistClient(4, tmp_path)
    est.fit(_FakeArray(X), _FakeArray(y), client=client)
    assert est._Booster is not None
    pred = est.predict(_FakeArray(X))
    assert np.mean(pred == y) > 0.9


@pytest.mark.skipif(not DASK_INSTALLED, reason="dask not installed")
def test_real_dask_roundtrip():
    import dask.array as da
    from distributed import Client, LocalCluster
    X, y = _make_data()
    with LocalCluster(n_workers=2, threads_per_worker=1,
                      processes=False) as cluster, Client(cluster) as client:
        dX = da.from_array(X, chunks=(150, 5))
        dy = da.from_array(y, chunks=(150,))
        est = DaskLGBMClassifier(n_estimators=10, num_leaves=15,
                                 verbosity=-1)
        est.fit(dX, dy, client=client)
        pred = np.asarray(est.predict(dX))
        assert np.mean(pred == y) > 0.9


@pytest.mark.slow
@pytest.mark.skipif(not DASK_INSTALLED, reason="dask not installed")
def test_real_dask_distributed_two_workers_matches_gather():
    """distributed=True on a REAL 2-process LocalCluster: each dask
    worker becomes a jax.distributed rank over its resident partitions
    (the per-worker plane the fake-client test drives via
    subprocesses), and the result must match the gather-to-client
    path's model — data-parallel histograms change only f32 summation
    order, so predictions agree to float noise.  Slow: spawns worker
    processes and a jax.distributed coordinator."""
    import dask.array as da
    from distributed import Client, LocalCluster
    X, y = _make_data(n=1200)
    with LocalCluster(n_workers=2, threads_per_worker=1, processes=True,
                      dashboard_address=None) as cluster, \
            Client(cluster) as client:
        dX = da.from_array(X, chunks=(300, X.shape[1]))
        dy = da.from_array(y, chunks=(300,))
        kw = dict(n_estimators=8, num_leaves=15, verbosity=-1,
                  min_child_samples=5)
        dist = DaskLGBMClassifier(**kw).fit(dX, dy, client=client,
                                            distributed=True)
        gath = DaskLGBMClassifier(**kw).fit(dX, dy, client=client,
                                            distributed=False)
        pd_dist = np.asarray(dist.predict(dX, raw_score=True))
        pd_gath = np.asarray(gath.predict(dX, raw_score=True))
        np.testing.assert_allclose(pd_dist, pd_gath, rtol=1e-3,
                                   atol=1e-4)
        pred = np.asarray(dist.predict(dX))
        assert np.mean(pred == y) > 0.9
