"""TPU-only tests for the Pallas partition kernel.

These are skipped under the CPU conftest (Pallas TPU kernels need real
Mosaic lowering); run them manually on a TPU host with
``JAX_PLATFORMS='' python -m pytest tests/test_pallas_tpu.py`` — the
driver's bench run exercises the same path end-to-end.  The oracle is
the XLA partition (models/learner.py:_partition_leaf), which produces a
bit-identical layout by construction.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas partition kernel requires a TPU backend")


def _oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl):
    """NumPy stable two-way partition of [start, start+cnt), mirroring
    DenseBin::Split numerical semantics (src/io/dense_bin.hpp:237-310)."""
    pb = pb.copy()
    pg = pg.copy()
    colv = pb[col, start:start + cnt].astype(np.int32)
    fb_raw = colv - bstart
    in_r = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = np.where(isb == 1, np.where(in_r, fb_raw, dbin), colv)
    if mtype == 1:
        miss = fb == dbin
    elif mtype == 2:
        miss = fb == nb - 1
    else:
        miss = np.zeros_like(fb, bool)
    gl = np.where(miss, dl != 0, fb <= thr)
    order = np.concatenate([np.where(gl)[0], np.where(~gl)[0]]) + start
    pb[:, start:start + cnt] = pb[:, order]
    pg[:, start:start + cnt] = pg[:, order]
    return pb, pg, int(gl.sum())


def test_partition_kernel_matches_oracle():
    from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                                   make_scalars)
    C, G32 = 1024, 32
    Np = 10 * C
    rng = np.random.RandomState(7)
    for trial in range(6):
        pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
        pg = rng.randn(8, Np).astype(np.float32)
        start = int(rng.randint(C, 5 * C))
        cnt = int(rng.randint(0, 4 * C))
        col = int(rng.randint(0, 28))
        isb = int(rng.rand() < 0.3)
        nb = int(rng.randint(10, 250))
        bstart = int(rng.randint(0, 5)) if isb else 0
        dbin = int(rng.randint(0, nb))
        mtype = int(rng.randint(0, 3))
        thr = int(rng.randint(0, nb))
        dl = int(rng.rand() < 0.5)

        epb, epg, enl = _oracle(pb, pg, start, cnt, col, bstart, isb, nb,
                                dbin, mtype, thr, dl)
        sc = make_scalars(start, cnt, col, bstart, isb, nb, dbin, mtype,
                          thr, dl)
        from lightgbm_tpu.ops.partition_pallas import SC_ROWS
        rpb, rpg, _, rnl = partition_leaf_pallas(
            jnp.asarray(pb), jnp.asarray(pg),
            jnp.zeros((SC_ROWS, Np), jnp.int32),
            sc, row_chunk=C)
        assert int(np.asarray(rnl)[0, 0]) == enl
        np.testing.assert_array_equal(np.asarray(rpb), epb)
        # only the live (g, h, rowid) rows are preserved through the
        # packed-payload kernel; the sublane-pad rows come back as zeros
        np.testing.assert_array_equal(
            np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))


def test_train_pallas_matches_xla():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    N, F = 5000, 8
    X = rng.randn(N, F)
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.3 * rng.randn(N)

    def train(kernel):
        params = {"objective": "regression", "num_leaves": 31,
                  "verbosity": -1, "tpu_partition_kernel": kernel,
                  "min_data_in_leaf": 20, "tpu_megakernel": "off"}
        return lgb.train(params, lgb.Dataset(X, label=y),
                         num_boost_round=10)

    p_pal = train("pallas").predict(X[:500])
    p_xla = train("xla").predict(X[:500])
    np.testing.assert_array_equal(p_pal, p_xla)


def test_megakernel_matches_oracles_on_device():
    """Mega-kernel on a real TPU: partition bit-equal to the NumPy
    oracle, histogram accumulator bit-equal to the XLA oracle, for both
    compaction networks and the zero-count trash-slot call."""
    from lightgbm_tpu.ops.partition_pallas import (make_scalars,
                                                   sc_rows_for)
    from lightgbm_tpu.ops.split_megakernel_pallas import (
        both_children_hist_xla, split_megakernel_pallas)
    C, G32, G, B = 1024, 32, 28, 255
    Np = 10 * C
    rng = np.random.RandomState(11)
    for trial in range(4):
        pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
        pg = rng.randn(8, Np).astype(np.float32)
        start = int(rng.randint(C, 5 * C))
        cnt = 0 if trial == 3 else int(rng.randint(1, 4 * C))
        col = int(rng.randint(0, G))
        nb = int(rng.randint(10, 250))
        mtype = int(rng.randint(0, 3))
        dbin = int(rng.randint(0, nb))
        thr = int(rng.randint(0, nb))
        dl = int(rng.rand() < 0.5)
        epb, epg, enl = _oracle(pb, pg, start, cnt, col, 0, 0, nb, dbin,
                                mtype, thr, dl)
        sc = make_scalars(start, cnt, col, 0, 0, nb, dbin, mtype, thr, dl)
        rpb, rpg, _, rnl, acc = split_megakernel_pallas(
            jnp.asarray(pb), jnp.asarray(pg),
            jnp.zeros((sc_rows_for(G32), Np), jnp.int32), sc,
            row_chunk=C, num_bins=B, num_groups=G,
            compact_radix=(trial == 2))
        assert int(np.asarray(rnl)[0, 0]) == enl
        np.testing.assert_array_equal(np.asarray(rpb), epb)
        np.testing.assert_array_equal(
            np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))
        acc_o = both_children_hist_xla(
            jnp.asarray(pb), jnp.asarray(pg), jnp.int32(start),
            jnp.int32(cnt), jnp.int32(col),
            tuple(jnp.int32(v) for v in (0, 0, nb, dbin, mtype, thr, dl)),
            row_chunk=C, num_bins=B, num_groups=G)
        np.testing.assert_array_equal(np.asarray(acc), np.asarray(acc_o))


def test_train_megakernel_matches_its_oracle_on_device():
    """E2E on device: tpu_megakernel=pallas trees bit-identical to the
    tpu_megakernel=xla oracle formulation (both run the Pallas
    partition and pair-search; only the fused histogram differs)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    N, F = 5000, 8
    X = rng.randn(N, F)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3)
         + 0.3 * rng.randn(N) > 0).astype(np.float64)

    def train(mode):
        params = {"objective": "binary", "num_leaves": 63,
                  "verbosity": -1, "min_data_in_leaf": 20,
                  "tpu_megakernel": mode}
        return lgb.train(params, lgb.Dataset(X, label=y),
                         num_boost_round=8)

    bx = train("xla")
    bp = train("pallas")
    assert bp._gbdt.learner._use_mega == "pallas"
    np.testing.assert_array_equal(bp.predict(X[:2000]),
                                  bx.predict(X[:2000]))
