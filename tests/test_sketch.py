"""Out-of-core dataset construction (ops/sketch.py + streaming paths).

Acceptance for the out-of-core PR (ISSUE 17):

* the sketch is CANONICAL — chunk order, chunk boundaries and rank
  sharding cannot change a single bit of its state or extracted cuts;
* at level 0 (distincts fit in ``sketch_k``) the sketch-derived
  BinMapper is bit-identical to the exact sort-based oracle, including
  NaN, zero-as-missing and categorical branches;
* in the lossy regime the measured CDF deviation stays under the
  reported ``rank_error_bound``;
* streaming construction from Sequences (two passes, free-host default,
  epoch re-streaming) trains bit-identically to the resident exact
  path, with mixed per-sequence batch sizes;
* a capped-RSS subprocess proves a dataset whose dense matrix exceeds
  the address-space cap still constructs AND trains.
"""

import itertools
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.ops.binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
from lightgbm_tpu.ops.sketch import (DEFAULT_K, FeatureSketch, SketchSet,
                                     resolve_bin_mode)

BASE = {"verbosity": -1}


def _mapper_dicts(ds):
    return [json.dumps(bm.to_dict(), sort_keys=True)
            for bm in ds.bin_mappers]


def _group_tuples(ds):
    return [(tuple(g.feature_indices), g.num_total_bin,
             tuple(g.bin_offsets)) for g in ds.groups]


def _tree_part(model_str: str) -> str:
    """The model string minus the echoed parameter block (the only part
    that legitimately differs between bin_construct_mode settings)."""
    head, sep, tail = model_str.partition("parameters:")
    return head


def _columns_matrix(rng, n):
    """Every mapper branch: dense, sparse, NaN, few-distinct, constant,
    all-negative, categorical (negative code), integer grid."""
    X = rng.normal(size=(n, 12))
    X[:, 1] = np.where(rng.rand(n) < 0.9, 0.0, X[:, 1])
    X[:, 2] = np.where(rng.rand(n) < 0.85, 0.0, X[:, 2])
    X[rng.rand(n) < 0.07, 3] = np.nan
    X[:, 4] = rng.randint(0, 5, size=n).astype(float)
    X[:, 5] = 3.25
    X[:, 6] = -np.abs(rng.normal(size=n)) - 0.5
    X[:, 7] = rng.randint(0, 9, size=n).astype(float)
    X[rng.rand(n) < 0.02, 7] = -1.0
    X[:, 8] = rng.randint(0, 3, size=n).astype(float)
    X[:, 9] = np.where(rng.rand(n) < 0.5, 0.0, np.abs(X[:, 9]))
    X[rng.rand(n) < 0.04, 9] = np.nan
    return X


class _Seq(lgb.Sequence):
    def __init__(self, mat, batch_size):
        self._m = mat
        self.batch_size = batch_size

    def __getitem__(self, idx):
        return self._m[idx]

    def __len__(self):
        return len(self._m)


def _state(s: FeatureSketch):
    return (s.level, s.keys.tobytes(), s.counts.tobytes(),
            s.maxes.tobytes(), s.nan_cnt, s.total_cnt)


# ---------------------------------------------------------------------------
# canonical merge: permutations, shardings, the wire format
# ---------------------------------------------------------------------------
def test_fold_order_permutation_invariance(rng):
    """All 120 orderings of 5 chunks fold to ONE bit-identical state
    (and the per-chunk sketch merge agrees with the fold), deep in the
    lossy regime (k=64 << distincts)."""
    col = np.concatenate([rng.normal(size=3500), np.zeros(400),
                          [np.nan] * 100])
    rng.shuffle(col)
    chunks = np.array_split(col, 5)
    ref = FeatureSketch(64)
    for c in chunks:
        ref.update(c)
    assert ref.level > 0           # the invariance claim must be lossy
    per_chunk = []
    for c in chunks:
        s = FeatureSketch(64)
        s.update(c)
        per_chunk.append(s)
    for perm in itertools.permutations(range(5)):
        s = FeatureSketch(64)
        for i in perm:
            s.update(chunks[i])
        assert _state(s) == _state(ref), perm
        m = FeatureSketch.merge([per_chunk[i] for i in perm])
        assert _state(m) == _state(ref), perm
    cuts_ref = json.dumps(ref.to_mapper(63).to_dict(), sort_keys=True)
    assert json.dumps(per_chunk[0].merge(per_chunk).to_mapper(63)
                      .to_dict(), sort_keys=True) == cuts_ref


def test_one_vs_four_shard_merge_bit_identity(rng):
    """One rank sketching everything == four rank-sharded sketches
    merged, for both contiguous row blocks and round-robin sharding —
    the distributed allgather's correctness claim, in-process."""
    X = _columns_matrix(rng, 3000)
    one = SketchSet(X.shape[1], k=64)
    for a in range(0, len(X), 777):
        one.update_chunk(X[a:a + 777])
    for shards in (
            [X[a::4] for a in range(4)],                    # round-robin
            np.array_split(X, 4)):                          # contiguous
        sets = []
        for sh in shards:
            ss = SketchSet(X.shape[1], k=64)
            ss.update_chunk(sh)
            sets.append(ss)
        merged = SketchSet.merge(sets)
        for f in range(X.shape[1]):
            assert _state(merged.sketches[f]) == _state(one.sketches[f]), f
        assert merged.serialize() == one.serialize()
    # the wire format round-trips bit-exactly
    back = SketchSet.deserialize(one.serialize())
    for f in range(X.shape[1]):
        assert _state(back.sketches[f]) == _state(one.sketches[f]), f


def test_merge_mixed_k_raises():
    with pytest.raises(ValueError):
        FeatureSketch.merge([FeatureSketch(64), FeatureSketch(128)])


# ---------------------------------------------------------------------------
# exact tier: bit-identity to the sort-based oracle
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opts", [
    {},
    {"max_bin": 15},
    {"zero_as_missing": True},
    {"use_missing": False},
    {"min_data_in_bin": 25},
])
def test_exact_tier_matches_oracle(rng, opts):
    """Distincts <= k keeps level 0: the sketch mapper must equal
    BinMapper.find_bin bit-for-bit on every column shape."""
    X = _columns_matrix(rng, 3000)
    for f in range(X.shape[1]):
        col = X[:, f]
        s = FeatureSketch(DEFAULT_K)
        for a in range(0, len(col), 997):
            s.update(col[a:a + 997])
        assert s.level == 0
        bt = BIN_CATEGORICAL if f == 7 else BIN_NUMERICAL
        kw = dict(max_bin=255, min_data_in_bin=3, min_split_data=0,
                  pre_filter=False, bin_type=bt, use_missing=True,
                  zero_as_missing=False)
        kw.update(opts)
        nonzero = col[(np.abs(col) > 1e-35) | np.isnan(col)]
        ref = BinMapper()
        ref.find_bin(nonzero, total_sample_cnt=len(col), **kw)
        got = s.to_mapper(**kw)
        assert (json.dumps(got.to_dict(), sort_keys=True)
                == json.dumps(ref.to_dict(), sort_keys=True)), f


def test_categorical_overflow_refuses_to_guess(rng):
    s = FeatureSketch(8)
    s.update(rng.randint(1, 1000, size=2000).astype(float))
    assert s.level > 0
    with pytest.raises(ValueError, match="categorical"):
        s.to_mapper(255, bin_type=BIN_CATEGORICAL)


def test_rank_error_bound_holds(rng):
    """Measured CDF deviation at every cell boundary stays under the
    reported bound in the lossy regime."""
    vals = rng.normal(size=20000)
    s = FeatureSketch(64)
    for a in range(0, len(vals), 3001):
        s.update(vals[a:a + 3001])
    bound = s.rank_error_bound()
    assert s.level > 0 and bound > 0
    sv = np.sort(vals)
    worst = 0
    for x in s.maxes:
        true_rank = int(np.searchsorted(sv, x, side="right"))
        worst = max(worst, abs(s.rank_upto(float(x)) - true_rank))
    assert worst <= bound, (worst, bound)


def test_resolve_bin_mode():
    assert resolve_bin_mode(Config(dict(BASE)), 10_000) == "exact"
    assert resolve_bin_mode(Config(dict(BASE)), 2_000_000) == "sketch"
    assert resolve_bin_mode(
        Config(dict(BASE, bin_construct_mode="sketch")), 10) == "sketch"
    assert resolve_bin_mode(
        Config(dict(BASE, bin_construct_mode="exact")),
        5_000_000) == "exact"
    assert resolve_bin_mode(
        Config(dict(BASE, sketch_row_threshold=100)), 101) == "sketch"


# ---------------------------------------------------------------------------
# dataset-level parity: from_matrix / from_sequences under sketch mode
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("extra", [{}, {"zero_as_missing": True}])
def test_from_matrix_sketch_parity(rng, extra):
    """bin_construct_mode=sketch on a resident matrix: mappers, EFB
    groups and the packed bin matrix all bit-match exact mode —
    including the NaN, zero-as-missing and categorical branches."""
    X = _columns_matrix(rng, 2500)
    y = X[:, 0]
    exact = BinnedDataset.from_matrix(
        X, Config(dict(BASE, bin_construct_mode="exact", **extra)),
        label=y, categorical_features=[7])
    sk = BinnedDataset.from_matrix(
        X, Config(dict(BASE, bin_construct_mode="sketch", **extra)),
        label=y, categorical_features=[7])
    assert _mapper_dicts(sk) == _mapper_dicts(exact)
    assert _group_tuples(sk) == _group_tuples(exact)
    np.testing.assert_array_equal(sk.host_binned(), exact.host_binned())


def test_from_sequences_sketch_free_host_default(rng):
    """Sequence construction in sketch mode: bit-parity with the exact
    resident path, host copy freed by DEFAULT (the ingest buffer is the
    only binned copy), sources retained for epoch re-streaming, and
    restream_ingest() rebuilds a bit-identical buffer."""
    X = _columns_matrix(rng, 2611)
    y = X[:, 0]
    one = BinnedDataset.from_matrix(
        X, Config(dict(BASE, bin_construct_mode="exact")), label=y)
    cuts = [0, 611, 1900, len(X)]
    seqs = [_Seq(X[a:b], 173) for a, b in zip(cuts[:-1], cuts[1:])]
    ds = BinnedDataset.from_sequences(
        seqs, Config(dict(BASE, bin_construct_mode="sketch")), label=y)
    assert _mapper_dicts(ds) == _mapper_dicts(one)
    assert _group_tuples(ds) == _group_tuples(one)
    if ds.device_ingest is not None:
        # free-host default-on: no resident (N, G) host matrix survives
        assert ds.binned is None
        assert ds._stream_src, "sequence sources must be retained"
        ing2 = ds.restream_ingest(1024)
        assert ing2 is not None
        np.testing.assert_array_equal(ing2.host_binned(),
                                      one.host_binned())
    np.testing.assert_array_equal(ds.host_binned(), one.host_binned())
    # explicit free_host_binned=false wins over the mode default
    ds2 = BinnedDataset.from_sequences(
        seqs, Config(dict(BASE, bin_construct_mode="sketch",
                          free_host_binned=False)), label=y)
    assert ds2.binned is not None
    np.testing.assert_array_equal(ds2.binned, one.host_binned())


def test_mixed_batch_sizes_bit_parity(rng):
    """Each sequence's OWN batch_size drives its chunking; mixing sizes
    (including one that defaults) changes nothing bit-wise."""
    X = _columns_matrix(rng, 2200)
    y = X[:, 0]
    one = BinnedDataset.from_matrix(
        X, Config(dict(BASE, bin_construct_mode="exact")), label=y)
    a, b = _Seq(X[:700], 37), _Seq(X[700:1500], 256)
    c = _Seq(X[1500:], 101)
    c.batch_size = None            # falls back to the default chunking
    for mode in ("exact", "sketch"):
        ds = BinnedDataset.from_sequences(
            [a, b, c], Config(dict(BASE, bin_construct_mode=mode)),
            label=y)
        assert _mapper_dicts(ds) == _mapper_dicts(one), mode
        np.testing.assert_array_equal(ds.host_binned(),
                                      one.host_binned())


def test_epoch_restream_training_parity(rng):
    """The full claim: sketch + streaming + free-host-by-default must
    train BIT-IDENTICAL trees to the exact resident-matrix path (the
    trainer re-streams epochs from the retained sources when the host
    matrix is gone)."""
    X = _columns_matrix(rng, 2000)
    y = X[:, 0] + 0.1 * rng.normal(size=len(X))
    p = dict(BASE, objective="regression", num_leaves=15,
             num_iterations=5, seed=3)
    m_exact = lgb.train(dict(p, bin_construct_mode="exact"),
                        lgb.Dataset(X, label=y))
    seqs = [_Seq(X[:900], 211), _Seq(X[900:], 173)]
    m_stream = lgb.train(dict(p, bin_construct_mode="sketch"),
                         lgb.Dataset(seqs, label=y))
    assert (_tree_part(m_stream.model_to_string())
            == _tree_part(m_exact.model_to_string()))


# ---------------------------------------------------------------------------
# out-of-core proof: dense matrix exceeds the address-space cap
# ---------------------------------------------------------------------------
_OOCORE_SCRIPT = textwrap.dedent("""
    import json, resource, sys
    import numpy as np
    import lightgbm_tpu as lgb

    ROWS, F = 800_000, 32          # dense f64 = 204.8 MB
    # warm jax + the trainer BEFORE capping: the cap must prove the
    # out-of-core path's working set, not the runtime's startup cost
    Xw = np.random.RandomState(0).normal(size=(512, F))
    lgb.train({"verbosity": -1, "objective": "regression",
               "num_iterations": 1, "num_leaves": 4},
              lgb.Dataset(Xw, label=Xw[:, 0]))

    def vm_data_kb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmData:"):
                    return int(line.split()[1])
        raise RuntimeError("no VmData")

    hard = resource.getrlimit(resource.RLIMIT_DATA)[1]

    # -- phase 1: CONSTRUCTION under a cap the dense matrix cannot fit.
    # 160 MB headroom covers chunk transients + sketches + the (G,
    # N_pad) ingest buffer (~binned-sized), but NOT the 204.8 MB dense
    # matrix (a single allocation larger than the whole headroom, so
    # the probe below fails no matter how the rest is laid out): if any
    # step materialized it, construction would die here.
    cap1 = (vm_data_kb() + 160 * 1024) * 1024
    resource.setrlimit(resource.RLIMIT_DATA, (cap1, hard))

    dense_failed = False
    try:
        np.ones((ROWS, F))
    except MemoryError:
        dense_failed = True

    class Seq(lgb.Sequence):
        batch_size = 32768
        def __len__(self):
            return ROWS
        def __getitem__(self, item):
            sl = item if isinstance(item, slice) else slice(item, item + 1)
            start, stop, _ = sl.indices(ROWS)
            i = np.arange(start, stop, dtype=np.int64)[:, None]
            j = np.arange(F, dtype=np.int64)[None, :]
            h = (i * 2654435761 + j * 40503) % 100003
            X = h.astype(np.float64) / 100003.0 * 6.0 - 3.0
            X[((j % 4 == 0) & (h * 7 % 10 < 9)).nonzero()] = 0.0
            return X if isinstance(item, slice) else X[0]

    y = (np.arange(ROWS, dtype=np.float64) % 97) / 97.0
    # a modest EFB sample: the default 200k-row gather would cost
    # 200k * F * 8B — a deliberate knob, not a hidden dense copy
    params = {"verbosity": -1, "objective": "regression",
              "num_iterations": 2, "num_leaves": 7,
              "bin_construct_mode": "sketch",
              "bin_construct_sample_cnt": 50_000}
    d = lgb.Dataset(Seq(), label=y, params=params).construct()

    # -- phase 2: TRAINING under a larger (binned-scale) cap.  The
    # trainer's residents are ~3x the 1-byte binned footprint plus the
    # runtime's program workspace — none of it raw-matrix-sized; the
    # raised cap still proves training never resurrects the dense f64
    # matrix on top of its working set.
    cap2 = (vm_data_kb() + 640 * 1024) * 1024
    resource.setrlimit(resource.RLIMIT_DATA, (cap2, hard))
    m = lgb.train(params, d)
    pred = m.predict(np.asarray(Seq()[0:256]))
    print(json.dumps({"dense_failed": dense_failed,
                      "trees": m.num_trees(),
                      "pred_finite": bool(np.isfinite(pred).all())}))
""")


def test_capped_rss_out_of_core_construct_and_train(tmp_path):
    """A dataset whose dense f64 matrix does NOT fit under the process
    address-space cap still constructs (sketch pass + streaming pack)
    and trains end to end — the PR's headline acceptance test.

    Two soft RLIMIT_DATA phases: phase 1 caps construction below the
    dense size (a np.ones dense probe must MemoryError, construction
    must not);
    phase 2 raises the soft cap — legal, hard limit untouched — to a
    binned-scale budget for training, whose working set is ~3x the
    1-byte binned footprint plus the runtime workspace, never
    raw-matrix-sized."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "oocore_capped.py"
    script.write_text(_OOCORE_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    out = subprocess.run([sys.executable, str(script)],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["dense_failed"] is True, \
        "the cap must be tight enough that the dense matrix cannot exist"
    assert rec["trees"] == 2 and rec["pred_finite"] is True


# ---------------------------------------------------------------------------
# single-copy residency proof: TRAINING-phase cap at ~1.5x binned
# ---------------------------------------------------------------------------
_TRAINCAP_SCRIPT = textwrap.dedent("""
    import json, resource, sys
    import numpy as np
    import lightgbm_tpu as lgb

    ROWS, F = {rows}, {features}
    SLACK = {slack_mb} * 1024 * 1024
    # warm jax + the trainer BEFORE capping: the cap must prove the
    # trainer's steady-state working set, not the runtime's startup cost
    Xw = np.random.RandomState(0).normal(size=(512, F))
    lgb.train({{"verbosity": -1, "objective": "regression",
               "num_iterations": 1, "num_leaves": 7}},
              lgb.Dataset(Xw, label=Xw[:, 0]))

    def vm_data_kb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmData:"):
                    return int(line.split()[1])
        raise RuntimeError("no VmData")

    hard = resource.getrlimit(resource.RLIMIT_DATA)[1]

    class Seq(lgb.Sequence):
        batch_size = 32768
        def __len__(self):
            return ROWS
        def __getitem__(self, item):
            sl = item if isinstance(item, slice) else slice(item, item + 1)
            start, stop, _ = sl.indices(ROWS)
            i = np.arange(start, stop, dtype=np.int64)[:, None]
            j = np.arange(F, dtype=np.int64)[None, :]
            h = (i * 2654435761 + j * 40503) % 100003
            X = h.astype(np.float64) / 100003.0 * 6.0 - 3.0
            X[((j % 4 == 0) & (h * 7 % 10 < 9)).nonzero()] = 0.0
            return X if isinstance(item, slice) else X[0]

    y = (np.arange(ROWS, dtype=np.float64) % 97) / 97.0
    params = {{"verbosity": -1, "objective": "regression",
              "num_leaves": 15, "metric": "",
              "bin_construct_mode": "sketch",
              "bin_construct_sample_cnt": 50_000}}
    d = lgb.Dataset(Seq(), label=y, params=params).construct()
    inner = d._inner
    binned_b = ROWS * len(inner.groups) * inner._bin_dtype()().nbytes

    # -- uncapped reference arm: 4 iterations, predictions are the oracle
    bst1 = lgb.Booster(params, d)
    for _ in range(4):
        bst1.update()
    ref = bst1.predict(np.asarray(Seq()[0:4096]))

    # -- capped arm on the SAME dataset.  bst1 ADOPTED the ingest buffer
    # (its physical carrier is now the only binned copy), so bst2's setup
    # exercises pristine-carrier recovery; the recovery transient and the
    # fused-step compiles happen in 2 settle iterations BEFORE the cap.
    bst2 = lgb.Booster(params, d)
    for _ in range(2):
        bst2.update()

    # cap = live + ~1.5x binned + a fixed XLA-workspace slack.  Training
    # under single-copy residency adds ZERO binned-sized allocations per
    # iteration (the donated carrier updates in place), so this headroom
    # is pure transient room.
    cap = vm_data_kb() * 1024 + int(1.5 * binned_b) + SLACK
    resource.setrlimit(resource.RLIMIT_DATA, (cap, hard))

    # canary: the pre-change layout kept TWO extra binned residents
    # (learner master buffer + ingest pristine copy on top of the
    # physical carrier); that much extra memory must NOT fit under the
    # cap, deterministically (2x binned + SLACK > 1.5x binned + SLACK).
    canary_failed = False
    try:
        np.ones(2 * binned_b + SLACK, np.uint8)
    except MemoryError:
        canary_failed = True

    for _ in range(2):
        bst2.update()
    resource.setrlimit(resource.RLIMIT_DATA, (hard, hard))
    pred = bst2.predict(np.asarray(Seq()[0:4096]))
    print(json.dumps({{
        "canary_failed": canary_failed,
        "trees": bst2.num_trees(),
        "binned_mb": round(binned_b / 1e6, 1),
        "bit_identical": bool((pred == ref).all()),
    }}))
""")


def _run_traincap(tmp_path, rows, features, slack_mb):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "traincap_capped.py"
    script.write_text(_TRAINCAP_SCRIPT.format(
        rows=rows, features=features, slack_mb=slack_mb))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    out = subprocess.run([sys.executable, str(script)],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["canary_failed"] is True, \
        "2 extra binned residents (the pre-change layout) must not fit"
    assert rec["trees"] == 4
    assert rec["bit_identical"] is True, \
        "capped arm (with carrier recovery) must bit-match the uncapped arm"
    return rec


@pytest.mark.slow  # ~50 s: tier-1 window trim per test_durations.json;
# test_capped_rss_training_phase_smoke keeps a fast in-window
# representative of the same cap/canary/bit-identity contract
def test_capped_rss_training_phase(tmp_path):
    """ISSUE 18 acceptance: TRAINING runs under a soft RLIMIT_DATA cap of
    ~1.5x the binned footprint (+ fixed XLA workspace slack) at 800k x 32,
    a canary allocating the pre-change layout's 2 extra binned residents
    MemoryErrors under the same cap, and the capped booster — which also
    exercises pristine-carrier recovery, since it shares the dataset with
    an earlier adopting booster — predicts bit-identically to the
    uncapped reference.

    The slack term covers XLA:CPU's fused-step temp arena, which is
    allocated PER EXECUTION (~152 MB at this size, ~190 B/row) — it is
    workspace, not residency, and the canary margin (0.5x binned) is
    independent of it."""
    _run_traincap(tmp_path, rows=800_000, features=32, slack_mb=192)


def test_capped_rss_training_phase_smoke(tmp_path):
    """Fast in-window representative of test_capped_rss_training_phase:
    the identical cap/canary/bit-identity contract at 120k x 12.  The
    slack term dominates the budget at this size, so the gate it keeps
    in-window is the structural one (no binned-scale allocation per
    step + deterministic canary margin of 0.5x binned), while the
    slow-marked full size makes the 1.5x multiplier itself bind."""
    _run_traincap(tmp_path, rows=120_000, features=12, slack_mb=48)


def test_profile_construct_trainmem_smoke():
    """tools/profile_construct.py --trainmem --smoke: stream-construct,
    train, and gate on RSS budget + single binned resident + ledger
    attribution (the profiling lane behind BENCH_history's
    profile_construct_trainmem series)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "profile_construct.py"),
         "--trainmem", "--smoke"],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    cells = rec["grid"]
    assert cells, "trainmem smoke grid must not be empty"
    for cell in cells:
        assert cell["rss_ok"] is True, cell
        assert cell["ledger_ok"] is True, cell
        assert cell["binned_residents"] == 1, cell
        assert cell["host_binned_freed"] is True, cell


def test_profile_construct_oocore_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "profile_construct.py"),
         "--oocore", "--smoke"],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["parity_ok"] is True
    assert rec["rss_ok"] is True
    assert rec["grid"], "oocore smoke grid must not be empty"
