"""CLI tests (model: reference tests/python_package_test/test_consistency.py
and examples/*/train.conf)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.cli import Application, model_to_cpp, parse_config_file
from lightgbm_tpu.utils.textio import load_text_file


@pytest.fixture
def workdir(tmp_path, rng):
    """Write a small binary-classification dataset as TSV (reference example
    format: label first, no header) plus a train.conf."""
    n, f = 400, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    train = np.column_stack([y, X])
    np.savetxt(tmp_path / "train.tsv", train, delimiter="\t", fmt="%.6f")
    np.savetxt(tmp_path / "test.tsv", train[:100], delimiter="\t", fmt="%.6f")
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task = train\n"
        "objective = binary  # comment here\n"
        "data = {d}/train.tsv\n"
        "valid = {d}/test.tsv\n"
        "num_trees = 15\n"
        "num_leaves = 15\n"
        "# full-line comment\n"
        "learning_rate = 0.2\n"
        "output_model = {d}/model.txt\n"
        "verbosity = -1\n".format(d=tmp_path))
    return tmp_path


def test_parse_config_file(workdir):
    params = parse_config_file(str(workdir / "train.conf"))
    assert params["objective"] == "binary"
    assert params["num_trees"] == "15"
    assert "learning_rate" in params


def test_cli_train_then_predict(workdir):
    Application([f"config={workdir}/train.conf"]).run()
    model_file = workdir / "model.txt"
    assert model_file.exists()
    assert "tree" in model_file.read_text()[:10]

    out = workdir / "preds.txt"
    Application([
        "task=predict", f"data={workdir}/test.tsv",
        f"input_model={model_file}", f"output_result={out}",
        "verbosity=-1",
    ]).run()
    preds = np.loadtxt(out)
    assert preds.shape == (100,)
    assert (preds >= 0).all() and (preds <= 1).all()
    # predictions should actually classify the training subset well
    labels = np.loadtxt(workdir / "test.tsv", delimiter="\t")[:, 0]
    assert (((preds > 0.5) == (labels > 0.5)).mean()) > 0.9


def test_cli_argv_overrides_config(workdir):
    Application([f"config={workdir}/train.conf", "num_trees=3",
                 f"output_model={workdir}/m3.txt"]).run()
    text = (workdir / "m3.txt").read_text()
    assert text.count("Tree=") == 3


def test_cli_refit(workdir):
    Application([f"config={workdir}/train.conf"]).run()
    Application([
        "task=refit", f"config={workdir}/train.conf",
        f"input_model={workdir}/model.txt",
        f"output_model={workdir}/refit.txt",
    ]).run()
    assert (workdir / "refit.txt").exists()
    # refit model predicts comparably on its own training data
    from lightgbm_tpu import Booster
    loaded = load_text_file(str(workdir / "test.tsv"))
    p = Booster(model_file=str(workdir / "refit.txt")).predict(loaded.X)
    assert (((p > 0.5) == (loaded.label > 0.5)).mean()) > 0.85


def test_cli_convert_model(workdir):
    Application([f"config={workdir}/train.conf", "num_trees=3",
                 f"output_model={workdir}/m.txt"]).run()
    Application([
        "task=convert_model", f"input_model={workdir}/m.txt",
        f"convert_model={workdir}/pred.cpp",
        "convert_model_language=cpp",
    ]).run()
    code = (workdir / "pred.cpp").read_text()
    assert "PredictTree0" in code and "void Predict" in code


def test_convert_model_compiles_and_matches(workdir, tmp_path):
    """The generated C++ must compile and reproduce raw predictions
    (reference: convert_model produces compilable gbdt_prediction.cpp)."""
    import ctypes

    Application([f"config={workdir}/train.conf", "num_trees=5",
                 f"output_model={workdir}/m.txt"]).run()
    from lightgbm_tpu import Booster
    bst = Booster(model_file=str(workdir / "m.txt"))
    code = model_to_cpp(bst)
    src = tmp_path / "pred.cpp"
    src.write_text(code + '\nextern "C" void PredictC(const double* f, '
                   'double* o) { Predict(f, o); }\n')
    lib = tmp_path / "pred.so"
    subprocess.check_call(["g++", "-O1", "-shared", "-fPIC",
                           str(src), "-o", str(lib)])
    so = ctypes.CDLL(str(lib))
    loaded = load_text_file(str(workdir / "test.tsv"))
    expect = bst.predict(loaded.X, raw_score=True)
    got = np.empty(1, dtype=np.float64)
    row = np.ascontiguousarray(loaded.X[0], dtype=np.float64)
    so.PredictC(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                got.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    np.testing.assert_allclose(got[0], expect[0], rtol=1e-10)


def test_textio_libsvm(tmp_path):
    (tmp_path / "d.svm").write_text(
        "1 0:1.5 3:2.0\n0 1:0.5\n1 0:3.0 2:1.0\n")
    loaded = load_text_file(str(tmp_path / "d.svm"))
    assert loaded.X.shape == (3, 4)
    np.testing.assert_array_equal(loaded.label, [1, 0, 1])
    assert loaded.X[0, 3] == 2.0 and loaded.X[1, 1] == 0.5


def test_textio_header_and_columns(tmp_path):
    (tmp_path / "d.csv").write_text(
        "id,target,w,f1,f2\n"
        "1,0.5,1.0,3.0,4.0\n"
        "2,1.5,2.0,5.0,6.0\n")
    loaded = load_text_file(str(tmp_path / "d.csv"), has_header=True,
                            label_column="name:target",
                            weight_column="name:w",
                            ignore_column="name:id")
    np.testing.assert_array_equal(loaded.label, [0.5, 1.5])
    np.testing.assert_array_equal(loaded.weight, [1.0, 2.0])
    assert loaded.X.shape == (2, 2)
    assert loaded.feature_names == ["f1", "f2"]


def test_cli_module_entry(workdir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.check_call(
        [sys.executable, "-m", "lightgbm_tpu",
         f"config={workdir}/train.conf", "num_trees=2",
         f"output_model={workdir}/m2.txt"],
        env=env, cwd="/root/repo")
    assert (workdir / "m2.txt").exists()


@pytest.mark.parametrize("example", [
    "multiclass_classification", "xendcg",
    # tier-1 window trim (PR 17): conf-driven training stays covered
    # in-window by the multiclass + xendcg rows; the distributed plane
    # itself is exercised in-process by test_parallel.py
    pytest.param("parallel_learning", marks=pytest.mark.slow)])
def test_example_confs_train(example, tmp_path):
    """The example dirs double as consistency fixtures (reference ships
    the same trio; BASELINE.md target configs 4-5)."""
    import shutil
    from lightgbm_tpu.cli import main as cli_main
    src = os.path.join(os.path.dirname(__file__), "..", "examples", example)
    work = tmp_path / example
    shutil.copytree(src, work)
    old = os.getcwd()
    try:
        os.chdir(work)
        cli_main(["config=train.conf", "num_iterations=3", "verbosity=-1"])
        assert os.path.exists("LightGBM_model.txt")
        import lightgbm_tpu as lgb
        bst = lgb.Booster(model_file="LightGBM_model.txt")
        assert bst.num_trees() >= 3
    finally:
        os.chdir(old)
