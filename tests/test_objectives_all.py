"""Every objective family trains and reduces its own loss.

Mirrors the breadth of the reference's test_engine.py objective coverage
(tests/python_package_test/test_engine.py): each objective is trained on
data shaped for it, the training metric must improve over iterations,
and family-specific invariants are asserted (positivity, quantile
coverage, probability simplex, ranking order).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _reg_data(rng, n=1500, f=6):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 2) + 0.3 * rng.normal(size=n)
    return X, y


def _pos_data(rng, n=1500, f=6):
    X, y = _reg_data(rng, n, f)
    return X, np.exp(y / (np.abs(y).max() + 1e-9) * 2) + 0.01


def _train_with_history(params, X, y, rounds=25, group=None):
    evals = {}
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train({**params, "verbosity": -1, "num_leaves": 15,
                     "min_data_in_leaf": 20}, ds,
                    num_boost_round=rounds,
                    valid_sets=[ds], valid_names=["t"],
                    callbacks=[lgb.record_evaluation(evals)])
    result = next(iter(evals.values()))      # train-as-valid: "training"
    metric_name, history = next(iter(result.items()))
    return bst, metric_name, history


@pytest.mark.parametrize("objective", [
    "regression", "regression_l1", "huber", "poisson", "quantile",
    "mape",
    # 4.4 s combined: tier-1 window offenders per test_durations.json;
    # huber stays the fast robust-loss representative and poisson the
    # fast log-link representative in the window, the variant
    # formulations keep full coverage in the slow lane
    pytest.param("fair", marks=pytest.mark.slow),
    pytest.param("gamma", marks=pytest.mark.slow),
    pytest.param("tweedie", marks=pytest.mark.slow)])
def test_regression_family_trains(objective, rng):
    if objective in ("poisson", "gamma", "tweedie", "mape"):
        X, y = _pos_data(rng)
    else:
        X, y = _reg_data(rng)
    bst, mname, hist = _train_with_history({"objective": objective}, X, y)
    assert hist[-1] < hist[0], (objective, mname, hist[0], hist[-1])
    p = bst.predict(X)
    assert np.isfinite(p).all()
    if objective in ("poisson", "gamma", "tweedie"):
        # log-link objectives predict positive means
        assert (p > 0).all(), objective


@pytest.mark.slow  # 12.7 s (2 x 60 rounds): tier-1 window offender per
# test_durations.json; test_regression_family_trains[quantile] keeps a
# fast quantile representative in the window
def test_quantile_coverage(rng):
    X, y = _reg_data(rng, n=3000)
    for alpha in (0.2, 0.8):
        bst, _, _ = _train_with_history(
            {"objective": "quantile", "alpha": alpha}, X, y, rounds=60)
        cover = float(np.mean(y <= bst.predict(X)))
        assert abs(cover - alpha) < 0.1, (alpha, cover)


@pytest.mark.parametrize("objective", ["binary", "cross_entropy",
                                       "cross_entropy_lambda"])
def test_binary_family_trains(objective, rng):
    X, yr = _reg_data(rng)
    y = (yr > np.median(yr)).astype(float)
    if objective == "cross_entropy":
        # xentropy accepts soft labels in [0, 1]
        y = np.clip(y * 0.9 + 0.05, 0.0, 1.0)
    bst, mname, hist = _train_with_history({"objective": objective}, X, y)
    assert hist[-1] < hist[0], (objective, mname)
    p = bst.predict(X)
    if objective == "cross_entropy_lambda":
        # xentlambda predicts the Poisson intensity lambda in (0, inf)
        # (reference: CrossEntropyLambda::ConvertOutput, log1p(exp(x)))
        assert (p > 0).all()
    else:
        assert ((p >= 0) & (p <= 1)).all()


@pytest.mark.parametrize("objective", [
    "multiclass",
    # 8.1 s: tier-1 window offender per test_durations.json; the
    # softmax case stays as the fast in-window representative, the OVA
    # formulation keeps full coverage in the slow lane
    pytest.param("multiclassova", marks=pytest.mark.slow)])
def test_multiclass_family_trains(objective, rng):
    X, yr = _reg_data(rng, n=2000)
    y = np.digitize(yr, np.quantile(yr, [0.33, 0.66]))
    bst, mname, hist = _train_with_history(
        {"objective": objective, "num_class": 3}, X, y)
    assert hist[-1] < hist[0], (objective, mname)
    p = bst.predict(X)
    assert p.shape == (len(y), 3)
    if objective == "multiclass":
        # softmax: a probability simplex; OVA is independent sigmoids
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    else:
        assert ((p >= 0) & (p <= 1)).all()
    acc = (p.argmax(axis=1) == y).mean()
    assert acc > 0.6, acc


@pytest.mark.parametrize("objective", [
    "lambdarank",
    # 3.2 s: tier-1 window offender per test_durations.json; lambdarank
    # stays the fast in-window ranking representative
    pytest.param("rank_xendcg", marks=pytest.mark.slow)])
def test_ranking_family_trains(objective, rng):
    n_query, per = 80, 20
    n = n_query * per
    X = rng.normal(size=(n, 6))
    rel = (X[:, 0] + 0.5 * rng.normal(size=n))
    y = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9])).astype(float)
    group = np.full(n_query, per)
    bst, mname, hist = _train_with_history(
        {"objective": objective, "metric": "ndcg", "ndcg_eval_at": [5]},
        X, y, rounds=30, group=group)
    # ndcg is maximized
    assert hist[-1] > hist[0], (objective, hist[0], hist[-1])


@pytest.mark.slow  # 2.2 s: tier-1 window offender per
# test_durations.json; test_dart_trains_and_renormalizes keeps a fast
# in-window dart representative
def test_dart_equals_gbdt_when_no_drops(rng):
    """With skip_drop=1.0 no trees are ever dropped, so DART must produce
    the same model as plain GBDT (reference: dart.hpp dropping logic)."""
    X, y = _reg_data(rng)
    common = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 20}
    b_gbdt = lgb.train({**common, "boosting": "gbdt"},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    b_dart = lgb.train({**common, "boosting": "dart", "skip_drop": 1.0},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    # DART runs the eager (non-fused) path, so allow float32 path noise
    np.testing.assert_allclose(b_dart.predict(X), b_gbdt.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_dart_trains_and_renormalizes(rng):
    X, y = _reg_data(rng)
    params = {"objective": "regression", "boosting": "dart",
              "drop_rate": 0.5, "skip_drop": 0.0, "verbosity": -1,
              "num_leaves": 15, "min_data_in_leaf": 20, "drop_seed": 7}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=25)
    assert bst.num_trees() == 25
    p = bst.predict(X)
    l2 = float(np.mean((p - y) ** 2))
    assert l2 < float(np.var(y)) * 0.7, l2
    # normalization: model predictions equal the sum of per-tree outputs
    # times shrinkage, i.e. the stored (scaled) leaf values are consistent
    p_half = bst.predict(X, num_iteration=12)
    assert np.isfinite(p_half).all()


def test_rf_averages_trees(rng):
    X, y = _reg_data(rng)
    params = {"objective": "regression", "boosting": "rf",
              "bagging_freq": 1, "bagging_fraction": 0.7,
              "feature_fraction": 0.8, "verbosity": -1,
              "num_leaves": 15, "min_data_in_leaf": 20}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    assert bst.num_trees() == 20
    p = bst.predict(X)
    l2 = float(np.mean((p - y) ** 2))
    assert l2 < float(np.var(y)) * 0.7, l2
    # average_output: prediction is the MEAN over trees -> adding more
    # trees must not scale the output magnitude linearly
    p5 = bst.predict(X, num_iteration=5)
    assert np.abs(np.mean(p5)) < 2 * np.abs(np.mean(y)) + 1.0
    # average_output flag round-trips through the model file
    s = bst.model_to_string()
    assert "average_output" in s


def test_rf_requires_bagging(rng):
    X, y = _reg_data(rng, n=300)
    params = {"objective": "regression", "boosting": "rf",
              "verbosity": -1, "num_leaves": 7}
    with pytest.raises(Exception):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)
