"""Fused multiclass iteration: all K class trees build inside one jitted
program (models/boosting.py _setup_fused_multiclass; reference analog:
gbdt.cpp:379 per-class Train loop).  These tests pin the fused path's
equivalence to the eager per-class dispatch path and its behavior across
the sampling/weight/valid combinations."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture
def mc_data(rng):
    X = rng.normal(size=(2500, 8))
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int))
    return X, y.astype(float)


def _train(X, y, params, rounds=12, force_eager=False, weight=None,
           valid=False):
    ds = lgb.Dataset(X, label=y, weight=weight)
    bst = lgb.Booster(params=params, train_set=ds)
    if force_eager:
        bst._gbdt._fused = None
        bst._gbdt._fused_phys = None
    if valid:
        vs = lgb.Dataset(X, label=y, weight=weight, reference=ds)
        bst.add_valid(vs, "v0")
    for _ in range(rounds):
        bst.update()
    return bst


BASE = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
        "min_data_in_leaf": 5, "verbosity": -1}


def _logloss(p, y):
    return -np.mean(np.log(np.maximum(
        p[np.arange(len(y)), y.astype(int)], 1e-12)))


def test_fused_multiclass_enabled_and_matches_eager(mc_data):
    """The fused program and the eager per-class dispatch path see the
    SAME pre-iteration gradients (the snapshot-by-rowid machinery), so
    the first class tree of the first iteration is bit-identical; later
    trees build histograms in permuted row order, so near-tie splits may
    flip on f32 rounding — quality must still be equivalent."""
    X, y = mc_data
    fused = _train(X, y, dict(BASE))
    assert fused._gbdt._fused is not None, "multiclass should fuse"
    eager = _train(X, y, dict(BASE), force_eager=True)
    fused._gbdt._flush_pending()
    t_f, t_e = fused._gbdt.models[0], eager._gbdt.models[0]
    assert t_f.num_leaves == t_e.num_leaves
    assert np.array_equal(t_f.split_feature, t_e.split_feature)
    assert np.allclose(t_f.leaf_value, t_e.leaf_value, atol=1e-6)
    pf, pe = fused.predict(X), eager.predict(X)
    assert len(fused._gbdt.models) == len(eager._gbdt.models) == 36
    lf, le = _logloss(pf, y), _logloss(pe, y)
    assert abs(lf - le) < 0.02 * max(le, 1e-3), (lf, le)
    assert (pf.argmax(1) == y).mean() == pytest.approx(
        (pe.argmax(1) == y).mean(), abs=0.01)


def test_fused_ova_matches_eager(mc_data):
    # class 0 builds before any permutation, so its first tree is
    # bit-identical; later trees see permuted histogram summation order
    # (see the softmax test's docstring) — quality must stay equivalent
    X, y = mc_data
    params = dict(BASE, objective="multiclassova")
    fused = _train(X, y, params)
    assert fused._gbdt._fused is not None
    eager = _train(X, y, params, force_eager=True)
    fused._gbdt._flush_pending()
    t_f, t_e = fused._gbdt.models[0], eager._gbdt.models[0]
    assert t_f.num_leaves == t_e.num_leaves
    assert np.array_equal(t_f.split_feature, t_e.split_feature)
    pf, pe = fused.predict(X), eager.predict(X)
    lf, le = _logloss(pf / np.maximum(pf.sum(1, keepdims=True), 1e-12), y), \
        _logloss(pe / np.maximum(pe.sum(1, keepdims=True), 1e-12), y)
    assert abs(lf - le) < 0.02 * max(le, 1e-3), (lf, le)


def test_fused_multiclass_weighted(mc_data, rng):
    X, y = mc_data
    w = rng.rand(len(y)) + 0.5
    fused = _train(X, y, dict(BASE), weight=w)
    assert fused._gbdt._fused is not None
    eager = _train(X, y, dict(BASE), weight=w, force_eager=True)
    pf, pe = fused.predict(X), eager.predict(X)
    lf, le = _logloss(pf, y), _logloss(pe, y)
    assert abs(lf - le) < 0.03 * max(le, 1e-3), (lf, le)


def test_fused_multiclass_many_classes(rng):
    # K=5 overflows the 8-row Pallas payload; the XLA partition widens
    # its ghi block instead (learner.py _ghi_rows) and still fuses
    X = rng.normal(size=(2000, 6))
    y = rng.randint(0, 5, size=2000).astype(float)
    bst = _train(X, y, dict(BASE, num_class=5), rounds=5)
    assert bst._gbdt._fused is not None
    p = bst.predict(X)
    assert p.shape == (2000, 5)
    assert np.allclose(p.sum(axis=1), 1.0, atol=1e-5)


@pytest.mark.slow  # 8.1 s: tier-1 window trim (PR 12, per
# test_durations.json); test_fused_multiclass_weighted keeps a fast
# in-window fused-multiclass-with-valid representative and bagging is
# covered across test_engine/test_frontier lanes
def test_fused_multiclass_bagging_and_valid(mc_data):
    X, y = mc_data
    params = dict(BASE, bagging_fraction=0.6, bagging_freq=2)
    bst = _train(X, y, params, valid=True)
    assert bst._gbdt._fused is not None
    res = bst.eval_valid()
    assert res and np.isfinite(res[0][2])
    acc = (bst.predict(X).argmax(1) == y).mean()
    assert acc > 0.9


def test_fused_multiclass_stop_on_empty(rng):
    # constant labels: every class tree is a stump after boost-from-avg,
    # so training must stop (all-K-empty iteration), not loop forever
    X = rng.normal(size=(500, 4))
    y = np.ones(500)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=dict(BASE, min_data_in_leaf=600),
                      train_set=ds)
    stopped = False
    for _ in range(5):
        if bst.update():
            stopped = True
            break
    bst._gbdt._flush_pending()
    assert stopped or all(
        t.num_leaves <= 1 for t in bst._gbdt.models)
