"""CEGB, interaction-constraint and per-node column sampling tests
(reference model: tests/python_package_test/test_engine.py
test_cegb / test_interaction_constraints)."""

import numpy as np

import lightgbm_tpu as lgb


def _make_data(n=800, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * 2.0 + X[:, 1] - X[:, 2] + X[:, 3] * 0.5
         + 0.1 * rng.normal(size=n))
    return X, y


BASE = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 5,
        "verbosity": -1}


def _used_features_per_tree(bst):
    model = bst.dump_model()
    out = []
    for t in model["tree_info"]:
        feats = set()

        def walk(node):
            if "split_feature" in node:
                feats.add(node["split_feature"])
                walk(node["left_child"])
                walk(node["right_child"])
        walk(t["tree_structure"])
        out.append(feats)
    return out


def test_interaction_constraints_respected():
    X, y = _make_data()
    bst = lgb.train({**BASE, "interaction_constraints": "[0,1],[2,3,4,5]"},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    allowed = [frozenset({0, 1}), frozenset({2, 3, 4, 5})]
    for feats in _used_features_per_tree(bst):
        # every tree branch must stay within one constraint set; since sets
        # partition the features here, each tree's PATHS must each fit a set
        assert any(feats <= a for a in allowed) or _paths_ok(bst, allowed)
    # quality: still learns something
    assert np.mean((y - bst.predict(X)) ** 2) < 0.6 * np.var(y)


def _paths_ok(bst, allowed):
    """Check every root->leaf path uses features from a single set."""
    model = bst.dump_model()
    ok = True

    def walk(node, path):
        nonlocal ok
        if "split_feature" in node:
            p = path | {node["split_feature"]}
            if not any(p <= a for a in allowed):
                ok = False
            walk(node["left_child"], p)
            walk(node["right_child"], p)
    for t in model["tree_info"]:
        walk(t["tree_structure"], set())
    return ok


def test_interaction_constraints_paths():
    X, y = _make_data(1000, 8, seed=3)
    bst = lgb.train({**BASE, "num_leaves": 31,
                     "interaction_constraints": [[0, 1, 2], [2, 3], [4, 5, 6, 7]]},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    assert _paths_ok(bst, [frozenset({0, 1, 2}), frozenset({2, 3}),
                           frozenset({4, 5, 6, 7})])


def test_cegb_penalty_split_reduces_leaves():
    X, y = _make_data()
    ds = lgb.Dataset(X, label=y)
    bst_free = lgb.train(dict(BASE), ds, num_boost_round=10)
    bst_pen = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                         "cegb_penalty_split": 1.0},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    n_free = sum(t["num_leaves"] for t in bst_free.dump_model()["tree_info"])
    n_pen = sum(t["num_leaves"] for t in bst_pen.dump_model()["tree_info"])
    assert n_pen < n_free


def test_cegb_coupled_feature_penalty_narrows_features():
    X, y = _make_data(1000, 6, seed=2)
    # make features 1..5 expensive; only feature 0 cheap
    pen = "0.0," + ",".join(["1e6"] * 5)
    bst = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_coupled": pen},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    used = set().union(*_used_features_per_tree(bst))
    assert used <= {0}


def test_cegb_lazy_feature_penalty_narrows_features():
    """cegb_penalty_feature_lazy: a huge lazy penalty on features 1..5 means
    only feature 0 is ever worth computing (reference: test_cegb — lazy
    penalties scale with the number of rows that have not used the feature
    yet)."""
    X, y = _make_data(1000, 6, seed=2)
    pen = "0.0," + ",".join(["1e6"] * 5)
    bst = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_lazy": pen},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    used = set().union(*_used_features_per_tree(bst))
    assert used <= {0}


def test_cegb_lazy_penalty_changes_trees():
    """A moderate lazy penalty must alter tree structure vs no penalty, and
    the model must still learn."""
    X, y = _make_data(1000, 6, seed=5)
    bst_free = lgb.train(dict(BASE), lgb.Dataset(X, label=y),
                         num_boost_round=10)
    bst_lazy = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                          "cegb_penalty_feature_lazy":
                              ",".join(["2.0"] * 6)},
                         lgb.Dataset(X, label=y), num_boost_round=10)
    assert bst_lazy.model_to_string() != bst_free.model_to_string()
    pred = bst_lazy.predict(X)
    assert np.mean((y - pred) ** 2) < 0.9 * np.var(y)


def test_feature_fraction_bynode_trains():
    X, y = _make_data(1000, 10, seed=4)
    bst = lgb.train({**BASE, "feature_fraction_bynode": 0.5},
                    lgb.Dataset(X, label=y), num_boost_round=25)
    assert np.mean((y - bst.predict(X)) ** 2) < 0.4 * np.var(y)
    # different trees should use different features (sampling active)
    per_tree = _used_features_per_tree(bst)
    assert len(set(map(frozenset, per_tree))) > 1


def test_forced_splits(tmp_path):
    """forcedsplits_filename: the first splits of every tree must follow the
    JSON spec (reference: ForceSplits, serial_tree_learner.cpp:614)."""
    import json
    X, y = _make_data(1000, 6, seed=9)
    fs = {"feature": 4, "threshold": 0.0,
          "left": {"feature": 5, "threshold": 0.25}}
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(fs))
    bst = lgb.train({**BASE, "forcedsplits_filename": str(p)},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    model = bst.dump_model()
    for t in model["tree_info"]:
        root = t["tree_structure"]
        assert root["split_feature"] == 4
        assert abs(root["threshold"] - 0.0) < 0.1
        left = root["left_child"]
        assert left["split_feature"] == 5
        assert abs(left["threshold"] - 0.25) < 0.1
    # still learns
    assert np.mean((y - bst.predict(X)) ** 2) < 0.5 * np.var(y)


def test_cegb_coupled_penalty_persists_across_trees():
    """The model-lifetime used-feature set must flow back into each tree
    build: a penalty that blocks every split with feat_used=empty must not
    block when the features are already acquired (reference:
    is_feature_used_in_split_ persists for the model lifetime)."""
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.models.learner import SerialTreeLearner

    X, y = _make_data(1000, 6, seed=13)
    cfg = Config({**BASE, "cegb_tradeoff": 1.0,
                  "cegb_penalty_feature_coupled": ",".join(["1e9"] * 6)})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    lr = SerialTreeLearner(ds, cfg)
    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)
    rec_fresh = lr.build_tree(g, h)                      # nothing acquired
    rec_acq = lr.build_tree(g, h,
                            feat_used=jnp.ones((lr.F,), dtype=bool))
    assert int(rec_fresh["s"]) == 0      # unaffordable penalty blocks all
    assert int(rec_acq["s"]) > 0         # acquired features are free
    # end-to-end: the booster threads the used set forward, so an
    # unaffordable coupled penalty yields stubs for EVERY tree (features
    # are never acquired), while the threading keeps the record consistent
    bst = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_coupled": ",".join(["1e9"] * 6)},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert all(len(f) == 0 for f in _used_features_per_tree(bst))
    # plumbing: the booster threads the model-lifetime used-feature set
    # through every build (a regression dropping _cegb_feat_used threading
    # must fail here)
    bst2 = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                      "cegb_penalty_feature_coupled": ",".join(["0.01"] * 6)},
                     lgb.Dataset(X, label=y), num_boost_round=4)
    used_model = np.asarray(bst2._gbdt._cegb_feat_used)
    used_trees = set().union(*_used_features_per_tree(bst2))
    lr2 = bst2._gbdt.learner
    orig_of_enum = {i: int(f) for i, f in
                    enumerate(np.asarray(lr2.ctx.feature_index))}
    acquired = {orig_of_enum[i] for i in np.nonzero(used_model)[0]}
    assert acquired == used_trees and len(acquired) > 0


def test_cegb_lazy_persists_under_sharded_learners():
    """cegb-lazy's per-(row, feature) used bitset persists across
    iterations under the distributed learners too (the psum'd aux rides
    the mesh between trees), so a sharded run matches serial training
    exactly (reference: cost_effective_gradient_boosting.hpp)."""
    import jax
    if len(jax.devices()) < 2:
        import pytest
        pytest.skip("needs a multi-device mesh")
    X, y = _make_data(n=1200)
    pen = ",".join(["0.05"] * 6)
    params = {**BASE, "cegb_tradeoff": 0.8,
              "cegb_penalty_feature_lazy": pen, "num_leaves": 7}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    sharded = lgb.train(dict(params, tree_learner="data"),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    p_s = np.asarray(serial.predict(X))
    p_d = np.asarray(sharded.predict(X))
    assert np.allclose(p_s, p_d, rtol=1e-5, atol=1e-5), \
        np.abs(p_s - p_d).max()
    # and the lazy penalty actually biased the model (vs no penalty)
    plain = lgb.train({**BASE, "num_leaves": 7},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    assert not np.allclose(np.asarray(plain.predict(X)), p_d)
