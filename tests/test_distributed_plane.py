"""Two-process distributed data plane (reference:
dataset_loader.cpp:203 rank-sharded loading, :658-740/:1228-1236
feature-sharded BinMapper construction + Allgather, application.cpp
:173-179 seed sync).  Spawns two real jax.distributed CPU processes."""

import functools
import json
import os
import subprocess
import sys

import numpy as np
import pytest

# Minimal two-process capability probe: jax.distributed bootstrap plus
# ONE process_allgather — exactly the collective plumbing the workers
# below rely on.  Some jax/backend combinations (e.g. jax 0.4.37 CPU)
# bootstrap fine but raise "Multiprocess computations aren't
# implemented on the CPU backend" at the first collective; the real
# tests then fail for a platform reason, not a product one.  Probing
# turns that into an explicit skip with the backend's own error text.
PROBE = r"""
import os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
    prefix="jax-cache-probe-")
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{sys.argv[2]}", num_processes=2,
                           process_id=int(sys.argv[1]))
import jax.numpy as jnp
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(
    jnp.arange(2) + 10 * int(sys.argv[1]))
assert out.reshape(-1).shape[0] == 4, out
print("PROBE_OK", flush=True)
"""


@functools.lru_cache(maxsize=1)
def _multiprocess_collectives_supported():
    """(ok, reason) — spawns the two-process probe once per session."""
    if sys.platform != "linux":
        return False, "process spawn probe requires linux"
    import tempfile
    with tempfile.TemporaryDirectory(prefix="dist-probe-") as td:
        probe = os.path.join(td, "probe.py")
        with open(probe, "w") as fh:
            fh.write(PROBE)
        port = str(13300 + os.getpid() % 400)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        procs = [subprocess.Popen(
            [sys.executable, probe, str(i), port], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(2)]
        try:
            logs = [p.communicate(timeout=120)[0].decode() for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            return False, "two-process jax.distributed probe timed out"
        for p, lg_ in zip(procs, logs):
            if p.returncode != 0 or "PROBE_OK" not in lg_:
                tail = [ln for ln in lg_.strip().splitlines() if ln][-1:]
                return False, ("multiprocess collectives unavailable on "
                               "this jax/backend: %s"
                               % (tail[0][:160] if tail else "no output"))
    return True, ""


def _require_multiprocess_collectives():
    ok, reason = _multiprocess_collectives_supported()
    if not ok:
        pytest.skip(reason)


WORKER = r"""
import json, os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
# the parent's compilation cache holds single-process executables whose
# reuse corrupts multi-process collectives (see conftest note)
os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
    prefix="jax-cache-dist-")
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
port = sys.argv[2]
data_path = sys.argv[3]
out_path = sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.distributed import (rank_shard_indices,
                                               sync_config_params)
from lightgbm_tpu.config import Config

full = np.loadtxt(data_path, delimiter=",")
keep = rank_shard_indices(full.shape[0], pid, 2)
X = full[keep, 1:]
y = full[keep, 0]
ds = lgb.Dataset(X, label=y)
ds.construct({"objective": "regression", "max_bin": 63, "verbosity": -1})
inner = ds._inner
mappers = [json.dumps(bm.to_dict(), sort_keys=True)
           for bm in inner.bin_mappers]

cfg = Config({"objective": "regression", "seed": 100 + pid,
              "bagging_seed": 7 - pid, "feature_fraction": 1.0})
sync_config_params(cfg)

with open(out_path, "w") as f:
    json.dump({"rank": pid, "n_local": int(X.shape[0]),
               "mappers": mappers,
               "num_total_features": inner.num_total_features,
               "seed": cfg.seed, "bagging_seed": cfg.bagging_seed}, f)
print("WORKER_DONE", pid, flush=True)
"""


@pytest.mark.skipif(sys.platform != "linux", reason="process spawn test")
def test_two_process_binmapper_sync(tmp_path, rng):
    _require_multiprocess_collectives()
    n, f = 3000, 6
    X = rng.normal(size=(n, f))
    X[:, 2] = np.where(rng.rand(n) < 0.5, 0.0, X[:, 2])
    y = X[:, 0] + 0.1 * rng.normal(size=n)
    data_path = tmp_path / "data.csv"
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",")
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    outs = [tmp_path / "out0.json", tmp_path / "out1.json"]
    port = str(12500 + os.getpid() % 400)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), port, str(data_path),
         str(outs[i])], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for i in range(2)]
    logs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, lg_ in zip(procs, logs):
        assert p.returncode == 0, lg_[-2000:]
    r0, r1 = [json.load(open(o)) for o in outs]
    # disjoint shards actually loaded
    assert r0["n_local"] + r1["n_local"] == n
    assert abs(r0["n_local"] - r1["n_local"]) <= 1
    # every rank ends with the IDENTICAL full mapper set
    assert r0["num_total_features"] == r1["num_total_features"] == f
    assert r0["mappers"] == r1["mappers"]
    # seeds agreed by min (reference GlobalSyncUpByMin); rank 0 passed
    # seed=100, rank 1 seed=101 (bagging_seed derives from seed in
    # Config, so it syncs to rank 0's derived value)
    assert r0["seed"] == r1["seed"] == 100
    assert r0["bagging_seed"] == r1["bagging_seed"]


TRAIN_WORKER = r"""
import json, os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
    prefix="jax-cache-dist-")
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
port = sys.argv[2]
data_path = sys.argv[3]
out_path = sys.argv[4]
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.distributed import rank_shard_indices

full = np.loadtxt(data_path, delimiter=",")
keep = rank_shard_indices(full.shape[0], pid, 2)
X = full[keep, 1:]
y = full[keep, 0]
params = {"objective": "regression", "num_leaves": 7, "max_bin": 63,
          "learning_rate": 0.2, "min_data_in_leaf": 5, "verbosity": -1,
          "tree_learner": "data", "metric": "l2", "seed": 7,
          "deterministic": True}
ds = lgb.Dataset(X, label=y)
bst = lgb.Booster(params=params, train_set=ds)
# round-5 un-gating: multi-process meshes must take the FUSED sharded
# single-program path (VERDICT r4 #4)
fused_active = bst._gbdt._fused is not None \
    and bst._gbdt._init_phys_fn is not None
for _ in range(20):
    bst.update()
ev = dict((n, v) for (dn, n, v, mb) in bst.eval_train())
bst.save_model(out_path + ".model.txt")

# eager arm: same data, fused disabled — must produce the same model
bst2 = lgb.Booster(params=dict(params, tpu_fused_iteration=False),
                   train_set=lgb.Dataset(X, label=y))
eager_off = bst2._gbdt._fused is None
for _ in range(20):
    bst2.update()
bst2.save_model(out_path + ".eager.model.txt")
with open(out_path, "w") as f:
    json.dump({"rank": pid, "n_local": int(X.shape[0]),
               "train_l2": ev.get("l2"),
               "fused_active": bool(fused_active),
               "eager_off": bool(eager_off)}, f)
print("WORKER_DONE", pid, flush=True)
"""


@pytest.mark.skipif(sys.platform != "linux", reason="process spawn test")
def test_two_process_training_matches_single(tmp_path, rng):
    """Rank-sharded 2-process data-parallel training produces the SAME
    model as single-process training on the union of the shards
    (reference posture: data_parallel_tree_learner.cpp — global
    histograms; binary_objective/gbdt.cpp init-score syncs)."""
    _require_multiprocess_collectives()
    n, f = 2049, 5
    # ODD row count: the two ranks hold unequal shards (1025/1024), so
    # the fused mesh-id space is GAPPED — regression-guards the pad
    # sentinel colliding with a real row id (round-5 review finding).
    # integer-grid features: any row subset yields identical BinMappers,
    # isolating the training math from sampling-dependent bin edges
    X = rng.randint(0, 16, size=(n, f)).astype(np.float64)
    y = (X[:, 0] * 3.0 + X[:, 1] * X[:, 2] + X[:, 3]).astype(np.float64)
    data_path = tmp_path / "data.csv"
    np.savetxt(data_path, np.column_stack([y, X]), delimiter=",")
    worker = tmp_path / "worker.py"
    worker.write_text(TRAIN_WORKER)
    outs = [tmp_path / "t0.json", tmp_path / "t1.json"]
    port = str(12900 + os.getpid() % 400)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), port, str(data_path),
         str(outs[i])], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for i in range(2)]
    logs = [p.communicate(timeout=900)[0].decode() for p in procs]
    for p, lg_ in zip(procs, logs):
        assert p.returncode == 0, lg_[-3000:]
    r0, r1 = [json.load(open(o)) for o in outs]
    # the fused sharded path is ACTIVE on the multi-process mesh
    # (round-4 verdict #4: no more _fused_sharded_reason gate)
    assert r0["fused_active"] and r1["fused_active"]
    assert r0["eager_off"] and r1["eager_off"]
    m0 = open(str(outs[0]) + ".model.txt").read()
    m1 = open(str(outs[1]) + ".model.txt").read()
    # every rank materializes the IDENTICAL model (init-score syncs +
    # psum'd histograms): bit-equal text
    assert m0 == m1
    # eager arm: ranks also bit-equal among themselves; fused vs eager
    # agree numerically (not bitwise: the fused state keeps rows in
    # persistent physical order across iterations, so histogram f32
    # summation order differs — same situation as single-process)
    e0 = open(str(outs[0]) + ".eager.model.txt").read()
    e1 = open(str(outs[1]) + ".eager.model.txt").read()
    assert e0 == e1
    # the synced train metric agrees across ranks
    assert r0["train_l2"] == pytest.approx(r1["train_l2"], rel=1e-9)

    # single-process comparison on the union of the shards.  EFB stays
    # off (the distributed plane disables bundling) so layouts match.
    import lightgbm_tpu as lgb
    params = {"objective": "regression", "num_leaves": 7, "max_bin": 63,
              "learning_rate": 0.2, "min_data_in_leaf": 5,
              "verbosity": -1, "metric": "l2", "seed": 7,
              "deterministic": True, "enable_bundle": False}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(20):
        bst.update()
    pred_single = np.asarray(bst.predict(X))
    loaded = lgb.Booster(model_file=str(outs[0]) + ".model.txt")
    pred_dist = np.asarray(loaded.predict(X))
    assert np.allclose(pred_dist, pred_single, rtol=1e-4, atol=1e-4), \
        np.abs(pred_dist - pred_single).max()
    # fused (default) and eager sharded paths agree numerically
    eager = lgb.Booster(model_file=str(outs[0]) + ".eager.model.txt")
    pred_eager = np.asarray(eager.predict(X))
    assert np.allclose(pred_dist, pred_eager, rtol=1e-4, atol=1e-4), \
        np.abs(pred_dist - pred_eager).max()
