"""Plotting tests (reference: tests/python_package_test/test_plotting.py)."""

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(500, 5))
    y = X[:, 0] * 2 + X[:, 1] - X[:, 2] + 0.1 * rng.normal(size=500)
    evals = {}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1},
                    ds, num_boost_round=10,
                    valid_sets=[ds], valid_names=["train"],
                    callbacks=[lgb.record_evaluation(evals)])
    return bst, evals


def test_plot_importance(trained):
    bst, _ = trained
    ax = lgb.plot_importance(bst)
    assert len(ax.patches) > 0
    ax2 = lgb.plot_importance(bst, importance_type="gain",
                              max_num_features=2)
    assert len(ax2.patches) <= 2


def test_plot_metric(trained):
    _, evals = trained
    ax = lgb.plot_metric(evals)
    assert len(ax.lines) == 1


def test_plot_tree(trained):
    bst, _ = trained
    ax = lgb.plot_tree(bst, tree_index=0)
    assert len(ax.texts) > 0
    with pytest.raises(IndexError):
        lgb.plot_tree(bst, tree_index=999)


def test_plot_split_value_histogram(trained):
    bst, _ = trained
    ax = lgb.plot_split_value_histogram(bst, feature=0)
    assert len(ax.patches) > 0


def test_create_tree_digraph_gate(trained):
    bst, _ = trained
    try:
        import graphviz  # noqa: F401
        g = lgb.create_tree_digraph(bst, tree_index=0)
        assert "yes" in g.source
    except ImportError:
        with pytest.raises(ImportError):
            lgb.create_tree_digraph(bst, tree_index=0)
