"""Distributed learner tests on an 8-device virtual CPU mesh.

Mirrors the reference's distributed-without-cluster strategy
(tests/distributed/_test_distributed.py) with jax.sharding instead of
localhost sockets: the parallel learners must produce the SAME tree as the
serial learner on identical data.
"""

import numpy as np
import pytest

import jax

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.models.learner import SerialTreeLearner
from lightgbm_tpu.parallel.trainer import ShardedTreeBuilder


def _make_data(n=1000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.1 * rng.normal(size=n)
    return X, y


def _serial_record(X, y, cfg):
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    lr = SerialTreeLearner(ds, cfg)
    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)
    return ds, lr.build_tree(g, h)


@pytest.mark.parametrize("mode", ["data", "feature", "voting"])
def test_parallel_matches_serial(mode):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    X, y = _make_data()
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 5, "verbosity": -1,
                  "tree_learner": mode})
    ds, rec_serial = _serial_record(X, y, cfg)

    builder = ShardedTreeBuilder(ds, cfg, mode=mode)
    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)
    rec_par = builder.build_tree(g, h)

    ns, npar = int(rec_serial["s"]), int(rec_par["s"])
    assert npar == ns
    # histogram psum reorders float additions vs the serial chunk order, so a
    # near-tie split can flip (the reference's distributed learners diverge
    # from serial the same way); require structural agreement on nearly all
    # splits rather than bit-exactness.
    f_s = np.asarray(rec_serial["node_feature"][:ns])
    f_p = np.asarray(rec_par["node_feature"][:ns])
    t_s = np.asarray(rec_serial["node_threshold"][:ns])
    t_p = np.asarray(rec_par["node_threshold"][:ns])
    same = (f_s == f_p) & (np.abs(t_s - t_p) <= 3)
    assert same.mean() >= 0.85, (f_s, f_p, t_s, t_p)
    np.testing.assert_array_equal(
        np.asarray(rec_serial["leaf_cnt_g"][:ns + 1]).sum(),
        np.asarray(rec_par["leaf_cnt_g"][:ns + 1]).sum())


def test_data_parallel_ragged_shards():
    """Row count not divisible by the mesh size must still match serial."""
    X, y = _make_data(n=997)
    cfg = Config({"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1})
    ds, rec_serial = _serial_record(X, y, cfg)
    builder = ShardedTreeBuilder(ds, cfg, mode="data")
    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)
    rec_par = builder.build_tree(g, h)
    ns = int(rec_serial["s"])
    assert int(rec_par["s"]) == ns
    np.testing.assert_array_equal(
        np.asarray(rec_serial["node_feature"][:ns]),
        np.asarray(rec_par["node_feature"][:ns]))


def test_train_api_with_data_parallel():
    """Public train() path picks up the sharded learner on a multi-device host."""
    import lightgbm_tpu as lgb
    X, y = _make_data(800, 6, seed=7)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "tree_learner": "data", "min_data_in_leaf": 5,
                     "verbosity": -1}, ds, num_boost_round=10)
    assert bst._gbdt.sharded_builder is not None
    pred = bst.predict(X)
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - pred) ** 2) < 0.4 * mse0


def test_voting_parallel_low_top_k_still_learns():
    """With top_k < num_features the vote compresses the histogram sync;
    training quality must hold (reference: PV-Tree accuracy claim)."""
    import lightgbm_tpu as lgb
    X, y = _make_data(1200, 16, seed=11)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "tree_learner": "voting", "top_k": 3,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=15)
    pred = bst.predict(X)
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - pred) ** 2) < 0.4 * mse0


def test_network_module_single_process():
    """Network facade degrades to no-ops in single-process mode
    (reference: Network::Init with num_machines=1)."""
    from lightgbm_tpu.parallel import network
    network.init_network(num_machines=1)
    assert network.num_machines() == 1
    assert network.rank() == 0
    assert network.global_sync_by_min(3.5) == 3.5
    assert network.global_sync_by_max(2.0) == 2.0
    np.testing.assert_allclose(network.global_sum([1.0, 2.0]), [1.0, 2.0])
    assert network.global_array(7.0) == [7.0]


def _train_pair(params, X, y, rounds=10):
    """Train serial vs data-parallel with identical seeds; return preds."""
    import lightgbm_tpu as lgb
    p_ser = dict(params, tree_learner="serial")
    p_par = dict(params, tree_learner="data")
    b_ser = lgb.train(p_ser, lgb.Dataset(X, label=y), num_boost_round=rounds)
    b_par = lgb.train(p_par, lgb.Dataset(X, label=y), num_boost_round=rounds)
    assert b_par._gbdt.sharded_builder is not None
    assert b_ser._gbdt.sharded_builder is None
    return b_ser.predict(X), b_par.predict(X)


def test_data_parallel_bagging_matches_serial():
    """Bagging masks are full-length row predicates, so the sharded learner
    must see the SAME in-bag rows as serial (reference: bagging.hpp:13
    composes with every parallel learner)."""
    X, y = _make_data(1000, 8, seed=3)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1,
              "bagging_freq": 1, "bagging_fraction": 0.6,
              "bagging_seed": 9}
    p_ser, p_par = _train_pair(params, X, y)
    # identical bagging rng; only histogram-psum float ordering differs
    corr = np.corrcoef(p_ser, p_par)[0, 1]
    assert corr > 0.99, corr
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - p_par) ** 2) < 0.4 * mse0


def test_data_parallel_goss_matches_serial():
    X, y = _make_data(1500, 8, seed=4)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1,
              "data_sample_strategy": "goss",
              "top_rate": 0.3, "other_rate": 0.2, "bagging_seed": 5}
    p_ser, p_par = _train_pair(params, X, y)
    corr = np.corrcoef(p_ser, p_par)[0, 1]
    assert corr > 0.99, corr


def test_data_parallel_l1_renewal():
    """regression_l1 leaf renewal (weighted median of residuals) now runs
    under the sharded learner via device traversal."""
    import lightgbm_tpu as lgb
    X, y = _make_data(1000, 8, seed=6)
    p_ser, p_par = _train_pair(
        {"objective": "regression_l1", "num_leaves": 15,
         "min_data_in_leaf": 5, "verbosity": -1}, X, y)
    corr = np.corrcoef(p_ser, p_par)[0, 1]
    assert corr > 0.99, corr
    # renewal really happened: leaf values are medians, so the parallel
    # model must track the serial one closely on l1
    assert np.mean(np.abs(y - p_par)) < 1.05 * np.mean(np.abs(y - p_ser))


def test_data_parallel_quantized_renewal():
    X, y = _make_data(1000, 8, seed=8)
    p_ser, p_par = _train_pair(
        {"objective": "regression", "num_leaves": 15,
         "min_data_in_leaf": 5, "verbosity": -1,
         "use_quantized_grad": True, "quant_train_renew_leaf": True,
         "num_grad_quant_bins": 16}, X, y)
    mse0 = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - p_par) ** 2) < 0.5 * mse0


def test_data_parallel_linear_tree():
    X, y = _make_data(1000, 6, seed=9)
    p_ser, p_par = _train_pair(
        {"objective": "regression", "num_leaves": 7, "linear_tree": True,
         "min_data_in_leaf": 20, "verbosity": -1, "linear_lambda": 0.01},
        X, y, rounds=8)
    corr = np.corrcoef(p_ser, p_par)[0, 1]
    assert corr > 0.99, corr
    mse0 = np.mean((y - y.mean()) ** 2)
    # linear leaves fit the within-leaf trend: should beat constant leaves
    assert np.mean((y - p_par) ** 2) < 0.3 * mse0


def test_data_scatter_ownership_512_groups():
    """ReduceScatter histogram ownership (round-4 verdict #5; reference:
    data_parallel_tree_learner.cpp:282-296): 8 devices x 512 feature
    groups — the scatter path must (a) produce the same tree as serial,
    (b) lower to reduce-scatter (not a full-histogram all-reduce) in the
    compiled HLO, quantifying the bytes-on-wire claim."""
    assert len(jax.devices()) == 8
    n, f = 2048, 512
    rng = np.random.RandomState(11)
    X = rng.randint(0, 16, size=(n, f)).astype(np.float64)
    y = (X[:, 0] * 2.0 + X[:, 5] - X[:, 100] * 0.5).astype(np.float64)
    base = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1,
            "max_bin": 31, "enable_bundle": False,
            "tree_learner": "data"}
    cfg_serial = Config(dict(base, tree_learner="serial"))
    ds, rec_serial = _serial_record(X, y, cfg_serial)

    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)
    recs = {}
    for sync in ("scatter", "psum"):
        cfg = Config(dict(base, tpu_data_hist_sync=sync))
        dsp = BinnedDataset.from_matrix(X, cfg, label=y)
        builder = ShardedTreeBuilder(dsp, cfg, mode="data")
        assert builder.learner._scatter_groups == (sync == "scatter")
        recs[sync] = builder.build_tree(g, h)

    ns = int(rec_serial["s"])
    for sync, rec in recs.items():
        assert int(rec["s"]) == ns, sync
        np.testing.assert_array_equal(
            np.asarray(rec["node_feature"][:ns]),
            np.asarray(rec_serial["node_feature"][:ns]), err_msg=sync)
        np.testing.assert_array_equal(
            np.asarray(rec["node_threshold"][:ns]),
            np.asarray(rec_serial["node_threshold"][:ns]), err_msg=sync)
        np.testing.assert_allclose(
            np.asarray(rec["leaf_value"][:ns + 1]),
            np.asarray(rec_serial["leaf_value"][:ns + 1]),
            rtol=1e-5, atol=1e-7, err_msg=sync)

    # bytes-on-wire: the scatter path's compiled HLO must move the
    # histogram through reduce-scatter; the psum path through all-reduce
    # of the FULL (G, B, 2) tensor.  Ring costs per device: all-reduce
    # 2*(n-1)/n * |hist| vs reduce-scatter (n-1)/n * |hist| on the
    # build, and the elected winner rides a ~scalar all-gather.
    cfg = Config(dict(base, tpu_data_hist_sync="scatter"))
    dsp = BinnedDataset.from_matrix(X, cfg, label=y)
    builder = ShardedTreeBuilder(dsp, cfg, mode="data")
    hlo = builder._build_lowered_hlo(g, h)
    assert "reduce-scatter" in hlo
    full_hist_allreduce = [
        ln for ln in hlo.splitlines()
        if "all-reduce" in ln and f"512,32,2" in ln]
    assert not full_hist_allreduce, full_hist_allreduce[:2]


def test_sharded_ingest_reshard_zero_host_materialization():
    """ISSUE 18 acceptance: ShardedTreeBuilder startup on an
    ingest-backed dataset resharding on-device must perform ZERO full
    host materializations — host_binned() is poisoned on both the
    dataset and the ingest — and the trees must be bit-identical to the
    blocked host-path arm (same sharded layout, same reductions)."""
    import lightgbm_tpu as lgb

    X, y = _make_data(n=1003, f=8, seed=2)   # not divisible by 8 devices
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 5,
                  "verbosity": -1, "bin_construct_mode": "sketch"})

    class _Seq(lgb.Sequence):
        batch_size = 173

        def __getitem__(self, idx):
            return X[idx]

        def __len__(self):
            return len(X)

    g = (0.0 - y).astype(np.float32)
    h = np.ones(len(y), np.float32)

    def _boom(*a, **k):
        raise AssertionError(
            "host_binned() materialized on the sharded startup path")

    recs = {}
    for mode in ("data", "voting", "feature"):
        ds = BinnedDataset.from_sequences([_Seq()], cfg, label=y)
        assert ds.device_ingest is not None
        assert ds.binned is None, "sketch streaming frees the host copy"
        ds.host_binned = _boom
        ds.device_ingest.host_binned = _boom
        builder = ShardedTreeBuilder(ds, cfg, mode=mode)
        assert builder._used_device_reshard
        recs[mode] = builder.build_tree(g, h)

    # host arm: resident matrix with the ingest disabled exercises the
    # pre-existing blocked host packing; binning is bit-identical
    # (sketch streaming == sketch resident == exact, pinned elsewhere)
    ds_host = BinnedDataset.from_matrix(X, cfg, label=y)
    assert ds_host.binned is not None
    ds_host.device_ingest = None
    for mode in ("data", "voting", "feature"):
        builder = ShardedTreeBuilder(ds_host, cfg, mode=mode)
        assert not builder._used_device_reshard
        rec_h = builder.build_tree(g, h)
        rec_d = recs[mode]
        s = int(rec_h["s"])
        assert int(rec_d["s"]) == s, mode
        for key in ("node_feature", "node_threshold", "node_left",
                    "node_right", "leaf_value"):
            np.testing.assert_array_equal(
                np.asarray(rec_d[key][:s + 1]),
                np.asarray(rec_h[key][:s + 1]),
                err_msg=f"{mode}:{key}")
