"""Dataset ingestion & binary serde tests (reference model:
tests/python_package_test/test_basic.py Dataset construction paths +
save_binary round-trips)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset


def _make(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def test_binary_roundtrip_identical_training(tmp_path):
    X, y = _make()
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y)
    ds.construct(params)
    f = tmp_path / "train.bin"
    ds.save_binary(str(f))

    ds2 = lgb.Dataset(str(f))
    bst1 = lgb.train(params, lgb.Dataset(X, label=y), 10)
    bst2 = lgb.train(params, ds2, 10)
    np.testing.assert_allclose(bst1.predict(X), bst2.predict(X), rtol=1e-6)


def test_binary_preserves_mappers_and_metadata(tmp_path):
    X, y = _make()
    w = np.abs(np.random.RandomState(1).normal(size=len(y))) + 0.1
    cfg = Config({"verbosity": -1})
    inner = BinnedDataset.from_matrix(X, cfg, label=y, weight=w)
    f = tmp_path / "d.bin"
    inner.save_binary(str(f))
    back = BinnedDataset.load_binary(str(f), cfg)
    assert back.num_data == inner.num_data
    assert back.num_total_features == inner.num_total_features
    np.testing.assert_array_equal(back.binned, inner.binned)
    np.testing.assert_allclose(back.metadata.label, inner.metadata.label)
    np.testing.assert_allclose(back.metadata.weight, inner.metadata.weight)
    for a, b in zip(back.bin_mappers, inner.bin_mappers):
        assert a.num_bin == b.num_bin
        np.testing.assert_allclose(a.bin_upper_bound, b.bin_upper_bound)


def test_scipy_sparse_input():
    scipy = pytest.importorskip("scipy.sparse")
    X, y = _make()
    Xs = scipy.csr_matrix(np.where(np.abs(X) < 0.5, 0.0, X))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=y), 10)
    p = bst.predict(Xs.toarray())
    assert 0 <= p.min() and p.max() <= 1


def test_pandas_category_dtype_auto_categorical():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(3)
    n = 600
    cat = rng.choice(["a", "b", "c", "d"], size=n)
    x1 = rng.normal(size=n)
    y = (np.isin(cat, ["a", "c"]).astype(float) * 2 + x1
         + 0.1 * rng.normal(size=n) > 1.0).astype(float)
    df = pd.DataFrame({"c": pd.Categorical(cat), "x": x1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(df, label=y), 20)
    mat, auto, _ = __import__("lightgbm_tpu.basic", fromlist=["x"]) \
        ._dataframe_to_matrix(df)
    assert auto == [0]
    pred = bst.predict(mat)
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.8


def test_text_file_path_as_data(tmp_path):
    X, y = _make(300, 4)
    path = tmp_path / "train.csv"
    with open(path, "w") as f:
        for i in range(len(y)):
            f.write(f"{y[i]:g}," + ",".join(f"{v!r}" for v in map(float, X[i]))
                    + "\n")
    ds = lgb.Dataset(str(path))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5}, ds, 10)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85


def test_auc_mu_metric():
    """auc_mu equals plain binary AUC averaged over class pairs for K=2 and
    stays in [0,1] for K=3 (reference: multiclass_metric.hpp AucMuMetric)."""
    rng = np.random.RandomState(5)
    n = 900
    X = rng.normal(size=(n, 6))
    y = np.argmax(X[:, :3] + 0.5 * rng.normal(size=(n, 3)), axis=1)
    evals = {}
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "metric": "auc_mu", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), 15,
                    valid_sets=[lgb.Dataset(X, label=y)],
                    valid_names=["train"],
                    callbacks=[lgb.record_evaluation(evals)])
    hist = evals["train"]["auc_mu"]
    assert all(0.0 <= v <= 1.0 for v in hist)
    assert hist[-1] > 0.9          # separable-ish problem, train metric
    assert hist[-1] >= hist[0]     # improves with boosting


def test_pred_early_stop_close_to_exact():
    X, y = _make(800, 6, seed=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), 60)
    exact = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=8.0)
    # classification decisions must agree
    assert np.mean((exact > 0.5) == (es > 0.5)) > 0.999
    # with a huge margin nothing stops early: identical
    es2 = bst.predict(X, pred_early_stop=True, pred_early_stop_margin=1e9)
    np.testing.assert_allclose(exact, es2)


def test_pandas_categorical_mapping_persists(tmp_path):
    """Predict-time DataFrames with different category order/appearance must
    be mapped with the TRAINING codes (reference: pandas_categorical in the
    model file)."""
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(7)
    n = 600
    cat = rng.choice(["a", "b"], size=n)
    y = (cat == "a").astype(float) * 8.8 - 4.4
    df = pd.DataFrame({"c": cat})     # object/str dtype: 'a' seen first? mixed
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "learning_rate": 1.0},
                    lgb.Dataset(df, label=y), 8)
    # predict frame where 'b' appears first: codes must still match training
    dfb = pd.DataFrame({"c": ["b", "a"]})
    pb, pa = bst.predict(dfb)
    assert abs(pb - (-4.4)) < 0.5 and abs(pa - 4.4) < 0.5
    # survives model save/load
    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    bst2 = lgb.Booster(model_file=str(f))
    assert bst2.pandas_categorical is not None
    pb2, pa2 = bst2.predict(dfb)
    assert abs(pb2 - pb) < 1e-9 and abs(pa2 - pa) < 1e-9
    # unseen category -> missing (finite prediction, no crash)
    assert np.isfinite(bst2.predict(pd.DataFrame({"c": ["zzz"]}))).all()


def test_binary_without_raw_rejects_linear_tree(tmp_path):
    X, y = _make(200, 3)
    ds = lgb.Dataset(X, label=y)
    ds.construct({"verbosity": -1})
    f = tmp_path / "noraw.bin"
    ds.save_binary(str(f))
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "linear_tree": True,
                   "verbosity": -1}, lgb.Dataset(str(f)), 2)
