"""Dataset ingestion & binary serde tests (reference model:
tests/python_package_test/test_basic.py Dataset construction paths +
save_binary round-trips)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset


def _make(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def test_binary_roundtrip_identical_training(tmp_path):
    X, y = _make()
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y)
    ds.construct(params)
    f = tmp_path / "train.bin"
    ds.save_binary(str(f))

    ds2 = lgb.Dataset(str(f))
    bst1 = lgb.train(params, lgb.Dataset(X, label=y), 10)
    bst2 = lgb.train(params, ds2, 10)
    np.testing.assert_allclose(bst1.predict(X), bst2.predict(X), rtol=1e-6)


def test_binary_preserves_mappers_and_metadata(tmp_path):
    X, y = _make()
    w = np.abs(np.random.RandomState(1).normal(size=len(y))) + 0.1
    cfg = Config({"verbosity": -1})
    inner = BinnedDataset.from_matrix(X, cfg, label=y, weight=w)
    f = tmp_path / "d.bin"
    inner.save_binary(str(f))
    back = BinnedDataset.load_binary(str(f), cfg)
    assert back.num_data == inner.num_data
    assert back.num_total_features == inner.num_total_features
    np.testing.assert_array_equal(back.binned, inner.binned)
    np.testing.assert_allclose(back.metadata.label, inner.metadata.label)
    np.testing.assert_allclose(back.metadata.weight, inner.metadata.weight)
    for a, b in zip(back.bin_mappers, inner.bin_mappers):
        assert a.num_bin == b.num_bin
        np.testing.assert_allclose(a.bin_upper_bound, b.bin_upper_bound)


def test_scipy_sparse_input():
    scipy = pytest.importorskip("scipy.sparse")
    X, y = _make()
    Xs = scipy.csr_matrix(np.where(np.abs(X) < 0.5, 0.0, X))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=y), 10)
    p = bst.predict(Xs.toarray())
    assert 0 <= p.min() and p.max() <= 1


def test_pandas_category_dtype_auto_categorical():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(3)
    n = 600
    cat = rng.choice(["a", "b", "c", "d"], size=n)
    x1 = rng.normal(size=n)
    y = (np.isin(cat, ["a", "c"]).astype(float) * 2 + x1
         + 0.1 * rng.normal(size=n) > 1.0).astype(float)
    df = pd.DataFrame({"c": pd.Categorical(cat), "x": x1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(df, label=y), 20)
    mat, auto, _ = __import__("lightgbm_tpu.basic", fromlist=["x"]) \
        ._dataframe_to_matrix(df)
    assert auto == [0]
    pred = bst.predict(mat)
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.8


def test_text_file_path_as_data(tmp_path):
    X, y = _make(300, 4)
    path = tmp_path / "train.csv"
    with open(path, "w") as f:
        for i in range(len(y)):
            f.write(f"{y[i]:g}," + ",".join(f"{v!r}" for v in map(float, X[i]))
                    + "\n")
    ds = lgb.Dataset(str(path))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5}, ds, 10)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85


def test_auc_mu_metric():
    """auc_mu equals plain binary AUC averaged over class pairs for K=2 and
    stays in [0,1] for K=3 (reference: multiclass_metric.hpp AucMuMetric)."""
    rng = np.random.RandomState(5)
    n = 900
    X = rng.normal(size=(n, 6))
    y = np.argmax(X[:, :3] + 0.5 * rng.normal(size=(n, 3)), axis=1)
    evals = {}
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "metric": "auc_mu", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), 15,
                    valid_sets=[lgb.Dataset(X, label=y)],
                    valid_names=["train"],
                    callbacks=[lgb.record_evaluation(evals)])
    hist = evals["train"]["auc_mu"]
    assert all(0.0 <= v <= 1.0 for v in hist)
    assert hist[-1] > 0.9          # separable-ish problem, train metric
    assert hist[-1] >= hist[0]     # improves with boosting


def test_pred_early_stop_close_to_exact():
    X, y = _make(800, 6, seed=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), 60)
    exact = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=8.0)
    # classification decisions must agree
    assert np.mean((exact > 0.5) == (es > 0.5)) > 0.999
    # with a huge margin nothing stops early: identical
    es2 = bst.predict(X, pred_early_stop=True, pred_early_stop_margin=1e9)
    np.testing.assert_allclose(exact, es2)


def test_pandas_categorical_mapping_persists(tmp_path):
    """Predict-time DataFrames with different category order/appearance must
    be mapped with the TRAINING codes (reference: pandas_categorical in the
    model file)."""
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(7)
    n = 600
    cat = rng.choice(["a", "b"], size=n)
    y = (cat == "a").astype(float) * 8.8 - 4.4
    df = pd.DataFrame({"c": cat})     # object/str dtype: 'a' seen first? mixed
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "learning_rate": 1.0},
                    lgb.Dataset(df, label=y), 8)
    # predict frame where 'b' appears first: codes must still match training
    dfb = pd.DataFrame({"c": ["b", "a"]})
    pb, pa = bst.predict(dfb)
    assert abs(pb - (-4.4)) < 0.5 and abs(pa - 4.4) < 0.5
    # survives model save/load
    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    bst2 = lgb.Booster(model_file=str(f))
    assert bst2.pandas_categorical is not None
    pb2, pa2 = bst2.predict(dfb)
    assert abs(pb2 - pb) < 1e-9 and abs(pa2 - pa) < 1e-9
    # unseen category -> missing (finite prediction, no crash)
    assert np.isfinite(bst2.predict(pd.DataFrame({"c": ["zzz"]}))).all()


def test_binary_without_raw_rejects_linear_tree(tmp_path):
    X, y = _make(200, 3)
    ds = lgb.Dataset(X, label=y)
    ds.construct({"verbosity": -1})
    f = tmp_path / "noraw.bin"
    ds.save_binary(str(f))
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "linear_tree": True,
                   "verbosity": -1}, lgb.Dataset(str(f)), 2)


def test_lambdarank_position_bias():
    """Unbiased lambdarank: per-position bias factors are learned via
    Newton steps when Dataset(position=...) is given (reference:
    rank_objective.hpp UpdatePositionBiasFactors)."""
    rng = np.random.RandomState(11)
    nq, per = 60, 10
    n = nq * per
    X = rng.normal(size=(n, 5))
    true_rel = np.clip((X[:, 0] + 0.3 * rng.normal(size=n)) > 0.5, 0, 1)
    # clicks biased by presentation position: top positions clicked more
    pos = np.tile(np.arange(per), nq)
    click_p = np.where(true_rel > 0, 0.9, 0.15) * (1.0 / (1 + 0.35 * pos))
    y = (rng.uniform(size=n) < click_p).astype(int)
    group = np.full(nq, per)
    ds = lgb.Dataset(X, label=y, group=group, position=pos)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1,
                     "lambdarank_position_bias_regularization": 0.1},
                    ds, 20)
    obj = bst._gbdt.objective
    biases = np.asarray(obj.pos_biases)
    assert biases.shape == (per,)
    assert np.any(biases != 0.0)
    # learned bias should favor top positions (clicks inflated there)
    assert biases[0] > biases[-1]
    # and training without positions is unaffected
    bst2 = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                      "min_data_in_leaf": 5, "verbosity": -1},
                     lgb.Dataset(X, label=y, group=group), 5)
    assert bst2._gbdt.objective.positions is None


def test_validation_dataframe_uses_training_codes():
    """A valid_set DataFrame with different category appearance order must be
    encoded with the training codes (metrics were corrupted otherwise)."""
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(19)
    n = 400
    cat = rng.choice(["x", "y"], size=n)
    y = (cat == "x").astype(float)
    df = pd.DataFrame({"c": cat})
    # valid set: same data REVERSED so the first-seen category differs
    dfv = pd.DataFrame({"c": cat[::-1]})
    ds = lgb.Dataset(df, label=y)
    vs = lgb.Dataset(dfv, label=y[::-1], reference=ds)
    evals = {}
    lgb.train({"objective": "binary", "num_leaves": 4, "verbosity": -1,
               "metric": "binary_error", "min_data_in_leaf": 5},
              ds, 5, valid_sets=[vs], valid_names=["v"],
              callbacks=[lgb.record_evaluation(evals)])
    assert evals["v"]["binary_error"][-1] < 0.01


def test_binary_roundtrip_preserves_positions(tmp_path):
    X = np.random.RandomState(0).normal(size=(100, 3))
    y = (X[:, 0] > 0).astype(float)
    pos = np.tile(np.arange(10), 10)
    ds = lgb.Dataset(X, label=y, group=np.full(10, 10), position=pos)
    ds.construct({"verbosity": -1})
    f = tmp_path / "p.bin"
    ds.save_binary(str(f))
    from lightgbm_tpu.config import Config
    back = BinnedDataset.load_binary(str(f), Config({"verbosity": -1}))
    np.testing.assert_array_equal(back.metadata.positions,
                                  ds._inner.metadata.positions)
    assert back.metadata.position_ids == ds._inner.metadata.position_ids


def test_libsvm_qid_group_loading(tmp_path):
    from lightgbm_tpu.utils.textio import load_text_file
    p = tmp_path / "rank.svm"
    p.write_text("2 qid:1 0:0.5 2:1.0\n1 qid:1 1:0.25\n0 qid:2 0:3.0\n"
                 "1 qid:2 1:1.0\n0 qid:3 0:0.1\n")
    lf = load_text_file(str(p))
    np.testing.assert_array_equal(lf.group, [2, 2, 1])
    assert lf.X.shape == (5, 3)
    assert lf.X[1, 0] == 0.0    # qid never leaks into features


class _ChunkSeq(lgb.Sequence):
    """Test sequence backed by a hidden matrix, chunk-accessible only."""

    batch_size = 128

    def __init__(self, mat):
        self._m = mat

    def __getitem__(self, idx):
        return self._m[idx]

    def __len__(self):
        return len(self._m)


def test_sequence_streaming_construction():
    """Dataset built from Sequences must train identically to the in-memory
    path (reference: Sequence ABC, basic.py:896)."""
    rng = np.random.RandomState(21)
    X = rng.normal(size=(900, 6))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    seqs = [_ChunkSeq(X[:400]), _ChunkSeq(X[400:])]
    bst_seq = lgb.train(params, lgb.Dataset(seqs, label=y), 10)
    bst_mem = lgb.train(params, lgb.Dataset(X, label=y), 10)
    np.testing.assert_allclose(bst_seq.predict(X), bst_mem.predict(X),
                               rtol=1e-5)


def test_sequence_valid_set_uses_training_mappers():
    """A valid Dataset built from Sequences with reference= must be binned
    in the TRAINING bin space (wrong mappers corrupt eval metrics)."""
    rng = np.random.RandomState(23)
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    Xv = rng.normal(size=(300, 5)) * 3.0   # different scale: own mappers differ
    yv = (Xv[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(_ChunkSeq(X), label=y)
    vs = lgb.Dataset(_ChunkSeq(Xv), label=yv, reference=ds)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "metric": "binary_error"},
                    ds, 15, valid_sets=[vs], valid_names=["v"],
                    callbacks=[lgb.record_evaluation(evals)])
    incr_err = evals["v"]["binary_error"][-1]
    fresh_err = float(np.mean((bst.predict(Xv) > 0.5) != yv))
    assert abs(incr_err - fresh_err) < 1e-6
    assert fresh_err < 0.1


def test_sequence_streaming_sparse_bundling_large():
    """Streaming construction with sparse (EFB-bundleable) features and a
    sample smaller than the dataset must not crash and must match the
    in-memory path (regression: bundling indexed sample columns with
    full-dataset row indices)."""
    rng = np.random.RandomState(31)
    n = 3000
    dense = rng.normal(size=(n, 2))
    sparse = np.where(rng.uniform(size=(n, 4)) < 0.95, 0.0,
                      np.abs(rng.normal(size=(n, 4))))
    X = np.column_stack([dense, sparse])
    y = (X[:, 0] + X[:, 2] > 0.2).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "bin_construct_sample_cnt": 500}
    bst = lgb.train(params, lgb.Dataset(_ChunkSeq(X), label=y), 10)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.8


def _write_csv(path, X, header=True, na="NA"):
    with open(path, "w") as f:
        if header:
            f.write(",".join(f"c{i}" for i in range(X.shape[1])) + "\n")
        for row in X:
            f.write(",".join(na if np.isnan(v) else repr(float(v))
                             for v in row) + "\n")


def test_text_file_sequence_chunk_boundary_bit_parity(tmp_path):
    """TextFileSequence feeds the two-pass streaming construction from
    disk; with a batch_size that does NOT divide the row count the
    chunk-boundary path must still produce a bit-identical binned
    matrix and bit-identical trees vs the resident from_matrix arm
    (repr round-trip of float64 is exact)."""
    rng = np.random.RandomState(21)
    n = 317
    X = rng.normal(size=(n, 7))
    X[rng.rand(n) < 0.08, 3] = np.nan
    X[:, 5] = rng.randint(0, 4, size=n).astype(float)
    y = X[:, 0] + 0.1 * rng.normal(size=n)
    p = tmp_path / "train.csv"
    _write_csv(p, X)

    seq = lgb.TextFileSequence(str(p), batch_size=50)   # 317 % 50 != 0
    assert len(seq) == n and seq.ncols == 7
    np.testing.assert_array_equal(np.asarray(seq[0:n]), X)
    np.testing.assert_array_equal(np.asarray(seq[10:73]), X[10:73])
    np.testing.assert_array_equal(np.asarray(seq[-1]), X[-1])
    np.testing.assert_array_equal(seq.read_column(3), X[:, 3])

    one = BinnedDataset.from_matrix(X, Config({"verbosity": -1}), label=y)
    ds = BinnedDataset.from_sequences([seq], Config({"verbosity": -1}),
                                      label=y)
    np.testing.assert_array_equal(ds.host_binned(), one.host_binned())

    params = {"verbosity": -1, "objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "num_iterations": 4, "seed": 3}
    m_mat = lgb.train(params, lgb.Dataset(X, label=y))
    m_txt = lgb.train(params, lgb.Dataset(seq, label=y))
    strip = lambda s: s.partition("parameters:")[0]
    assert strip(m_txt.model_to_string()) == strip(m_mat.model_to_string())


def test_text_file_sequence_headerless_whitespace_usecols(tmp_path):
    """Headerless whitespace-delimited files with NA-ish tokens and a
    usecols projection parse to exactly the selected float64 columns."""
    rng = np.random.RandomState(22)
    X = rng.normal(size=(60, 5))
    p = tmp_path / "train.txt"
    with open(p, "w") as f:
        for i, row in enumerate(X):
            cells = [repr(float(v)) for v in row]
            if i == 7:
                cells[2] = "?"          # NA token -> NaN
            f.write(" ".join(cells) + "\n")
    X[7, 2] = np.nan
    seq = lgb.TextFileSequence(str(p), delimiter=" ", header=False,
                               usecols=[0, 2, 4], batch_size=17)
    assert seq.ncols == 3
    np.testing.assert_array_equal(np.asarray(seq[0:60]), X[:, [0, 2, 4]])
    # read_column addresses ORIGINAL file columns (label-column use)
    np.testing.assert_array_equal(seq.read_column(2), X[:, 2])
    np.testing.assert_array_equal(seq.read_column(1), X[:, 1])
