"""Linear tree tests (reference model: tests/python_package_test/
test_engine.py test_linear_trees*)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_piecewise_linear(n=1200, seed=0):
    """Data a piecewise-LINEAR model fits far better than piecewise-constant."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-2, 2, size=n)
    z = rng.normal(size=n)
    y = np.where(x > 0, 3 * x + 1, -2 * x - 1) + 0.05 * rng.normal(size=n)
    X = np.column_stack([x, z])
    return X, y


BASE = {"objective": "regression", "num_leaves": 4, "min_data_in_leaf": 20,
        "verbosity": -1, "learning_rate": 0.5}


def test_linear_tree_beats_constant_on_linear_data():
    X, y = _make_piecewise_linear()
    bst_c = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 10)
    bst_l = lgb.train({**BASE, "linear_tree": True},
                      lgb.Dataset(X, label=y), 10)
    mse_c = np.mean((y - bst_c.predict(X)) ** 2)
    mse_l = np.mean((y - bst_l.predict(X)) ** 2)
    assert mse_l < 0.5 * mse_c, (mse_l, mse_c)


def test_linear_tree_save_load_roundtrip(tmp_path):
    X, y = _make_piecewise_linear(600)
    bst = lgb.train({**BASE, "linear_tree": True},
                    lgb.Dataset(X, label=y), 8)
    p1 = bst.predict(X, raw_score=True)
    f = tmp_path / "linear.txt"
    bst.save_model(str(f))
    bst2 = lgb.Booster(model_file=str(f))
    p2 = bst2.predict(X, raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-6)
    # dump_model carries the leaf linear models
    m = bst2.dump_model()
    leaf = m["tree_info"][0]["tree_structure"]
    while "left_child" in leaf:
        leaf = leaf["left_child"]
    assert "leaf_const" in leaf and "leaf_coeff" in leaf


def test_linear_tree_nan_rows_fall_back_to_constant():
    X, y = _make_piecewise_linear(800)
    bst = lgb.train({**BASE, "linear_tree": True},
                    lgb.Dataset(X, label=y), 8)
    Xn = X[:5].copy()
    Xn[:, 0] = np.nan
    p = bst.predict(Xn)
    assert np.isfinite(p).all()


def test_linear_tree_with_early_stopping_valid_scores():
    X, y = _make_piecewise_linear(1000, seed=3)
    Xv, yv = _make_piecewise_linear(300, seed=4)
    ds = lgb.Dataset(X, label=y)
    vs = lgb.Dataset(Xv, label=yv, reference=ds)
    evals = {}
    bst = lgb.train({**BASE, "linear_tree": True, "metric": "l2"},
                    ds, 30, valid_sets=[vs], valid_names=["v"],
                    callbacks=[lgb.record_evaluation(evals)])
    hist = evals["v"]["l2"]
    # the recorded (incrementally-updated) valid score must match a fresh
    # prediction-based eval at the end
    fresh = np.mean((yv - bst.predict(Xv)) ** 2)
    assert abs(hist[-1] - fresh) < 1e-4 * max(1.0, fresh)


def test_linear_tree_rollback_restores_scores():
    """rollback_one_iter must exactly undo a linear tree's score update
    (recomputed from the host tree, including the first-iteration
    init-score fold)."""
    X, y = _make_piecewise_linear(500, seed=7)
    bst = lgb.train({**BASE, "linear_tree": True},
                    lgb.Dataset(X, label=y), 3)
    g = bst._gbdt
    before = np.asarray(g.scores).copy()
    g.train_one_iter()
    g.rollback_one_iter()
    np.testing.assert_allclose(np.asarray(g.scores), before,
                               rtol=1e-5, atol=1e-5)
    # rollback all the way through the init-folded first tree
    g.rollback_one_iter()
    g.rollback_one_iter()
    g.rollback_one_iter()
    assert np.allclose(np.asarray(g.scores), np.asarray(g.scores)[0])
