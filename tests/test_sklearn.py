"""sklearn estimator API tests (model: reference tests/python_package_test/test_sklearn.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor


def _make_regression(rng, n=500, f=10):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 3 - X[:, 1] * 2 + 0.1 * rng.normal(size=n)
    return X, y


def _make_binary(rng, n=500, f=10):
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    return X, y


def test_regressor_basic(rng):
    X, y = _make_regression(rng)
    reg = LGBMRegressor(n_estimators=30, num_leaves=15)
    reg.fit(X, y)
    pred = reg.predict(X)
    assert pred.shape == (len(y),)
    mse = np.mean((pred - y) ** 2)
    assert mse < np.var(y) * 0.2
    assert reg.n_features_ == 10
    assert len(reg.feature_importances_) == 10
    assert reg.feature_importances_.sum() > 0


def test_classifier_binary(rng):
    X, y = _make_binary(rng)
    clf = LGBMClassifier(n_estimators=30, num_leaves=15)
    clf.fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    pred = clf.predict(X)
    acc = np.mean(pred == y)
    assert acc > 0.9
    assert set(clf.classes_) == {0, 1}
    assert clf.n_classes_ == 2


def test_classifier_multiclass_string_labels(rng):
    X = rng.normal(size=(600, 5))
    yi = np.argmax(X[:, :3] + 0.2 * rng.normal(size=(600, 3)), axis=1)
    y = np.array(["a", "b", "c"])[yi]
    clf = LGBMClassifier(n_estimators=20, num_leaves=7)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X)
    assert proba.shape == (600, 3)
    pred = clf.predict(X)
    assert set(pred) <= {"a", "b", "c"}
    assert np.mean(pred == y) > 0.8


def test_early_stopping_and_eval_set(rng):
    X, y = _make_binary(rng, n=800)
    Xt, yt = X[:600], y[:600]
    Xv, yv = X[600:], y[600:]
    clf = LGBMClassifier(n_estimators=200, num_leaves=7, learning_rate=0.3)
    clf.fit(Xt, yt, eval_set=[(Xv, yv)],
            callbacks=[lgb.early_stopping(5, verbose=False)])
    assert clf.best_iteration_ > 0
    assert "valid_0" in clf.evals_result_
    assert "binary_logloss" in clf.evals_result_["valid_0"]


def test_sklearn_integration(rng):
    from sklearn.model_selection import cross_val_score

    X, y = _make_binary(rng, n=300, f=5)
    clf = LGBMClassifier(n_estimators=10, num_leaves=7)
    scores = cross_val_score(clf, X, y, cv=3)
    assert scores.mean() > 0.8


def test_get_set_params():
    clf = LGBMClassifier(n_estimators=5, max_bin=63)
    params = clf.get_params()
    assert params["n_estimators"] == 5
    assert params["max_bin"] == 63
    clf.set_params(num_leaves=9)
    assert clf.get_params()["num_leaves"] == 9
    import copy
    clf2 = copy.deepcopy(clf)
    assert clf2.get_params()["max_bin"] == 63


def test_custom_objective_and_metric(rng):
    X, y = _make_regression(rng)

    def l2_obj(y_true, y_pred):
        return (y_pred - y_true), np.ones_like(y_true)

    def mae_metric(y_true, y_pred):
        return "mae_custom", float(np.mean(np.abs(y_true - y_pred))), False

    reg = LGBMRegressor(n_estimators=20, num_leaves=15, objective=l2_obj)
    reg.fit(X, y, eval_set=[(X, y)], eval_metric=mae_metric)
    pred = reg.predict(X)
    mse = np.mean((pred - y) ** 2)
    assert mse < np.var(y) * 0.3
    assert "mae_custom" in str(reg.evals_result_)


def test_ranker(rng):
    n_q, per_q = 30, 20
    X = rng.normal(size=(n_q * per_q, 8))
    rel = np.clip((X[:, 0] * 2 + rng.normal(size=n_q * per_q)).astype(int) % 4,
                  0, 3)
    group = np.full(n_q, per_q)
    rk = LGBMRanker(n_estimators=15, num_leaves=7)
    rk.fit(X, rel, group=group)
    pred = rk.predict(X)
    assert pred.shape == (n_q * per_q,)
    # scores should correlate with relevance
    assert np.corrcoef(pred, rel)[0, 1] > 0.3


def test_ranker_requires_group(rng):
    X, y = _make_binary(rng, n=50, f=3)
    with pytest.raises(ValueError):
        LGBMRanker(n_estimators=2).fit(X, y)


def test_class_weight_balanced(rng):
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] > 1.0).astype(int)  # imbalanced
    clf = LGBMClassifier(n_estimators=20, num_leaves=7,
                         class_weight="balanced")
    clf.fit(X, y)
    pred = clf.predict(X)
    # with balancing, the minority class must actually get predicted
    assert pred.sum() > 0


def test_predict_feature_mismatch(rng):
    X, y = _make_binary(rng, n=100, f=6)
    clf = LGBMClassifier(n_estimators=2, num_leaves=7).fit(X, y)
    with pytest.raises(ValueError):
        clf.predict(X[:, :4])


def test_custom_metric_on_distinct_eval_set(rng):
    X, y = _make_regression(rng, n=400)
    Xv, yv = _make_regression(rng, n=100)

    def mae_metric(y_true, y_pred):
        return "mae_custom", float(np.mean(np.abs(y_true - y_pred))), False

    reg = LGBMRegressor(n_estimators=10, num_leaves=7)
    reg.fit(X, y, eval_set=[(Xv, yv)], eval_metric=mae_metric)
    assert "mae_custom" in reg.evals_result_["valid_0"]


def test_class_weight_dict_original_labels(rng):
    X = rng.normal(size=(400, 4))
    y = np.where(X[:, 0] > 1.0, "pos", "neg")
    clf = LGBMClassifier(n_estimators=10, num_leaves=7,
                         class_weight={"pos": 25.0})
    clf.fit(X, y)
    # the weight must bias the model toward the minority 'pos' class
    assert (clf.predict(X) == "pos").sum() >= (y == "pos").sum() * 0.5


def test_custom_objective_classifier_raw(rng):
    X, y = _make_binary(rng)

    def logloss_obj(y_true, y_pred):
        p = 1.0 / (1.0 + np.exp(-y_pred))
        return p - y_true, p * (1 - p)

    clf = LGBMClassifier(n_estimators=20, num_leaves=7, objective=logloss_obj)
    clf.fit(X, y)
    raw = clf.predict(X)
    # raw scores returned for custom objective; sign should separate classes
    assert np.mean((raw > 0).astype(int) == y) > 0.85


def test_cv_custom_objective(rng):
    import lightgbm_tpu as lgb
    X, y = _make_regression(rng, n=200, f=5)

    def l2_obj(y_pred, dataset):
        lbl = dataset.get_label()
        return y_pred - lbl, np.ones_like(lbl)

    res = lgb.cv({"objective": l2_obj, "metric": "l2", "num_leaves": 7,
                  "verbosity": -1},
                 lgb.Dataset(X, label=y), num_boost_round=5, nfold=2)
    assert "valid l2-mean" in res
