"""Device serving engine (models/serving.py + ops/shap.py).

Covers the PR-3 acceptance gates: device TreeSHAP parity <= 1e-10
against the host recursion oracle on a categorical+NaN+multiclass model
matrix, the compile-count guard (N same-bucket calls = exactly one
trace per (pred kind, bucket)), and cache invalidation on model
mutation (update/rollback) with stale results proven impossible.

Models are module-scoped: every test shares three trainings (the
engine's packs/jit caches are per-booster, so sharing models does not
share the state under test except where a test explicitly warms it).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.shap import predict_contrib as host_contrib

BASE = {"verbosity": -1, "min_data_in_leaf": 10, "metric": ""}
N, F = 4500, 8


def _matrix(seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(N, F))
    X[:, 5] = rng.randint(0, 12, size=N)      # categorical column
    X[::7, 2] = np.nan                        # NaN column
    signal = (X[:, 0] * 2 + np.sin(X[:, 1] * 2)
              + np.where(np.isin(X[:, 5], [2, 5, 7]), 1.5, -0.5)
              + np.nan_to_num(X[:, 2]))
    return X, signal


@pytest.fixture(scope="module")
def reg_model():
    """Regression, numeric-only columns of the shared matrix."""
    X, signal = _matrix()
    y = signal + 0.1 * np.random.RandomState(1).normal(size=N)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=31),
                    lgb.Dataset(X[:, :5], label=y), num_boost_round=10)
    bst._gbdt._flush_pending()
    return bst, X[:, :5].astype(np.float64)


@pytest.fixture(scope="module")
def bin_model():
    """Binary + categorical + NaN, 20 rounds (early-stop fixture).
    IMBALANCED (30/70) so boost_from_average folds a non-trivial init
    score into tree 0 — early-stop margins must include it on the
    device path too (review finding, PR 3)."""
    X, signal = _matrix(11)
    y = (signal > np.quantile(signal, 0.7)).astype(np.float64)
    bst = lgb.train(dict(BASE, objective="binary", num_leaves=31,
                         categorical_feature=[5], enable_bundle=False),
                    lgb.Dataset(X, label=y), num_boost_round=20)
    bst._gbdt._flush_pending()
    return bst, X.astype(np.float64)


@pytest.fixture(scope="module")
def mc_model():
    """Multiclass + categorical, 5 rounds."""
    X, signal = _matrix(13)
    y = np.digitize(signal, np.quantile(signal, [1 / 3, 2 / 3]))
    bst = lgb.train(dict(BASE, objective="multiclass", num_class=3,
                         num_leaves=15, categorical_feature=[5],
                         enable_bundle=False),
                    lgb.Dataset(X, label=y), num_boost_round=5)
    bst._gbdt._flush_pending()
    return bst, X.astype(np.float64)


# ---------------------------------------------------------------------------
# Acceptance: device pred_contrib vs host oracle <= 1e-10 on a
# categorical + NaN + multiclass model matrix
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("model", ["reg", "bin", "mc"])
def test_device_contrib_matches_host_oracle(model, reg_model, bin_model,
                                            mc_model):
    bst, X = {"reg": reg_model, "bin": bin_model, "mc": mc_model}[model]
    g = bst._gbdt
    Xq = X[:400]
    dev = g.serving.contrib(Xq, 0, len(g.models) // g.num_tree_per_iteration)
    assert dev is not None, "device TreeSHAP must engage for this model"
    got = bst.predict(Xq, pred_contrib=True)
    oracle = host_contrib(g, Xq, 0, -1)
    np.testing.assert_allclose(got, oracle, rtol=0, atol=1e-10)
    # additivity: contributions sum to the raw score
    raw = np.asarray(bst.predict(Xq, raw_score=True))
    K = g.num_tree_per_iteration
    nf = g.max_feature_idx + 1
    sums = got.reshape(len(Xq), K, nf + 1).sum(axis=2)
    np.testing.assert_allclose(np.squeeze(sums), np.squeeze(raw),
                               rtol=1e-6, atol=1e-6)


def test_device_contrib_slicing_matches_host(reg_model):
    bst, X = reg_model
    g = bst._gbdt
    Xq = X[:200]
    for s, m in [(0, 4), (3, 5), (5, -1)]:
        dev = bst.predict(Xq, pred_contrib=True, start_iteration=s,
                          num_iteration=m)
        oracle = host_contrib(g, Xq, s, m)
        np.testing.assert_allclose(dev, oracle, rtol=0, atol=1e-10)


# ---------------------------------------------------------------------------
# Acceptance: compile-count guard — N same-bucket calls, one trace per
# (pred kind, bucket); invalidation on update/rollback, stale impossible
# ---------------------------------------------------------------------------
def test_compile_count_one_trace_per_bucket(reg_model):
    bst, X = reg_model
    eng = bst._gbdt.serving
    bst.predict(X, raw_score=True)       # N >= 4096: warms the pack
    assert eng._warm("insession"), "big batch must warm the engine"
    for n in (700, 700, 600, 900, 513):          # all pad to bucket 1024
        bst.predict(X[:n], raw_score=True)
        bst.predict(X[:n], pred_contrib=True)
        bst.predict(X[:n], pred_leaf=True)

    def contrib_traces(bucket):
        # contrib compiles one program per depth-group of the packed
        # forest; each (group, bucket) must still trace exactly once
        return {k: v for k, v in eng.stats()["traces"].items()
                if k[0].startswith("contrib") and k[1] == bucket}

    tr = eng.stats()["traces"]
    assert tr[("raw", 1024)] == 1, tr
    assert tr[("leaf", 1024)] == 1, tr
    c1024 = contrib_traces(1024)
    assert c1024 and all(v == 1 for v in c1024.values()), c1024
    # a different bucket is a new trace, exactly one
    bst.predict(X[:200], raw_score=True)
    bst.predict(X[:129], raw_score=True)
    assert eng.stats()["traces"][("raw", 256)] == 1
    # same bucket, sliced iteration ranges: ONE extra trace per distinct
    # slice LENGTH (the range is served from a per-range sub-pack whose
    # stacked shapes key the jit cache; see ServingEngine._range_sub) —
    # repeats and equal-length ranges reuse it
    bst.predict(X[:700], raw_score=True, start_iteration=2,
                num_iteration=3)
    tr = eng.stats()["traces"]
    assert tr[("raw", 1024)] == 2, tr
    bst.predict(X[:700], raw_score=True, start_iteration=2,
                num_iteration=3)          # repeat: cached sub-pack
    bst.predict(X[:700], raw_score=True, start_iteration=1,
                num_iteration=3)          # same length: same shapes
    assert eng.stats()["traces"][("raw", 1024)] == 2
    # contrib slices stay mask-driven (per depth-group masks): no
    # re-trace for a sliced contrib
    bst.predict(X[:700], pred_contrib=True, num_iteration=4)
    assert contrib_traces(1024) == c1024


def test_range_subpack_parity_and_lru(reg_model):
    """start/num_iteration slices served from the bounded per-range
    sub-pack cache match the host oracle bit-for-bit, and the LRU stays
    within RANGE_CACHE entries."""
    bst, X = reg_model
    eng = bst._gbdt.serving
    g = bst._gbdt
    bst.predict(X, raw_score=True)                  # warm
    Xq = X[:300]
    for s, m in [(0, 3), (2, 2), (1, 4), (3, 1), (0, 4), (2, 2)]:
        dev = np.asarray(bst.predict(Xq, raw_score=True,
                                     start_iteration=s,
                                     num_iteration=m)).reshape(-1)
        oracle = sum(t.predict(Xq) for t in g.models[s:s + m])
        np.testing.assert_allclose(dev, oracle, rtol=1e-6, atol=1e-6)
        assert len(eng._range_packs) <= eng.RANGE_CACHE
    # leaf slices flow through the same sub-pack
    lv_full = bst.predict(Xq, pred_leaf=True)
    lv_sl = bst.predict(Xq, pred_leaf=True, start_iteration=1,
                        num_iteration=3)
    np.testing.assert_array_equal(np.asarray(lv_sl),
                                  np.asarray(lv_full)[:, 1:4])


def test_cache_invalidates_on_update_and_rollback():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(N, 6))
    y = X[:, 0] + 0.2 * rng.normal(size=N)
    ds = lgb.Dataset(X, label=y)
    params = dict(BASE, objective="regression", num_leaves=15)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(5):
        bst.update()
    g = bst._gbdt
    g._flush_pending()
    p5 = bst.predict(X, raw_score=True)           # warms pack @ 5 trees
    c5 = bst.predict(X[:500], pred_contrib=True)
    assert g.serving._warm("insession"), "device path must be serving"
    v5 = g._model_version
    # mutation: one more iteration -> version bump -> packs rebuilt
    bst.update()
    g._flush_pending()
    assert g._model_version > v5
    p6 = bst.predict(X, raw_score=True)
    c6 = bst.predict(X[:500], pred_contrib=True)
    assert not np.allclose(p5, p6), "stale pack served after update"
    assert not np.allclose(c5, c6), "stale contrib pack served after update"
    # rollback: same tree-count shape as the 5-tree forest -> the jit
    # cache is reused (no new trace) but the PACK must refresh
    bst.rollback_one_iter()
    p5b = bst.predict(X, raw_score=True)
    c5b = bst.predict(X[:500], pred_contrib=True)
    np.testing.assert_allclose(p5b, p5, rtol=0, atol=0)
    np.testing.assert_allclose(c5b, c5, rtol=0, atol=0)
    # explicit invalidate drops packs; results unchanged after rebuild
    g.serving.invalidate()
    assert g.serving.stats()["packs"] == []
    np.testing.assert_allclose(bst.predict(X, raw_score=True), p5b,
                               rtol=0, atol=0)


# ---------------------------------------------------------------------------
# pred_early_stop through the engine
# ---------------------------------------------------------------------------
def test_early_stop_device_matches_host(bin_model):
    bst, X = bin_model
    g = bst._gbdt
    kw = dict(raw_score=True, pred_early_stop=True,
              pred_early_stop_freq=5, pred_early_stop_margin=3.0)
    dev = bst.predict(X, **kw)
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    host = bst.predict(X, **kw)
    g.device_trees = saved
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-5)
    # degenerate margins: nothing stops == plain raw; everything stops
    # after the first block == first-freq prediction
    huge = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_freq=5,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(huge, bst.predict(X, raw_score=True),
                               rtol=2e-6, atol=2e-6)
    tiny = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_freq=4,
                       pred_early_stop_margin=1e-12)
    np.testing.assert_allclose(
        tiny, bst.predict(X, raw_score=True, num_iteration=4),
        rtol=2e-6, atol=2e-6)


def test_early_stop_multiclass_device(mc_model):
    bst, X = mc_model
    g = bst._gbdt
    kw = dict(raw_score=True, pred_early_stop=True,
              pred_early_stop_freq=2, pred_early_stop_margin=1.0)
    dev = bst.predict(X, **kw)
    saved = g.device_trees
    g.device_trees = [None] * len(saved)
    host = bst.predict(X, **kw)
    g.device_trees = saved
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# pred_leaf through the engine (in-session device path + slicing)
# ---------------------------------------------------------------------------
def test_pred_leaf_insession_device_and_slicing(reg_model):
    bst, X = reg_model
    g = bst._gbdt
    leaves = bst.predict(X, pred_leaf=True)
    host = np.stack([t.predict_leaf(X) for t in g.models], axis=1)
    np.testing.assert_array_equal(leaves, host)
    sl = bst.predict(X, pred_leaf=True, start_iteration=2,
                     num_iteration=3)
    np.testing.assert_array_equal(sl, host[:, 2:5])


def test_raw_slicing_decomposes(reg_model):
    """predict(raw) over [0, a) plus [a, end) equals the full range —
    through the device engine (reference: test_engine.py
    test_predict_with_start_iteration)."""
    bst, X = reg_model
    full = bst.predict(X, raw_score=True)
    a = bst.predict(X, raw_score=True, num_iteration=4)
    b = bst.predict(X, raw_score=True, start_iteration=4,
                    num_iteration=-1)
    np.testing.assert_allclose(a + b, full, rtol=1e-5, atol=1e-5)


def test_refit_invalidates_serving_pack(reg_model):
    """refit's in-place leaf rewrites must invalidate the serving pack
    its own predict_leaf_index call warmed — a stale pack would serve
    PRE-refit leaf values on big batches (review finding, PR 3)."""
    bst, X = reg_model
    y2 = np.random.RandomState(2).normal(size=len(X)) * 3 + 10.0
    refitted = bst.refit(X, y2)           # X >= 4096: warms loaded pack
    big = refitted.predict(X)             # big batch -> device path
    clean = lgb.Booster(model_str=refitted.model_to_string()).predict(X)
    np.testing.assert_allclose(big, clean, rtol=1e-6, atol=1e-6)
    assert not np.allclose(big, bst.predict(X)), \
        "refit output should differ from the original model"


def test_contrib_small_batch_host_fallback_matches(mc_model):
    """Cold-engine tiny batches fall back to the host oracle; warm
    engine serves them from the device — both agree."""
    bst, X = mc_model
    bst._gbdt.serving.invalidate()                  # force a cold engine
    tiny = X[:32]
    cold = bst.predict(tiny, pred_contrib=True)     # host path (cold)
    bst.predict(X[:400], pred_contrib=True)         # warm the engine
    warm = bst.predict(tiny, pred_contrib=True)     # device path
    np.testing.assert_allclose(cold, warm, rtol=0, atol=1e-10)

# ---------------------------------------------------------------------------
# predict_leaf_index start/num_iteration (PR-3 API, first covered here):
# slicing parity on device AND host paths, plus the past-the-end edge
# ---------------------------------------------------------------------------
def test_pred_leaf_slicing_matrix_multiclass(mc_model):
    """K=3: sliced leaf indices equal the matching K-interleaved column
    block of the full matrix for every (start, num) combination."""
    bst, X = mc_model
    g = bst._gbdt
    K = g.num_tree_per_iteration
    total = len(g.models) // K
    full = bst.predict(X, pred_leaf=True)
    assert full.shape == (len(X), total * K)
    for s, m in [(0, 2), (1, 3), (2, -1), (4, 1), (0, 100)]:
        end = total if m < 0 else min(total, s + m)
        sl = bst.predict(X, pred_leaf=True, start_iteration=s,
                         num_iteration=m)
        np.testing.assert_array_equal(sl, full[:, s * K:end * K])


def test_pred_leaf_slicing_host_path_parity(reg_model, monkeypatch):
    """The host fallback must slice identically to the device engine."""
    bst, X = reg_model
    g = bst._gbdt
    dev = bst.predict(X, pred_leaf=True, start_iteration=3,
                      num_iteration=4)
    monkeypatch.setattr(g.serving, "leaves_insession",
                        lambda *a, **k: None)
    monkeypatch.setattr(g.serving, "leaves_loaded",
                        lambda *a, **k: None)
    host = bst.predict(X, pred_leaf=True, start_iteration=3,
                       num_iteration=4)
    np.testing.assert_array_equal(dev, host)


def test_pred_leaf_past_the_end_is_empty(reg_model):
    """start_iteration past the model end yields an empty (n, 0)
    matrix like the other pred kinds, not a crash (and the same on the
    host fallback path)."""
    bst, X = reg_model
    g = bst._gbdt
    total = len(g.models) // g.num_tree_per_iteration
    out = bst.predict(X[:64], pred_leaf=True, start_iteration=total + 5,
                      num_iteration=3)
    assert out.shape == (64, 0)
    out2 = bst.predict(X[:64], pred_leaf=True, start_iteration=total,
                       num_iteration=-1)
    assert out2.shape == (64, 0)
    # zero-width interior slice too
    out3 = bst.predict(X[:64], pred_leaf=True, start_iteration=2,
                       num_iteration=0)
    # num_iteration=0 means "all remaining" (reference c_api semantics)
    assert out3.shape == (64, total - 2)


# ---------------------------------------------------------------------------
# pickle / deepcopy round trip: the restored engine re-warms LAZILY on
# the first predict — exactly one compile per (kind, bucket), never a
# crash or a per-call cold trace (PR-3 handoff note)
# ---------------------------------------------------------------------------
def test_pickle_round_trip_one_compile_post_restore(reg_model):
    import pickle
    bst, X = reg_model
    bst.predict(X, raw_score=True)        # ensure the engine is warm
    ref = bst.predict(X[:300], raw_score=True)
    bst2 = pickle.loads(pickle.dumps(bst))
    eng2 = bst2._gbdt.serving
    assert eng2.trace_counts == {}, "restored engine must start untraced"
    # SMALL batch: the re-warm hint must bypass the cold-row gate so
    # the device path engages immediately
    p1 = bst2.predict(X[:300], raw_score=True)
    p2 = bst2.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(p1, ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(p1, p2)
    traced = dict(eng2.trace_counts)
    assert traced, "device serving must engage on the first predict"
    assert all(v == 1 for v in traced.values()), traced
    # same bucket again: served from the SAME compiled program
    bst2.predict(X[:290], raw_score=True)
    assert dict(eng2.trace_counts) == traced, "cold-traced per call"
    # second-generation pickle: names not yet re-packed must STAY
    # pending (union of live packs and owed re-warms, not a fallback)
    eng3 = pickle.loads(pickle.dumps(bst2))._gbdt.serving
    assert "contrib" in eng3._rewarm and "loaded" in eng3._rewarm, \
        eng3._rewarm


def test_pickle_never_warmed_keeps_cold_gating():
    """A booster whose engine never warmed must not pay the pack cost
    for tiny batches after unpickling (the re-warm hint is only set
    when the original was actually serving)."""
    import pickle
    rng = np.random.RandomState(17)
    X = rng.normal(size=(500, 5))
    y = X[:, 0] + 0.1 * rng.normal(size=500)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=7),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    bst._gbdt._flush_pending()
    bst2 = pickle.loads(pickle.dumps(bst))
    bst2.predict(X[:32])
    assert bst2._gbdt.serving.trace_counts == {}, \
        "tiny batch on a never-warm copy must stay on the host path"


def test_deepcopy_round_trip_predicts(reg_model):
    import copy
    bst, X = reg_model
    ref = bst.predict(X[:100])
    clone = copy.deepcopy(bst)
    np.testing.assert_allclose(clone.predict(X[:100]), ref,
                               rtol=1e-6, atol=1e-6)


def test_standalone_engine_pickle_rewarm(reg_model):
    """A STANDALONE ServingEngine pickle (a registry snapshot, a
    worker shipping one engine — not riding a Booster) used to crash
    on the GBDT's jitted closures (PR-3 note).  It now snapshots the
    forest to its model string: warm pack names survive the round
    trip and the restored copy's first predict re-packs + traces once
    per (kind, bucket) — never per-call cold traces."""
    import copy
    import pickle
    bst, X = reg_model
    g = bst._gbdt
    bst.predict(X, raw_score=True)            # ensure warm
    ref = np.asarray(bst.predict(X[:300], raw_score=True)).reshape(-1)
    total = len(g.models) // g.num_tree_per_iteration
    for clone in (pickle.loads(pickle.dumps(g.serving)),
                  copy.deepcopy(g.serving)):
        assert clone.trace_counts == {}, "restored engine starts cold"
        # SMALL batch: warmth survived, so the device path engages
        # immediately (the restored forest is a loaded model — no
        # training mappers — so it serves from the loaded pack family)
        out = clone.raw_loaded(X[:300], 0, total)
        assert out is not None, "re-warm hint must lift the cold gate"
        np.testing.assert_allclose(np.asarray(out).reshape(-1), ref,
                                   rtol=1e-6, atol=1e-6)
        traced = dict(clone.trace_counts)
        assert traced and all(v == 1 for v in traced.values()), traced
        clone.raw_loaded(X[:290], 0, total)   # same bucket: no trace
        assert dict(clone.trace_counts) == traced


def test_standalone_engine_pickle_never_warm_stays_cold():
    import pickle
    rng = np.random.RandomState(19)
    X = rng.normal(size=(400, 5))
    y = X[:, 0] + 0.1 * rng.normal(size=400)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=7),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    bst._gbdt._flush_pending()
    eng2 = pickle.loads(pickle.dumps(bst._gbdt.serving))
    assert eng2.raw_loaded(X[:32], 0, 3) is None, \
        "tiny batch on a never-warm standalone copy stays on the host"


# ---------------------------------------------------------------------------
# multi-forest cohort dispatch (serving/registry.py CohortPack +
# serving/service.py cohort lanes over ops/forest_tensor.py)
# ---------------------------------------------------------------------------
def _tenant_booster(seed, rounds=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(500, 5))
    y = X[:, 0] + 0.5 * np.sin(X[:, 1]) + 0.1 * rng.normal(size=500)
    bst = lgb.train(dict(BASE, objective="regression", num_leaves=7,
                         min_data_in_leaf=5, seed=seed),
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    bst._gbdt._flush_pending()
    return bst, X


def test_cohort_wave_is_one_dispatch_with_pinned_compiles():
    """The acceptance gate: an N-tenant same-bucket raw wave serves in
    ONE dispatch (compile/dispatch counters under concurrent clients),
    repeated waves never re-trace the cohort program, and every
    tenant's cohort answers are bit-identical to its own single-model
    dispatch."""
    import threading

    from lightgbm_tpu.serving import ModelRegistry, ServingService

    boosters = {f"m{i}": _tenant_booster(20 + i) for i in range(3)}
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         queue_depth=1024, cohort=True)
    for name, (bst, X) in boosters.items():
        reg.publish(name, bst, gate_rows=X)
    want = {name: np.asarray(bst.predict(X[:40], raw_score=True))
            for name, (bst, X) in boosters.items()}

    tickets = {}

    def client(name):
        _, X = boosters[name]
        tickets[name] = [svc.submit(X[i].reshape(1, -1), model=name,
                                    kind="raw", tenant=name)
                         for i in range(40)]

    threads = [threading.Thread(target=client, args=(n,))
               for n in boosters]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert svc.pump(force=True) == 1, "one cohort dispatch for the wave"
    assert svc.counters["dispatches"] == 1
    assert svc.counters["cohort_dispatches"] == 1
    assert svc.counters["cohort_models"] == 3
    for name, ts in tickets.items():
        got = np.asarray([t.result for t in ts]).reshape(-1)
        np.testing.assert_array_equal(got, want[name].reshape(-1))

    # repeated same-cohort waves: calls accumulate, traces stay pinned
    for _ in range(2):
        for name, (bst, X) in boosters.items():
            svc.submit(X[:40], model=name, kind="raw", tenant=name)
        svc.pump(force=True)
    assert svc.counters["cohort_dispatches"] == 3
    traces = dict(reg.cohort_traces)
    assert traces == {("cohort_raw", 128): 1}, traces
    assert reg.cohort_calls[("cohort_raw", 128)] == 3

    # a member publish bumps its version: the stale pack is impossible
    # (rebuild) but the SAME padded shapes hit the jit cache — zero new
    # compiles
    bst2, X2 = _tenant_booster(77)
    reg.publish("m1", bst2, gate_rows=X2)
    want2 = np.asarray(bst2.predict(X2[:40], raw_score=True))
    t2 = svc.submit(X2[:40], model="m1", kind="raw", tenant="m1")
    for name in ("m0", "m2"):
        bst, X = boosters[name]
        svc.submit(X[:40], model=name, kind="raw", tenant=name)
    svc.pump(force=True)
    np.testing.assert_array_equal(
        np.asarray(t2.result).reshape(-1), want2.reshape(-1))
    assert dict(reg.cohort_traces) == {("cohort_raw", 128): 1}
    assert svc.counters["cohort_dispatches"] == 4


def test_cohort_ineligible_members_fall_back_per_model(mc_model):
    """Sliced ranges, non-raw kinds and categorical (cohort-ineligible)
    members keep the per-model path; eligible pairs still cohort."""
    from lightgbm_tpu.serving import ModelRegistry, ServingService

    mc, Xmc = mc_model
    (b0, X0), (b1, X1) = _tenant_booster(31), _tenant_booster(33)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         queue_depth=1024, cohort=True)
    reg.publish("a", b0, gate_rows=X0)
    reg.publish("b", b1, gate_rows=X1)
    reg.publish("cat", mc, gate_rows=Xmc)
    # a sliced lane and a leaf lane never join a cohort wave
    ta = svc.submit(X0[:8], model="a", kind="raw", num_iteration=2)
    tb = svc.submit(X1[:8], model="b", kind="leaf")
    svc.pump(force=True)
    assert svc.counters["cohort_dispatches"] == 0
    assert svc.counters["dispatches"] == 2
    assert ta.status == "ok" and tb.status == "ok"
    # a categorical member degrades the WAVE to per-model dispatch
    # (cohort_pack returns None), but every ticket still answers
    svc.submit(X0[:8], model="a", kind="raw")
    svc.submit(X1[:8], model="b", kind="raw")
    tc = svc.submit(Xmc[:8], model="cat", kind="raw")
    svc.pump(force=True)
    assert svc.counters["cohort_dispatches"] == 0
    assert tc.status == "ok"
    np.testing.assert_allclose(
        np.asarray(tc.result),
        np.asarray(mc.predict(Xmc[:8], raw_score=True)),
        rtol=0, atol=0)


def test_cohort_pack_purged_on_publish_and_remove():
    """publish/rollback/remove purge cached cohort packs stacking the
    name: a cohort that never re-forms must not pin the replaced (or
    removed) booster's device tensors in the LRU."""
    from lightgbm_tpu.serving import ModelRegistry, ServingService

    boosters = {f"p{i}": _tenant_booster(50 + i) for i in range(2)}
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=64, max_delay=10.0,
                         queue_depth=1024, cohort=True)
    for name, (bst, X) in boosters.items():
        reg.publish(name, bst, gate_rows=X)
    for name, (bst, X) in boosters.items():
        svc.submit(X[:16], model=name, kind="raw")
    assert svc.pump(force=True) == 1
    assert len(reg._cohorts) == 1
    bst2, X2 = _tenant_booster(59)
    reg.publish("p0", bst2, gate_rows=X2)
    assert len(reg._cohorts) == 0, "publish must purge member cohorts"
    svc.submit(X2[:16], model="p0", kind="raw")
    svc.submit(boosters["p1"][1][:16], model="p1", kind="raw")
    assert svc.pump(force=True) == 1           # rebuilt, still 1 wave
    assert len(reg._cohorts) == 1
    reg.remove("p1")
    assert len(reg._cohorts) == 0, "remove must purge member cohorts"
