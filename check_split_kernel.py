"""TPU check: best_split_pair_pallas vs find_best_split_fast."""
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from lightgbm_tpu.ops import split as so
from lightgbm_tpu.ops.split_pallas import best_split_pair_pallas

rng = np.random.RandomState(5)
F, BF = 28, 255
for trial in range(6):
    num_bin = rng.randint(3, BF + 1, size=F).astype(np.int32)
    missing = rng.randint(0, 3, size=F).astype(np.int32)
    dflt = np.where(missing == 1, rng.randint(0, 3, size=F), 0).astype(np.int32)
    ctx = so.SplitContext(jnp.asarray(num_bin), jnp.asarray(missing),
                          jnp.asarray(dflt), jnp.zeros(F, jnp.int32),
                          jnp.arange(F, dtype=jnp.int32))
    half = np.zeros((F, 8), np.int32)
    half[:, 0] = num_bin; half[:, 1] = missing; half[:, 2] = dflt
    fmeta = jnp.asarray(np.concatenate([half, half]))
    args_static = dict(l1=0.0 if trial % 2 else 0.3, l2=1e-3,
                       max_delta_step=0.0, min_gain_to_split=0.0,
                       min_data_in_leaf=5, min_sum_hessian=1e-3,
                       max_depth=0)
    hists, infos, refs = [], [], []
    for c in range(2):
        hist = np.zeros((F, BF, 2), np.float32)
        for f in range(F):
            hist[f, :num_bin[f], 0] = rng.normal(size=num_bin[f])
            hist[f, :num_bin[f], 1] = rng.uniform(0.01, 2.0, size=num_bin[f])
        sum_g = float(hist[0, :, 0].sum()); sum_h = float(hist[0, :, 1].sum())
        cnt = 2000 + c * 500
        mask = rng.rand(F) > 0.2
        ref = so.find_best_split_fast(
            jnp.asarray(hist), ctx, jnp.float32(sum_g), jnp.float32(sum_h),
            jnp.int32(cnt), args_static["l1"], args_static["l2"], 0.0, 0.0,
            5, 1e-3, jnp.asarray(mask))
        refs.append(ref)
        hists.append(hist)
        info = np.zeros((F, 8), np.float32)
        info[:, 0] = sum_g; info[:, 1] = sum_h; info[:, 2] = cnt
        info[:, 3] = 1.0; info[:, 4] = mask
        infos.append(info)
    hg = jnp.asarray(np.concatenate([hists[0][..., 0], hists[1][..., 0]]))
    hh = jnp.asarray(np.concatenate([hists[0][..., 1], hists[1][..., 1]]))
    info = jnp.asarray(np.concatenate(infos))
    tile = np.asarray(best_split_pair_pallas(hg, hh, fmeta, info,
                                             **args_static))
    for c, ref in enumerate(refs):
        row = tile[c]
        gain = row[0]
        feat = row[1:2].view(np.int32)[0]
        thr = row[2:3].view(np.int32)[0]
        dl = row[3] > 0.5
        lc = row[4:5].view(np.int32)[0]
        assert np.isclose(gain, float(ref.gain), rtol=2e-4, atol=1e-5) or \
            (not np.isfinite(gain) and not np.isfinite(float(ref.gain))), \
            (trial, c, gain, float(ref.gain))
        assert feat == int(ref.feature), (trial, c, feat, int(ref.feature))
        assert thr == int(ref.threshold), (trial, c, thr, int(ref.threshold))
        assert dl == bool(ref.default_left), (trial, c)
        assert abs(lc - int(ref.left_count)) <= 1, (trial, c)
        np.testing.assert_allclose(row[6], float(ref.left_sum_g), rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(row[10], float(ref.left_output), rtol=2e-4, atol=1e-5)
    print("trial", trial, "ok", flush=True)
print("ALL OK")
