"""Interleaved A/B benchmark harness (round-5 measurement discipline).

The TPU is attached through a tunnel whose dispatch latency drifts by
+/-6% day-to-day (PERF.md "tunnel health note"), which is larger than
most single-change wins.  Comparing two runs taken at different times is
therefore blind below ~15 ms/iter.  This harness removes the
between-attachment variance by interleaving the two arms WITHIN one
attachment:

    settle, A, B, A, B, ... (>= 5 blocks per arm), one completion
    barrier per block

and reporting median + MAD per arm plus the paired per-position deltas
(the tunnel drift is slow, so adjacent A/B blocks see the same tunnel
state and the PAIRED delta cancels it).

Arms differ by booster params only: land a perf change behind a config
flag, A/B it here, then flip the default.  Usage:

    python tools/ab_bench.py --rows 1000000 --iters 20 --blocks 5 \
        --b tpu_row_chunk=8192

With no --b overrides the two arms run identical code — the self-test
that the harness resolves below 2% (VERDICT round-4 ask #2).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_overrides(items):
    out = {}
    for it in items or []:
        k, v = it.split("=", 1)
        try:
            v = int(v)
        except ValueError:
            try:
                v = float(v)
            except ValueError:
                pass
        out[k] = v
    return out


def _write_obs(guard, args, tool, config, timings, health=None,
               metrics=None, rows=None, fingerprint_extra=None):
    """Drop the machine-readable BENCH_obs.json artifact (schema v3:
    hardware fingerprint + aborted flag) AND its BENCH_history.jsonl
    trajectory entry through the mode's abort guard (a lane that dies
    BEFORE writing still emits one with aborted=true): config +
    timings + the telemetry session's compile counts + memory peaks,
    so perf rounds have diffable, regression-gated artifacts, not just
    PERF.md prose.  ``metrics`` names the scalars the trajectory
    tracks per fingerprint; ``rows`` and ``fingerprint_extra`` let a
    lane fingerprint what it actually measured (the frontier/drift
    lanes do not train at the top-level --rows, and two different
    override experiments must never share a series)."""
    path = guard.write(timings, tool=tool, config=config, health=health,
                       metrics=metrics,
                       rows=rows if rows is not None
                       else getattr(args, "rows", None),
                       features=getattr(args, "features", None),
                       fingerprint_extra=fingerprint_extra)
    print(f"wrote {path}", file=sys.stderr)


def _fault_smoke(args, guard):
    """Robustness-cost smoke (`--fault`): the checkpoint guard rails
    must stay under `--max-overhead-pct` of training wall-clock at the
    bench config, and kill+resume must land.  Two interleaved full
    trainings per arm (no-checkpoint vs checkpointing) cancel the slow
    tunnel drift like the A/B harness does; the report adds the resume
    wall-clock for a kill at 3/4 of the run."""
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.robustness import faultinject

    rng = np.random.RandomState(7)
    X = rng.normal(size=(args.rows, args.features)).astype(np.float32)
    w = rng.normal(size=args.features)
    y = ((X.dot(w) * 0.5 + rng.normal(size=args.rows)) > 0).astype(np.float32)
    rounds = args.iters * args.blocks
    interval = args.ckpt_interval
    base = {"objective": "binary", "num_leaves": args.leaves,
            "learning_rate": 0.1, "max_bin": 255, "verbosity": -1,
            "metric": ""}
    ds = lgb.Dataset(X, label=y)
    ds.construct(base)
    work = tempfile.mkdtemp(prefix="ab-fault-")

    def run(extra=None, nbr=rounds, resume=False):
        t0 = time.time()
        bst = lgb.train({**base, **(extra or {})}, ds, num_boost_round=nbr,
                        resume=resume)
        return time.time() - t0, bst

    try:
        run(nbr=max(interval, 2))                 # compile warmup
        base_times, ckpt_times = [], []
        for rep in range(args.fault_reps):
            base_times.append(run()[0])
            ckpt_dir = os.path.join(work, f"ck{rep}")
            ckpt_times.append(run({"checkpoint_dir": ckpt_dir,
                                   "checkpoint_interval": interval})[0])
        t_base = float(np.median(base_times))
        t_ckpt = float(np.median(ckpt_times))
        overhead_pct = 100.0 * (t_ckpt - t_base) / t_base

        resume_dir = os.path.join(work, "resume")
        ck = {"checkpoint_dir": resume_dir, "checkpoint_interval": interval}
        kill_at = max((3 * rounds // 4) // interval * interval + 1, 1)
        try:
            with faultinject.injected(kill_at_iteration=kill_at):
                run(ck)
            raise SystemExit("--fault: kill injection did not fire")
        except faultinject.TrainingKilled:
            pass
        resume_s, bst = run(ck, resume=True)
        resumed_iters = rounds - (kill_at // interval) * interval
        report = {
            "fault_mode": True, "rows": args.rows, "rounds": rounds,
            "obs_artifact": args.obs_out,
            "checkpoint_interval": interval,
            "base_s": [round(t, 3) for t in base_times],
            "ckpt_s": [round(t, 3) for t in ckpt_times],
            "checkpoint_overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": args.max_overhead_pct,
            "overhead_ok": overhead_pct < args.max_overhead_pct,
            "resume_wallclock_s": round(resume_s, 3),
            "resumed_iterations": resumed_iters,
            "resumed_trees": int(bst.num_trees()),
        }
        print(json.dumps(report))
        _write_obs(guard, args, "ab_bench.fault",
                   {"rows": args.rows, "rounds": rounds,
                    "checkpoint_interval": interval},
                   report,
                   metrics={"base_train_s": t_base,
                            "ckpt_train_s": t_ckpt,
                            "resume_wallclock_s": resume_s},
                   fingerprint_extra={"rounds": rounds,
                                      "ckpt_interval": interval})
        if not report["overhead_ok"]:
            raise SystemExit(
                f"--fault: checkpoint overhead {overhead_pct:.2f}% exceeds "
                f"the {args.max_overhead_pct}% budget")
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _drift_smoke(args, guard):
    """Continual-runtime smoke (`--drift`): inject a covariate shift,
    assert the rollback watchdog fires within `--rollback-within` ticks
    of a forced post-swap regression AND that the restored model serves
    bit-identically to the last-good pack; plus the full swap drill
    (detection within the window, kill-mid-retrain resumed from
    checkpoint, at most one compile per (kind, bucket) per swap); plus
    the ISSUE-9 health lane — the single-feature covariate-shift drill
    whose skew attribution must rank the planted feature #1, recorded
    in the BENCH_obs.json ``health`` section and asserted here."""
    import shutil
    import tempfile

    from lightgbm_tpu.continual import run_drift_drill
    from lightgbm_tpu.obs import benchio

    work = tempfile.mkdtemp(prefix="ab-drift-")
    try:
        swap = run_drift_drill("swap", rows=args.drift_rows, drift_at=4,
                               post_ticks=5, checkpoint_dir=work)
        roll = run_drift_drill("rollback", rows=args.drift_rows,
                               drift_at=3, post_ticks=5)
        attr = run_drift_drill("attribution", rows=args.drift_rows,
                               drift_at=4, post_ticks=6)
        rollback_delay = (None if roll.get("rollback_tick") is None else
                          roll["rollback_tick"] - roll["swap_tick"])
        health = {
            "planted_feature": attr.get("planted_feature"),
            "planted_rank": attr.get("planted_rank"),
            "skew_top": attr.get("skew_top"),
            "attribution_detect_tick": attr.get("detect_tick"),
        }
        report = {
            "drift_mode": True, "rows_per_tick": args.drift_rows,
            "detect_tick": swap.get("detect_tick"),
            "drift_at": swap.get("drift_at"),
            "detected_within_window": swap.get("detected_within_window"),
            "retrain_attempts": swap.get("retrain_attempts"),
            "swap_new_traces": swap.get("swap_new_traces"),
            "one_trace_per_key": swap.get("one_trace_per_key"),
            "swap_latency_s": round(
                float(swap.get("swap_latency_s") or 0.0), 4),
            "metric_recovered": swap.get("metric_recovered"),
            "rollback_delay_ticks": rollback_delay,
            "rollback_within": args.rollback_within,
            "rollback_ok": (rollback_delay is not None
                            and rollback_delay <= args.rollback_within),
            "post_rollback_parity": roll.get("pre_post_identical"),
            "health": health,
        }
        print(json.dumps(report))
        _write_obs(guard, args, "ab_bench.drift",
                   {"rows_per_tick": args.drift_rows,
                    "rollback_within": args.rollback_within},
                   report, health=health,
                   metrics={"swap_latency_s": report["swap_latency_s"]},
                   rows=args.drift_rows)
        problems = []
        if not report["detected_within_window"]:
            problems.append("regression not detected within the window")
        if swap.get("swap_tick") is None:
            problems.append("no hot-swap happened")
        if not report["one_trace_per_key"]:
            problems.append("swap cost more than one compile per "
                            "(kind, bucket)")
        if not report["rollback_ok"]:
            problems.append(
                f"rollback fired after {rollback_delay} tick(s), budget "
                f"{args.rollback_within}")
        if not report["post_rollback_parity"]:
            problems.append("post-rollback serving is not bit-identical "
                            "to the last-good pack")
        if health["planted_rank"] != 1:
            problems.append(
                "skew attribution ranked the planted feature "
                f"#{health['planted_rank']} (feature "
                f"{health['planted_feature']}), not #1")
        # the artifact this lane just wrote must satisfy the schema
        obs_path = args.obs_out or benchio.default_path()
        try:
            with open(obs_path) as fh:
                doc = json.load(fh)
            problems += [f"BENCH_obs: {p}"
                         for p in benchio.validate_bench_obs(doc)]
        except (OSError, ValueError) as exc:
            problems.append(f"BENCH_obs unreadable: {exc}")
        if problems:
            raise SystemExit("--drift: " + "; ".join(problems))
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _frontier_smoke(args, guard):
    """Frontier-batching A/B (`--frontier`): K=1 oracle vs
    tpu_frontier_k=K at several row counts, asserting TREE BIT-IDENTITY
    between the arms after every timed iteration, and reporting per-arm
    per-iteration AFFINE FITS t(rows) = fixed + slope*rows — the
    frontier win is the FIXED (row-independent, per-split bookkeeping)
    term, so the headline number is the fixed-cost reduction.  Exits
    non-zero on any tree mismatch or when the reduction undercuts
    `--frontier-min-pct`."""
    import jax.numpy as jnp
    import lightgbm_tpu as lgb

    rows_list = [int(r) for r in args.frontier_rows.split(",") if r]
    if len(rows_list) < 2:
        raise SystemExit("--frontier needs >= 2 row counts for the "
                         "affine fit (--frontier-rows r1,r2[,...])")
    K = args.frontier_k
    base = {"objective": "binary", "num_leaves": args.frontier_leaves,
            "learning_rate": 0.1, "max_bin": 255, "verbosity": -1,
            "metric": ""}
    arms = {"A": {**base, "tpu_frontier_k": 1},
            "B": {**base, "tpu_frontier_k": K}}

    def trees(bst):
        return [ln for ln in bst.model_to_string().splitlines()
                if not ln.startswith("[")]

    def sync(bst):
        return float(jnp.sum(bst._gbdt.scores))

    per_rows = {}
    mismatch = []
    rng = np.random.RandomState(7)
    for rows in rows_list:
        X = rng.normal(size=(rows, args.features)).astype(np.float32)
        w = rng.normal(size=args.features)
        y = ((X.dot(w) * 0.5 + rng.normal(size=rows)) > 0
             ).astype(np.float32)
        ds = lgb.Dataset(X, label=y)
        ds.construct(arms["A"])
        boosters = {n: lgb.Booster(params=p, train_set=ds)
                    for n, p in arms.items()}
        for n in boosters:          # compile + settle
            boosters[n].update()
            sync(boosters[n])
        times = {"A": [], "B": []}
        for _ in range(args.frontier_blocks):
            for n in ("A", "B"):
                bst = boosters[n]
                t0 = time.time()
                for _ in range(args.frontier_iters):
                    bst.update()
                sync(bst)
                times[n].append((time.time() - t0) / args.frontier_iters)
        if trees(boosters["A"]) != trees(boosters["B"]):
            mismatch.append(rows)
        kb = boosters["B"]._gbdt.learner.frontier_k
        per_rows[rows] = {
            "A_s_per_iter": round(float(np.median(times["A"])), 5),
            "B_s_per_iter": round(float(np.median(times["B"])), 5),
            "A_mad": round(float(np.median(np.abs(
                np.asarray(times["A"]) - np.median(times["A"])))), 5),
            "B_mad": round(float(np.median(np.abs(
                np.asarray(times["B"]) - np.median(times["B"])))), 5),
            "trees_identical": rows not in mismatch,
            "effective_k": int(kb),
        }

    rr = np.asarray(rows_list, np.float64)
    ta = np.asarray([per_rows[r]["A_s_per_iter"] for r in rows_list])
    tb = np.asarray([per_rows[r]["B_s_per_iter"] for r in rows_list])
    slope_a, fixed_a = np.polyfit(rr, ta, 1)
    slope_b, fixed_b = np.polyfit(rr, tb, 1)
    red = 100.0 * (1.0 - fixed_b / fixed_a) if fixed_a > 0 else 0.0
    report = {
        "frontier_mode": True, "k": K, "leaves": args.frontier_leaves,
        "features": args.features, "iters": args.frontier_iters,
        "blocks": args.frontier_blocks,
        "per_rows": per_rows,
        "fit_A": {"fixed_s_per_iter": round(float(fixed_a), 5),
                  "slope_s_per_mrow": round(float(slope_a * 1e6), 4)},
        "fit_B": {"fixed_s_per_iter": round(float(fixed_b), 5),
                  "slope_s_per_mrow": round(float(slope_b * 1e6), 4)},
        "fixed_reduction_pct": round(float(red), 2),
        "min_reduction_pct": args.frontier_min_pct,
        "trees_identical": not mismatch,
    }
    report["kernels_B"] = {
        "_use_mega": getattr(
            boosters["B"]._gbdt.learner, "_use_mega", None),
        "frontier_k": int(boosters["B"]._gbdt.learner.frontier_k),
    }
    print(json.dumps(report))
    _write_obs(guard, args, "ab_bench.frontier",
               {"rows": rows_list, "k": K,
                "leaves": args.frontier_leaves,
                "iters": args.frontier_iters,
                "blocks": args.frontier_blocks}, report,
               metrics={"fixed_A_s": float(fixed_a),
                        "fixed_B_s": float(fixed_b),
                        "slope_A_s_per_mrow": float(slope_a * 1e6),
                        "slope_B_s_per_mrow": float(slope_b * 1e6)},
               rows=max(rows_list),
               fingerprint_extra={"frontier_rows": rows_list,
                                  "frontier_k": K,
                                  "num_leaves": args.frontier_leaves})
    problems = []
    if mismatch:
        problems.append(f"frontier trees NOT bit-identical to the K=1 "
                        f"oracle at rows={mismatch}")
    if args.frontier_min_pct is not None and red < args.frontier_min_pct:
        problems.append(
            f"fixed-cost reduction {red:.2f}% undercuts the "
            f"{args.frontier_min_pct}% bar")
    if problems:
        raise SystemExit("--frontier: " + "; ".join(problems))


def _chunk_smoke(args, guard):
    """Chunk-policy A/B (`--chunk`): tpu_chunk_policy=fixed vs adaptive
    at several (rows, num_leaves) regimes, asserting TREE BIT-IDENTITY
    between the arms after every timed block.  Reports per-regime
    speedups plus per-arm affine fits t(rows) = fixed + slope*rows over
    the small-leaf-heavy row counts (`--chunk-rows` at
    `--chunk-leaves`), and a separate large-uniform-leaf regime
    (`--chunk-uniform`) that must stay inside the perfwatch noise floor
    (adaptive bands are a no-op there — every leaf covers base chunks).
    Each regime also appends a `chunk_sweep` trajectory entry (winning
    base width + measured adaptive speedup under the knob-free
    host/shape fingerprint) that `tpu_row_chunk=auto` /
    `tpu_chunk_policy=auto` consult (ops/chunkpolicy.py).  Exits
    non-zero on any tree mismatch, when the small-leaf speedup
    undercuts `--chunk-min-x`, or when the uniform regime regresses
    past the noise floor."""
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import regress
    from lightgbm_tpu.ops import chunkpolicy

    rows_list = [int(r) for r in args.chunk_rows.split(",") if r]
    if len(rows_list) < 2:
        raise SystemExit("--chunk needs >= 2 row counts for the affine "
                         "fit (--chunk-rows r1,r2[,...])")
    u_rows, u_leaves = (int(v) for v in args.chunk_uniform.split(":"))
    regimes = ([(r, args.chunk_leaves) for r in rows_list]
               + [(u_rows, u_leaves)])
    base = {"objective": "binary", "learning_rate": 0.1, "max_bin": 255,
            "verbosity": -1, "metric": ""}

    def trees(bst):
        return [ln for ln in bst.model_to_string().splitlines()
                if not ln.startswith("[")]

    def sync(bst):
        return float(jnp.sum(bst._gbdt.scores))

    per = {}
    mismatch = []
    rng = np.random.RandomState(7)
    for rows, leaves in regimes:
        X = rng.normal(size=(rows, args.features)).astype(np.float32)
        w = rng.normal(size=args.features)
        y = ((X.dot(w) * 0.5 + rng.normal(size=rows)) > 0
             ).astype(np.float32)
        p = {**base, "num_leaves": leaves}
        ds = lgb.Dataset(X, label=y)
        ds.construct(p)
        boosters = {n: lgb.Booster(params={**p, "tpu_chunk_policy": n},
                                   train_set=ds)
                    for n in ("fixed", "adaptive")}
        for n in boosters:          # compile warmup
            boosters[n].update()
            sync(boosters[n])
        for _ in range(2):          # settle (the _ab_body discipline)
            for n in boosters:
                boosters[n].update()
        for n in boosters:
            sync(boosters[n])
        times = {"fixed": [], "adaptive": []}
        for _ in range(args.chunk_blocks):
            for n in ("fixed", "adaptive"):
                bst = boosters[n]
                t0 = time.time()
                for _ in range(args.chunk_iters):
                    bst.update()
                sync(bst)
                times[n].append((time.time() - t0) / args.chunk_iters)
        key = f"{rows}x{leaves}"
        if trees(boosters["fixed"]) != trees(boosters["adaptive"]):
            mismatch.append(key)
        tf = float(np.median(times["fixed"]))
        ta = float(np.median(times["adaptive"]))
        pol = boosters["adaptive"]._gbdt.learner._chunk_policy
        per[key] = {
            "rows": rows, "leaves": leaves,
            "fixed_s_per_iter": round(tf, 5),
            "adaptive_s_per_iter": round(ta, 5),
            "speedup": round(tf / ta, 3) if ta > 0 else None,
            "menu": list(pol.sizes), "hist_menu": list(pol.hist_sizes),
            "adaptive_engaged": bool(pol.adaptive),
            "trees_identical": key not in mismatch,
        }
        # the measured verdict tpu_row_chunk=auto / tpu_chunk_policy=
        # auto consult: keyed by the knob-free host/shape fingerprint.
        # A regime that failed bit-identity must NOT feed the auto
        # modes a speedup verdict for a broken path — its entry is
        # recorded aborted (evidence kept, detector and consult skip).
        regress.append_entry(
            chunkpolicy.SWEEP_TOOL,
            {"best_row_chunk": int(pol.base),
             "adaptive_speedup": tf / ta if ta > 0 else 0.0},
            config={"rows": rows, "features": args.features,
                    "leaves": leaves},
            fingerprint_doc=chunkpolicy.sweep_fingerprint(
                rows, args.features),
            aborted=key in mismatch)

    rr = np.asarray(rows_list, np.float64)
    tf = np.asarray([per[f"{r}x{args.chunk_leaves}"]["fixed_s_per_iter"]
                     for r in rows_list])
    ta = np.asarray([per[f"{r}x{args.chunk_leaves}"]["adaptive_s_per_iter"]
                     for r in rows_list])
    slope_f, fixed_f = np.polyfit(rr, tf, 1)
    slope_a, fixed_a = np.polyfit(rr, ta, 1)
    small_speedups = [per[f"{r}x{args.chunk_leaves}"]["speedup"]
                      for r in rows_list]
    best_speedup = float(max(small_speedups))
    ukey = f"{u_rows}x{u_leaves}"
    u_ratio = (per[ukey]["adaptive_s_per_iter"]
               / per[ukey]["fixed_s_per_iter"])
    noise_floor = 1.0 + regress.FLOOR_PCT / 100.0
    report = {
        "chunk_mode": True, "features": args.features,
        "iters": args.chunk_iters, "blocks": args.chunk_blocks,
        "per_regime": per,
        "fit_fixed": {"fixed_s_per_iter": round(float(fixed_f), 5),
                      "slope_s_per_mrow": round(float(slope_f * 1e6), 4)},
        "fit_adaptive": {"fixed_s_per_iter": round(float(fixed_a), 5),
                         "slope_s_per_mrow": round(float(slope_a * 1e6),
                                                   4)},
        "small_leaf_speedups": small_speedups,
        "small_leaf_speedup_best": round(best_speedup, 3),
        "chunk_min_x": args.chunk_min_x,
        "uniform_ratio": round(float(u_ratio), 4),
        "uniform_noise_floor": round(noise_floor, 4),
        "trees_identical": not mismatch,
    }
    print(json.dumps(report))
    _write_obs(guard, args, "ab_bench.chunk",
               {"rows": rows_list, "leaves": args.chunk_leaves,
                "uniform": args.chunk_uniform,
                "iters": args.chunk_iters, "blocks": args.chunk_blocks},
               report,
               metrics={"fixed_arm_fixed_s": float(fixed_f),
                        "adaptive_arm_fixed_s": float(fixed_a),
                        "fixed_arm_slope_s_per_mrow": float(slope_f * 1e6),
                        "adaptive_arm_slope_s_per_mrow": float(
                            slope_a * 1e6),
                        "small_leaf_speedup": best_speedup,
                        "uniform_ratio": float(u_ratio)},
               rows=max(rows_list),
               fingerprint_extra={"chunk_rows": rows_list,
                                  "chunk_leaves": args.chunk_leaves,
                                  "uniform": args.chunk_uniform})
    problems = []
    if mismatch:
        problems.append(f"adaptive trees NOT bit-identical to the fixed "
                        f"grid at {mismatch}")
    if args.chunk_min_x is not None and best_speedup < args.chunk_min_x:
        problems.append(
            f"best small-leaf speedup {best_speedup:.2f}x undercuts "
            f"the {args.chunk_min_x}x bar")
    if u_ratio > noise_floor:
        problems.append(
            f"large-uniform-leaf regime regressed {100 * (u_ratio - 1):.1f}%"
            f" — past the {regress.FLOOR_PCT}% perfwatch noise floor")
    if problems:
        raise SystemExit("--chunk: " + "; ".join(problems))


def _linear_smoke(args, guard):
    """Piece-wise-linear trees A/B (`--linear`): constant leaves vs
    linear_tree refit vs linear_tree_mode=leafwise_gain (the in-search
    PL split gain) on a smooth synthetic, reporting per-arm wall clock
    and TREES-TO-TARGET-RMSE — the headline is how many fewer trees the
    linear arms need to reach the constant arm's final validation RMSE.
    Exits non-zero when the leafwise arm saves fewer than
    ``--linear-min-tree-save`` %% of the trees, or when it REGRESSES
    the constant arm's final accuracy (the PL gain must never lose to
    the model it generalizes)."""
    import time

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import benchio

    rng = np.random.RandomState(11)
    n, f = args.linear_rows, args.linear_features
    X = rng.normal(size=(n, f)).astype(np.float32)
    # smooth target: one dominant linear direction + a nonlinearity in
    # a second feature — the regime linear_tree docs target and where
    # single-feature leaf models shine (with leafwise_gain the search
    # spends its splits on the sine because the leaf self-models
    # already carry the x0 ramp; constant trees must staircase it)
    y = (3.0 * X[:, 0] + np.sin(2.0 * X[:, 1])
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    cut = int(n * 0.75)
    Xtr, Xva, ytr, yva = X[:cut], X[cut:], y[:cut], y[cut:]
    arms = {
        "constant": {},
        "refit": {"linear_tree": True, "linear_tree_mode": "refit"},
        "leafwise_gain": {"linear_tree": True,
                          "linear_tree_mode": "leafwise_gain"},
    }
    out = {}
    for name, extra in arms.items():
        p = {"objective": "regression", "metric": "rmse",
             "num_leaves": args.linear_leaves, "learning_rate": 0.1,
             "verbosity": -1, **extra}
        ds = lgb.Dataset(Xtr, label=ytr)
        vds = lgb.Dataset(Xva, label=yva, reference=ds)
        hist = {}
        t0 = time.perf_counter()
        lgb.train(p, ds, num_boost_round=args.linear_iters,
                  valid_sets=[vds], valid_names=["va"],
                  callbacks=[lgb.record_evaluation(hist)])
        wall = time.perf_counter() - t0
        curve = [float(v) for v in hist["va"]["rmse"]]
        out[name] = {"wall_s": round(wall, 3),
                     "final_rmse": round(curve[-1], 6),
                     "curve": [round(v, 6) for v in curve]}

    target = out["constant"]["final_rmse"]

    def trees_to(curve):
        for i, v in enumerate(curve):
            if v <= target:
                return i + 1
        return None

    report = {"linear_mode": True, "rows": n, "features": f,
              "leaves": args.linear_leaves, "iters": args.linear_iters,
              "target_rmse": target}
    for name in arms:
        t = trees_to(out[name]["curve"])
        out[name]["trees_to_target"] = t
        report[name] = {k: out[name][k] for k in
                        ("wall_s", "final_rmse", "trees_to_target")}
    lw = out["leafwise_gain"]["trees_to_target"]
    save_pct = (None if lw is None else
                round(100.0 * (1.0 - lw / args.linear_iters), 1))
    report["leafwise_tree_save_pct"] = save_pct
    print(json.dumps(report))
    _write_obs(guard, args, "ab_bench.linear",
               {"rows": n, "features": f, "leaves": args.linear_leaves,
                "iters": args.linear_iters},
               report,
               metrics={
                   "constant_wall_s": out["constant"]["wall_s"],
                   "refit_wall_s": out["refit"]["wall_s"],
                   "leafwise_wall_s": out["leafwise_gain"]["wall_s"],
                   "leafwise_final_rmse":
                       out["leafwise_gain"]["final_rmse"],
                   "leafwise_trees_to_target": float(lw or -1),
               },
               rows=n,
               fingerprint_extra={"lane": "linear",
                                  "linear_leaves": args.linear_leaves,
                                  "linear_iters": args.linear_iters})
    problems = []
    if lw is None:
        problems.append("leafwise_gain never reached the constant "
                        "arm's final RMSE")
    elif save_pct < args.linear_min_tree_save:
        problems.append(
            f"leafwise_gain needed {lw}/{args.linear_iters} trees "
            f"({save_pct}% saved) — under the "
            f"{args.linear_min_tree_save}% tree-save bar")
    if (out["leafwise_gain"]["final_rmse"]
            > out["constant"]["final_rmse"] * 1.001):
        problems.append(
            "accuracy regression: leafwise_gain final RMSE "
            f"{out['leafwise_gain']['final_rmse']} vs constant "
            f"{out['constant']['final_rmse']}")
    obs_path = args.obs_out or benchio.default_path()
    try:
        with open(obs_path) as fh:
            doc = json.load(fh)
        problems += [f"BENCH_obs: {p}"
                     for p in benchio.validate_bench_obs(doc)]
    except (OSError, ValueError) as exc:
        problems.append(f"BENCH_obs unreadable: {exc}")
    if problems:
        raise SystemExit("--linear: " + "; ".join(problems))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--iters", type=int, default=20,
                    help="boosting iterations per timed block")
    ap.add_argument("--blocks", type=int, default=5,
                    help="timed blocks PER ARM (interleaved)")
    ap.add_argument("--settle", type=int, default=5)
    ap.add_argument("--a", action="append", metavar="K=V",
                    help="param override for arm A (repeatable)")
    ap.add_argument("--b", action="append", metavar="K=V",
                    help="param override for arm B (repeatable)")
    ap.add_argument("--fault", action="store_true",
                    help="robustness smoke: checkpoint overhead %%, "
                    "kill+resume wall-clock (asserts the overhead budget)")
    ap.add_argument("--ckpt-interval", type=int, default=10,
                    help="--fault: checkpoint every N iterations")
    ap.add_argument("--fault-reps", type=int, default=3,
                    help="--fault: interleaved trainings per arm")
    ap.add_argument("--max-overhead-pct", type=float, default=3.0,
                    help="--fault: checkpoint overhead budget to assert")
    ap.add_argument("--drift", action="store_true",
                    help="continual-runtime smoke: drift detection, "
                    "swap compile counts, rollback-within-N + last-good "
                    "serving parity (asserts all of them)")
    ap.add_argument("--drift-rows", type=int, default=256,
                    help="--drift: rows per tick")
    ap.add_argument("--rollback-within", type=int, default=3,
                    help="--drift: ticks within which rollback must "
                    "fire after an injected post-swap regression")
    ap.add_argument("--frontier", action="store_true",
                    help="frontier-batching A/B: K=1 oracle vs "
                    "tpu_frontier_k=K across --frontier-rows, asserting "
                    "tree bit-identity and the fixed-cost reduction of "
                    "the per-iter affine fits")
    ap.add_argument("--frontier-rows", default="16384,65536",
                    metavar="R1,R2[,..]",
                    help="--frontier: row counts for the affine fit")
    ap.add_argument("--frontier-k", type=int, default=4,
                    help="--frontier: batch width of arm B")
    ap.add_argument("--frontier-leaves", type=int, default=63,
                    help="--frontier: num_leaves (own default: the "
                    "bench-wide 255 is CPU-hostile)")
    ap.add_argument("--frontier-iters", type=int, default=8,
                    help="--frontier: iterations per timed block")
    ap.add_argument("--frontier-blocks", type=int, default=3,
                    help="--frontier: timed blocks per arm (interleaved)")
    ap.add_argument("--frontier-min-pct", type=float, default=None,
                    help="--frontier: minimum fixed-cost reduction %% to "
                    "assert (exit non-zero below it; default: report "
                    "only — on CPU hosts the fixed cost is padded-chunk "
                    "compute, not the bookkeeping the batching "
                    "amortizes, see PERF.md round 12)")
    ap.add_argument("--chunk", action="store_true",
                    help="chunk-policy A/B: tpu_chunk_policy=fixed vs "
                    "adaptive across --chunk-rows at --chunk-leaves "
                    "plus the --chunk-uniform regime, asserting tree "
                    "bit-identity, the speedup bar and the uniform "
                    "noise gate; appends chunk_sweep trajectory "
                    "entries the auto modes consult")
    ap.add_argument("--chunk-rows", default="8192,16384,65536",
                    metavar="R1,R2[,..]",
                    help="--chunk: small-leaf-heavy row counts for the "
                    "affine fit")
    ap.add_argument("--chunk-leaves", type=int, default=255,
                    help="--chunk: num_leaves of the small-leaf-heavy "
                    "regimes")
    ap.add_argument("--chunk-uniform", default="262144:31",
                    metavar="ROWS:LEAVES",
                    help="--chunk: large-uniform-leaf regime that must "
                    "stay inside the perfwatch noise floor")
    ap.add_argument("--chunk-iters", type=int, default=4,
                    help="--chunk: iterations per timed block")
    ap.add_argument("--chunk-blocks", type=int, default=3,
                    help="--chunk: timed blocks per arm (interleaved)")
    ap.add_argument("--chunk-min-x", type=float, default=None,
                    help="--chunk: minimum small-leaf speedup to assert "
                    "(exit non-zero below it; default: report only)")
    ap.add_argument("--linear", action="store_true",
                    help="piece-wise-linear tree A/B: constant leaves "
                    "vs linear_tree refit vs "
                    "linear_tree_mode=leafwise_gain on a smooth "
                    "synthetic; reports per-arm wall clock and "
                    "trees-to-target-RMSE, exiting non-zero when the "
                    "leafwise arm saves fewer than "
                    "--linear-min-tree-save %% of the trees or "
                    "regresses the constant arm's accuracy")
    ap.add_argument("--linear-rows", type=int, default=24_000,
                    help="--linear: dataset rows")
    ap.add_argument("--linear-features", type=int, default=8,
                    help="--linear: dataset features")
    ap.add_argument("--linear-leaves", type=int, default=31,
                    help="--linear: num_leaves for all arms")
    ap.add_argument("--linear-iters", type=int, default=120,
                    help="--linear: boosting rounds per arm (also the "
                    "trees-to-target denominator)")
    ap.add_argument("--linear-min-tree-save", type=float, default=25.0,
                    help="--linear: minimum %% of trees the leafwise "
                    "arm must save vs the full budget to reach the "
                    "constant arm's final RMSE")
    ap.add_argument("--obs-out", default=None, metavar="PATH",
                    help="BENCH_obs.json artifact path (default: "
                    "$BENCH_OBS_PATH or ./BENCH_obs.json)")
    args = ap.parse_args(argv)

    # telemetry at counters: the artifact records the run's compile
    # events and memory peaks alongside the timings (zero-HLO, and the
    # per-iteration span cost is noise vs the timed blocks)
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import benchio
    obs.get().enable("counters")

    mode = ("ab_bench.fault" if args.fault else
            "ab_bench.drift" if args.drift else
            "ab_bench.frontier" if args.frontier else
            "ab_bench.chunk" if args.chunk else
            "ab_bench.linear" if args.linear else "ab_bench")
    # export-on-failure: a lane that dies mid-measurement still leaves
    # an aborted BENCH_obs artifact + trajectory entry; lanes that
    # wrote their artifact and THEN failed an assertion keep the real
    # (non-aborted) artifact — the measurement finished, the gate
    # didn't
    with benchio.abort_guard(mode, {"rows": args.rows,
                                    "features": args.features,
                                    "leaves": args.leaves},
                             path=args.obs_out) as guard:
        if args.fault:
            _fault_smoke(args, guard)
            return
        if args.drift:
            _drift_smoke(args, guard)
            return
        if args.frontier:
            _frontier_smoke(args, guard)
            return
        if args.chunk:
            _chunk_smoke(args, guard)
            return
        if args.linear:
            _linear_smoke(args, guard)
            return
        _ab_body(args, guard)


def _ab_body(args, guard):
    import jax.numpy as jnp
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    X = rng.normal(size=(args.rows, args.features)).astype(np.float32)
    w = rng.normal(size=args.features)
    y = ((X.dot(w) * 0.5 + rng.normal(size=args.rows)) > 0).astype(np.float32)

    base = {"objective": "binary", "num_leaves": args.leaves,
            "learning_rate": 0.1, "max_bin": 255, "verbosity": -1,
            "metric": ""}
    pa = {**base, **_parse_overrides(args.a)}
    pb = {**base, **_parse_overrides(args.b)}

    # the two arms share ONE binned dataset (constructed with arm A's
    # params); overrides that change the binning itself would be
    # silently vacuous, so reject them
    _DATASET_KEYS = {"max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
                     "max_bin_by_feature", "feature_pre_filter",
                     "categorical_feature", "use_missing", "zero_as_missing",
                     "enable_bundle", "min_data_per_group"}
    bad = (_DATASET_KEYS & set(_parse_overrides(args.a))) | \
          (_DATASET_KEYS & set(_parse_overrides(args.b)))
    if bad:
        raise SystemExit(f"dataset-construction params {sorted(bad)} cannot "
                         "be A/B'd here: both arms share one binned dataset")

    ds = lgb.Dataset(X, label=y)
    ds.construct(pa)
    boosters = {"A": lgb.Booster(params=pa, train_set=ds),
                "B": lgb.Booster(params=pb, train_set=ds)}

    def sync(bst):
        # host materialization: the only reliable completion barrier on
        # remote-attached TPUs (PERF.md measurement pitfalls)
        return float(jnp.sum(bst._gbdt.scores))

    # warm both compiles, then settle both arms
    for name in ("A", "B"):
        boosters[name].update()
        sync(boosters[name])
    for _ in range(args.settle):
        for name in ("A", "B"):
            boosters[name].update()
    for name in ("A", "B"):
        sync(boosters[name])

    times = {"A": [], "B": []}
    for _ in range(args.blocks):
        for name in ("A", "B"):
            bst = boosters[name]
            t0 = time.time()
            for _ in range(args.iters):
                bst.update()
            sync(bst)
            times[name].append((time.time() - t0) / args.iters)

    def stats(v):
        v = np.asarray(v)
        med = float(np.median(v))
        mad = float(np.median(np.abs(v - med)))
        return {"median_s_per_iter": round(med, 5),
                "mad_s_per_iter": round(mad, 5),
                "mad_pct": round(100 * mad / med, 2),
                "blocks": [round(x, 5) for x in v]}

    def kernel_flags(bst):
        lr = bst._gbdt.learner
        out = {k: bool(getattr(lr, k, False)) for k in
               ("_use_pallas_part", "_use_pallas_search",
                "_use_flat_hist", "_pack_rowid", "_use_pallas",
                "_compact_radix")}
        # None | "pallas" | "xla" — the arm report must show whether the
        # mega-kernel actually engaged (probe fallbacks are silent)
        out["_use_mega"] = getattr(lr, "_use_mega", None)
        return out

    sa, sb = stats(times["A"]), stats(times["B"])
    paired = np.asarray(times["B"]) - np.asarray(times["A"])
    delta_med = float(np.median(paired))
    report = {
        "rows": args.rows, "iters_per_block": args.iters,
        "blocks_per_arm": args.blocks,
        "a_params": _parse_overrides(args.a), "b_params": _parse_overrides(args.b),
        "a_kernels": kernel_flags(boosters["A"]),
        "b_kernels": kernel_flags(boosters["B"]),
        "A": sa, "B": sb,
        "paired_delta_s_per_iter": round(delta_med, 5),
        "paired_delta_pct_of_A": round(
            100 * delta_med / sa["median_s_per_iter"], 2),
        "paired_delta_mad": round(float(np.median(np.abs(
            paired - delta_med))), 5),
    }
    print(json.dumps(report))
    _write_obs(guard, args, "ab_bench",
               {"rows": args.rows, "features": args.features,
                "leaves": args.leaves, "iters": args.iters,
                "blocks": args.blocks,
                "a_params": report["a_params"],
                "b_params": report["b_params"]},
               report,
               metrics={"A_median_s": sa["median_s_per_iter"],
                        "B_median_s": sb["median_s_per_iter"],
                        "paired_delta_s": delta_med},
               fingerprint_extra={"a": report["a_params"],
                                  "b": report["b_params"]})


if __name__ == "__main__":
    main()
