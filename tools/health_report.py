"""Model & data health report tool.

Three modes:

    python tools/health_report.py model.txt     # saved model: print its
                                                # embedded reference
                                                # profile summary
    python tools/health_report.py --smoke       # tier-1 self-check
    python tools/health_report.py --overhead    # paired off-vs-counters
                                                # digest overhead measure

``--smoke`` trains a small model at ``health=trace``, drives the
serving path, and validates ``Booster.health_report()`` end to end
(flight recorder populated with per-iteration split decisions, the
reference profile present and model-persisted, serving skew digests
accumulating), then runs the single-feature covariate-shift drill and
asserts the skew attribution ranks the planted feature #1 — one JSON
line, non-zero exit on any broken invariant.

``--overhead`` measures what the health layer costs where it is hot:
interleaved full trainings + warm predicts with health off vs counters
(paired per-position deltas cancel slow host drift, the PERF.md
measurement discipline) — the honest number the ≤2% budget is judged
against.
"""

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _train_small(params, rows=4608, features=8, rounds=8, seed=3):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, features))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) - X[:, 3] \
        + 0.1 * rng.normal(size=rows)
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 10, "metric": ""}
    base.update(params)
    bst = lgb.train(base, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, X


# ---------------------------------------------------------------------------
def smoke(rows: int) -> int:
    from lightgbm_tpu import obs
    from lightgbm_tpu.continual import run_drift_drill
    from lightgbm_tpu.obs import health as obs_health

    problems: List[str] = []
    health_prev = obs_health.get().mode
    tel_prev = obs.get().mode
    try:
        bst, X = _train_small({"health": "trace"})
        bst.predict(X, raw_score=True)        # warms + digests serving
        bst.predict(X[:700], raw_score=True)  # a second bucket
        rep = bst.health_report()
        fr = rep.get("flight_recorder") or {}
        if fr.get("trees_recorded", 0) < 8:
            problems.append(f"flight recorder has "
                            f"{fr.get('trees_recorded')} trees, want 8")
        if not fr.get("top_features"):
            problems.append("flight recorder has no per-feature totals")
        if not fr.get("gain_trajectory"):
            problems.append("flight recorder has no gain trajectory")
        prof = rep.get("reference_profile")
        if not prof or prof.get("num_features") != X.shape[1]:
            problems.append(f"reference profile malformed: {prof!r}")
        skew = rep.get("serving_skew")
        if not skew or skew.get("rows_seen", 0) < len(X):
            problems.append(f"serving skew digests missing rows: "
                            f"{skew and skew.get('rows_seen')}")
        if skew and sum(skew.get("margin_hist", [])) <= 0:
            problems.append("prediction-margin histogram is empty")
        # the profile must survive the model file round trip
        import lightgbm_tpu as lgb
        bst2 = lgb.Booster(model_str=bst.model_to_string())
        if bst2._gbdt.health_profile is None:
            problems.append("reference profile lost in the model string")

        # covariate-shift attribution drill: planted feature must rank #1
        drill = run_drift_drill("attribution", rows=rows, drift_at=4,
                                post_ticks=6)
        if not drill.get("planted_ranked_first"):
            problems.append(
                f"attribution ranked the planted feature "
                f"#{drill.get('planted_rank')} "
                f"(top: {(drill.get('skew_top') or [None])[0]})")
        print(json.dumps({
            "metric": "health_report_smoke", "ok": not problems,
            "trees_recorded": fr.get("trees_recorded"),
            "top_features": fr.get("top_features"),
            "serving_rows": skew and skew.get("rows_seen"),
            "planted_feature": drill.get("planted_feature"),
            "planted_rank": drill.get("planted_rank"),
            "skew_top": (drill.get("skew_top") or [])[:3],
            "problems": problems}))
        return 1 if problems else 0
    finally:
        obs_health.get().set_mode(health_prev)
        obs.get().set_mode(tel_prev)


# ---------------------------------------------------------------------------
def overhead(reps: int, rows: int) -> int:
    """Paired health=off vs health=counters cost of (a) a full small
    training and (b) a warm bucketed predict — the two paths the layer
    instruments."""
    from lightgbm_tpu.obs import health as obs_health

    health_prev = obs_health.get().mode
    times: Dict[str, Dict[str, List[float]]] = {
        "train": {"off": [], "counters": []},
        "predict": {"off": [], "counters": []}}
    try:
        # warm compiles once per mode arm
        for mode in ("off", "counters"):
            obs_health.get().set_mode("off")
            _train_small({"health": mode}, rows=rows)
        for _ in range(reps):
            for mode in ("off", "counters"):
                obs_health.get().set_mode("off")
                t0 = time.perf_counter()
                bst, X = _train_small({"health": mode}, rows=rows)
                times["train"][mode].append(time.perf_counter() - t0)
                bst.predict(X, raw_score=True)      # warm the engine
                t0 = time.perf_counter()
                for _ in range(5):
                    bst.predict(X, raw_score=True)
                times["predict"][mode].append(time.perf_counter() - t0)
    finally:
        obs_health.get().set_mode(health_prev)

    report: Dict[str, Any] = {"metric": "health_overhead", "rows": rows,
                              "reps": reps}
    for phase, arms in times.items():
        off = np.asarray(arms["off"])
        on = np.asarray(arms["counters"])
        paired = on - off
        med_off = float(np.median(off))
        report[phase] = {
            "off_s": round(med_off, 4),
            "counters_s": round(float(np.median(on)), 4),
            "paired_delta_s": round(float(np.median(paired)), 4),
            "paired_delta_pct": round(
                100.0 * float(np.median(paired)) / med_off, 2),
            "mad_s": round(float(np.median(
                np.abs(paired - np.median(paired)))), 4),
        }
    print(json.dumps(report))
    return 0


# ---------------------------------------------------------------------------
def model_summary(path: str) -> int:
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_file=path)
    prof = bst._gbdt.health_profile
    if prof is None:
        print(json.dumps({"path": path, "health_profile": None,
                          "hint": "model was saved without health "
                                  "enabled (health=counters|trace)"}))
        return 1
    feats = prof.get("features", [])
    out = {
        "path": path,
        "num_data": prof.get("num_data"),
        "num_features": len(feats),
        "features": [{k: fe.get(k) for k in
                      ("index", "name", "num_bin", "missing_rate",
                       "zero_rate", "cardinality")} for fe in feats],
    }
    print(json.dumps(out))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("model", nargs="?",
                    help="saved model file: print its embedded health "
                         "profile")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 self-check (see module docstring)")
    ap.add_argument("--rows", type=int, default=192,
                    help="--smoke: rows per drill tick")
    ap.add_argument("--overhead", action="store_true",
                    help="paired health=off vs counters cost measurement")
    ap.add_argument("--reps", type=int, default=3,
                    help="--overhead: paired repetitions")
    ap.add_argument("--overhead-rows", type=int, default=20000,
                    help="--overhead: training rows")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke(args.rows)
    if args.overhead:
        return overhead(args.reps, args.overhead_rows)
    if not args.model:
        ap.error("give a model file, --smoke or --overhead")
    return model_summary(args.model)


if __name__ == "__main__":
    sys.exit(main())
