"""Standalone cost of the XLA histogram formulation on a live TPU, with
A/B variants of the one-hot generation.  Times R accumulations of a full
N-row leaf."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 50
G, B, C = 32, 255, 4096


def variant_current(part_bins, ghi, start, cnt):
    from lightgbm_tpu.ops.histogram import leaf_hist_slice
    return leaf_hist_slice(part_bins, ghi, start, cnt,
                           num_bins=B, row_chunk=C)


def variant_fusedgen(part_bins, ghi, start, cnt):
    """Weighted high-digit one-hots generated directly via where (no raw
    oh_hi materialization)."""
    Np = part_bins.shape[1]
    BH = (B + 15) // 16
    gblock = max(1, (4 * 1024 * 1024) // (C * (16 + 2 * BH) * 4))
    nblk = (G + gblock - 1) // gblock
    Gp = nblk * gblock
    n_chunks = (cnt + C - 1) // C
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (1, 1, BH), 2)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 16), 2)

    def body(ci, acc):
        row0 = start + ci * C
        bins = jax.lax.dynamic_slice(part_bins, (0, row0),
                                     (G, C)).astype(jnp.int32)
        gh3 = jax.lax.dynamic_slice(ghi, (0, row0), (ghi.shape[0], C))
        valid = (ci * C + jax.lax.iota(jnp.int32, C)) < cnt
        gv = (gh3[0] * valid)[None, :, None]
        hv = (gh3[1] * valid)[None, :, None]
        if Gp > G:
            bins = jnp.pad(bins, ((0, Gp - G), (0, 0)), constant_values=-1)
        out = []
        for i in range(nblk):
            blk = bins[i * gblock:(i + 1) * gblock, :]
            m_hi = (blk >> 4)[:, :, None] == iota_hi
            oh_lo = ((blk & 15)[:, :, None] == iota_lo).astype(jnp.float32)
            wg = jnp.concatenate([jnp.where(m_hi, gv, 0.0),
                                  jnp.where(m_hi, hv, 0.0)], axis=2)
            out.append(jax.lax.dot_general(
                wg, oh_lo, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32))
        return acc + jnp.stack(out)

    acc = jnp.zeros((nblk, gblock, 2 * BH, 16), jnp.float32)
    acc = jax.lax.fori_loop(0, n_chunks, body, acc)
    per = acc.reshape(Gp, 2 * BH, 16)[:G].reshape(G, 2, BH * 16)
    return jnp.moveaxis(per[:, :, :B], 1, 2)


def run(name, fn):
    Npad = ((N + 2 * C + 127) // 128) * 128 + 2 * C
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(G, Npad)).astype(np.uint8))
    ghi = jnp.asarray(rng.normal(size=(8, Npad)).astype(np.float32))

    @jax.jit
    def many(b, g):
        def one(i, acc):
            return acc + fn(b, g, jnp.int32(C), jnp.int32(N))[0, 0, 0]
        return jax.lax.fori_loop(0, REPS, one, jnp.float32(0.0))

    float(many(bins, ghi))
    t0 = time.time()
    float(many(bins, ghi))
    wall = time.time() - t0 - 0.105
    per_pass_ms = wall / REPS * 1e3
    print(f"{name:12s} per-pass={per_pass_ms:.2f} ms/Mrow-pass")
    return per_pass_ms


if __name__ == "__main__":
    print(f"N={N} reps={REPS} {jax.devices()}")
    from lightgbm_tpu.obs import benchio
    # trajectory wiring: one fingerprinted entry per run (aborted=true
    # if a variant dies, e.g. off-TPU), so on-hardware rounds of this
    # harness are regression-gated like every other producer
    with benchio.abort_guard("profile_hist",
                             {"rows": N, "reps": REPS}) as guard:
        metrics = {f"{name}_per_pass_ms": run(name, fn)
                   for name, fn in (("current", variant_current),
                                    ("fusedgen", variant_fusedgen))}
        guard.write(dict(metrics), metrics=metrics, rows=N)
