"""Render/summarize a lightgbm_tpu telemetry trace.

Reads either artifact the obs exporters write — a Chrome-trace
``trace.json`` (the ``traceEvents`` object Perfetto loads) or a
``telemetry.jsonl`` event log — validates its structure, and prints ONE
JSON summary line: span counts + total/mean durations by name, compile
events, counter tracks, and any validation problems (non-zero exit when
the artifact is malformed).

    python tools/trace_report.py out/trace.json
    python tools/trace_report.py out/telemetry.jsonl
    python tools/trace_report.py merge -o merged.json r0.jsonl r1.jsonl
    python tools/trace_report.py --smoke      # tier-1 self-check

``merge`` combines multiple per-rank/per-process exports (either
format) into ONE Chrome trace with a distinct pid per input file —
multi-process mesh runs write one telemetry file per rank, and
Perfetto shows them as separate process tracks only when their pids
differ (they usually don't: every rank reports its own os.getpid).

``--smoke`` runs the continual drift drills (swap + rollback, with
``health=counters`` so drift-attribution marks ride the trace) at
``telemetry=trace``, exports the Chrome trace, validates it, asserts
the spans an operator needs are all present — ``continual.tick`` /
``continual.retrain`` / ``continual.swap`` / ``continual.rollback`` —
plus at least one runtime compile event and the ``health.drift``
attribution mark, and validates a BENCH_obs.json v3 artifact
round-trip (schema + health section).
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_KNOWN_PH = {"X", "B", "E", "C", "i", "I", "M", "s", "t", "f"}


# ---------------------------------------------------------------------------
# loading + validation
# ---------------------------------------------------------------------------
def load_events(path: str) -> List[Dict[str, Any]]:
    """Events from a Chrome-trace object or a JSONL export."""
    with open(path, encoding="utf-8") as fh:
        head = fh.read(1)
        fh.seek(0)
        if head == "{":
            first = fh.readline()
            rest = fh.read()
            if rest.strip():
                # JSONL whose first line is the report object
                events = []
                for ln in rest.splitlines():
                    if ln.strip():
                        events.append(json.loads(ln))
                json.loads(first)           # header must parse too
                return events
            doc = json.loads(first)
            return list(doc.get("traceEvents", []))
        return [json.loads(ln) for ln in fh if ln.strip()]


def validate(events: List[Dict[str, Any]]) -> List[str]:
    """Structural problems (Chrome-trace requirements the exporter
    guarantees; a regression here breaks Perfetto loading)."""
    problems = []
    if not events:
        problems.append("no events")
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph is None:
            problems.append(f"event {i} missing ph")
            continue
        if ph not in _KNOWN_PH:
            problems.append(f"event {i} unknown ph {ph!r}")
        if ph != "M" and "ts" not in ev:
            problems.append(f"event {i} ({ev.get('name')}) missing ts")
        if ph == "X" and (not isinstance(ev.get("dur"), int)
                          or ev["dur"] < 0):
            problems.append(f"event {i} ({ev.get('name')}) bad dur")
        if ph != "M" and "name" not in ev:
            problems.append(f"event {i} missing name")
        if len(problems) > 20:
            problems.append("... (truncated)")
            break
    return problems


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    spans: Dict[str, Dict[str, Any]] = {}
    compiles: Dict[str, int] = {}
    counters: Dict[str, float] = {}
    marks: Dict[str, int] = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "?")
        if ph == "X":
            s = spans.setdefault(name, {"count": 0, "total_us": 0})
            s["count"] += 1
            s["total_us"] += int(ev.get("dur", 0))
        elif ph in ("i", "I") and name.startswith("compile:"):
            key = name[len("compile:"):]
            compiles[key] = compiles.get(key, 0) + 1
        elif ph in ("i", "I"):
            # non-compile instant marks (e.g. the health layer's
            # flight-recorder / skew / drift-attribution events)
            marks[name] = marks.get(name, 0) + 1
        elif ph == "C":
            args = ev.get("args") or {}
            counters[name] = args.get("value", args)
    for s in spans.values():
        s["mean_us"] = round(s["total_us"] / max(s["count"], 1), 1)
    return {"events": len(events),
            "spans": dict(sorted(spans.items())),
            "compiles": dict(sorted(compiles.items())),
            "counters": dict(sorted(counters.items())),
            "marks": dict(sorted(marks.items()))}


# ---------------------------------------------------------------------------
# merge: per-rank exports -> one Chrome trace with distinct pids
# ---------------------------------------------------------------------------
def merge_traces(inputs: List[str], out_path: str) -> Dict[str, Any]:
    """Combine per-rank/per-process telemetry exports (JSONL or Chrome
    trace) into one Chrome trace.  Every rank reports its own
    ``os.getpid()``, which collide across hosts and hide the per-rank
    structure — each input file gets its OWN pid track (1-based input
    order) plus a ``process_name`` metadata row naming the source
    file, so Perfetto renders one labeled track per rank."""
    merged: List[Dict[str, Any]] = []
    for i, path in enumerate(inputs):
        pid = i + 1
        merged.append({"ph": "M", "name": "process_name", "pid": pid,
                       "ts": 0,
                       "args": {"name": f"rank{i}:"
                                f" {os.path.basename(path)}"}})
        for ev in load_events(path):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue               # replaced by the per-file row
            ev = dict(ev)
            ev["pid"] = pid
            merged.append(ev)
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "tools/trace_report.py merge",
                      "merged_from": [os.path.basename(p)
                                      for p in inputs]},
    }
    tmp = out_path + f".tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    os.replace(tmp, out_path)
    return doc


def merge_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report.py merge",
        description="merge per-rank telemetry exports into one Chrome "
                    "trace with distinct pids")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank trace.json / telemetry.jsonl files")
    ap.add_argument("-o", "--out", required=True,
                    help="merged Chrome trace output path")
    args = ap.parse_args(argv)
    doc = merge_traces(args.inputs, args.out)
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    problems = validate(events)
    pids = sorted({e.get("pid") for e in events})
    out = summarize(events)
    out["problems"] = problems
    out["path"] = args.out
    out["pids"] = pids
    out["inputs"] = len(args.inputs)
    if len(pids) != len(args.inputs):
        out["problems"].append(
            f"expected {len(args.inputs)} distinct pids, got {len(pids)}")
    print(json.dumps(out))
    return 1 if out["problems"] else 0


# ---------------------------------------------------------------------------
# --smoke: drive a drill at telemetry=trace and validate its trace
# ---------------------------------------------------------------------------
_REQUIRED_SPANS = ("continual.tick", "continual.retrain",
                   "continual.swap", "continual.rollback")


def smoke(rows: int) -> int:
    import shutil
    import tempfile

    from lightgbm_tpu import obs
    from lightgbm_tpu.continual import run_drift_drill
    from lightgbm_tpu.obs import benchio
    from lightgbm_tpu.obs import health as obs_health

    sess = obs.get()
    sess.reset(mode="trace")
    health_prev = obs_health.get().mode
    obs_health.get().set_mode("counters")
    work = tempfile.mkdtemp(prefix="trace-report-")
    problems: List[str] = []
    try:
        # swap drill: tick + detection + (killed-once, resumed) retrain
        # + gated swap spans; rollback drill adds the rollback span.
        # health=counters rides along so the regression tick emits its
        # drift-attribution mark onto the trace ring
        swap = run_drift_drill("swap", rows=rows, drift_at=4,
                               post_ticks=5, checkpoint_dir=work,
                               params={"health": "counters"})
        roll = run_drift_drill("rollback", rows=rows, drift_at=3,
                               post_ticks=5,
                               params={"health": "counters"})
        if swap.get("swap_tick") is None:
            problems.append("swap drill produced no hot-swap")
        if roll.get("rollback_tick") is None:
            problems.append("rollback drill never rolled back")
        detect = next((t for t in swap.get("ticks", [])
                       if t.get("drift_detected")), None)
        skew_top = (detect or {}).get("skew_top") or []
        if not skew_top:
            problems.append("swap drill's regression tick carried no "
                            "skew attribution")
        obs.memory_snapshot()
        trace_path = os.path.join(work, "trace.json")
        obs.export_chrome_trace(sess, trace_path)
        events = load_events(trace_path)
        problems += validate(events)
        summary = summarize(events)
        for name in _REQUIRED_SPANS:
            if name not in summary["spans"]:
                problems.append(f"required span missing: {name}")
        if not summary["compiles"]:
            problems.append("no runtime compile events recorded")
        if "health.drift" not in summary["marks"]:
            problems.append("health.drift attribution mark missing "
                            "from the trace")
        # BENCH_obs round trip (schema v3 since ISSUE-11): write an
        # artifact carrying the drill's health section, read it back,
        # validate the schema
        obs_path = os.path.join(work, "BENCH_obs.json")
        benchio.write_bench_obs(
            "trace_report.smoke", {"rows": rows},
            {"swap_tick": swap.get("swap_tick"),
             "rollback_tick": roll.get("rollback_tick")},
            health={"skew_top": skew_top}, path=obs_path,
            # a validation smoke is not a bench round: keep its
            # trajectory entry in the same scratch dir, never in the
            # committed BENCH_history.jsonl
            history_path=os.path.join(work, "BENCH_history.jsonl"))
        try:
            with open(obs_path) as fh:
                doc = json.load(fh)
            problems += [f"BENCH_obs: {p}"
                         for p in benchio.validate_bench_obs(doc)]
        except (OSError, ValueError) as exc:
            problems.append(f"BENCH_obs unreadable: {exc}")
        print(json.dumps({"metric": "trace_report_smoke",
                          "ok": not problems,
                          "trace_events": summary["events"],
                          "spans": {k: v["count"]
                                    for k, v in summary["spans"].items()},
                          "compiles": summary["compiles"],
                          "marks": summary["marks"],
                          "problems": problems}))
        return 1 if problems else 0
    finally:
        sess.reset(mode="off")
        obs_health.get().set_mode(health_prev)
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "merge":
        return merge_main(argv[1:])
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", nargs="?", help="trace.json or telemetry.jsonl")
    ap.add_argument("--smoke", action="store_true",
                    help="run the continual drills at telemetry=trace "
                         "and validate the exported Chrome trace")
    ap.add_argument("--rows", type=int, default=192,
                    help="--smoke: rows per drill tick")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke(args.rows)
    if not args.trace:
        ap.error("give a trace file or --smoke")
    events = load_events(args.trace)
    problems = validate(events)
    out = summarize(events)
    out["problems"] = problems
    out["path"] = args.trace
    print(json.dumps(out))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
