"""Render/summarize a lightgbm_tpu telemetry trace.

Reads either artifact the obs exporters write — a Chrome-trace
``trace.json`` (the ``traceEvents`` object Perfetto loads) or a
``telemetry.jsonl`` event log — validates its structure, and prints ONE
JSON summary line: span counts + total/mean durations by name, compile
events, counter tracks, and any validation problems (non-zero exit when
the artifact is malformed).

    python tools/trace_report.py out/trace.json
    python tools/trace_report.py out/telemetry.jsonl
    python tools/trace_report.py --smoke      # tier-1 self-check

``--smoke`` runs the continual drift drills (swap + rollback) with the
session at ``telemetry=trace``, exports the Chrome trace, validates it,
and asserts the spans an operator needs are all present —
``continual.tick`` / ``continual.retrain`` / ``continual.swap`` /
``continual.rollback`` — plus at least one runtime compile event.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_KNOWN_PH = {"X", "B", "E", "C", "i", "I", "M", "s", "t", "f"}


# ---------------------------------------------------------------------------
# loading + validation
# ---------------------------------------------------------------------------
def load_events(path: str) -> List[Dict[str, Any]]:
    """Events from a Chrome-trace object or a JSONL export."""
    with open(path, encoding="utf-8") as fh:
        head = fh.read(1)
        fh.seek(0)
        if head == "{":
            first = fh.readline()
            rest = fh.read()
            if rest.strip():
                # JSONL whose first line is the report object
                events = []
                for ln in rest.splitlines():
                    if ln.strip():
                        events.append(json.loads(ln))
                json.loads(first)           # header must parse too
                return events
            doc = json.loads(first)
            return list(doc.get("traceEvents", []))
        return [json.loads(ln) for ln in fh if ln.strip()]


def validate(events: List[Dict[str, Any]]) -> List[str]:
    """Structural problems (Chrome-trace requirements the exporter
    guarantees; a regression here breaks Perfetto loading)."""
    problems = []
    if not events:
        problems.append("no events")
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph is None:
            problems.append(f"event {i} missing ph")
            continue
        if ph not in _KNOWN_PH:
            problems.append(f"event {i} unknown ph {ph!r}")
        if ph != "M" and "ts" not in ev:
            problems.append(f"event {i} ({ev.get('name')}) missing ts")
        if ph == "X" and (not isinstance(ev.get("dur"), int)
                          or ev["dur"] < 0):
            problems.append(f"event {i} ({ev.get('name')}) bad dur")
        if ph != "M" and "name" not in ev:
            problems.append(f"event {i} missing name")
        if len(problems) > 20:
            problems.append("... (truncated)")
            break
    return problems


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    spans: Dict[str, Dict[str, Any]] = {}
    compiles: Dict[str, int] = {}
    counters: Dict[str, float] = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "?")
        if ph == "X":
            s = spans.setdefault(name, {"count": 0, "total_us": 0})
            s["count"] += 1
            s["total_us"] += int(ev.get("dur", 0))
        elif ph in ("i", "I") and name.startswith("compile:"):
            key = name[len("compile:"):]
            compiles[key] = compiles.get(key, 0) + 1
        elif ph == "C":
            args = ev.get("args") or {}
            counters[name] = args.get("value", args)
    for s in spans.values():
        s["mean_us"] = round(s["total_us"] / max(s["count"], 1), 1)
    return {"events": len(events),
            "spans": dict(sorted(spans.items())),
            "compiles": dict(sorted(compiles.items())),
            "counters": dict(sorted(counters.items()))}


# ---------------------------------------------------------------------------
# --smoke: drive a drill at telemetry=trace and validate its trace
# ---------------------------------------------------------------------------
_REQUIRED_SPANS = ("continual.tick", "continual.retrain",
                   "continual.swap", "continual.rollback")


def smoke(rows: int) -> int:
    import shutil
    import tempfile

    from lightgbm_tpu import obs
    from lightgbm_tpu.continual import run_drift_drill

    sess = obs.get()
    sess.reset(mode="trace")
    work = tempfile.mkdtemp(prefix="trace-report-")
    problems: List[str] = []
    try:
        # swap drill: tick + detection + (killed-once, resumed) retrain
        # + gated swap spans; rollback drill adds the rollback span
        swap = run_drift_drill("swap", rows=rows, drift_at=4,
                               post_ticks=5, checkpoint_dir=work)
        roll = run_drift_drill("rollback", rows=rows, drift_at=3,
                               post_ticks=5)
        if swap.get("swap_tick") is None:
            problems.append("swap drill produced no hot-swap")
        if roll.get("rollback_tick") is None:
            problems.append("rollback drill never rolled back")
        obs.memory_snapshot()
        trace_path = os.path.join(work, "trace.json")
        obs.export_chrome_trace(sess, trace_path)
        events = load_events(trace_path)
        problems += validate(events)
        summary = summarize(events)
        for name in _REQUIRED_SPANS:
            if name not in summary["spans"]:
                problems.append(f"required span missing: {name}")
        if not summary["compiles"]:
            problems.append("no runtime compile events recorded")
        print(json.dumps({"metric": "trace_report_smoke",
                          "ok": not problems,
                          "trace_events": summary["events"],
                          "spans": {k: v["count"]
                                    for k, v in summary["spans"].items()},
                          "compiles": summary["compiles"],
                          "problems": problems}))
        return 1 if problems else 0
    finally:
        sess.reset(mode="off")
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", nargs="?", help="trace.json or telemetry.jsonl")
    ap.add_argument("--smoke", action="store_true",
                    help="run the continual drills at telemetry=trace "
                         "and validate the exported Chrome trace")
    ap.add_argument("--rows", type=int, default=192,
                    help="--smoke: rows per drill tick")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke(args.rows)
    if not args.trace:
        ap.error("give a trace file or --smoke")
    events = load_events(args.trace)
    problems = validate(events)
    out = summarize(events)
    out["problems"] = problems
    out["path"] = args.trace
    print(json.dumps(out))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
