"""Generate the parameter-coverage table for COVERAGE.md.

Compares the reference's canonical parameter list (extracted from
src/io/config_auto.cpp parameter2aliases — the same generated table the
reference's ~600 documented names collapse into) against this
framework's Config table, and classifies every reference parameter as:

  implemented   — present in the table AND read by engine code
  accepted-noop — present in the table, intentionally inert here, with
                  the reason (device/threading semantics the TPU stack
                  replaces by construction)
  missing       — not recognized at all (would warn "Unknown parameter")

Run:  python tools/param_audit.py /path/to/reference > table.md
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# why each accepted parameter is intentionally inert on this stack
NOOP_REASONS = {
    "num_threads": "XLA owns intra-device parallelism (SURVEY 2.6; no host thread pool)",
    "device_type": "single TPU backend; the Pallas learner IS the device learner",
    "deterministic": "TPU/XLA execution is deterministic by construction",
    "force_col_wise": "one tuned row-wise histogram strategy (TrainingShareStates by-design row)",
    "force_row_wise": "row-wise is the only (and always) layout",
    "histogram_pool_size": "per-leaf HBM hist slots; no LRU pool needed at TPU HBM sizes",
    "is_enable_sparse": "dense u8/u16 device matrix; EFB handles sparsity (SURVEY 2.3)",
    "pre_partition": "distributed loading shards by rank in parallel/distributed.py",
    "two_round": "native parser streams; no two-round memory mode needed",
    "precise_float_parser": "the C++ text parser always parses exactly (strtod)",
    "parser_config_file": "no pluggable parser plugins; CSV/TSV/LibSVM built in",
    "machine_list_filename": "cluster bootstrap belongs to jax.distributed, not a machine file",
    "gpu_platform_id": "no OpenCL platform concept on TPU",
    "gpu_device_id": "device selection via JAX platform config",
    "gpu_use_dp": "histograms are f32 (bf16 pair mode covers the half-precision analog)",
    "num_gpu": "multi-chip via jax.sharding Mesh, not a device count knob",
}


def reference_params(ref_root):
    src = open(os.path.join(ref_root, "src/io/config_auto.cpp")).read()
    m = re.search(r"Config::parameter2aliases\(\)\s*{(.*?)\n}", src, re.S)
    return sorted(set(re.findall(r'\{"([a-z0-9_]+)",', m.group(1))))


def engine_usage():
    """Parameter names referenced anywhere outside the config table."""
    text = ""
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for base, _, files in os.walk(os.path.join(root, "lightgbm_tpu")):
        for f in files:
            if f.endswith((".py", ".cpp")) and f != "config.py":
                text += open(os.path.join(base, f)).read()
    for f in ("bench.py", "tpu_selfcheck.py"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            text += open(p).read()
    return text


def main():
    ref_root = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    from lightgbm_tpu.config import _PARAM_BY_NAME, _ALIAS2NAME
    refp = reference_params(ref_root)
    text = engine_usage()
    rows = []
    counts = {"implemented": 0, "accepted-noop": 0, "missing": 0}
    for name in refp:
        canon = _ALIAS2NAME.get(name)
        if canon is None:
            status, note = "missing", "warns Unknown parameter"
        elif name in NOOP_REASONS:
            status, note = "accepted-noop", NOOP_REASONS[name]
        else:
            used = (re.search(r"\.%s\b" % re.escape(canon), text)
                    or re.search(r"['\"]%s['\"]" % re.escape(canon), text))
            if used:
                status, note = "implemented", ""
            else:
                status, note = "accepted-noop", "accepted; no engine read"
        counts[status] += 1
        rows.append((name, status, note))
    print("| reference param | status | note |")
    print("|---|---|---|")
    for name, status, note in rows:
        print(f"| `{name}` | {status} | {note} |")
    print()
    print(f"**{counts['implemented']} implemented, "
          f"{counts['accepted-noop']} accepted-noop, "
          f"{counts['missing']} missing** of {len(refp)} reference "
          "canonical parameters; unknown keys warn "
          "(`Unknown parameter: <k>`), matching config.h:1242.")


if __name__ == "__main__":
    main()
