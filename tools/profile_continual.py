"""Continual-training runtime harness: refit-tick overhead, swap and
rollback latency, end-to-end drift drills.

Measures the tick loop of ``lightgbm_tpu/continual``:

* **refit tick overhead** — median wall-clock of a full tick (prequential
  eval + in-place leaf refit through the serving engine's leaf-refresh
  fast path) vs a predict-only tick over the same batches;
* **swap latency** — candidate warm-up (pack build + one compile per
  (kind, bucket)) through the atomic install, from the swap drill;
* **rollback latency** — watchdog-triggered restore of the pre-swap
  booster (no pack rebuild: its engine kept its own packs);
* **drift drills** — the three deterministic scenarios of
  ``continual/drift.py`` (swap with kill+resume, retry-exhaustion
  degradation, forced-regression rollback), asserted when ``--smoke``.

Prints ONE JSON line (like bench.py):

  {"metric": "continual", "detail": {...}}

Usage:
  python tools/profile_continual.py [--rows 4096] [--features 10]
      [--ticks 20] [--smoke]

``--smoke`` shrinks everything to seconds for the tier-1 lane and exits
non-zero when a drill invariant breaks (detection within window, one
compile per (kind, bucket) per swap, rollback within the window with
bit-identical pre-swap predictions, graceful degradation).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def tick_overhead(rows, features, ticks, params=None):
    """Median tick wall-clock with refit vs predict-only, same batches."""
    from lightgbm_tpu.continual.drift import _DRILL_PARAMS, DriftStream
    from lightgbm_tpu.continual.runtime import ContinualBooster, tick_metric

    p = dict(_DRILL_PARAMS)
    p.update(params or {})
    stream = DriftStream(num_features=features, rows=rows, seed=3)
    warm = DriftStream(num_features=features, rows=4 * rows, seed=4)
    X0, y0 = warm.batch(0)
    cb = ContinualBooster(p, X0, y0)
    batches = [stream.batch(t) for t in range(ticks)]
    # settle compiles
    cb.tick(*batches[0])
    cb.predict(batches[0][0], raw_score=True)

    pred_t, tick_t = [], []
    for X, y in batches:
        t0 = time.perf_counter()
        raw = cb.predict(X, raw_score=True)
        tick_metric(cb.metric_name, y, np.asarray(raw))
        pred_t.append(time.perf_counter() - t0)
    for X, y in batches:
        t0 = time.perf_counter()
        cb.tick(X, y)
        tick_t.append(time.perf_counter() - t0)
    eng = cb.serving_engine
    snap_before = len(batches)
    return {
        "rows_per_tick": rows,
        "predict_only_ms": round(1e3 * float(np.median(pred_t)), 3),
        "tick_ms": round(1e3 * float(np.median(tick_t)), 3),
        "refit_overhead_ms": round(
            1e3 * (float(np.median(tick_t)) - float(np.median(pred_t))),
            3),
        "trace_counts": {str(k): v for k, v in eng.trace_counts.items()},
        "ticks": snap_before,
    }


def run(rows, features, ticks, smoke):
    import jax

    from lightgbm_tpu.continual import run_drift_drill

    detail = {"device": jax.devices()[0].platform,
              "smoke": bool(smoke)}
    detail["tick"] = tick_overhead(rows, features, ticks)

    work = tempfile.mkdtemp(prefix="continual-profile-")
    drill_rows = min(rows, 256) if smoke else rows
    drills = {}
    try:
        drills["swap"] = run_drift_drill(
            "swap", rows=drill_rows, features=features, drift_at=4,
            post_ticks=5, checkpoint_dir=work)
        drills["degrade"] = run_drift_drill(
            "degrade", rows=drill_rows, features=features, drift_at=4,
            post_ticks=5)
        drills["rollback"] = run_drift_drill(
            "rollback", rows=drill_rows, features=features, drift_at=3,
            post_ticks=5)
    finally:
        shutil.rmtree(work, ignore_errors=True)
    for name, rep in drills.items():
        rep.pop("ticks", None)
        rep.pop("history", None)
    detail["drills"] = drills
    detail["swap_latency_ms"] = round(
        1e3 * float(drills["swap"].get("swap_latency_s") or 0.0), 3)
    return detail


def check(detail):
    """Smoke-lane invariants; returns a list of failures."""
    bad = []
    d = detail["drills"]
    if not d["swap"].get("detected_within_window"):
        bad.append("swap: regression not detected within the window")
    if d["swap"].get("swap_tick") is None:
        bad.append("swap: no hot-swap happened")
    if not d["swap"].get("one_trace_per_key"):
        bad.append("swap: more than one compile per (kind, bucket)")
    if not d["swap"].get("metric_recovered"):
        bad.append("swap: metric did not recover after the swap")
    if d["degrade"].get("degrade_tick") is None:
        bad.append("degrade: retry exhaustion did not degrade")
    if not d["degrade"].get("still_serving"):
        bad.append("degrade: last-good model stopped serving")
    if d["degrade"].get("generation") != 0:
        bad.append("degrade: a failed retrain must not swap")
    if not d["rollback"].get("rollback_within"):
        bad.append("rollback: watchdog did not fire within the window")
    if not d["rollback"].get("pre_post_identical"):
        bad.append("rollback: post-rollback predictions differ from the "
                   "pre-swap pack")
    return bad


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4096,
                    help="rows per tick for the overhead measurement")
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--ticks", type=int, default=20)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes + assert the drill invariants "
                    "(tier-1 lane)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.rows = min(args.rows, 512)
        args.features = min(args.features, 6)
        args.ticks = min(args.ticks, 6)
    from lightgbm_tpu.obs import benchio
    cfg = {"rows": args.rows, "features": args.features,
           "ticks": args.ticks, "smoke": bool(args.smoke)}
    # export-on-failure guard: a crashed drill still drops an aborted
    # BENCH_obs artifact + BENCH_history.jsonl trajectory entry
    with benchio.abort_guard("profile_continual", cfg) as guard:
        detail = run(args.rows, args.features, args.ticks, args.smoke)
        guard.write(detail,
                    metrics={"tick_ms": detail["tick"]["tick_ms"],
                             "predict_only_ms":
                                 detail["tick"]["predict_only_ms"],
                             "swap_latency_ms":
                                 detail["swap_latency_ms"]},
                    rows=args.rows, features=args.features)
    print(json.dumps({"metric": "continual", "detail": detail}))
    if args.smoke:
        bad = check(detail)
        if bad:
            print("continual smoke failed:\n  " + "\n  ".join(bad),
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
