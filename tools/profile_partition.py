"""Per-chunk cost attribution for the Pallas partition / split-mega
kernels on a live TPU.  Times R back-to-back partitions of an N-row
leaf under each variant and several chunk sizes, with the
many-reps-in-one-program + single-materialization discipline PERF.md
prescribes for this tunnel.

Variants:
  full / onenet / nonet — the partition kernel with both / one / zero
    compaction networks (the ablations produce WRONG layouts by design;
    they exist only here, for attribution);
  radix                 — partition kernel, radix-4 compaction network;
  mega / mega-radix     — the split mega-kernel (partition + BOTH
    children's histograms in one program): its per-chunk delta over
    "full" is the in-kernel histogram cost the e2e paired A/B
    (tools/ab_bench.py --b tpu_megakernel=pallas) trades against the
    per-split fixed work it removes.

Usage: python tools/profile_partition.py [N] [reps]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops import partition_pallas as pp
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)

_REAL_COMPACT = pp._compact


def _set_variant(variant):
    """Monkeypatch the compaction networks for A/B attribution (the
    ablated kernels produce WRONG partitions by design; they exist only
    here, never in the shipped kernel)."""
    if variant == "full":
        pp._compact = _REAL_COMPACT
    elif variant == "onenet":
        calls = {"n": 0}

        def one(payload, flag, shift0, C, logc):
            calls["n"] ^= 1
            return (_REAL_COMPACT(payload, flag, shift0, C, logc)
                    if calls["n"] else payload)
        pp._compact = one
    elif variant == "nonet":
        pp._compact = lambda payload, flag, shift0, C, logc: payload

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 30
G32 = 32
GHL = 5      # bench-like payload: grad, hess, rowid, score, slw


def run(C, variant):
    Npad = ((N + 2 * C + 127) // 128) * 128 + 2 * C
    rng = np.random.RandomState(0)
    bins = rng.randint(0, 255, size=(G32, Npad)).astype(np.uint8)
    ghi = rng.normal(size=(8, Npad)).astype(np.float32)
    sc = np.zeros((sc_rows_for(G32), Npad), np.int32)
    scal = make_scalars(jnp.int32(C), jnp.int32(N), 3, 0, 0, 255, 0, 0,
                        128, 1)
    mega = variant.startswith("mega")
    radix = variant.endswith("radix")

    _set_variant(variant if variant in ("full", "onenet", "nonet")
                 else "full")

    def one(c, _):
        pb, pg, sp = c
        if mega:
            from lightgbm_tpu.ops.split_megakernel_pallas import (
                split_megakernel_pallas)
            pb, pg, sp, nl, acc = split_megakernel_pallas(
                pb, pg, sp, scal, row_chunk=C, num_bins=255,
                num_groups=28, ghi_live=GHL, compact_radix=radix)
            return (pb, pg, sp), nl[0, 0] + jnp.sum(acc).astype(jnp.int32)
        pb, pg, sp, nl = partition_leaf_pallas(
            pb, pg, sp, scal, row_chunk=C, ghi_live=GHL,
            compact_radix=radix)
        return (pb, pg, sp), nl[0, 0]

    @jax.jit
    def many(pb, pg, sp):
        (pb, pg, sp), nls = jax.lax.scan(
            one, (pb, pg, sp), None, length=REPS)
        return pb, pg, sp, jnp.sum(nls)

    args = (jnp.asarray(bins), jnp.asarray(ghi), jnp.asarray(sc))
    out = many(*args)
    float(out[3])                      # compile + settle
    t0 = time.time()
    out = many(*args)
    float(out[3])                      # host materialization barrier
    wall = time.time() - t0 - 0.105    # subtract the tunnel round trip
    chunks = (N + C - 1) // C
    per_chunk = wall / REPS / chunks * 1e6
    print(f"C={C:5d} variant={variant:7s} wall={wall:.3f}s "
          f"per-pass={wall / REPS * 1e3:.2f}ms per-chunk={per_chunk:.2f}us")
    return per_chunk


if __name__ == "__main__":
    print(f"N={N} reps={REPS} device={jax.devices()}")
    from lightgbm_tpu.obs import benchio
    # trajectory wiring: one fingerprinted entry per run with every
    # surviving (chunk, variant) cell as a gated `_us` metric, so
    # on-hardware rounds of this harness are regression-gated too
    with benchio.abort_guard("profile_partition",
                             {"rows": N, "reps": REPS}) as guard:
        metrics = {}
        for C in (4096, 2048, 8192):
            for variant in ("full", "onenet", "nonet", "radix", "mega",
                            "mega-radix"):
                try:
                    metrics[f"C{C}_{variant}_per_chunk_us"] = \
                        run(C, variant)
                except Exception as e:
                    print(f"C={C} variant={variant} FAILED: "
                          + str(e).split(chr(10))[0][:100])
        guard.write(dict(metrics), metrics=metrics, rows=N)
