"""perfwatch — the benchmark-trajectory regression gate.

    python tools/perfwatch.py check            # rc!=0 on a confirmed
                                               # regression in the latest
                                               # same-fingerprint samples
    python tools/perfwatch.py report           # render the trajectory
                                               # per metric
    python tools/perfwatch.py drill            # plant a 3x slowdown via
                                               # clock injection, assert
                                               # the gate detects it AND
                                               # that identical re-runs
                                               # pass clean (tier-1 smoke)

Reads ``BENCH_history.jsonl`` (``--history`` / ``$BENCH_HISTORY_PATH``
/ repo root), the append-only store every measurement producer feeds:
``bench.py``, ``tools/ab_bench.py`` (all modes), the ``profile_*``
tools and the pytest conftest duration artifact.  Entries are keyed by
a hardware/config fingerprint (device kind & count, CPU cores, jax
versions, x64, dataset shape band, ``tpu_*`` knobs), and ``check``
compares only within a fingerprint: the exact paired median/MAD
statistic PERF.md rounds 10–12 compute by hand, behind a
``--min-samples`` warmup and a MAD/floor threshold so 2-core CPU noise
does not false-alarm.  See :mod:`lightgbm_tpu.obs.regress`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.obs import regress


def _detector_kw(args):
    return {"min_samples": args.min_samples, "z": args.z,
            "floor_pct": args.floor_pct}


def cmd_check(args) -> int:
    entries, skipped = regress.read_history(args.history)
    if skipped:
        print(f"# skipped {skipped} unparseable line(s) (torn tail / "
              "foreign content)", file=sys.stderr)
    if getattr(args, "tool", None):
        unfiltered = len(entries)
        entries = [e for e in entries
                   if args.tool in str(e.get("tool", ""))]
        if unfiltered and not entries:
            # a typo'd --tool must not silently gate nothing and
            # report success
            print(f"no entries match --tool {args.tool!r} "
                  f"({unfiltered} entries in the store)",
                  file=sys.stderr)
            return 2
    if not entries:
        print("trajectory is empty — run a bench/profile tool (or "
              "perfwatch drill) to seed it", file=sys.stderr)
        return 0
    findings = regress.evaluate(entries, **_detector_kw(args))
    bad = regress.regressions(findings)
    shown = bad if args.quiet else findings
    for f in shown:
        print(f.to_json() if args.as_json else f.render())
    n_gated = sum(1 for f in findings if f.direction != 0
                  and f.status != "warmup")
    print(f"# {len(findings)} series ({n_gated} gated), "
          f"{len(bad)} regression(s)", file=sys.stderr)
    return 1 if bad else 0


def cmd_report(args) -> int:
    entries, skipped = regress.read_history(args.history)
    if skipped:
        print(f"# skipped {skipped} unparseable line(s)",
              file=sys.stderr)
    print(regress.render_report(entries, metric_filter=args.metric,
                                tool_filter=args.tool))
    return 0


def cmd_drill(args) -> int:
    """Deterministic end-to-end exercise of the gate in a hermetic
    store: baseline entries recorded on a fixed step clock, then one
    entry recorded through a ``--scale``-times clock (the faultinject-
    style planted slowdown — no sleeps, no host dependence).  The gate
    must pass the identical baseline (rc 0), flag the planted slowdown
    (rc != 0), and pass again once an identical re-run follows it.
    Exit 0 only when all three hold."""
    own_tmp = args.history is None
    if own_tmp:
        fd, hist = tempfile.mkstemp(prefix="perfwatch-drill-",
                                    suffix=".jsonl")
        os.close(fd)
    else:
        hist = args.history
    # the drill's verdict is scoped to its OWN series: on a shared
    # store (explicit --history) an unrelated pre-existing regression
    # must not fail the drill, and the drill must not mask it either
    check_args = argparse.Namespace(
        history=hist, min_samples=args.min_samples, z=args.z,
        floor_pct=args.floor_pct, as_json=False, quiet=True,
        tool="perfwatch.drill")
    dt = 0.1
    config = {"drill": True, "scale": args.scale}
    try:
        try:
            for _ in range(args.min_samples + 1):
                regress.set_clock(regress.StepClock(dt))
                with regress.recording("perfwatch.drill", path=hist,
                                       config=config):
                    pass
            clean_rc = cmd_check(check_args)
            # planted slowdown: same workload, clock scaled 3x
            regress.set_clock(regress.scaled_clock(
                args.scale, base=regress.StepClock(dt)))
            with regress.recording("perfwatch.drill", path=hist,
                                   config=config):
                pass
            planted_rc = cmd_check(check_args)
            # identical re-run after the incident: back in the noise band
            regress.set_clock(regress.StepClock(dt))
            with regress.recording("perfwatch.drill", path=hist,
                                   config=config):
                pass
            rerun_rc = cmd_check(check_args)
        finally:
            regress.set_clock(None)
        ok = clean_rc == 0 and planted_rc != 0 and rerun_rc == 0
        print(json.dumps({
            "drill": True, "scale": args.scale, "history": hist,
            "clean_rc": clean_rc, "planted_rc": planted_rc,
            "rerun_rc": rerun_rc, "detected": planted_rc != 0,
            "ok": ok}))
        if not ok:
            print("drill FAILED: the gate must pass identical "
                  "measurements (rc 0) and flag the planted "
                  f"{args.scale}x slowdown (rc != 0)", file=sys.stderr)
            return 1
        return 0
    finally:
        if own_tmp and os.path.exists(hist):
            os.unlink(hist)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--history", default=None, metavar="PATH",
                       help="trajectory store (default: "
                       "$BENCH_HISTORY_PATH or <repo>/BENCH_history"
                       ".jsonl)")
        p.add_argument("--min-samples", type=int,
                       default=regress.MIN_SAMPLES,
                       help="prior same-fingerprint runs required "
                       "before a metric can regress (noise warmup)")
        p.add_argument("--z", type=float, default=regress.Z_SCORE,
                       help="MAD z-score multiplier of the change "
                       "threshold")
        p.add_argument("--floor-pct", type=float,
                       default=regress.FLOOR_PCT,
                       help="relative change floor %% (keeps zero-MAD "
                       "histories from flagging on jitter)")

    pc = sub.add_parser("check", help="gate the latest samples; "
                        "rc!=0 on confirmed regression")
    common(pc)
    pc.add_argument("--json", action="store_true", dest="as_json")
    pc.add_argument("--quiet", action="store_true",
                    help="print only regressions")
    pc.add_argument("--tool", default=None,
                    help="substring filter on tool names (gate one "
                    "producer's series only)")
    pc.set_defaults(fn=cmd_check)

    pr = sub.add_parser("report", help="render the trajectory per "
                        "metric")
    common(pr)
    pr.add_argument("--metric", default=None,
                    help="substring filter on metric names")
    pr.add_argument("--tool", default=None,
                    help="substring filter on tool names")
    pr.set_defaults(fn=cmd_report)

    pd = sub.add_parser("drill", help="plant a known slowdown via "
                        "clock injection and assert detection")
    common(pd)
    pd.add_argument("--scale", type=float, default=3.0,
                    help="planted slowdown factor")
    pd.set_defaults(fn=cmd_drill)
    pd.description = ("The drill appends its own perfwatch.drill "
                      "entries: with an explicit --history they stay "
                      "in that store (its checks are scoped to the "
                      "drill's series); by default a temp file is "
                      "used and removed.")

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
