"""Serving-plane load harness: p50/p99/QPS/shed-rate under concurrent
tenants, through the full stack (admission -> micro-batcher -> device
ServingEngine).

Spawns ``--clients`` tenant threads, each firing ``--requests``
requests of ``--rows-per-request`` rows at the in-process
ServingService (client-side latency measured per request), then
reports percentiles, throughput, shed rate, coalescing stats and the
per-(kind, bucket) compile counts — the invariant: every traced key
compiled EXACTLY once however many clients ran (non-zero exit
otherwise, like profile_predict).

Prints ONE JSON line (like bench.py):

  {"metric": "serve_load", "value": ..., "unit": "req_per_s",
   "detail": {...}}

and drops a BENCH_obs v3 artifact + BENCH_history.jsonl trajectory
entry whose fingerprint_extra carries the tenant count and bucket
grid, so two differently-shaped load experiments never share a
detector series.

Usage:
  python tools/profile_serve.py [--clients 8] [--requests 100]
      [--rows-per-request 1] [--trees 50] [--features 10]
      [--flush-rows 256] [--flush-ms 2.0] [--smoke]
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _train(lgb, rng, n_train, features, trees):
    X = rng.normal(size=(n_train, features))
    y = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=n_train)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    bst._gbdt._flush_pending()
    return bst, X


def run(args):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry, ServingService

    rng = np.random.RandomState(7)
    bst, X = _train(lgb, rng, min(args.train_rows, 20000),
                    args.features, args.trees)
    reg = ModelRegistry()
    svc = ServingService(reg, flush_rows=args.flush_rows,
                         max_delay=args.flush_ms / 1e3,
                         queue_depth=args.queue_depth)
    reg.publish("m", bst,
                gate_rows=X[:min(args.flush_rows, len(X))])
    eng = bst._gbdt.serving
    base = dict(eng.trace_counts)
    svc.start()
    lat_ms = []
    lat_lock = threading.Lock()
    pool = rng.normal(size=(max(4096, 2 * args.rows_per_request),
                            args.features))
    span = len(pool) - args.rows_per_request + 1   # full-width slices

    def client(i):
        mine = []
        for j in range(args.requests):
            start = (i * args.requests + j) % span
            rows = pool[start:start + args.rows_per_request]
            t0 = time.perf_counter()
            t = svc.submit(rows, model="m", tenant=f"t{i}")
            t.wait(60.0)
            if t.status == "ok":
                mine.append(1e3 * (time.perf_counter() - t0))
        with lat_lock:
            lat_ms.extend(mine)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    svc.stop()
    stats = svc.stats()
    new_traces = {f"{k[0]}@{k[1]}": v - base.get(k, 0)
                  for k, v in eng.trace_counts.items()
                  if v - base.get(k, 0) > 0}
    warm_keys = {f"{k[0]}@{k[1]}" for k in base}
    # the invariant has two halves: a NEW key compiles exactly once,
    # and a key the publish warm-up already compiled never compiles
    # again — growth on a warm key is a retrace even at delta 1
    multi = {k: v for k, v in new_traces.items()
             if v != 1 or k in warm_keys}
    total = args.clients * args.requests
    served = len(lat_ms)
    lat = np.asarray(lat_ms) if lat_ms else np.zeros(1)
    buckets = sorted({k[1] for k in eng.trace_counts})
    import jax
    detail = {
        "clients": args.clients, "requests_per_client": args.requests,
        "rows_per_request": args.rows_per_request,
        "trees": args.trees, "flush_rows": args.flush_rows,
        "flush_ms": args.flush_ms,
        "wall_s": round(wall, 4),
        "served": served, "submitted": total,
        "req_per_s": round(served / max(wall, 1e-9), 1),
        "p50_ms": round(float(np.percentile(lat, 50)), 3),
        "p99_ms": round(float(np.percentile(lat, 99)), 3),
        "shed_rate": stats["shed_rate"],
        "dispatches": stats["counters"]["dispatches"],
        "coalesced_sizes": stats["batcher"]["coalesced_sizes"],
        "rows_per_dispatch": round(
            served * args.rows_per_request
            / max(stats["counters"]["dispatches"], 1), 2),
        "buckets": buckets,
        "new_traces": new_traces, "multi_traced": multi,
        "smoke": bool(args.smoke),
        "device": jax.default_backend(),
    }
    return {"metric": "serve_load", "value": detail["req_per_s"],
            "unit": "req_per_s", "detail": detail}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8,
                    help="concurrent tenant threads")
    ap.add_argument("--requests", type=int, default=100,
                    help="requests per client")
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--train-rows", type=int, default=20000)
    ap.add_argument("--trees", type=int, default=50)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--flush-rows", type=int, default=256)
    ap.add_argument("--flush-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=4096)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny run for the tier-1 smoke lane")
    args = ap.parse_args(argv)
    if args.smoke:
        args.clients = min(args.clients, 4)
        args.requests = min(args.requests, 25)
        args.trees = min(args.trees, 8)
        args.train_rows = min(args.train_rows, 3000)
    from lightgbm_tpu.obs import benchio
    cfg = {"rows": args.train_rows, "trees": args.trees,
           "features": args.features, "clients": args.clients,
           "requests": args.requests, "smoke": bool(args.smoke)}
    # export-on-failure + series identity: tenant count and the bucket
    # grid fork the trajectory (a 4-client smoke must never gate an
    # 8-client headline, nor flush_rows=256 a flush_rows=1024 run)
    extra = {"tenants": args.clients,
             "flush_rows": args.flush_rows,
             "rows_per_request": args.rows_per_request}
    with benchio.abort_guard("profile_serve", cfg) as guard:
        out = run(args)
        d = out["detail"]
        guard.write(d,
                    metrics={"req_per_s": d["req_per_s"],
                             "p50_ms": d["p50_ms"],
                             "p99_ms": d["p99_ms"],
                             "shed_rate": d["shed_rate"]},
                    rows=args.train_rows, features=args.features,
                    fingerprint_extra=extra)
    print(json.dumps(out))
    # the compile-count invariant is the whole point: fail loudly when
    # concurrent load traced any (kind, bucket) more than once
    return 1 if out["detail"]["multi_traced"] else 0


if __name__ == "__main__":
    sys.exit(main())
