"""Predict + pred_contrib serving throughput harness.

Times the device serving engine (models/serving.py) over a rows x trees
grid: warm raw-score predict, warm pred_contrib (vectorized device
TreeSHAP, ops/shap.py), the host TreeSHAP recursion on a subsample (the
before/after the engine replaces), and the per-(kind, bucket) compile
counts proving repeated serving-shaped calls never re-trace.

Prints ONE JSON line (like bench.py):

  {"metric": "predict_serving", "detail": {"grid": [...],
   "traces": {...}, "device": "..."}}

Two PR-13 lanes ride along:

* **layered-vs-loop A/B** (always on): two identically-trained
  boosters — one forced to the layered dense kernel
  (ops/forest_tensor.py), one to the while-loop oracle — timed at
  serving shapes (128..64k-row buckets), reporting rows*trees/sec per
  kernel and the speedup, with each engine's per-(kind, bucket)
  compile counts still pinned at one.
* **--cohort N**: N tenant forests behind the serving plane with
  ``serve_cohort`` on — one same-bucket raw wave per pump must cost
  exactly ONE dispatch (asserted; rc!=0 on violation), timed against
  the per-model dispatch baseline.

Usage:
  python tools/profile_predict.py [--rows 100000] [--trees 100]
      [--features 10] [--cohort 0] [--smoke]

``--smoke`` shrinks the grid to seconds for the tier-1 lane.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _train(lgb, rng, n_train, features, trees):
    X = rng.normal(size=(n_train, features))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    bst._gbdt._flush_pending()
    return bst


def _timed(fn, *args, **kw):
    t0 = time.time()
    out = fn(*args, **kw)
    return time.time() - t0, out


def _warm_median(fn, reps=3):
    return float(np.median([_timed(fn)[0] for _ in range(reps)]))


def run_ab(rows, trees, features, smoke):
    """Layered-vs-loop kernel A/B over serving-shaped row buckets.

    Two boosters trained on the same data/seed hold bit-identical
    trees; one serves through ``predict_kernel=layered``, one through
    ``loop``, so each engine keeps its own jit caches and its own
    pinned one-trace-per-(kind, bucket) counts."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(5)
    n_train = min(rows, 20000)
    X = rng.normal(size=(n_train, features))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)

    def train(kernel):
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbosity": -1, "metric": "",
                         "predict_kernel": kernel},
                        lgb.Dataset(X, label=y), num_boost_round=trees)
        bst._gbdt._flush_pending()
        return bst

    lay, loop = train("layered"), train("loop")
    warm = rng.normal(size=(max(4096, min(rows, 8192)), features))
    for b in (lay, loop):
        b.predict(warm, raw_score=True)
    pack = lay._gbdt.serving._packs["insession"][1]
    assert pack.get("layers_depth") is not None, \
        "A/B forest must be layered-eligible"
    grid = []
    row_grid = [n for n in (128, 1024, 8192, 65536) if n <= rows]
    parity = 0.0
    for n in row_grid:
        Xp = rng.normal(size=(n, features))
        a = np.asarray(lay.predict(Xp, raw_score=True))
        b = np.asarray(loop.predict(Xp, raw_score=True))
        parity = max(parity, float(np.max(np.abs(a - b))))
        t_lay = _warm_median(lambda: lay.predict(Xp, raw_score=True))
        t_loop = _warm_median(lambda: loop.predict(Xp, raw_score=True))
        grid.append({
            "rows": n, "trees": trees,
            "layered_warm_s": round(t_lay, 5),
            "loop_warm_s": round(t_loop, 5),
            "layered_rows_trees_per_s":
                round(n * trees / max(t_lay, 1e-9)),
            "loop_rows_trees_per_s":
                round(n * trees / max(t_loop, 1e-9)),
            "layered_speedup": round(t_loop / max(t_lay, 1e-9), 3)})
    multi = {}
    for tag, b in (("layered", lay), ("loop", loop)):
        for k, v in b._gbdt.serving.stats()["traces"].items():
            if v != 1:
                multi[f"{tag}:{k[0]}@{k[1]}"] = v
    return {"grid": grid, "bit_parity_max_abs": parity,
            "multi_traced": multi, "depth": pack["layers_depth"]}


def run_cohort(n_models, trees, features, smoke):
    """N tenant forests behind the serving plane with cohort lanes on:
    every same-bucket raw wave must cost exactly ONE dispatch, timed
    against the per-model dispatch baseline."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry, ServingService

    rng = np.random.RandomState(7)
    wave_rows = 128 if smoke else 1024
    waves = 3 if smoke else 10
    boosters = []
    for i in range(n_models):
        Xt = rng.normal(size=(2000, features))
        yt = Xt[:, 0] + 0.5 * np.sin(Xt[:, 1]) \
            + 0.1 * rng.normal(size=2000)
        bst = lgb.train({"objective": "regression", "num_leaves": 31,
                         "verbosity": -1, "metric": "", "seed": i},
                        lgb.Dataset(Xt, label=yt),
                        num_boost_round=trees)
        bst._gbdt._flush_pending()
        boosters.append((f"m{i}", bst, Xt))

    def service(cohort):
        reg = ModelRegistry()
        svc = ServingService(reg, flush_rows=wave_rows, max_delay=10.0,
                             queue_depth=1 << 16, cohort=cohort)
        for name, bst, Xt in boosters:
            reg.publish(name, bst, gate_rows=Xt)
        return reg, svc

    def wave(svc):
        for name, bst, Xt in boosters:
            svc.submit(Xt[:wave_rows], model=name, kind="raw",
                       tenant=name)
        return svc.pump(force=True)

    violations = []
    reg_c, svc_c = service(True)
    wave(svc_c)                                    # warm cohort pack
    t0 = time.time()
    for _ in range(waves):
        if wave(svc_c) != 1:
            violations.append("cohort wave took >1 dispatch")
    cohort_s = (time.time() - t0) / waves
    if svc_c.counters["cohort_dispatches"] != waves + 1:
        violations.append(
            f"cohort_dispatches={svc_c.counters['cohort_dispatches']}"
            f" want {waves + 1}")
    bad_traces = {f"{k[0]}@{k[1]}": v
                  for k, v in reg_c.cohort_traces.items() if v != 1}
    if bad_traces:
        violations.append(f"cohort retrace: {bad_traces}")

    reg_p, svc_p = service(False)
    wave(svc_p)                                    # warm per-model
    t0 = time.time()
    for _ in range(waves):
        if wave(svc_p) != n_models:
            violations.append("per-model wave dispatch count off")
    permodel_s = (time.time() - t0) / waves
    return {"models": n_models, "wave_rows": wave_rows,
            "waves": waves,
            "cohort_wave_s": round(cohort_s, 5),
            "permodel_wave_s": round(permodel_s, 5),
            "cohort_waves_per_s": round(1.0 / max(cohort_s, 1e-9), 2),
            "permodel_waves_per_s":
                round(1.0 / max(permodel_s, 1e-9), 2),
            "cohort_speedup":
                round(permodel_s / max(cohort_s, 1e-9), 3),
            "cohort_traces": {f"{k[0]}@{k[1]}": v
                              for k, v in reg_c.cohort_traces.items()},
            "violations": violations}


def run(rows, trees, features, smoke, host_oracle_rows):
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(5)
    bst = _train(lgb, rng, min(rows, 20000), features, trees)
    g = bst._gbdt
    # one big call warms the engine pack so small serving-shaped batches
    # take the device path from the start (see ServingEngine.COLD_MIN_ROWS)
    bst.predict(rng.normal(size=(max(4096, min(rows, 8192)), features)),
                raw_score=True)
    grid = []
    row_grid = sorted({min(1000, rows), min(10000, rows), rows})
    for n in row_grid:
        Xp = rng.normal(size=(n, features))
        # cold call pays the pack + trace; the second call is the
        # serving-shaped steady state
        cold_raw, _ = _timed(bst.predict, Xp, raw_score=True)
        warm_raw, _ = _timed(bst.predict, Xp, raw_score=True)
        cold_con, _ = _timed(bst.predict, Xp, pred_contrib=True)
        warm_con, contrib = _timed(bst.predict, Xp, pred_contrib=True)
        row = {"rows": n, "trees": trees,
               "raw_cold_s": round(cold_raw, 4),
               "raw_warm_s": round(warm_raw, 4),
               "raw_rows_per_s": round(n / max(warm_raw, 1e-9)),
               "contrib_cold_s": round(cold_con, 4),
               "contrib_warm_s": round(warm_con, 4),
               "contrib_rows_per_s": round(n / max(warm_con, 1e-9))}
        if host_oracle_rows and n == row_grid[0]:
            from lightgbm_tpu.models.shap import predict_contrib
            m = min(host_oracle_rows, n)
            host_s, host = _timed(predict_contrib, g,
                                  np.asarray(Xp[:m], np.float64), 0, -1)
            row["host_contrib_s"] = round(host_s, 4)
            row["host_contrib_rows"] = m
            row["host_parity_max_abs"] = float(
                np.max(np.abs(np.asarray(contrib[:m]) - host)))
        grid.append(row)
    stats = g.serving.stats()
    # compile-count invariant: every (kind, bucket) traced at most once
    multi = {f"{k[0]}@{k[1]}": v for k, v in stats["traces"].items()
             if v != 1}
    import jax
    return {"metric": "predict_serving",
            "value": grid[-1]["contrib_rows_per_s"],
            "unit": "contrib_rows_per_s",
            "detail": {"grid": grid,
                       "traces": {f"{k[0]}@{k[1]}": v
                                  for k, v in stats["traces"].items()},
                       "calls": {f"{k[0]}@{k[1]}": v
                                 for k, v in stats["calls"].items()},
                       "multi_traced": multi,
                       "smoke": bool(smoke),
                       "device": jax.default_backend()}}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--trees", type=int, default=100)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--host-oracle-rows", type=int, default=2000,
                    help="rows for the host-recursion comparison point "
                         "(0 disables)")
    ap.add_argument("--cohort", type=int, default=0, metavar="N",
                    help="multi-forest lane: N tenant forests, one "
                         "cohort dispatch per wave asserted "
                         "(0 disables)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny grid for the tier-1 smoke lane")
    args = ap.parse_args(argv)
    if args.smoke:
        args.rows = min(args.rows, 3000)
        args.trees = min(args.trees, 10)
        args.host_oracle_rows = min(args.host_oracle_rows, 200)
    from lightgbm_tpu.obs import benchio
    cfg = {"rows": args.rows, "trees": args.trees,
           "features": args.features, "smoke": bool(args.smoke),
           "cohort": args.cohort}
    # export-on-failure guard: a crashed harness still drops an aborted
    # BENCH_obs artifact + BENCH_history.jsonl trajectory entry
    with benchio.abort_guard("profile_predict", cfg) as guard:
        out = run(args.rows, args.trees, args.features, args.smoke,
                  args.host_oracle_rows)
        ab = run_ab(args.rows, args.trees, args.features, args.smoke)
        out["detail"]["kernel_ab"] = ab
        metrics = {"raw_rows_per_s":
                       out["detail"]["grid"][-1]["raw_rows_per_s"],
                   "contrib_rows_per_s":
                       out["detail"]["grid"][-1]["contrib_rows_per_s"],
                   "raw_warm_s":
                       out["detail"]["grid"][-1]["raw_warm_s"],
                   "contrib_warm_s":
                       out["detail"]["grid"][-1]["contrib_warm_s"]}
        abg = ab["grid"][-1]
        metrics.update({
            "layered_rows_trees_per_s":
                abg["layered_rows_trees_per_s"],
            "loop_rows_trees_per_s": abg["loop_rows_trees_per_s"],
            "layered_speedup": abg["layered_speedup"]})
        violations = list(ab["multi_traced"].items())
        if ab["bit_parity_max_abs"] != 0.0:
            violations.append(("layered_bit_parity",
                               ab["bit_parity_max_abs"]))
        if args.cohort:
            co = run_cohort(args.cohort, args.trees, args.features,
                            args.smoke)
            out["detail"]["cohort"] = co
            metrics.update({
                "cohort_waves_per_s": co["cohort_waves_per_s"],
                "cohort_speedup": co["cohort_speedup"]})
            violations.extend((v, 1) for v in co["violations"])
        guard.write(out["detail"], metrics=metrics,
                    rows=args.rows, features=args.features,
                    fingerprint_extra={"cohort": args.cohort}
                    if args.cohort else None)
    print(json.dumps(out))
    # non-zero exit when a pinned invariant breaks: a retrace per
    # (kind, bucket), layered-vs-loop bit divergence, or a cohort wave
    # costing more than one dispatch
    return 1 if (out["detail"]["multi_traced"] or violations) else 0


if __name__ == "__main__":
    sys.exit(main())
