"""Predict + pred_contrib serving throughput harness.

Times the device serving engine (models/serving.py) over a rows x trees
grid: warm raw-score predict, warm pred_contrib (vectorized device
TreeSHAP, ops/shap.py), the host TreeSHAP recursion on a subsample (the
before/after the engine replaces), and the per-(kind, bucket) compile
counts proving repeated serving-shaped calls never re-trace.

Prints ONE JSON line (like bench.py):

  {"metric": "predict_serving", "detail": {"grid": [...],
   "traces": {...}, "device": "..."}}

Usage:
  python tools/profile_predict.py [--rows 100000] [--trees 100]
      [--features 10] [--smoke]

``--smoke`` shrinks the grid to seconds for the tier-1 lane.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _train(lgb, rng, n_train, features, trees):
    X = rng.normal(size=(n_train, features))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "metric": ""},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    bst._gbdt._flush_pending()
    return bst


def _timed(fn, *args, **kw):
    t0 = time.time()
    out = fn(*args, **kw)
    return time.time() - t0, out


def run(rows, trees, features, smoke, host_oracle_rows):
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(5)
    bst = _train(lgb, rng, min(rows, 20000), features, trees)
    g = bst._gbdt
    # one big call warms the engine pack so small serving-shaped batches
    # take the device path from the start (see ServingEngine.COLD_MIN_ROWS)
    bst.predict(rng.normal(size=(max(4096, min(rows, 8192)), features)),
                raw_score=True)
    grid = []
    row_grid = sorted({min(1000, rows), min(10000, rows), rows})
    for n in row_grid:
        Xp = rng.normal(size=(n, features))
        # cold call pays the pack + trace; the second call is the
        # serving-shaped steady state
        cold_raw, _ = _timed(bst.predict, Xp, raw_score=True)
        warm_raw, _ = _timed(bst.predict, Xp, raw_score=True)
        cold_con, _ = _timed(bst.predict, Xp, pred_contrib=True)
        warm_con, contrib = _timed(bst.predict, Xp, pred_contrib=True)
        row = {"rows": n, "trees": trees,
               "raw_cold_s": round(cold_raw, 4),
               "raw_warm_s": round(warm_raw, 4),
               "raw_rows_per_s": round(n / max(warm_raw, 1e-9)),
               "contrib_cold_s": round(cold_con, 4),
               "contrib_warm_s": round(warm_con, 4),
               "contrib_rows_per_s": round(n / max(warm_con, 1e-9))}
        if host_oracle_rows and n == row_grid[0]:
            from lightgbm_tpu.models.shap import predict_contrib
            m = min(host_oracle_rows, n)
            host_s, host = _timed(predict_contrib, g,
                                  np.asarray(Xp[:m], np.float64), 0, -1)
            row["host_contrib_s"] = round(host_s, 4)
            row["host_contrib_rows"] = m
            row["host_parity_max_abs"] = float(
                np.max(np.abs(np.asarray(contrib[:m]) - host)))
        grid.append(row)
    stats = g.serving.stats()
    # compile-count invariant: every (kind, bucket) traced at most once
    multi = {f"{k[0]}@{k[1]}": v for k, v in stats["traces"].items()
             if v != 1}
    import jax
    return {"metric": "predict_serving",
            "value": grid[-1]["contrib_rows_per_s"],
            "unit": "contrib_rows_per_s",
            "detail": {"grid": grid,
                       "traces": {f"{k[0]}@{k[1]}": v
                                  for k, v in stats["traces"].items()},
                       "calls": {f"{k[0]}@{k[1]}": v
                                 for k, v in stats["calls"].items()},
                       "multi_traced": multi,
                       "smoke": bool(smoke),
                       "device": jax.default_backend()}}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--trees", type=int, default=100)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--host-oracle-rows", type=int, default=2000,
                    help="rows for the host-recursion comparison point "
                         "(0 disables)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny grid for the tier-1 smoke lane")
    args = ap.parse_args(argv)
    if args.smoke:
        args.rows = min(args.rows, 3000)
        args.trees = min(args.trees, 10)
        args.host_oracle_rows = min(args.host_oracle_rows, 200)
    from lightgbm_tpu.obs import benchio
    cfg = {"rows": args.rows, "trees": args.trees,
           "features": args.features, "smoke": bool(args.smoke)}
    # export-on-failure guard: a crashed harness still drops an aborted
    # BENCH_obs artifact + BENCH_history.jsonl trajectory entry
    with benchio.abort_guard("profile_predict", cfg) as guard:
        out = run(args.rows, args.trees, args.features, args.smoke,
                  args.host_oracle_rows)
        top = out["detail"]["grid"][-1]
        guard.write(out["detail"],
                    metrics={"raw_rows_per_s": top["raw_rows_per_s"],
                             "contrib_rows_per_s":
                                 top["contrib_rows_per_s"],
                             "raw_warm_s": top["raw_warm_s"],
                             "contrib_warm_s": top["contrib_warm_s"]},
                    rows=args.rows, features=args.features)
    print(json.dumps(out))
    # non-zero exit when the compile-count invariant is violated, so the
    # smoke lane fails loudly on a retrace regression
    return 1 if out["detail"]["multi_traced"] else 0


if __name__ == "__main__":
    sys.exit(main())
