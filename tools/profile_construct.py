"""Profile dataset construction: host per-feature loop vs vectorized vs
device (ops/construct.py).

Times, for each (rows, features) grid cell:

* ``host_loop_s``   — end-to-end ``BinnedDataset.from_matrix`` through
  the original per-feature Python loops (``construct_device=off``, the
  oracle).
* ``vectorized_s``  — the same construction through the batched path
  (``construct_device=auto``: one column-wise sort for bin finding, one
  batched searchsorted for the mapping, matmul EFB conflicts, streaming
  device ingest).
* ``device_map_s``  — the values->bins mapping stage alone executed on
  the default JAX backend via the SAME BatchedMapper code path
  (``jnp`` instead of ``numpy``), including the host->device transfer;
  null when the backend is unavailable.

Parity (binned matrices bit-identical between arms) is asserted on
every cell.  Prints ONE JSON line:

  {"grid": [{rows, features, host_loop_s, vectorized_s, speedup,
             device_map_s}...],
   "parity_ok": true, "backend": "...", "smoke": bool}

``--smoke`` runs a seconds-sized grid (tier-1 wiring:
tests/test_construct_device.py); the full grid tops out at 1M x 100 —
the PERF.md acceptance cell (>= 4x vectorized vs host loop on CPU).

On-device A/B (run where a TPU is attached):
  JAX_PLATFORMS=tpu python tools/profile_construct.py
  JAX_PLATFORMS=cpu python tools/profile_construct.py
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _make_matrix(rows: int, features: int, seed: int = 0) -> np.ndarray:
    """Mixed-shape matrix: dense normals, sparse (EFB-candidate)
    columns, one NaN column, one few-distinct column."""
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, features))
    for j in range(0, features, 4):            # every 4th column sparse
        X[:, j] = np.where(rng.rand(rows) < 0.9, 0.0, X[:, j])
    if features > 2:
        X[rng.rand(rows) < 0.05, 2] = np.nan
    if features > 3:
        X[:, 3] = rng.randint(0, 12, size=rows).astype(float)
    return X


def _construct(X, mode: str):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    cfg = Config({"verbosity": -1, "construct_device": mode})
    t0 = time.time()
    ds = BinnedDataset.from_matrix(X, cfg, label=X[:, 0])
    dt = time.time() - t0
    return ds, dt


def _device_map_time(ds, X):
    """The batched mapping stage on the default JAX backend (jnp code
    path of BatchedMapper.map_chunk), transfer included."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        return None
    try:
        bmap = ds.batched_mapper()
        sub = np.asarray(X[:, ds.used_features], dtype=np.float64)
        out = bmap.map_chunk(jnp.asarray(sub), xp=jnp)   # compile+warm
        jax.block_until_ready(out)
        t0 = time.time()
        out = bmap.map_chunk(jnp.asarray(sub), xp=jnp)
        jax.block_until_ready(out)
        return time.time() - t0
    except Exception:
        return None


def run_cell(rows: int, features: int):
    X = _make_matrix(rows, features)
    ds_oracle, host_s = _construct(X, "off")
    ds_vec, vec_s = _construct(X, "auto")
    parity = (
        [bm.to_dict() for bm in ds_oracle.bin_mappers]
        == [bm.to_dict() for bm in ds_vec.bin_mappers]
        and [(g.feature_indices, g.num_total_bin, g.bin_offsets)
             for g in ds_oracle.groups]
        == [(g.feature_indices, g.num_total_bin, g.bin_offsets)
            for g in ds_vec.groups]
        and np.array_equal(ds_oracle.binned, ds_vec.host_binned()))
    dev_s = _device_map_time(ds_vec, X)
    return {
        "rows": rows, "features": features,
        "host_loop_s": round(host_s, 3),
        "vectorized_s": round(vec_s, 3),
        "speedup": round(host_s / vec_s, 2) if vec_s > 0 else None,
        "device_map_s": round(dev_s, 3) if dev_s is not None else None,
    }, parity


class _SynthSeq:
    """Deterministic on-the-fly row chunks for the out-of-core lane:
    every value is a pure function of (absolute row, column), so the
    dense matrix NEVER exists — only ``batch_size`` rows at a time.
    Mixed shape like ``_make_matrix``: sparse every-4th columns, a
    NaN-dotted column, a few-distinct integer column."""

    def __init__(self, rows: int, features: int, batch_size: int = 65536):
        self.rows, self.features = int(rows), int(features)
        self.batch_size = int(batch_size)

    def __len__(self):
        return self.rows

    def __getitem__(self, item):
        sl = item if isinstance(item, slice) else slice(item, item + 1)
        start, stop, _ = sl.indices(self.rows)
        i = np.arange(start, stop, dtype=np.int64)[:, None]
        j = np.arange(self.features, dtype=np.int64)[None, :]
        h = (i * 2654435761 + j * 40503) % 100003
        X = h.astype(np.float64) / 100003.0 * 6.0 - 3.0
        X[((j % 4 == 0) & (h * 7 % 10 < 9)).nonzero()] = 0.0
        if self.features > 3:
            X[:, 3] = (h[:, 3] % 12).astype(np.float64)
        if self.features > 2:
            X[(h[:, 2] % 20) == 0, 2] = np.nan
        return X if isinstance(item, slice) else X[0]


def _synth_label(rows: int) -> np.ndarray:
    return (np.arange(rows, dtype=np.float64) % 97) / 97.0


def _rss_kb() -> int:
    import resource
    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)


def run_oocore_cell(rows: int, features: int, check_parity: bool):
    """One out-of-core cell: sketch + two-pass streaming construction
    from a synthetic sequence, peak-RSS delta tracked against the
    BINNED (not raw) footprint; optionally an exact in-core A/B + full
    mapper parity check at matrix-feasible sizes.

    The parity cell pins ``sketch_k >= rows`` so every column stays in
    the sketch's exact tier (level 0: cells ARE distinct values) and
    bit-identity to the exact oracle is the hard requirement; the
    perf cells run the default k, where near-continuous columns
    coarsen to the bounded-rank-error regime (tests/test_sketch.py
    asserts that bound separately)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    seq = _SynthSeq(rows, features)
    lab = _synth_label(rows)
    params = {"verbosity": -1, "bin_construct_mode": "sketch"}
    if check_parity:
        params["sketch_k"] = max(8192, rows)
    rss0 = _rss_kb()
    t0 = time.time()
    ds = BinnedDataset.from_sequences(seq, Config(params), label=lab)
    stream_s = time.time() - t0
    rss_delta_mb = max(_rss_kb() - rss0, 0) / 1024.0
    nbytes = ds._bin_dtype()().nbytes
    binned_mb = rows * len(ds.groups) * nbytes / 1e6
    raw_mb = rows * features * 8 / 1e6
    # the ingest buffer (host memory on the CPU backend) is ~1x the
    # binned footprint; chunk transients and sketch state ride in the
    # slack — ">2x binned" means the streaming path leaked a dense copy
    oocore_ok = (ds.binned is None
                 and rss_delta_mb <= 2.0 * binned_mb + 96.0)
    cell = {
        "rows": rows, "features": features,
        "stream_s": round(stream_s, 3),
        "rows_per_s": round(rows / stream_s) if stream_s > 0 else None,
        "rss_delta_mb": round(rss_delta_mb, 1),
        "binned_mb": round(binned_mb, 1),
        "raw_mb": round(raw_mb, 1),
        "host_binned_freed": ds.binned is None,
        "rss_ok": bool(oocore_ok),
    }
    parity = True
    if check_parity:
        X = np.asarray(seq[0:rows], dtype=np.float64)
        t0 = time.time()
        ds_x = BinnedDataset.from_matrix(
            X, Config({"verbosity": -1, "bin_construct_mode": "exact"}),
            label=lab)
        cell["exact_s"] = round(time.time() - t0, 3)
        parity = (
            [bm.to_dict() for bm in ds.bin_mappers]
            == [bm.to_dict() for bm in ds_x.bin_mappers]
            and [(g.feature_indices, g.num_total_bin, g.bin_offsets)
                 for g in ds.groups]
            == [(g.feature_indices, g.num_total_bin, g.bin_offsets)
                for g in ds_x.groups]
            and np.array_equal(ds.host_binned(), ds_x.binned))
    return cell, parity, oocore_ok


def main_oocore(args) -> int:
    import jax

    from lightgbm_tpu.obs import benchio
    if args.rows or args.features:
        rows = [int(r) for r in (args.rows or "500000").split(",")]
        feats = [int(f) for f in (args.features or "20").split(",")]
        grid = [(r, f) for r in rows for f in feats]
    elif args.smoke:
        grid = [(120_000, 12)]
    else:
        grid = [(1_000_000, 20), (1_000_000, 50)]
    parity_cell = (min(min(r for r, _ in grid), 60_000),
                   min(f for _, f in grid))
    # warm the backend OUTSIDE the measured cells so jit/compile arenas
    # don't land in the first cell's RSS delta
    run_oocore_cell(4096, parity_cell[1], check_parity=False)
    big_rows, big_feats = max(grid)
    cfg = {"rows": big_rows, "features": big_feats, "cells": len(grid),
           "smoke": bool(args.smoke), "oocore": True}
    with benchio.abort_guard("profile_construct_oocore", cfg) as guard:
        cells = []
        parity_ok = True
        rss_ok = True
        pcell, parity, _ = run_oocore_cell(*parity_cell, check_parity=True)
        parity_ok = parity_ok and parity
        cells.append(pcell)
        print(f"# parity {parity_cell[0]}x{parity_cell[1]}: "
              f"stream {pcell['stream_s']}s exact {pcell['exact_s']}s "
              f"parity={parity}", file=sys.stderr)
        for rows, features in grid:
            cell, parity, ok = run_oocore_cell(rows, features,
                                               check_parity=False)
            parity_ok = parity_ok and parity
            rss_ok = rss_ok and ok
            cells.append(cell)
            print(f"# {rows}x{features}: stream {cell['stream_s']}s "
                  f"({cell['rows_per_s']} rows/s) rss +"
                  f"{cell['rss_delta_mb']}MB vs binned "
                  f"{cell['binned_mb']}MB raw {cell['raw_mb']}MB",
                  file=sys.stderr)
        big = [c for c in cells
               if (c["rows"], c["features"]) == (big_rows, big_feats)][0]
        rec = {"grid": cells, "parity_ok": bool(parity_ok),
               "rss_ok": bool(rss_ok),
               "backend": jax.default_backend(), "smoke": bool(args.smoke),
               "oocore": True}
        guard.write(rec,
                    metrics={"stream_s": big["stream_s"],
                             "rows_per_s": float(big["rows_per_s"] or 0),
                             "rss_delta_mb": big["rss_delta_mb"],
                             "exact_s": cells[0].get("exact_s", 0.0)},
                    rows=big_rows, features=big_feats)
    print(json.dumps(rec))
    return 0 if (parity_ok and rss_ok) else 1


def run_trainmem_cell(rows: int, features: int, iters: int):
    """One training-memory cell: stream-construct (host binned freed),
    then train ``iters`` fused iterations and track

    * peak-RSS delta beyond the post-construct baseline — under
      single-copy residency the trainer ADOPTS the ingest buffer, so
      the binned data adds ZERO new bytes; the budget covers the ghi
      working rows, tree/score state and the XLA compile arena;
    * binned residency — exactly ONE live binned-footprint device
      buffer (the adopted physical carrier) after training;
    * the HBM ledger's dedup accounting of that carrier."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import memory as obs_memory

    # the Dataset wrapper dispatches on isinstance(Sequence): a plain
    # duck-typed _SynthSeq would be np.asarray'd into the dense matrix
    class _Seq(_SynthSeq, lgb.Sequence):
        pass
    seq = _Seq(rows, features)
    lab = _synth_label(rows)
    params = {"verbosity": -1, "bin_construct_mode": "sketch",
              "objective": "regression", "num_leaves": 31, "metric": ""}
    dset = lgb.Dataset(seq, label=lab, params=params)
    dset.construct(params)
    inner = dset._inner
    nbytes = inner._bin_dtype()().nbytes
    G = len(inner.groups)
    binned_mb = rows * G * nbytes / 1e6
    rss0 = _rss_kb()
    t0 = time.time()
    bst = lgb.Booster(params, dset)
    for _ in range(iters):
        bst.update()
    train_s = time.time() - t0
    rss_delta_mb = max(_rss_kb() - rss0, 0) / 1024.0
    g = bst._gbdt
    lr = g.learner
    phys = g._phys if g._phys is not None else g._phys_carrier
    ghi_mb = (g._phys[1].nbytes / 1e6 if g._phys is not None else
              32.0 * rows / 1e6)
    residents = 1 if phys is not None else 0
    ing = getattr(lr, "_ingest", None)
    for cand in (getattr(ing, "buffer", None),
                 getattr(lr, "_part0", None)):
        if cand is not None and not cand.is_deleted():
            residents += 1
    snap = obs_memory.snapshot()
    train_state = snap["owners"].get("train.state", {})
    ledger_ok = (phys is None or
                 train_state.get("device_unique_bytes", 0)
                 >= int(phys[0].nbytes))
    # budget: ghi + scores/trees + jitted fused program's arena.  The
    # binned term is 0.25x SLACK, not a copy allowance — the pre-change
    # 3x layout held 2 extra binned copies and fails this budget at any
    # size where binned dominates the fixed terms
    budget_mb = 0.25 * binned_mb + 2.0 * ghi_mb + 640.0
    rss_ok = rss_delta_mb <= budget_mb
    cell = {
        "rows": rows, "features": features, "iters": iters,
        "train_s": round(train_s, 3),
        "iters_per_s": round(iters / train_s, 2) if train_s > 0 else None,
        "binned_mb": round(binned_mb, 1),
        "ghi_mb": round(ghi_mb, 1),
        "train_rss_delta_mb": round(rss_delta_mb, 1),
        "budget_mb": round(budget_mb, 1),
        "binned_residents": residents,
        "host_binned_freed": inner.binned is None,
        "ledger_ok": bool(ledger_ok),
        "rss_ok": bool(rss_ok),
    }
    return cell, bool(rss_ok and ledger_ok and residents == 1)


def main_trainmem(args) -> int:
    import jax

    from lightgbm_tpu.obs import benchio
    if args.rows or args.features:
        rows = [int(r) for r in (args.rows or "800000").split(",")]
        feats = [int(f) for f in (args.features or "32").split(",")]
        grid = [(r, f) for r in rows for f in feats]
    elif args.smoke:
        grid = [(120_000, 12)]
    else:
        grid = [(800_000, 32)]
    iters = 3 if args.smoke else 8
    big_rows, big_feats = max(grid)
    cfg = {"rows": big_rows, "features": big_feats, "cells": len(grid),
           "iters": iters, "smoke": bool(args.smoke), "trainmem": True}
    with benchio.abort_guard("profile_construct_trainmem", cfg) as guard:
        cells = []
        ok = True
        for rows, features in grid:
            cell, cell_ok = run_trainmem_cell(rows, features, iters)
            ok = ok and cell_ok
            cells.append(cell)
            print(f"# {rows}x{features}x{iters}it: train {cell['train_s']}s"
                  f" rss +{cell['train_rss_delta_mb']}MB (budget "
                  f"{cell['budget_mb']}MB, binned {cell['binned_mb']}MB) "
                  f"residents={cell['binned_residents']} "
                  f"ledger_ok={cell['ledger_ok']}", file=sys.stderr)
        big = [c for c in cells
               if (c["rows"], c["features"]) == (big_rows, big_feats)][0]
        rec = {"grid": cells, "ok": bool(ok),
               "backend": jax.default_backend(), "smoke": bool(args.smoke),
               "trainmem": True}
        guard.write(rec,
                    metrics={"train_s": big["train_s"],
                             "train_rss_delta_mb": big["train_rss_delta_mb"],
                             "binned_mb": big["binned_mb"],
                             "binned_residents": big["binned_residents"]},
                    rows=big_rows, features=big_feats)
    print(json.dumps(rec))
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-sized grid for tier-1")
    ap.add_argument("--oocore", action="store_true",
                    help="out-of-core lane: sketch + streaming "
                         "construction from synthetic sequences with "
                         "peak-RSS tracking and sketch-vs-exact parity")
    ap.add_argument("--trainmem", action="store_true",
                    help="training-memory lane: stream-construct, train "
                         "N fused iterations, gate peak RSS delta and "
                         "single-copy binned residency")
    ap.add_argument("--rows", type=str, default="",
                    help="comma-separated row counts (overrides grid)")
    ap.add_argument("--features", type=str, default="",
                    help="comma-separated feature counts")
    args = ap.parse_args(argv)
    if args.oocore:
        return main_oocore(args)
    if args.trainmem:
        return main_trainmem(args)

    if args.rows or args.features:
        rows = [int(r) for r in (args.rows or "100000").split(",")]
        feats = [int(f) for f in (args.features or "20").split(",")]
        grid = [(r, f) for r in rows for f in feats]
    elif args.smoke:
        grid = [(20000, 10), (50000, 20)]
    else:
        grid = [(100_000, 20), (100_000, 100),
                (1_000_000, 20), (1_000_000, 100)]

    import jax

    from lightgbm_tpu.obs import benchio
    big_rows, big_feats = max(grid)
    cfg = {"rows": big_rows, "features": big_feats,
           "cells": len(grid), "smoke": bool(args.smoke)}
    # export-on-failure guard: a crashed cell still drops an aborted
    # BENCH_obs artifact + BENCH_history.jsonl trajectory entry
    with benchio.abort_guard("profile_construct", cfg) as guard:
        cells = []
        parity_ok = True
        for rows, features in grid:
            cell, parity = run_cell(rows, features)
            parity_ok = parity_ok and parity
            cells.append(cell)
            print(f"# {rows}x{features}: host {cell['host_loop_s']}s "
                  f"vec {cell['vectorized_s']}s "
                  f"({cell['speedup']}x) device-map "
                  f"{cell['device_map_s']}", file=sys.stderr)
        rec = {"grid": cells, "parity_ok": bool(parity_ok),
               "backend": jax.default_backend(), "smoke": bool(args.smoke)}
        big = [c for c in cells
               if (c["rows"], c["features"]) == (big_rows, big_feats)][0]
        guard.write(rec,
                    metrics={"vectorized_s": big["vectorized_s"],
                             "host_loop_s": big["host_loop_s"],
                             "construct_speedup": big["speedup"] or 0.0},
                    rows=big_rows, features=big_feats)
    print(json.dumps(rec))
    return 0 if parity_ok else 1


if __name__ == "__main__":
    sys.exit(main())
