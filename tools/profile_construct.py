"""Profile dataset construction: host per-feature loop vs vectorized vs
device (ops/construct.py).

Times, for each (rows, features) grid cell:

* ``host_loop_s``   — end-to-end ``BinnedDataset.from_matrix`` through
  the original per-feature Python loops (``construct_device=off``, the
  oracle).
* ``vectorized_s``  — the same construction through the batched path
  (``construct_device=auto``: one column-wise sort for bin finding, one
  batched searchsorted for the mapping, matmul EFB conflicts, streaming
  device ingest).
* ``device_map_s``  — the values->bins mapping stage alone executed on
  the default JAX backend via the SAME BatchedMapper code path
  (``jnp`` instead of ``numpy``), including the host->device transfer;
  null when the backend is unavailable.

Parity (binned matrices bit-identical between arms) is asserted on
every cell.  Prints ONE JSON line:

  {"grid": [{rows, features, host_loop_s, vectorized_s, speedup,
             device_map_s}...],
   "parity_ok": true, "backend": "...", "smoke": bool}

``--smoke`` runs a seconds-sized grid (tier-1 wiring:
tests/test_construct_device.py); the full grid tops out at 1M x 100 —
the PERF.md acceptance cell (>= 4x vectorized vs host loop on CPU).

On-device A/B (run where a TPU is attached):
  JAX_PLATFORMS=tpu python tools/profile_construct.py
  JAX_PLATFORMS=cpu python tools/profile_construct.py
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _make_matrix(rows: int, features: int, seed: int = 0) -> np.ndarray:
    """Mixed-shape matrix: dense normals, sparse (EFB-candidate)
    columns, one NaN column, one few-distinct column."""
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, features))
    for j in range(0, features, 4):            # every 4th column sparse
        X[:, j] = np.where(rng.rand(rows) < 0.9, 0.0, X[:, j])
    if features > 2:
        X[rng.rand(rows) < 0.05, 2] = np.nan
    if features > 3:
        X[:, 3] = rng.randint(0, 12, size=rows).astype(float)
    return X


def _construct(X, mode: str):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    cfg = Config({"verbosity": -1, "construct_device": mode})
    t0 = time.time()
    ds = BinnedDataset.from_matrix(X, cfg, label=X[:, 0])
    dt = time.time() - t0
    return ds, dt


def _device_map_time(ds, X):
    """The batched mapping stage on the default JAX backend (jnp code
    path of BatchedMapper.map_chunk), transfer included."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        return None
    try:
        bmap = ds.batched_mapper()
        sub = np.asarray(X[:, ds.used_features], dtype=np.float64)
        out = bmap.map_chunk(jnp.asarray(sub), xp=jnp)   # compile+warm
        jax.block_until_ready(out)
        t0 = time.time()
        out = bmap.map_chunk(jnp.asarray(sub), xp=jnp)
        jax.block_until_ready(out)
        return time.time() - t0
    except Exception:
        return None


def run_cell(rows: int, features: int):
    X = _make_matrix(rows, features)
    ds_oracle, host_s = _construct(X, "off")
    ds_vec, vec_s = _construct(X, "auto")
    parity = (
        [bm.to_dict() for bm in ds_oracle.bin_mappers]
        == [bm.to_dict() for bm in ds_vec.bin_mappers]
        and [(g.feature_indices, g.num_total_bin, g.bin_offsets)
             for g in ds_oracle.groups]
        == [(g.feature_indices, g.num_total_bin, g.bin_offsets)
            for g in ds_vec.groups]
        and np.array_equal(ds_oracle.binned, ds_vec.host_binned()))
    dev_s = _device_map_time(ds_vec, X)
    return {
        "rows": rows, "features": features,
        "host_loop_s": round(host_s, 3),
        "vectorized_s": round(vec_s, 3),
        "speedup": round(host_s / vec_s, 2) if vec_s > 0 else None,
        "device_map_s": round(dev_s, 3) if dev_s is not None else None,
    }, parity


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-sized grid for tier-1")
    ap.add_argument("--rows", type=str, default="",
                    help="comma-separated row counts (overrides grid)")
    ap.add_argument("--features", type=str, default="",
                    help="comma-separated feature counts")
    args = ap.parse_args(argv)

    if args.rows or args.features:
        rows = [int(r) for r in (args.rows or "100000").split(",")]
        feats = [int(f) for f in (args.features or "20").split(",")]
        grid = [(r, f) for r in rows for f in feats]
    elif args.smoke:
        grid = [(20000, 10), (50000, 20)]
    else:
        grid = [(100_000, 20), (100_000, 100),
                (1_000_000, 20), (1_000_000, 100)]

    import jax

    from lightgbm_tpu.obs import benchio
    big_rows, big_feats = max(grid)
    cfg = {"rows": big_rows, "features": big_feats,
           "cells": len(grid), "smoke": bool(args.smoke)}
    # export-on-failure guard: a crashed cell still drops an aborted
    # BENCH_obs artifact + BENCH_history.jsonl trajectory entry
    with benchio.abort_guard("profile_construct", cfg) as guard:
        cells = []
        parity_ok = True
        for rows, features in grid:
            cell, parity = run_cell(rows, features)
            parity_ok = parity_ok and parity
            cells.append(cell)
            print(f"# {rows}x{features}: host {cell['host_loop_s']}s "
                  f"vec {cell['vectorized_s']}s "
                  f"({cell['speedup']}x) device-map "
                  f"{cell['device_map_s']}", file=sys.stderr)
        rec = {"grid": cells, "parity_ok": bool(parity_ok),
               "backend": jax.default_backend(), "smoke": bool(args.smoke)}
        big = [c for c in cells
               if (c["rows"], c["features"]) == (big_rows, big_feats)][0]
        guard.write(rec,
                    metrics={"vectorized_s": big["vectorized_s"],
                             "host_loop_s": big["host_loop_s"],
                             "construct_speedup": big["speedup"] or 0.0},
                    rows=big_rows, features=big_feats)
    print(json.dumps(rec))
    return 0 if parity_ok else 1


if __name__ == "__main__":
    sys.exit(main())
