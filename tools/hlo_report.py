"""HLO op-count report for the compiled tree-build while-body (CLI).

The parsing/compile core lives in ``lightgbm_tpu.analysis.hlo`` so the
jaxlint Tier B artifact checks (tools/jaxlint.py, jaxlint_baseline.json)
and the tier-1 guards (tests/test_hlo_guard.py) share one
implementation; this module keeps the original CLI and import surface:

    python tools/hlo_report.py                       # default path
    python tools/hlo_report.py --param tpu_megakernel=xla
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.analysis.hlo import (  # noqa: E402,F401
    _OP_RE, _SHAPE_RE, _computation_blocks, _while_bodies, body_counts,
    compile_tree_build, entry_name, report,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--param", action="append", metavar="K=V",
                    help="booster param override (repeatable)")
    args = ap.parse_args()
    params = {}
    for it in args.param or []:
        k, v = it.split("=", 1)
        try:
            v = int(v)
        except ValueError:
            pass
        params[k] = v
    print(json.dumps(report(params), indent=2))


if __name__ == "__main__":
    main()
