"""jaxlint CLI — static analysis + compile-artifact guards.

    python tools/jaxlint.py                  # human-readable report
    python tools/jaxlint.py --check          # exit 1 on any non-baseline
                                             # finding / budget breach /
                                             # stale baseline entry
    python tools/jaxlint.py --json           # one JSON line per finding,
                                             # budget metric and problem
    python tools/jaxlint.py --tier a         # AST lint only (fast)
    python tools/jaxlint.py --tier b         # artifact budgets only
    python tools/jaxlint.py --tier c         # concurrency lint only (fast)
    python tools/jaxlint.py --update-baseline  # rewrite the ratchet

Tier A/C findings and Tier B budgets are compared against the
committed ``jaxlint_baseline.json`` (see
lightgbm_tpu/analysis/baseline.py for the ratchet rules).  Tiers A and
C are pure-stdlib AST passes; tier B compiles the designated entry
points on the current backend, so run it with ``JAX_PLATFORMS=cpu``
for the tier-1-equivalent numbers.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _load_standalone(modname: str, relpath: str):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        modname, os.path.join(REPO_ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod      # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod

# HLO budget headroom for legitimate toolchain drift (mirrors
# tests/test_hlo_guard.py's ~50% ceilings); invariant metrics (pinned
# at 0/1 exact) never get headroom — baseline.make skips zero values.
TIER_B_HEADROOM = {
    "while_body.default": {"total_ops": 60, "fusions": 30, "copies": 8},
    "while_body.mega": {"copies": 8},
    # serving.transfers gets NO headroom on purpose: zero entry copies
    # / transfers / callbacks in the serving program is an invariant,
    # not a drifting count
    "shap.kernel": {"entry_copies": 6},
    # linear.gain's delta metrics are invariants (constant-mode bodies
    # bit-identical with the leafwise machinery present); only the
    # leafwise body's own op count drifts with the toolchain
    "linear.gain": {"leafwise_total_ops": 90},
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on any non-baseline finding")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one machine-readable JSON line per finding")
    ap.add_argument("--tier", choices=("a", "b", "c", "all"),
                    default="all")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: <root>/jaxlint_baseline.json)")
    args = ap.parse_args(argv)

    if args.tier in ("a", "c"):
        # Tiers A and C are pure stdlib: load the lint modules straight
        # from their files so a lint-only run (CI fast lane,
        # pre-commit) never pays the package's jax import
        astlint = _load_standalone("jaxlint_astlint",
                                   "lightgbm_tpu/analysis/astlint.py")
        conlint = _load_standalone("jaxlint_conlint",
                                   "lightgbm_tpu/analysis/conlint.py")
        baseline = _load_standalone("jaxlint_baseline_mod",
                                    "lightgbm_tpu/analysis/baseline.py")
    else:
        from lightgbm_tpu.analysis import astlint, baseline, conlint

    bl_path = args.baseline or os.path.join(args.root,
                                            baseline.DEFAULT_BASELINE)
    bl = baseline.load(bl_path)
    problems = []
    findings = []
    counts = {}
    tier_b = {}
    c_findings = []
    c_counts = {}

    if args.tier in ("a", "all"):
        findings = astlint.lint_tree(args.root)
        counts = astlint.finding_counts(findings)
        problems += baseline.compare_tier_a(counts, bl)

    if args.tier in ("b", "all"):
        from lightgbm_tpu.analysis import artifacts
        tier_b = artifacts.collect_tier_b()
        problems += baseline.compare_tier_b(tier_b, bl)

    if args.tier in ("c", "all"):
        c_findings = conlint.lint_tree(args.root)
        c_counts = conlint.finding_counts(c_findings)
        problems += baseline.compare_tier_c(c_counts, bl)

    if args.update_baseline:
        if args.tier != "all":
            print("--update-baseline needs --tier all (the baseline "
                  "document covers every tier)", file=sys.stderr)
            return 2
        baseline.save(bl_path, baseline.make(counts, tier_b,
                                             headroom=TIER_B_HEADROOM,
                                             tier_c_counts=c_counts))
        print(f"wrote {bl_path}")
        return 0

    if args.as_json:
        for f in findings:
            print(f.to_json())
        for f in c_findings:
            print(f.to_json())
        for check, metrics in sorted(tier_b.items()):
            budgets = bl.get("tier_b", {}).get(check, {})
            for metric, value in sorted(metrics.items()):
                import json as _json
                print(_json.dumps({"tier": "B", "check": check,
                                   "metric": metric, "value": value,
                                   "budget": budgets.get(metric)},
                                  sort_keys=True))
        for p in problems:
            print(p.to_json())
    else:
        if findings:
            print(f"-- tier A: {len(findings)} finding(s) "
                  f"({len(counts)} key(s); baselined keys are OK)")
            for f in findings:
                print("  " + f.render())
        if c_findings:
            print(f"-- tier C: {len(c_findings)} finding(s) "
                  f"({len(c_counts)} key(s); baselined keys are OK)")
            for f in c_findings:
                print("  " + f.render())
        if tier_b:
            print("-- tier B artifact budgets")
            for check, metrics in sorted(tier_b.items()):
                budgets = bl.get("tier_b", {}).get(check, {})
                row = ", ".join(
                    f"{m}={v}/{budgets.get(m, '?')}"
                    for m, v in sorted(metrics.items()))
                print(f"  {check}: {row}   (measured/budget)")
        if problems:
            print(f"-- {len(problems)} problem(s) vs {bl_path}")
            for p in problems:
                print("  " + p.render())
        else:
            print(f"-- clean vs {bl_path}")

    if args.check and problems:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
