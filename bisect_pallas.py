import sys
import numpy as np, jax, jax.numpy as jnp
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, sc_rows_for)
C = int(sys.argv[1]); live = int(sys.argv[2])
G32 = 32
Np = C*34
SCR = sc_rows_for(G32)
rng = np.random.RandomState(1)
pb0 = jnp.asarray(rng.randint(0, 255, (G32, Np)).astype(np.uint8))
pg0 = jnp.asarray(rng.randn(8, Np).astype(np.float32))
sp0 = jnp.zeros((SCR, Np), jnp.int32)
sc = make_scalars(C+7, C*20+13, 3, 0, 0, 200, 5, 1, 100, 0)
out = partition_leaf_pallas(pb0, pg0, sp0, sc, row_chunk=C, ghi_live=live)
print("sum", float(jnp.sum(out[3])))
