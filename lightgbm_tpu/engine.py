"""Training entry points: ``train`` and ``cv``.

TPU-native re-implementation of python-package/lightgbm/engine.py
(train:66, cv:580, CVBooster:339) with the same signatures.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .callback import EarlyStopException
from .config import Config, reset_unknown_param_warnings
from .robustness import faultinject
from .robustness.checkpoint import CheckpointCallback, restore_training_state
from .utils import log
from .utils.log import LightGBMError

__all__ = ["train", "cv", "CVBooster"]


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval=None, init_model=None, keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          resume: Optional[bool] = None) -> Booster:
    """Train a booster (reference: engine.py train:66).

    ``resume=True`` (or ``checkpoint_resume=true`` in params) restores
    the latest checkpoint under ``checkpoint_dir`` and continues the run
    bit-exact with an uninterrupted one (robustness/checkpoint.py);
    requires ``checkpoint_dir`` + ``checkpoint_interval`` (or an explicit
    ``CheckpointCallback`` in ``callbacks``).
    """
    reset_unknown_param_warnings()
    params = dict(params or {})
    # LightGBM 4.x style: a callable objective in params drives the custom
    # gradient path (reference: engine.py train:150-160)
    fobj = None
    if callable(params.get("objective")):
        fobj = params.pop("objective")
        params["objective"] = "none"
    cfg = Config(params)
    if "num_iterations" in {Config.canonical_name(k) for k in params}:
        num_boost_round = cfg.num_iterations

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        booster._continue_from(init_model)

    valid_contain_train = False
    name_valid_sets = []
    if valid_sets is not None:
        user_named = valid_names is not None
        if valid_names is None:
            valid_names = [f"valid_{i}" for i in range(len(valid_sets))]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                valid_contain_train = True
                # the train set keeps the reference's "training" label
                # unless the USER named it (auto-filled valid_i must not
                # leak into eval rows / evals_result keys)
                train_name = valid_names[i] if user_named else "training"
                name_valid_sets.append(train_name)
                # early stopping and eval rows must carry the user's
                # name for the train set (callback.py _is_train_row)
                booster._train_data_name = train_name
                continue
            vs.reference = train_set
            booster.add_valid(vs, valid_names[i])
    if valid_contain_train:
        booster._gbdt.config = booster._gbdt.config.update(
            {"is_provide_training_metric": True})

    callbacks = list(callbacks) if callbacks else []
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=cfg.verbosity >= 1, min_delta=cfg.early_stopping_min_delta))
    # iteration-level checkpointing (robustness/checkpoint.py): auto-wire
    # the callback from checkpoint_dir/checkpoint_interval unless the
    # caller passed one explicitly
    ckpt_cb = next((cb for cb in callbacks
                    if isinstance(cb, CheckpointCallback)), None)
    if (ckpt_cb is None and cfg.checkpoint_dir
            and cfg.checkpoint_interval > 0):
        ckpt_cb = CheckpointCallback(cfg.checkpoint_dir,
                                     cfg.checkpoint_interval,
                                     keep=cfg.checkpoint_keep)
        callbacks.append(ckpt_cb)
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    from . import obs
    from .obs import telemetry as obs_tel

    booster.best_iteration = -1
    begin_iteration = 0
    if resume is None:
        resume = bool(cfg.checkpoint_resume)
    if resume:
        if ckpt_cb is None:
            raise LightGBMError(
                "resume=True needs checkpoint_dir and checkpoint_interval "
                "set (or an explicit CheckpointCallback in callbacks)")
        state = ckpt_cb.manager.latest()
        if state is None:
            log.warning("resume=True but no checkpoint found under %s; "
                        "starting from scratch", ckpt_cb.manager.dir)
        else:
            begin_iteration = restore_training_state(booster, state)
            ckpt_cb.seed_history(state.eval_history)
            log.info("resumed training from checkpoint at iteration %d "
                     "(%s)", begin_iteration, ckpt_cb.manager.dir)
    with obs_tel.span("train.total", rounds=num_boost_round,
                      begin=begin_iteration):
        for i in range(begin_iteration, num_boost_round):
            if faultinject.is_active():
                faultinject.maybe_kill(i)
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=begin_iteration,
                    end_iteration=num_boost_round,
                    evaluation_result_list=None))
            should_stop = booster.update(fobj=fobj)
            evaluation_result_list = []
            if valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            if booster._valid_names:
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=begin_iteration,
                        end_iteration=num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                for item in es.best_score:
                    booster.best_score.setdefault(
                        item[0], {})[item[1]] = item[2]
                break
            if should_stop:
                break
    # the train boundary is already host-synchronized (trees fetched),
    # so attribute HBM to owners here in trace mode
    if obs_tel.get().mode == "trace":
        obs.memory_snapshot()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference: engine.py CVBooster:339)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params, seed,
                  stratified: bool, shuffle: bool, ranking: bool = False):
    """Fold index generator (reference: engine.py _make_n_folds:491-546):
    ranking objectives split by whole query groups, stratified splits
    per class, otherwise plain splits."""
    full_data.construct(params)
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if ranking:
        # split according to groups so no query straddles folds
        # (reference: _LGBMGroupKFold path, engine.py:529-532)
        group_info = np.asarray(full_data.get_group(), dtype=np.int64)
        ngroups = len(group_info)
        starts = np.concatenate([[0], np.cumsum(group_info)])
        gidx = np.arange(ngroups)
        if shuffle:
            rng.shuffle(gidx)
        for chunk in np.array_split(gidx, nfold):
            test_idx = np.concatenate(
                [np.arange(starts[g], starts[g + 1]) for g in sorted(chunk)])
            yield np.setdiff1d(np.arange(num_data), test_idx), test_idx
        return
    if stratified and full_data.get_label() is not None:
        label = np.asarray(full_data.get_label())
        folds = [[] for _ in range(nfold)]
        for cls in np.unique(label):
            idx = np.nonzero(label == cls)[0]
            if shuffle:
                rng.shuffle(idx)
            for i, chunk in enumerate(np.array_split(idx, nfold)):
                folds[i].extend(chunk.tolist())
        test_indices = [np.asarray(sorted(f)) for f in folds]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        test_indices = [np.sort(c) for c in np.array_split(idx, nfold)]
    for test_idx in test_indices:
        train_idx = np.setdiff1d(np.arange(num_data), test_idx)
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, feval=None, init_model=None,
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, Any]:
    """Cross validation (reference: engine.py cv:580)."""
    reset_unknown_param_warnings()
    params = dict(params or {})
    fobj = None
    if callable(params.get("objective")):
        fobj = params.pop("objective")
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config(params)
    if "num_iterations" in {Config.canonical_name(k) for k in params}:
        num_boost_round = cfg.num_iterations
    if cfg.objective in ("lambdarank", "rank_xendcg") and stratified:
        stratified = False

    ranking = cfg.objective in ("lambdarank", "rank_xendcg")
    if folds is not None:
        # sklearn splitter objects expose .split; ranking groups ride as
        # the ``groups`` argument (reference: engine.py:507-517)
        if hasattr(folds, "split"):
            train_set.construct(params)
            num_data = train_set.num_data()
            group_info = train_set.get_group()
            if group_info is not None:
                group_info = np.asarray(group_info, dtype=np.int64)
                flatted_group = np.repeat(
                    np.arange(len(group_info)), repeats=group_info)
            else:
                flatted_group = np.zeros(num_data, dtype=np.int32)
            fold_iter = folds.split(X=np.empty(num_data),
                                    y=train_set.get_label(),
                                    groups=flatted_group)
        elif hasattr(folds, "__iter__"):
            fold_iter = folds
        else:
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object with "
                "split method")
    else:
        fold_iter = _make_n_folds(train_set, nfold, params, seed,
                                  stratified and cfg.objective in (
                                      "binary", "multiclass", "multiclassova"),
                                  shuffle, ranking=ranking)

    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in fold_iter:
        tr = train_set.subset(np.sort(np.asarray(train_idx)), params)
        te = train_set.subset(np.sort(np.asarray(test_idx)), params)
        te.reference = tr
        # per-fold preprocessing hook (reference: engine.py:553-556)
        tparam = params
        if fpreproc is not None:
            tr, te, tparam = fpreproc(tr, te, dict(params))
        bst = Booster(params=tparam, train_set=tr)
        if init_model is not None:
            # before add_valid, so the valid scores seed from the init
            # model's predictions (same order as train(), engine.py:43)
            bst._continue_from(init_model)
        if eval_train_metric:
            bst._gbdt.config = bst._gbdt.config.update(
                {"is_provide_training_metric": True})
        bst.add_valid(te, "valid")
        cvbooster.append(bst)
        fold_data.append((tr, te))

    callbacks = list(callbacks) if callbacks else []
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    es_cb = None
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        es_cb = cfg.early_stopping_round

    results: Dict[str, List[float]] = {}
    best_iter = num_boost_round
    # per-metric early-stopping state (mirrors the early_stopping callback:
    # stop when ANY tracked metric exceeds its patience)
    best_mean: Dict[str, float] = {}
    best_round: Dict[str, int] = {}
    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(
                model=cvbooster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        all_evals: Dict[str, List[float]] = {}
        for bst in cvbooster.boosters:
            bst.update(fobj=fobj)
            evals = []
            if eval_train_metric:
                evals.extend(("train", m, v, hb)
                             for _, m, v, hb in bst.eval_train(feval))
            evals.extend(bst.eval_valid(feval))
            for dname, mname, val, is_max in evals:
                all_evals.setdefault((dname, mname, is_max), []).append(val)
        agg = []     # reference _agg_cv_result rows for the callbacks
        stop_now = False
        for (dname, mname, is_max), vals in all_evals.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results.setdefault(f"{dname} {mname}-mean", []).append(mean)
            results.setdefault(f"{dname} {mname}-stdv", []).append(std)
            agg.append(("cv_agg", f"{dname} {mname}", mean, is_max, std))
            if es_cb is not None and dname == "valid":
                cur = mean if is_max else -mean
                if mname not in best_mean or cur > best_mean[mname]:
                    best_mean[mname] = cur
                    best_round[mname] = i
                elif i - best_round[mname] >= es_cb:
                    stop_now = True
                    best_iter = best_round[mname] + 1
        try:
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg))
        except EarlyStopException as es:
            best_iter = es.best_iteration + 1
            stop_now = True
        if stop_now:
            break
    cvbooster.best_iteration = best_iter
    if best_iter < num_boost_round:
        # reference (engine.py:843-848): truncate the aggregate series
        # to the best iteration and stamp it on the fold boosters so
        # len(results[...]) and predict() defaults are consistent
        for k in results:
            results[k] = results[k][:best_iter]
        for bst in cvbooster.boosters:
            bst.best_iteration = best_iter
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return results
