"""Multi-host network initialization and collectives facade.

TPU-native replacement for the reference's communication backend
(src/network/: socket TCP mesh / MPI with custom Bruck, recursive-halving
and ring collectives; include/LightGBM/network.h:89-275 typed helpers;
`LGBM_NetworkInit` in the C API; application.cpp:171 Network::Init).

On TPU all five collective algorithms collapse into XLA collectives over
ICI/DCN scheduled by the compiler inside `shard_map`/`pjit`; what remains
of the reference's Network layer is (a) process-group bootstrap — here
`jax.distributed.initialize` — and (b) the small set of typed host-level
reductions used outside the jitted learners (config/seed sync, global
sums for metrics), provided below over `jax.experimental.multihost_utils`.

The reference's `machines`/`local_listen_port`/`num_machines` parameters
are accepted and mapped onto the JAX coordinator bootstrap so existing
configs keep working (rank 0's address becomes the coordinator).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import log

_initialized = False


def init_network(machines: Optional[str] = None,
                 local_listen_port: int = 12400,
                 num_machines: int = 1,
                 machine_rank: Optional[int] = None,
                 time_out: int = 120,
                 retries: int = 5,
                 retry_base_delay: float = 1.0) -> None:
    """Initialize multi-host training (reference: Network::Init via
    `LGBM_NetworkInit`, c_api.cpp; socket mesh construction
    linkers_socket.cpp:166).

    `machines` is the reference's comma-separated "host:port,host:port,..."
    list; the FIRST entry is used as the JAX distributed coordinator.  On
    TPU pods where the runtime already knows the topology, calling with
    defaults (or not at all) is fine — `jax.distributed.initialize()`
    auto-detects.

    Hardened bootstrap (robustness/retry.py): a flaky or slow-starting
    coordinator is retried with capped exponential backoff under a
    `time_out`-seconds deadline, rank/num_machines disagreements raise a
    clear error instead of hanging the barrier, and "already
    initialized" errors are never retried.
    """
    global _initialized
    if _initialized:
        return
    import jax

    from ..robustness import faultinject
    from ..robustness.retry import retry_with_backoff
    from ..utils.log import LightGBMError
    if num_machines <= 1 and not machines:
        log.info("init_network: single process; nothing to do")
        _initialized = True
        return
    kwargs = {}
    if machines:
        hosts = [h.strip() for h in str(machines).split(",") if h.strip()]
        if num_machines > 1 and len(hosts) > 1 and len(hosts) != num_machines:
            # every rank hangs on the coordinator barrier if the group
            # sizes disagree; fail fast with the actionable mismatch
            raise LightGBMError(
                f"machines= lists {len(hosts)} hosts but "
                f"num_machines={num_machines}: every rank must agree on "
                "the machine list and num_machines (reference: "
                "config.h network section)")
        coordinator = hosts[0]
        if ":" not in coordinator:
            coordinator = f"{coordinator}:{local_listen_port}"
        kwargs["coordinator_address"] = coordinator
        kwargs["num_processes"] = num_machines if num_machines > 1 \
            else len(hosts)
        if machine_rank is not None:
            kwargs["process_id"] = machine_rank
    kwargs["initialization_timeout"] = time_out

    def _attempt():
        faultinject.maybe_fail_bootstrap()
        jax.distributed.initialize(**kwargs)

    retry_with_backoff(
        _attempt, attempts=max(int(retries), 1),
        base_delay=float(retry_base_delay), deadline=float(time_out),
        fatal_if=lambda e: "already initialized" in str(e).lower(),
        describe="distributed bootstrap (jax.distributed.initialize)")
    expected = int(kwargs.get("num_processes", num_machines) or 0)
    actual = jax.process_count()
    if expected > 1 and actual != expected:
        raise LightGBMError(
            f"distributed bootstrap came up with {actual} process(es) but "
            f"this rank's config says num_machines={expected}: the ranks "
            "disagree on num_machines / the machines list; fix the "
            "per-rank configs (all must be identical)")
    _initialized = True
    log.info("init_network: process %d / %d initialized",
             jax.process_index(), jax.process_count())


def init_from_config(config: Config) -> None:
    """CLI/application entry (reference: application.cpp:169-179 — network
    init followed by cross-rank param sync)."""
    if config.num_machines > 1 or config.machines:
        init_network(machines=config.machines,
                     local_listen_port=config.local_listen_port,
                     num_machines=config.num_machines,
                     time_out=config.time_out,
                     retries=getattr(config, "bootstrap_retries", 5),
                     retry_base_delay=getattr(config, "bootstrap_retry_delay",
                                              1.0))


def num_machines() -> int:
    import jax
    return jax.process_count()


def rank() -> int:
    import jax
    return jax.process_index()


# ---------------------------------------------------------------------------
# Typed host-level reductions (reference: network.h:168-275 GlobalSyncUpBy*)
# ---------------------------------------------------------------------------
def _all_reduce(value: np.ndarray, op: str) -> np.ndarray:
    import jax
    if jax.process_count() <= 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(value))
    if op == "sum":
        return np.sum(gathered, axis=0)
    if op == "min":
        return np.min(gathered, axis=0)
    if op == "max":
        return np.max(gathered, axis=0)
    if op == "mean":
        return np.mean(gathered, axis=0)
    raise ValueError(op)


def global_sync_by_min(value: float) -> float:
    return float(_all_reduce(np.asarray(value), "min"))


def global_sync_by_max(value: float) -> float:
    return float(_all_reduce(np.asarray(value), "max"))


def global_sync_by_mean(value: float) -> float:
    return float(_all_reduce(np.asarray(value), "mean"))


def global_sum(values: Sequence[float]) -> np.ndarray:
    return _all_reduce(np.asarray(values, dtype=np.float64), "sum")


def global_array(value: float) -> List[float]:
    """Each rank's value, indexed by rank (reference: Network::GlobalArray)."""
    import jax
    if jax.process_count() <= 1:
        return [float(value)]
    from jax.experimental import multihost_utils
    return [float(v) for v in
            multihost_utils.process_allgather(np.asarray(value))]


def global_concat(values: np.ndarray) -> np.ndarray:
    """Concatenate every rank's rows (rank order, unequal lengths OK).

    The gather primitive behind exact global non-decomposable metrics
    over rank-sharded rows (e.g. ``distributed_exact_auc``): ranks pad
    their shard to the group max length, allgather once, and strip the
    padding with the gathered true lengths.  (The reference has no
    counterpart — src/metric/ never calls Network; this powers the
    EXACT option layered over the reference-shaped weighted-mean
    default, see models/metric.py _rank_mean.)"""
    import jax
    arr = np.asarray(values)
    if jax.process_count() <= 1:
        return arr
    from jax.experimental import multihost_utils
    n_local = arr.shape[0]
    sizes = multihost_utils.process_allgather(
        np.asarray(n_local, dtype=np.int64))
    n_max = int(np.max(sizes))
    if n_max > n_local:
        pad = np.zeros((n_max - n_local,) + arr.shape[1:], arr.dtype)
        arr = np.concatenate([arr, pad], axis=0)
    gathered = multihost_utils.process_allgather(arr)   # (P, n_max, ...)
    return np.concatenate(
        [gathered[p, :int(sizes[p])] for p in range(len(sizes))], axis=0)
