"""Distributed tree learning over a `jax.sharding.Mesh`.

TPU-native replacement for the reference's socket/MPI parallel learners
(src/treelearner/parallel_tree_learner.h, src/network/): the custom
Bruck/recursive-halving collectives become XLA collectives over ICI inside
``shard_map``:

  * ``tree_learner=data``    — rows sharded over the 'data' axis; local
    histograms are summed with ``psum`` (the reference uses ReduceScatter by
    feature then an arg-max Allreduce of SplitInfo,
    data_parallel_tree_learner.cpp:282-441).
  * ``tree_learner=feature`` — rows replicated; per-device feature masks shard
    the split search; the winner is agreed with an all-gather + arg-max
    (feature_parallel_tree_learner.cpp:71).
  * ``tree_learner=voting``  — PV-Tree (voting_parallel_tree_learner.cpp):
    rows sharded, leaf histograms stay device-local; each device votes its
    top-k features by local gain, the global top-2k are elected via a
    ``psum`` of votes, and only the elected features' histograms cross ICI
    before the (globally identical) split evaluation.

World size is fixed for the life of the trainer, matching the reference's
static `Network::Init` posture; recovery is checkpoint/restart.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dataset import BinnedDataset
from ..models.learner import SerialTreeLearner
from ..utils import log

AXIS = "data"


class ShardedTreeBuilder:
    """Builds trees SPMD over an N-device mesh.

    Rows are padded to a multiple of the mesh size; each device holds a
    ``(local_rows + 1, G)`` block whose last row is its sentinel.
    """

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None, mode: Optional[str] = None):
        self.config = config
        self.dataset = dataset
        if mesh is None:
            devices = np.asarray(jax.devices())
            mesh = Mesh(devices, (AXIS,))
        self.mesh = mesh
        self.ndev = mesh.devices.size
        mode = mode or {"data": "data", "feature": "feature",
                        "voting": "voting"}.get(config.tree_learner, "data")
        self.mode = mode
        # multi-process SPMD: `dataset` holds THIS RANK's rows only
        # (rank-sharded by the distributed data plane); each process
        # contributes its block of the global mesh array.  Mirrors the
        # reference's one-rank-per-machine socket/MPI learners
        # (parallel_tree_learner.h) with the collectives moved into XLA.
        self.nproc = jax.process_count()
        self.local_ndev = (len([d for d in self.mesh.devices.flat
                                if d.process_index == jax.process_index()])
                           if self.nproc > 1 else self.ndev)

        def _put(arr, sharding):
            # single-process: plain device_put; multi-process: this rank's
            # block of the global mesh array
            if self.nproc > 1:
                return jax.make_array_from_process_local_data(sharding, arr)
            return jax.device_put(arr, sharding)
        self._put = _put

        # The builder needs its own mesh-sharded layout, not the serial
        # learner's (G, N_pad) pad.  With a live/recoverable device
        # ingest the relayout runs ON DEVICE: one jitted
        # slice-transpose-reshape from the (G, N_pad) master buffer to
        # the per-device (local_n+1, G) blocks, placed by out_shardings
        # — startup never round-trips the matrix through the host.
        # Without one (host-resident dataset; or multi-process, where
        # each rank's dataset holds only ITS row shard and
        # make_array_from_process_local_data wants host blocks), the
        # host path packs rank-local blocks from host_binned(), which
        # now streams in bounded row blocks.
        di = getattr(dataset, "device_ingest", None)
        self._used_device_reshard = di is not None and self.nproc == 1
        if self._used_device_reshard:
            N, G = di.N, di.G           # geometry without materializing
            bin_dtype = np.dtype(di.dtype)
            binned = None
        else:
            binned = dataset.host_binned()
            if binned is None:
                raise ValueError(
                    "dataset has no binned data (construct it first)")
            N, G = binned.shape         # local rows when multi-process
            bin_dtype = binned.dtype
        sent = np.zeros((1, G), dtype=bin_dtype)
        sharding = NamedSharding(self.mesh, P(AXIS))
        if self.nproc > 1:
            from . import network
            if self.mode == "feature":
                # the reference's feature-parallel keeps the FULL data on
                # every machine (docs/Parallel-Learning-Guide.rst); verify
                # the ranks agree on the row count
                if len(set(int(v) for v in network.global_array(
                        float(N)))) != 1:
                    raise ValueError(
                        "tree_learner=feature requires the full dataset "
                        "on every machine (rank row counts differ)")
                self.N = N
            else:
                self.N = int(network.global_sum([float(N)])[0])
            # one static per-device row count across the whole mesh
            self.local_n = int(network.global_sync_by_max(
                float(-(-N // self.local_ndev))))
        else:
            self.N = N
            self.local_n = ((N + self.ndev - 1) // self.ndev
                            if self.mode != "feature" else N)
        if self.mode == "feature":
            self.local_n = self.N
            if self._used_device_reshard:
                self.binned_sharded = self._device_reshard(
                    di, N, G, feature=True)
            else:
                host_binned = np.concatenate([binned, sent])
                self.binned_sharded = _put(host_binned,
                                           NamedSharding(self.mesh, P()))
            counts = [self.N] * self.local_ndev
        elif self._used_device_reshard:
            self.binned_sharded = self._device_reshard(
                di, N, G, feature=False)
            counts = [min(self.local_n, max(0, N - d * self.local_n))
                      for d in range(self.local_ndev)]
        else:
            # blocked binned: (local_ndev * (local_n + 1), G) per process;
            # per-device sentinel row
            blocks = []
            counts = []
            for d in range(self.local_ndev):
                blk = binned[d * self.local_n:(d + 1) * self.local_n]
                counts.append(len(blk))
                if len(blk) < self.local_n:
                    blk = np.concatenate(
                        [blk,
                         np.zeros((self.local_n - len(blk), G), binned.dtype)])
                blocks.append(np.concatenate([blk, sent]))
            host_binned = np.concatenate(blocks, axis=0)
            self.binned_sharded = _put(host_binned, sharding)
        self.local_counts = _put(np.asarray(counts, dtype=np.int32), sharding)
        from ..obs import memory as obs_memory
        obs_memory.register(
            "parallel.binned_sharded", self,
            lambda sb: [sb.binned_sharded, sb.local_counts])
        self.learner = SerialTreeLearner(
            dataset, config, axis_name=AXIS, parallel_mode=mode,
            num_shards=self.ndev, local_num_data=self.local_n)

        lr = self.learner

        def build_shard(binned, grad, hess, bag_cnt, feature_mask, seed,
                        feat_used, lazy_aux):
            # binned: (local_n+1, G); grad/hess: (local_n,); bag_cnt: (1,)
            # local in-bag rows (== local valid rows without sampling)
            C = lr.row0
            part_bins = jnp.pad(
                binned.T, ((0, 0), (C, lr.N_pad - C - binned.shape[0])))
            grad_l = grad[: lr.N]
            hess_l = hess[: lr.N]
            if self.mode == "feature":
                # shard the split search: contiguous feature blocks per device
                d = jax.lax.axis_index(AXIS)
                F = lr.F
                per = (F + self.ndev - 1) // self.ndev
                fidx = jnp.arange(F)
                mine = (fidx >= d * per) & (fidx < (d + 1) * per)
                feature_mask = feature_mask & mine
            aux0 = lazy_aux[:, : lr.N] if lazy_aux is not None else None
            return lr._build_impl(part_bins, grad_l, hess_l,
                                  bag_cnt[0], feature_mask, seed, feat_used,
                                  aux0)

        row_spec = P() if self.mode == "feature" else P(AXIS)
        has_lazy = lr.cegb_lazy is not None
        aux_spec = (P(None, AXIS) if self.mode != "feature" else P()) \
            if has_lazy else None
        in_specs = (row_spec, row_spec, row_spec, P(AXIS), P(), P(), P()) \
            + ((aux_spec,) if has_lazy else ())
        out_specs = (P(), aux_spec) if has_lazy else P()

        def wrapper(binned, grad, hess, bag_cnt, feature_mask, seed,
                    feat_used, *maybe_aux):
            rec = build_shard(binned, grad, hess, bag_cnt, feature_mask,
                              seed, feat_used,
                              maybe_aux[0] if maybe_aux else None)
            # model-lifetime cegb-lazy persistence: scatter this shard's
            # partitioned used-feature bitset back to ITS original rows
            # (shards own contiguous row blocks, so row-sharded output
            # reassembles the full original-order aux)
            aux_out = None
            if has_lazy:
                aux_out = lr.lazy_aux_to_original_order(rec)
            # drop per-shard-varying state (partition arrays and LOCAL leaf
            # offsets/counts) — only globally-identical values may be
            # replicated out; consumers must use leaf_cnt_g
            # ("hist" is also dropped: per-leaf histograms are device-local
            # in voting mode and no consumer reads them — replicating the
            # (L, G, B, 2) tensor would cost a full all-reduce per tree)
            rec = {k: v for k, v in rec.items()
                   if k not in ("indices", "part_bins", "part_grad",
                                "part_hess", "part_ghi", "sc32",
                                "sc_bins", "sc_ghi",
                                "part_aux", "sc_aux",
                                "leaf_start", "leaf_cnt", "hist")}

            def replicate(x):
                # values are identical on every device; pmax proves
                # replication to shard_map's type system
                if x.dtype == jnp.bool_:
                    return jax.lax.pmax(x.astype(jnp.int32), AXIS).astype(jnp.bool_)
                return jax.lax.pmax(x, AXIS)

            rec = jax.tree.map(replicate, rec)
            if has_lazy:
                if self.mode == "feature":
                    # rows replicated: the aux is identical on every device
                    aux_out = jax.lax.pmax(aux_out, AXIS)
                return rec, aux_out
            return rec

        from ..utils.compat import shard_map as _compat_shard_map
        self._build_sharded = jax.jit(_compat_shard_map(
            wrapper, mesh=self.mesh,
            in_specs=in_specs, out_specs=out_specs))

    # ------------------------------------------------------------------
    def _device_reshard(self, di, N: int, G: int, feature: bool):
        """On-device relayout of the ingest master buffer to the mesh
        layout: ``(G, N_pad)`` column-major rows → per-device
        ``(local_n+1, G)`` blocks (zero row pad + zero sentinel row),
        bit-identical to the host blocked packing.  One jitted program;
        ``out_shardings`` places the blocks, so the matrix never visits
        the host and no (N, G) host copy materializes."""
        C = di.row0
        buf = di.live_buffer()
        ndev, local_n = self.ndev, self.local_n
        if feature:
            spec = P()                    # rows replicated per device

            def relay(b):
                bt = b[:G, C:C + N].T
                return jnp.concatenate(
                    [bt, jnp.zeros((1, G), bt.dtype)], axis=0)
        else:
            spec = P(AXIS)
            total = ndev * local_n

            def relay(b):
                bt = b[:G, C:C + N].T                      # (N, G)
                bt = jnp.pad(bt, ((0, total - N), (0, 0)))
                bt = bt.reshape(ndev, local_n, G)
                bt = jnp.concatenate(
                    [bt, jnp.zeros((ndev, 1, G), bt.dtype)], axis=1)
                return bt.reshape(ndev * (local_n + 1), G)
        # once-per-startup relayout: the trace is the product (shapes
        # differ per dataset, nothing to rebind)
        return jax.jit(relay,                    # jaxlint: ok=JL002
                       out_shardings=NamedSharding(self.mesh, spec))(buf)

    def pad_rows(self, arr: np.ndarray) -> jnp.ndarray:
        """Pad a per-row array (process-local rows when multi-process) to
        the mesh row layout and shard it."""
        arr = np.asarray(arr, dtype=np.float32)
        if self.mode == "feature":
            return self._put(arr, NamedSharding(self.mesh, P()))
        total = self.local_ndev * self.local_n
        if len(arr) < total:
            arr = np.concatenate([arr, np.zeros(total - len(arr), np.float32)])
        return self._put(arr, NamedSharding(self.mesh, P(AXIS)))

    def pad_aux(self, aux) -> jnp.ndarray:
        """Shard the (aux_rows, N) cegb-lazy bitset over the mesh rows
        (replicated under feature-parallel).  The previous iteration's
        sharded output passes through untouched — build_tree returns the
        aux in mesh layout so it never materializes on the host (the
        shards may not even be host-addressable under multi-process)."""
        lr = self.learner
        # the pass-through check sees the GLOBAL array shape (all mesh
        # devices), while host-side padding below builds the LOCAL block
        total_global = (self.N if self.mode == "feature"
                        else self.ndev * self.local_n)
        if isinstance(aux, jax.Array) and aux.ndim == 2 \
                and aux.shape[1] == total_global and aux.dtype == jnp.int32:
            return aux
        if aux is None:
            aux = np.zeros((lr.aux_rows, self.N), np.int32)
        aux = np.asarray(aux, dtype=np.int32)
        if self.mode == "feature":
            return self._put(aux, NamedSharding(self.mesh, P()))
        total_local = self.local_ndev * self.local_n
        if aux.shape[1] < total_local:
            aux = np.concatenate(
                [aux, np.zeros((aux.shape[0], total_local - aux.shape[1]),
                               np.int32)], axis=1)
        return self._put(aux, NamedSharding(self.mesh, P(None, AXIS)))

    def build_tree(self, grad, hess, feature_mask=None,
                   seed: int = 0, feat_used=None,
                   bag_mask=None, lazy_aux=None):
        lr = self.learner
        if feature_mask is None:
            feature_mask = jnp.ones((lr.F,), dtype=bool)
        if feat_used is None:
            feat_used = jnp.zeros((lr.F,), dtype=bool)
        if bag_mask is None:
            bag_counts = self.local_counts
        else:
            # bagging/GOSS masks are full-length row predicates; each shard
            # needs ITS in-bag count for count estimation (the reference's
            # bagging composes with every parallel learner, bagging.hpp:13)
            m = np.asarray(bag_mask).astype(bool)
            if self.mode == "feature":
                counts = [int(m.sum())] * self.local_ndev
            else:
                counts = [int(m[d * self.local_n:(d + 1) * self.local_n]
                              .sum()) for d in range(self.local_ndev)]
            bag_counts = self._put(np.asarray(counts, np.int32),
                                   NamedSharding(self.mesh, P(AXIS)))
        args = (self.binned_sharded, self.pad_rows(grad),
                self.pad_rows(hess), bag_counts,
                feature_mask, jnp.int32(seed), feat_used)
        if self.learner.cegb_lazy is not None:
            return self._build_sharded(*args, self.pad_aux(lazy_aux))
        return self._build_sharded(*args)

    def _build_lowered_hlo(self, grad, hess) -> str:
        """Optimized HLO of the sharded tree build (test/inspection hook:
        verifies which collectives the histogram sync lowers to)."""
        lr = self.learner
        args = (self.binned_sharded, self.pad_rows(grad),
                self.pad_rows(hess), self.local_counts,
                jnp.ones((lr.F,), dtype=bool), jnp.int32(0),
                jnp.zeros((lr.F,), dtype=bool))
        return self._build_sharded.lower(*args).compile().as_text()
