"""Distributed training subpackage: mesh tree builders + multi-host network.

reference analog: src/network/ (collectives + linkers) and the parallel
tree learners of src/treelearner/parallel_tree_learner.h.
"""

from .network import (global_array, global_sum, global_sync_by_max,
                      global_sync_by_mean, global_sync_by_min,
                      init_network, num_machines, rank)
from .trainer import ShardedTreeBuilder

__all__ = ["ShardedTreeBuilder", "init_network", "num_machines", "rank",
           "global_sum", "global_array", "global_sync_by_min",
           "global_sync_by_max", "global_sync_by_mean"]
