"""Distributed data plane: rank-sharded loading and bin-mapper sync.

TPU-native port of the reference's distributed loading protocol
(src/io/dataset_loader.cpp):
  * `LoadFromFile(rank, num_machines)` keeps only this rank's rows —
    round-robin when the file is not pre-partitioned (:203);
  * bin mappers are found FEATURE-SHARDED (each rank bins its slice of
    the feature space from its local sample) and exchanged so every rank
    ends with the identical full mapper set (:658-740, the Allgather of
    serialized BinMappers at :1228-1236);
  * `num_total_features` agrees by max (:602).

The exchange rides the typed host-level helpers in
``parallel/network.py`` (jax.experimental.multihost_utils); with a
single process everything degrades to local computation.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ..utils import log
from . import network


def rank_shard_indices(n: int, rank: int, num_machines: int,
                       pre_partition: bool = False) -> np.ndarray:
    """Row indices this rank keeps (reference: dataset_loader.cpp:203 —
    round-robin `line % num_machines == rank` unless the input files are
    already pre-partitioned per machine)."""
    if pre_partition or num_machines <= 1:
        return np.arange(n)
    return np.arange(rank, n, num_machines)


def allgather_bin_mappers(local_mappers: dict, num_total_features: int):
    """Exchange feature-sharded BinMappers so every process holds the
    full, identical set.

    Args:
      local_mappers: {feature_index: BinMapper} for THIS rank's feature
        shard (feature f belongs to rank f % num_machines).
      num_total_features: local feature count (synced by max).
    Returns (mappers_by_feature: dict, num_total_features_global).
    """
    from ..ops.binning import BinMapper
    nmach = network.num_machines()
    num_total = int(network.global_sync_by_max(float(num_total_features)))
    if nmach <= 1:
        return dict(local_mappers), num_total
    payload = json.dumps(
        {str(f): bm.to_dict() for f, bm in local_mappers.items()},
        separators=(",", ":")).encode()
    import jax
    from jax.experimental import multihost_utils
    # two-phase exchange: lengths first, then the padded byte tensors
    lens = multihost_utils.process_allgather(
        np.asarray([len(payload)], np.int32))
    maxlen = int(lens.max())
    buf = np.zeros((maxlen,), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    bufs = multihost_utils.process_allgather(buf)
    merged = {}
    for r in range(bufs.shape[0]):
        raw = bytes(bufs[r][:int(lens[r, 0])].tobytes())
        for fs, d in json.loads(raw.decode()).items():
            merged[int(fs)] = BinMapper.from_dict(d)
    missing = [f for f in range(num_total) if f not in merged]
    if missing:
        log.warning("allgather_bin_mappers: features %s missing from every "
                    "rank's shard", missing[:8])
    return merged, num_total


def allgather_feature_sketches(sset):
    """Exchange per-rank feature sketches (each rank sketched only its
    ROW shard, all features) and return the canonical merge — the
    out-of-core twin of ``allgather_bin_mappers``: what crosses rank
    boundaries is one fixed-size sketch state per feature
    (ops/sketch.py), never row samples or the matrix itself.  The merge
    is a pure function of the global value multiset, so every rank
    derives bit-identical BinMappers for ANY rank count or row
    sharding (tests/test_sketch.py asserts 1-vs-4-shard identity)."""
    from ..ops.sketch import SketchSet
    nmach = network.num_machines()
    if nmach <= 1:
        return sset
    payload = sset.serialize()
    from jax.experimental import multihost_utils
    # two-phase exchange: lengths first, then the padded byte tensors
    # (the same wire pattern as allgather_bin_mappers above)
    lens = multihost_utils.process_allgather(
        np.asarray([len(payload)], np.int32))
    maxlen = int(lens.max())
    buf = np.zeros((maxlen,), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    bufs = multihost_utils.process_allgather(buf)
    shards = [SketchSet.deserialize(
        bytes(bufs[r][:int(lens[r, 0])].tobytes()))
        for r in range(bufs.shape[0])]
    return SketchSet.merge(shards)


def sync_config_params(config) -> None:
    """Cross-rank parameter agreement at startup (reference:
    application.cpp:173-179 — the seeds and sampled fractions must match
    on every machine or the replicated split decisions diverge; the
    reference syncs by GlobalSyncUpByMin)."""
    if network.num_machines() <= 1:
        return
    for name in ("seed", "data_random_seed", "bagging_seed",
                 "feature_fraction_seed", "drop_seed", "extra_seed",
                 "objective_seed"):
        if hasattr(config, name) and getattr(config, name) is not None:
            setattr(config, name,
                    int(network.global_sync_by_min(
                        float(getattr(config, name)))))
    for name in ("feature_fraction", "bagging_fraction"):
        if hasattr(config, name):
            setattr(config, name,
                    float(network.global_sync_by_min(
                        float(getattr(config, name)))))
