"""Distributed training on dask collections.

TPU-native re-design of the reference's dask integration
(python-package/lightgbm/dask.py): the reference launches one socket rank
per dask worker (`_train_part` + `LGBM_NetworkInit` over a `machines`
list, dask.py:182-360, 734-795).  In this framework the communication
backend is XLA collectives over a `jax.sharding.Mesh`
(parallel/trainer.py), and TPU hosts are gang-scheduled, so the natural
mapping is:

  * the dask cluster handles the DATA plane — partitions are gathered
    per worker and concatenated in worker order (the reference's
    `_split_to_parts` + per-worker grouping);
  * the TPU mesh handles the COMPUTE plane — training runs on the
    process that holds the accelerator(s), sharding rows over the mesh
    exactly like `tree_learner=data|feature|voting` elsewhere.

This keeps the reference's user-facing API (`DaskLGBMClassifier`,
`DaskLGBMRegressor`, `DaskLGBMRanker` with dask Arrays/DataFrames in,
dask Arrays out of `predict`) while replacing its socket bootstrap with
the mesh runtime.  dask itself remains an optional dependency: the module
imports without it and raises a clear error on use.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
from .utils import log

__all__ = ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]

try:
    import dask
    from dask import array as da
    from dask import dataframe as dd
    from distributed import Client, default_client, wait
    DASK_INSTALLED = True
except ImportError:       # pragma: no cover - exercised via fakes in tests
    dask = None
    da = dd = None
    Client = default_client = wait = None
    DASK_INSTALLED = False


def _require_dask() -> None:
    if not DASK_INSTALLED:
        raise ImportError(
            "dask / distributed are required for lightgbm_tpu.dask; "
            "install them or use the plain sklearn API")


def _is_dask_collection(x: Any) -> bool:
    return hasattr(x, "dask") and (hasattr(x, "to_delayed")
                                   or hasattr(x, "compute"))


def _materialize_parts(collection, client) -> List[Any]:
    parts = collection.to_delayed()
    parts = list(np.asarray(parts).ravel())
    futures = client.compute(parts)
    wait(futures)
    return futures


def _worker_order(futures, client) -> List[int]:
    """Partition permutation grouped by the worker holding each part
    (the reference's `_split_to_parts` + worker grouping, dask.py:95-160),
    so row order is deterministic per cluster layout."""
    who_has = client.who_has(futures)
    return sorted(
        range(len(futures)),
        key=lambda i: (sorted(who_has.get(futures[i].key, ())), i))


def _concat_parts(parts: List[Any]) -> np.ndarray:
    if not parts:
        raise ValueError("empty dask collection")
    first = parts[0]
    if hasattr(first, "values"):          # pandas
        parts = [np.asarray(p) for p in parts]
    if first.ndim == 1 or (hasattr(first, "ndim") and first.ndim == 1):
        return np.concatenate([np.asarray(p).reshape(-1) for p in parts])
    return np.concatenate([np.asarray(p) for p in parts], axis=0)


def _group_parts_by_worker(futures, client):
    """{worker_address: [future, ...]} in deterministic partition order
    (the reference's _split_to_parts + worker grouping, dask.py:95-160)."""
    who_has = client.who_has(futures)
    by_worker: dict = {}
    for i, f in enumerate(futures):
        owners = sorted(who_has.get(f.key, ()))
        w = owners[0] if owners else None
        by_worker.setdefault(w, []).append(f)
    return by_worker


def _free_port() -> int:
    """Bind-then-release a kernel-assigned port (runs ON the rank-0
    worker so the probed port is free on the coordinator HOST)."""
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return int(s.getsockname()[1])
    finally:
        s.close()


def _probe_coordinator_port(client, worker) -> int:
    """Free port on the rank-0 worker's host via bind-then-release —
    unlike a uuid-derived draw from a fixed range, two concurrent
    distributed fits can't collide (ADVICE round 5).  Falls back to the
    derived draw if the probe task itself fails."""
    try:
        return int(client.submit(_free_port, workers=[worker],
                                 allow_other_workers=False,
                                 pure=False).result())
    except Exception as exc:
        import uuid
        port = 12400 + (uuid.uuid4().int % 4000)
        log.warning("free-port probe on %s failed (%s); falling back to "
                    "derived port %d", worker, exc, port)
        return port


def _train_part(params, num_boost_round, x_parts, y_parts, w_parts,
                g_parts, classes, rank, num_machines, coordinator):
    """One rank of the distributed training job, executed ON a dask
    worker against its LOCAL partitions (reference: dask.py:182-360
    _train_part + LGBM_NetworkInit — here the network layer is
    jax.distributed + the multi-process mesh trainer, so the client
    never materializes any data).  Class encoding uses the CLUSTER-wide
    class set (a shard missing a class must not collapse num_class).
    Returns the model text on rank 0."""
    import numpy as np

    import jax

    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_machines,
                                   process_id=rank)
    except RuntimeError as exc:
        # the XLA backend is already up on this worker (a prior task
        # touched JAX): acceptable only if this process already belongs
        # to an equivalent process group.  jax.distributed.initialize is
        # once-per-process, so a SECOND distributed fit on persistent
        # workers with a different group shape can never bootstrap —
        # fail with the remedy instead of a barrier hang / cryptic error
        if (jax.process_count() != num_machines
                or jax.process_index() != rank):
            raise RuntimeError(
                "this dask worker already hosts a jax distributed "
                f"runtime (process {jax.process_index()} of "
                f"{jax.process_count()}) and cannot join this fit as "
                f"rank {rank} of {num_machines}: jax.distributed."
                "initialize is once-per-process, so only ONE distributed "
                "fit per worker process is supported.  Restart the "
                "workers (client.restart()) between distributed fits, "
                "or pass distributed=False to use the gather-to-client "
                "path.") from exc
    import lightgbm_tpu as lgb

    X = np.concatenate([np.asarray(p) for p in x_parts], axis=0)
    y = np.concatenate([np.asarray(p).reshape(-1) for p in y_parts])
    if classes is not None:
        y = np.searchsorted(np.asarray(classes), y).astype(np.float64)
    w = (None if w_parts is None else np.concatenate(
        [np.asarray(p).reshape(-1) for p in w_parts]))
    g = (None if g_parts is None else np.concatenate(
        [np.asarray(p).reshape(-1) for p in g_parts]))
    ds = lgb.Dataset(X, label=y, weight=w, group=g, params=params)
    bst = lgb.train(params, ds, num_boost_round=num_boost_round)
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    if rank == 0:
        return bst.model_to_string()
    return None


class _DaskLGBMModel:
    """Mixin implementing fit/predict over dask collections."""

    def _dask_fit_distributed(self, model_cls, X, y, sample_weight, group,
                              client, **kwargs):
        """Per-worker training: each dask worker becomes a
        jax.distributed rank over ITS resident partitions; nothing is
        gathered to the client (reference posture: dask.py:182-360, one
        socket rank per worker — out-of-core by construction).  Requires
        every aligned collection to share X's partitioning."""
        unsupported = sorted(k for k, v in kwargs.items() if v is not None)
        if unsupported:
            raise ValueError(
                f"fit arguments {unsupported} are not supported by "
                "distributed dask training (each worker trains its own "
                "rank via the native engine); pass distributed=False to "
                "use the gather-to-client path instead")
        X_fut = _materialize_parts(X, client)
        by_worker = _group_parts_by_worker(X_fut, client)
        workers = sorted(k for k in by_worker if k is not None)
        if not workers:
            # who_has resolved no owners (dask-version-dependent key
            # stringification, or futures released between wait and
            # who_has) — fail clearly instead of IndexError below
            raise RuntimeError(
                "could not resolve partition placement via "
                "client.who_has; re-run with distributed=False to use "
                "the gather-to-client path")
        n_machines = len(workers)
        pos_of = {f.key: i for i, f in enumerate(X_fut)}

        def aligned_parts(collection, name):
            if collection is None:
                return {w: None for w in workers}
            fut = _materialize_parts(collection, client)
            if len(fut) != len(X_fut):
                raise ValueError(
                    f"{name} has {len(fut)} partitions but X has "
                    f"{len(X_fut)}; repartition them identically")
            out = {}
            for w in workers:
                idxs = [pos_of[f.key] for f in by_worker[w]]
                out[w] = [fut[i] for i in idxs]
            return out

        y_by = aligned_parts(y, "y")
        w_by = aligned_parts(sample_weight, "sample_weight")
        g_by = aligned_parts(group, "group")

        # estimator-type preparation normally done by the subclass fit
        # (class set, objective); classes come from small PER-PART uniques
        # so labels never gather to the client
        classes = None
        if isinstance(self, LGBMClassifier):
            y_fut = _materialize_parts(y, client)
            uniqs = client.gather([
                client.submit(lambda p: np.unique(np.asarray(p)), f,
                              pure=False) for f in y_fut])
            classes = np.unique(np.concatenate(
                [np.asarray(u).reshape(-1) for u in uniqs]))
            self._classes = classes
            self._n_classes = len(classes)
            if self._n_classes > 2:
                self._objective = self.objective or "multiclass"
                self._other_params["num_class"] = self._n_classes
            elif self.objective is None:
                self._objective = "binary"
        elif isinstance(self, LGBMRanker):
            if self.objective is None:
                self._objective = "lambdarank"
        elif self.objective is None:
            self._objective = "regression"
        params = self._process_params(stage="fit")
        params.setdefault("tree_learner", "data")
        params.pop("n_estimators", None)

        # rank 0's worker hosts the jax.distributed coordinator.  With no
        # explicit local_listen_port, probe a kernel-assigned free port
        # ON that worker (bind-then-release) so concurrent distributed
        # fits on one cluster can't collide at jax.distributed.initialize
        host0 = workers[0].split("://")[-1].rsplit(":", 1)[0]
        if params.get("local_listen_port"):
            port = int(params["local_listen_port"])
        else:
            port = _probe_coordinator_port(client, workers[0])
        coordinator = f"{host0}:{port}"
        log.info("lightgbm_tpu.dask: distributed fit over %d workers "
                 "(%d partitions), coordinator %s",
                 n_machines, len(X_fut), coordinator)
        futures = []
        for rank, w in enumerate(workers):
            futures.append(client.submit(
                _train_part, params, self.n_estimators, by_worker[w],
                y_by[w], w_by[w], g_by[w], classes, rank, n_machines,
                coordinator, workers=[w], allow_other_workers=False,
                pure=False))
        results = client.gather(futures)
        model_str = next(r for r in results if r is not None)
        from .basic import Booster
        self._Booster = Booster(model_str=model_str)
        self._n_features = int(self._Booster.num_feature())
        self.fitted_ = True
        return self

    def _dask_fit(self, model_cls, X, y, sample_weight=None, group=None,
                  client: Optional["Client"] = None,
                  distributed: Optional[bool] = None, **kwargs):
        _require_dask()
        client = client or default_client()
        if not _is_dask_collection(X):
            raise TypeError("X must be a dask Array or DataFrame")
        n_workers = len(client.scheduler_info()["workers"])
        if distributed is None:
            # explicit opt-in: per-worker jax.distributed training
            # requires every dask worker to own its own accelerator /
            # process slot (single-host TPUs enforce single-process
            # ownership), so a multi-worker LocalCluster on one device
            # would crash or hang on the initialize barrier if this
            # defaulted on.  The gather-to-client path is the safe
            # default; pass distributed=True for the per-worker ranks.
            distributed = False
        if distributed and n_workers > 1:
            return self._dask_fit_distributed(
                model_cls, X, y, sample_weight, group, client, **kwargs)
        # ONE placement permutation, derived from X and applied to every
        # aligned collection: ordering each collection by its OWN placement
        # silently misaligns rows and labels whenever corresponding
        # partitions land on different workers (work stealing, rebalance).
        # The reference zips (data, label, weight) into single per-part
        # tuples for the same reason (dask.py:553-571).
        X_fut = _materialize_parts(X, client)
        order = _worker_order(X_fut, client)

        def aligned(collection, name):
            fut = _materialize_parts(collection, client)
            if len(fut) != len(X_fut):
                raise ValueError(
                    f"{name} has {len(fut)} partitions but X has "
                    f"{len(X_fut)}; repartition them identically")
            return _concat_parts([fut[i].result() for i in order])

        X_local = _concat_parts([X_fut[i].result() for i in order])
        y_local = aligned(y, "y")
        w_local = (None if sample_weight is None else
                   aligned(sample_weight, "sample_weight"))
        g_local = (None if group is None else aligned(group, "group"))
        n_workers = len(client.scheduler_info()["workers"])
        if n_workers > 1:
            log.info("lightgbm_tpu.dask: gathered %d partitions from %d "
                     "workers; training on the TPU mesh (rows sharded over "
                     "devices, reference analog: one socket rank per "
                     "worker)", len(X_fut), n_workers)
        fit_kwargs = {}
        if w_local is not None:
            fit_kwargs["sample_weight"] = w_local
        if g_local is not None:
            fit_kwargs["group"] = g_local
        model_cls.fit(self, X_local, y_local, **fit_kwargs, **kwargs)
        return self

    def _dask_predict(self, model_cls, X, method="predict", **kwargs):
        _require_dask()
        if not _is_dask_collection(X):
            return getattr(model_cls, method)(self, X, **kwargs)
        fn = getattr(model_cls, method)

        def block(part):
            return fn(self, part, **kwargs)

        # a column-chunked array would hand partial-feature blocks to the
        # model; collapse axis 1 to one chunk first (reference does the
        # same via map_blocks over row partitions only)
        if getattr(X, "ndim", 1) > 1 and hasattr(X, "rechunk"):
            try:
                if len(X.chunks[1]) > 1:
                    X = X.rechunk({1: X.shape[1]})
            except Exception:
                pass
        # Output width: without explicit chunks dask assumes output chunks
        # equal input chunks, declaring n_features columns while real
        # blocks have num_class / num_trees / contrib columns.  ncols=None
        # means the per-block result is 1-D.  raw_score (and a callable
        # custom objective, whose probabilities can't be computed —
        # sklearn.py predict_proba) return raw margins: 1-D for
        # binary/regression, (rows, num_class) for multiclass.
        nclass = max(int(getattr(self, "_n_classes", 1)), 1)
        multiclass = nclass > 2
        raw_like = bool(kwargs.get("raw_score")) or (
            method == "predict_proba"
            and callable(getattr(self, "_objective", None)))
        if kwargs.get("pred_leaf"):
            try:
                ncols = int(self._Booster.num_trees())
            except Exception:
                ncols = -1          # 2-D, width unknown
        elif kwargs.get("pred_contrib"):
            ncols = (int(X.shape[1]) + 1) * (nclass if multiclass else 1)
        elif method == "predict_proba":
            ncols = nclass if multiclass else (None if raw_like else 2)
        else:
            ncols = nclass if (multiclass and raw_like) else None
        if ncols is not None:
            meta = np.empty((0, 0), dtype=np.float64)
            if ncols > 0 and getattr(X, "chunks", None) is not None:
                return X.map_blocks(block, meta=meta,
                                    chunks=(X.chunks[0], (ncols,)))
            return X.map_blocks(block, meta=meta)
        meta = np.empty((0,), dtype=np.float64)
        return X.map_blocks(block, meta=meta, drop_axis=(
            [1] if getattr(X, "ndim", 1) > 1 else None))

    def _lgb_dask_to_local(self, model_cls):
        """Return the equivalent non-dask estimator (reference:
        DaskLGBMModel.to_local, dask.py:1080)."""
        params = self.get_params()
        params.pop("client", None)
        local = model_cls(**params)
        local.__dict__.update({k: v for k, v in self.__dict__.items()
                               if not k.startswith("_client")})
        return local


class DaskLGBMClassifier(LGBMClassifier, _DaskLGBMModel):
    """Classifier over dask collections (reference: dask.py:1113)."""

    def fit(self, X, y, sample_weight=None, client=None, **kwargs):
        return self._dask_fit(LGBMClassifier, X, y,
                              sample_weight=sample_weight, client=client,
                              **kwargs)

    def predict(self, X, **kwargs):
        return self._dask_predict(LGBMClassifier, X, "predict", **kwargs)

    def predict_proba(self, X, **kwargs):
        return self._dask_predict(LGBMClassifier, X, "predict_proba",
                                  **kwargs)

    def to_local(self) -> LGBMClassifier:
        return self._lgb_dask_to_local(LGBMClassifier)


class DaskLGBMRegressor(LGBMRegressor, _DaskLGBMModel):
    """Regressor over dask collections (reference: dask.py:1316)."""

    def fit(self, X, y, sample_weight=None, client=None, **kwargs):
        return self._dask_fit(LGBMRegressor, X, y,
                              sample_weight=sample_weight, client=client,
                              **kwargs)

    def predict(self, X, **kwargs):
        return self._dask_predict(LGBMRegressor, X, "predict", **kwargs)

    def to_local(self) -> LGBMRegressor:
        return self._lgb_dask_to_local(LGBMRegressor)


class DaskLGBMRanker(LGBMRanker, _DaskLGBMModel):
    """Ranker over dask collections (reference: dask.py:1483)."""

    def fit(self, X, y, sample_weight=None, group=None, client=None,
            **kwargs):
        return self._dask_fit(LGBMRanker, X, y, sample_weight=sample_weight,
                              group=group, client=client, **kwargs)

    def predict(self, X, **kwargs):
        return self._dask_predict(LGBMRanker, X, "predict", **kwargs)

    def to_local(self) -> LGBMRanker:
        return self._lgb_dask_to_local(LGBMRanker)
