"""lightgbm_tpu — a TPU-native gradient-boosting framework.

A from-scratch JAX/XLA re-design with the capabilities of the reference
LightGBM fork (see SURVEY.md): leaf-wise histogram GBDT on TPU via MXU one-hot
matmul histograms, device-resident binned datasets, GOSS/EFB, the full
objective & metric matrix, DART/RF, data-/feature-/voting-parallel training
over `jax.sharding` meshes, a LightGBM-compatible model format, Python
Dataset/Booster/train/cv and sklearn APIs, and a `config=`-file CLI.
"""

import os as _os

import jax as _jax

# Persistent XLA compilation cache: the jitted tree-builder programs are
# expensive to compile (many bucket-size specializations); cache them across
# processes.  Opt out with LIGHTGBM_TPU_DISABLE_COMPILE_CACHE=1.
if _os.environ.get("LIGHTGBM_TPU_DISABLE_COMPILE_CACHE", "0") != "1":
    _cache_dir = _os.environ.get(
        "LIGHTGBM_TPU_COMPILE_CACHE",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "..", ".jax_cache"))
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # older jax without these flags
        pass

from .basic import (Booster, Dataset, LightGBMError, Sequence,
                    TextFileSequence)
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       record_evaluation, reset_parameter)
from .engine import CVBooster, cv, train

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "LightGBMError", "CVBooster",
    "Sequence", "TextFileSequence",
    "train", "cv",
    "early_stopping", "log_evaluation", "record_evaluation", "reset_parameter",
    "EarlyStopException", "CheckpointCallback",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "plot_importance", "plot_metric", "plot_tree", "create_tree_digraph",
    "plot_split_value_histogram", "register_logger",
]


def __getattr__(name):
    # lazy imports to keep base import light
    if name == "register_logger":
        from .utils.log import register_logger
        return register_logger
    if name == "CheckpointCallback":
        from .robustness.checkpoint import CheckpointCallback
        return CheckpointCallback
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("plot_importance", "plot_metric", "plot_tree",
                "create_tree_digraph", "plot_split_value_histogram"):
        from . import plotting as _pl
        return getattr(_pl, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
