"""Training callbacks.

TPU-native re-implementation of python-package/lightgbm/callback.py:
early_stopping (:87), log_evaluation, record_evaluation, reset_parameter —
same semantics and CallbackEnv structure.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional

from .utils import log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list)
            log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def _is_train_row(item, train_name: str = "training") -> bool:
    """True for training-set eval rows, incl. cv aggregate rows labeled
    ("cv_agg", "train <metric>", ...) (reference: callback.py
    _EarlyStoppingCallback._is_train_set compares against the model's
    ACTUAL train data name, not the literal "training" — a user who
    names the training eval set e.g. "train" must not have train-set
    scores drive early stopping)."""
    return item[0] == train_name or (
        item[0] == "cv_agg" and str(item[1]).startswith("train "))


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """reference: callback.py early_stopping:87 (_EarlyStoppingCallback)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]
    train_name = ["training"]

    def _init(env: CallbackEnv) -> None:
        # the booster's actual train-data name (engine.train stamps it
        # from valid_names).  Read the instance __dict__: CVBooster's
        # __getattr__ manufactures a method for ANY name, so a plain
        # getattr would return a function instead of the default.
        train_name[0] = env.model.__dict__.get("_train_data_name",
                                               "training")
        if not env.evaluation_result_list:
            enabled[0] = False
            log.warning("Early stopping is not available in dart mode" if False
                        else "For early stopping, at least one dataset and "
                        "eval metric is required for evaluation")
            return
        if verbose:
            log.info("Training until validation scores don't improve for %d rounds",
                     stopping_rounds)
        # first metric = first NON-train entry's metric (reference
        # _EarlyStoppingCallback: train sets never drive stopping; under
        # cv the rows are ("cv_agg", "train <m>"/"valid <m>", ...))
        non_train = [it for it in env.evaluation_result_list
                     if not _is_train_row(it, train_name[0])]
        first_metric[0] = (non_train[0][1].split(" ")[-1] if non_train
                           else env.evaluation_result_list[0][1])
        for item in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if item[3]:  # is_max_better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y + min_delta)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y - min_delta)

    def _callback(env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            _init(env)
        if not enabled[0]:
            return
        for i, item in enumerate(env.evaluation_result_list):
            score = item[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != item[1].split(" ")[-1]:
                continue
            if _is_train_row(item, train_name[0]):
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info("Early stopping, best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    log.info("Did not meet early stopping. Best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
