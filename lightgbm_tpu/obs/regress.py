"""Persisted benchmark trajectory + noise-aware perf-regression gate.

Twelve PERF.md rounds of honest measurements lived as hand-transcribed
prose, and every ``BENCH_obs.json`` artifact was written once and
discarded — nothing could *detect* a perf regression.  This module is
the substrate that fixes that:

* **Trajectory store** — ``BENCH_history.jsonl``, an append-only JSONL
  log at the repo root (``$BENCH_HISTORY_PATH`` overrides).  Appends go
  through :func:`lightgbm_tpu.obs.exporters._atomic_append` (one
  ``O_APPEND`` write per record, torn-tail detach), so concurrent
  writers interleave whole lines and a crash mid-write loses at most
  the torn line — readers skip unparseable lines and keep going.
* **Hardware/config fingerprint** — every entry is keyed by the things
  that legitimately shift numbers: device kind & count, CPU cores,
  jax/jaxlib versions, the x64 flag, a log2 dataset shape band, and the
  perf-relevant ``tpu_*`` knobs.  Series only ever compare
  same-fingerprint runs, so a 2-core CPU trajectory never gates a TPU
  round and a 16k-row smoke never gates a 10.5M-row headline.
* **Noise-aware change detector** — the exact statistic PERF.md rounds
  10–12 compute by hand: the latest sample vs the median/MAD of its
  same-fingerprint predecessors, flagged only past
  ``max(z * 1.4826 * MAD, floor * median)`` and only after a
  ``min_samples`` warmup, so 2-core CPU noise (measured run-to-run MAD
  ~2–6%) does not false-alarm.

``tools/perfwatch.py`` is the CLI on top (``check`` / ``report`` /
``drill``); :func:`lightgbm_tpu.obs.benchio.write_bench_obs` appends a
trajectory entry for every BENCH_obs artifact, which wires bench.py,
ab_bench.py (all modes), the profile_* tools and the conftest duration
artifact through this store.

The module is host-only by contract: no device ops, no syncs — pinned
by the jaxlint tier-B ``perfwatch.off`` budget (same zero-HLO contract
as ``telemetry.off``) and by JL001 scope covering this file.

Clock injection (``set_clock`` / ``StepClock`` / ``scaled_clock``)
exists for the ``perfwatch drill`` and tests: a planted slowdown is a
scaled clock, never a sleep, so the drill is deterministic.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import math
import os
import statistics
import time
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from .exporters import _atomic_append

SCHEMA = "lightgbm-tpu/bench-history/v1"
DEFAULT_FILENAME = "BENCH_history.jsonl"

# defaults of the change detector (CLI-overridable): warmup sample
# count before anything can regress, the MAD z multiplier, and the
# relative floor that keeps zero-MAD micro-histories from flagging on
# trivial jitter.  Floor 15% sits above the 2-core host's measured
# run-to-run spread (PERF.md: MAD ~2% train / ~6% predict) and far
# below any slowdown worth a round.
MIN_SAMPLES = 3
Z_SCORE = 4.0
FLOOR_PCT = 15.0
_MAD_TO_SIGMA = 1.4826

# booster/config knobs that legitimately shift perf numbers enough to
# split trajectories; anything else (seeds, verbosity, paths) must NOT
# fork the series
_FINGERPRINT_KNOBS = (
    "tpu_row_chunk", "tpu_chunk_policy", "tpu_frontier_k",
    "tpu_megakernel", "tpu_compact_radix", "tpu_kernel_interpret",
    "construct_device", "tree_learner", "num_leaves", "max_bin",
    "telemetry", "health",
)
# producer-config spellings of the same knobs (bench.py/ab_bench.py
# record "leaves"): without the alias, leaf-count changes would not
# fork the series and an intentional config change would false-alarm
_KNOB_ALIASES = {"leaves": "num_leaves"}

__all__ = [
    "SCHEMA", "MIN_SAMPLES", "Z_SCORE", "FLOOR_PCT", "Finding",
    "default_path", "shape_band", "fingerprint", "fingerprint_key",
    "append_entry", "read_history", "evaluate", "regressions",
    "render_report", "metric_direction", "recording", "set_clock",
    "clock", "StepClock", "scaled_clock",
]


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------
def default_path() -> str:
    env = os.environ.get("BENCH_HISTORY_PATH")
    if env:
        return env
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, DEFAULT_FILENAME)


def shape_band(n: Optional[int]) -> Optional[str]:
    """Log2 band of a dataset dimension (``2^17`` holds 65537..131072):
    runs only share a trajectory when their data sits in the same
    power-of-two band — fine enough to separate a smoke from a
    headline, coarse enough that a 5% row-count tweak stays in-series."""
    if n is None or n <= 0:
        return None
    return f"2^{max(int(math.ceil(math.log2(n))), 0)}"


def fingerprint(config: Optional[Dict[str, Any]] = None,
                rows: Optional[int] = None,
                features: Optional[int] = None,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The hardware/software/shape identity a measurement is only
    comparable within.  ``extra`` lets a producer fork its series on
    experiment parameters the knob list cannot know (e.g. ab_bench's
    per-arm overrides — two different A/B experiments must never share
    a trajectory).  jax is imported lazily and optionally so the store
    stays usable from processes that never touch a backend."""
    device_kind, device_count, backend = "none", 0, "none"
    jax_ver, jaxlib_ver, x64 = None, None, False
    try:
        import jax
        backend = jax.default_backend()
        devs = jax.devices()
        device_count = len(devs)
        device_kind = getattr(devs[0], "device_kind", backend)
        jax_ver = jax.__version__
        x64 = bool(jax.config.jax_enable_x64)
        import jaxlib
        jaxlib_ver = getattr(jaxlib, "__version__", None)
    except Exception:
        pass
    cfg = config or {}
    if rows is None:
        rows = cfg.get("rows")
    if features is None:
        features = cfg.get("features")
    knobs = {k: cfg[k] for k in _FINGERPRINT_KNOBS if k in cfg}
    for alias, canon in _KNOB_ALIASES.items():
        if canon not in knobs and alias in cfg:
            knobs[canon] = cfg[alias]
    if extra:
        knobs["extra"] = extra
    return {
        "device_kind": str(device_kind),
        "device_count": int(device_count),
        "backend": str(backend),
        "cpu_count": int(os.cpu_count() or 0),
        "jax": jax_ver,
        "jaxlib": jaxlib_ver,
        "x64": bool(x64),
        "shape_band": {"rows": shape_band(rows),
                       "features": shape_band(features)},
        "knobs": knobs,
    }


def fingerprint_key(fp: Dict[str, Any]) -> str:
    """Stable 12-hex digest of the canonicalized fingerprint — the
    grouping key of the trajectory."""
    canon = json.dumps(fp, sort_keys=True, default=str)
    return hashlib.sha1(canon.encode("utf-8")).hexdigest()[:12]


def append_entry(tool: str, metrics: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None,
                 fingerprint_doc: Optional[Dict[str, Any]] = None,
                 rows: Optional[int] = None,
                 features: Optional[int] = None,
                 aborted: bool = False,
                 path: Optional[str] = None) -> Dict[str, Any]:
    """Append one trajectory record and return it.  ``metrics`` keeps
    only finite numeric scalars; ``aborted`` records that the measured
    tool died — the detector excludes such entries, but the trajectory
    keeps the evidence."""
    fp = fingerprint_doc or fingerprint(config, rows, features)
    clean: Dict[str, float] = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        v = float(v)
        if math.isfinite(v):
            clean[str(k)] = v
    entry = {
        "schema": SCHEMA,
        "unix_time": round(time.time(), 3),
        "tool": str(tool),
        "fingerprint": fp,
        "fingerprint_key": fingerprint_key(fp),
        "metrics": clean,
        "aborted": bool(aborted),
    }
    if config:
        entry["config"] = config
    _atomic_append(path or default_path(),
                   json.dumps(entry, sort_keys=True, default=str))
    return entry


def read_history(path: Optional[str] = None
                 ) -> Tuple[List[Dict[str, Any]], int]:
    """All parseable trajectory entries (append order) plus the count
    of skipped lines — torn tails, interleaving damage and foreign
    lines degrade to data loss of that one line, never a read error."""
    path = path or default_path()
    entries: List[Dict[str, Any]] = []
    skipped = 0
    if not os.path.exists(path):
        return entries, skipped
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if (not isinstance(doc, dict) or doc.get("schema") != SCHEMA
                    or not isinstance(doc.get("metrics"), dict)):
                skipped += 1
                continue
            entries.append(doc)
    return entries, skipped


# ---------------------------------------------------------------------------
# injectable clock + measured recording (the drill's substrate)
# ---------------------------------------------------------------------------
_CLOCK: Callable[[], float] = time.perf_counter


def set_clock(fn: Optional[Callable[[], float]] = None) -> None:
    """Swap the wall clock the recording helper reads (faultinject
    style: process-local, explicit, tests/drills only; ``None``
    restores ``time.perf_counter``)."""
    global _CLOCK
    _CLOCK = fn or time.perf_counter


def clock() -> float:
    return _CLOCK()


class StepClock:
    """Deterministic clock: every read advances a fixed ``dt`` — a
    recorded block measures exactly ``dt`` regardless of host load, so
    drill runs are reproducible bit-for-bit."""

    def __init__(self, dt: float, start: float = 0.0):
        self.dt = float(dt)
        self.now = float(start)

    def __call__(self) -> float:
        self.now += self.dt
        return self.now


def scaled_clock(scale: float,
                 base: Optional[Callable[[], float]] = None
                 ) -> Callable[[], float]:
    """A clock running ``scale`` times faster than ``base`` — the
    planted slowdown of ``perfwatch drill``: a 3x-scaled clock makes an
    unchanged workload *measure* 3x slower, with no sleeps and no
    dependence on the host."""
    base = base or time.perf_counter
    origin = base()

    def _scaled() -> float:
        return origin + (base() - origin) * float(scale)

    return _scaled


@contextlib.contextmanager
def recording(tool: str, metric: str = "wall_s",
              config: Optional[Dict[str, Any]] = None,
              path: Optional[str] = None, **append_kw):
    """Measure the block on the (injectable) clock and append one
    trajectory entry on exit.  The yielded dict takes extra metrics;
    if the block raises, the entry is still appended with
    ``aborted: true`` (the export-on-failure contract) and the error
    propagates."""
    def _append(metrics: Dict[str, Any], aborted: bool) -> None:
        # a failed STORE write must neither sink a finished measurement
        # nor replace the measured block's own exception
        try:
            append_entry(tool, metrics, config=config, aborted=aborted,
                         path=path, **append_kw)
        except OSError as exc:
            from ..utils import log
            log.warning("could not append %s: %s",
                        path or default_path(), exc)

    extra: Dict[str, Any] = {}
    t0 = clock()
    try:
        yield extra
    except BaseException:
        extra[metric] = clock() - t0
        _append(extra, True)
        raise
    extra[metric] = clock() - t0
    _append(extra, False)


# ---------------------------------------------------------------------------
# noise-aware change detection
# ---------------------------------------------------------------------------
# direction of "worse": +1 when a higher value is a regression (time-
# like metrics), -1 when a lower value is (throughput-like).  Metrics
# matching neither are recorded and reported but never gate — gating on
# a metric whose good direction is unknown manufactures false alarms.
_WORSE_HIGH_SUFFIXES = ("_s", "_ms", "_us", "_s_per_iter", "_seconds",
                        "_s_per_mrow")
_WORSE_LOW_SUFFIXES = ("_per_s", "_per_sec", "speedup")
_WORSE_LOW_NAMES = {"vs_baseline"}
# memory metrics: peak/extra footprint is higher-worse.  Checked BEFORE
# the "delta" report-only rule — a "peak_rss_delta_mb" is a bounded
# footprint measurement (how much a phase grew RSS), not a signed
# near-zero A/B difference, so it must gate.
_WORSE_HIGH_MEM_SUFFIXES = ("_mb", "_rss", "_rss_kb", "_bytes")


def metric_direction(name: str) -> int:
    if name.endswith(_WORSE_HIGH_MEM_SUFFIXES):
        return 1
    if "delta" in name:
        # signed difference metrics (ab_bench paired_delta_s) center on
        # ~0, so the relative floor vanishes and small-n MAD alone
        # would gate sub-millisecond jitter — report, never gate
        return 0
    if name in _WORSE_LOW_NAMES or name.endswith(_WORSE_LOW_SUFFIXES):
        return -1
    if name.endswith(_WORSE_HIGH_SUFFIXES) or name == "wall_s":
        return 1
    return 0


@dataclass
class Finding:
    """One (fingerprint, tool, metric) series judged at its latest
    sample."""
    fingerprint_key: str
    tool: str
    metric: str
    value: float
    median: float           # of the prior same-fingerprint samples
    mad: float
    n_prior: int
    direction: int          # +1 higher-is-worse, -1 lower-is-worse, 0 ungated
    threshold: float        # absolute excess-over-median that would flag
    regressed: bool
    status: str             # "ok" | "warmup" | "ungated" | "REGRESSED" | "improved"

    @property
    def delta_pct(self) -> float:
        if self.median == 0:
            return 0.0
        return 100.0 * (self.value - self.median) / abs(self.median)

    def render(self) -> str:
        return (f"[{self.status}] {self.tool}/{self.metric} "
                f"@{self.fingerprint_key}: {self.value:.6g} vs median "
                f"{self.median:.6g} ±{self.mad:.2g} MAD over "
                f"{self.n_prior} run(s) ({self.delta_pct:+.1f}%)")

    def to_json(self) -> str:
        return json.dumps({
            "fingerprint_key": self.fingerprint_key, "tool": self.tool,
            "metric": self.metric, "value": self.value,
            "median": self.median, "mad": self.mad,
            "n_prior": self.n_prior, "direction": self.direction,
            "threshold": self.threshold, "regressed": self.regressed,
            "status": self.status,
            "delta_pct": round(self.delta_pct, 2)}, sort_keys=True)


def _median(values: Sequence[float]) -> float:
    return float(statistics.median(values))


def _series(entries: Iterable[Dict[str, Any]]
            ) -> Dict[Tuple[str, str, str], List[float]]:
    """(fingerprint_key, tool, metric) -> samples in append order,
    aborted entries excluded (a crashed run has no comparable number)."""
    out: Dict[Tuple[str, str, str], List[float]] = {}
    for e in entries:
        if e.get("aborted"):
            continue
        key_base = (str(e.get("fingerprint_key")), str(e.get("tool")))
        for metric, value in e.get("metrics", {}).items():
            if isinstance(value, bool) or not isinstance(value,
                                                         (int, float)):
                continue
            out.setdefault(key_base + (metric,), []).append(float(value))
    return out


def evaluate(entries: Iterable[Dict[str, Any]],
             min_samples: int = MIN_SAMPLES, z: float = Z_SCORE,
             floor_pct: float = FLOOR_PCT) -> List[Finding]:
    """Judge the LATEST sample of every series against the median/MAD
    of its predecessors — the paired statistic PERF.md rounds 10–12
    compute by hand, with an explicit warmup so thin histories never
    gate."""
    findings: List[Finding] = []
    for (fkey, tool, metric), values in sorted(_series(entries).items()):
        prior, last = values[:-1], values[-1]
        direction = metric_direction(metric)
        # even at --min-samples 0 a first-ever sample has nothing to
        # compare against: one prior is the hard floor
        if len(prior) < max(min_samples, 1):
            findings.append(Finding(fkey, tool, metric, last,
                                    _median(prior) if prior else last,
                                    0.0, len(prior), direction, 0.0,
                                    False, "warmup"))
            continue
        med = _median(prior)
        mad = _median([abs(v - med) for v in prior])
        threshold = max(z * _MAD_TO_SIGMA * mad,
                        floor_pct / 100.0 * abs(med))
        if direction == 0:
            findings.append(Finding(fkey, tool, metric, last, med, mad,
                                    len(prior), 0, threshold, False,
                                    "ungated"))
            continue
        excess = (last - med) * direction
        if excess > threshold:
            status, regressed = "REGRESSED", True
        elif excess < -threshold:
            status, regressed = "improved", False
        else:
            status, regressed = "ok", False
        findings.append(Finding(fkey, tool, metric, last, med, mad,
                                len(prior), direction, threshold,
                                regressed, status))
    return findings


def regressions(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if f.regressed]


def render_report(entries: Sequence[Dict[str, Any]],
                  metric_filter: Optional[str] = None,
                  tool_filter: Optional[str] = None,
                  tail: int = 8) -> str:
    """Human-readable trajectory per metric: every series with its
    sample count, median/MAD, the last ``tail`` values and the
    detector's verdict on the latest one."""
    series = _series(entries)
    verdicts = {(f.fingerprint_key, f.tool, f.metric): f
                for f in evaluate(entries)}
    lines: List[str] = []
    for (fkey, tool, metric), values in sorted(series.items()):
        if metric_filter and metric_filter not in metric:
            continue
        if tool_filter and tool_filter not in tool:
            continue
        med = _median(values)
        mad = _median([abs(v - med) for v in values])
        f = verdicts.get((fkey, tool, metric))
        recent = ", ".join(f"{v:.6g}" for v in values[-tail:])
        lines.append(f"{tool}/{metric} @{fkey}  n={len(values)}  "
                     f"median={med:.6g} mad={mad:.2g}  "
                     f"[{f.status if f else '?'}]")
        lines.append(f"    last {min(len(values), tail)}: {recent}")
    if not lines:
        return "(empty trajectory)"
    return "\n".join(lines)
