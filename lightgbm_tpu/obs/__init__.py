"""lightgbm_tpu.obs — runtime telemetry (spans, retrace/compile
counters, device-memory accounting, exportable traces).

See :mod:`lightgbm_tpu.obs.telemetry` for the core contract (zero-HLO,
zero-sync, off-is-free), :mod:`lightgbm_tpu.obs.memory` for HBM
attribution to named owners, :mod:`lightgbm_tpu.obs.exporters` for the
JSONL / Chrome-trace / Prometheus writers and
:mod:`lightgbm_tpu.obs.benchio` for the ``BENCH_obs.json`` benchmark
artifact.  Enabled by the ``telemetry=off|counters|trace`` parameter
(or ``LIGHTGBM_TPU_TELEMETRY``); read at runtime via
``Booster.telemetry_report()`` or the CLI's ``telemetry_out=`` export.

Model & data health rides on top: :mod:`lightgbm_tpu.obs.digest`
(on-device per-feature bin-occupancy digests with a bit-identical
NumPy oracle, PSI/chi-square skew scoring) and
:mod:`lightgbm_tpu.obs.health` (the ``health=off|counters|trace``
session, training flight recorder, training↔serving skew monitor,
drift attribution) — read via ``Booster.health_report()``.

Perf trajectory: :mod:`lightgbm_tpu.obs.regress` persists every
benchmark as a fingerprinted ``BENCH_history.jsonl`` entry and judges
new samples against same-fingerprint history (median/MAD, noise-aware)
— ``tools/perfwatch.py`` is the check/report/drill CLI on top.
"""

from . import digest, health, memory, regress
from .exporters import (export_all, export_chrome_trace, export_jsonl,
                        export_prometheus, prometheus_text)
from .telemetry import (MODES, NULL, Telemetry, compile_event,
                        configure_from_config, counter, enabled, gauge,
                        get, instant, span)

__all__ = [
    "MODES", "NULL", "Telemetry", "compile_event",
    "configure_from_config", "counter", "enabled", "gauge", "get",
    "instant", "span", "digest", "health", "memory", "regress",
    "memory_snapshot",
    "export_all", "export_chrome_trace", "export_jsonl",
    "export_prometheus", "prometheus_text",
]


def memory_snapshot():
    """Ledger snapshot; when the session is enabled the per-owner
    byte counts also land as gauges (and, in trace mode, as counter
    tracks in the exported trace)."""
    tel = get()
    if tel.enabled:
        return memory.snapshot_to(tel)
    return memory.snapshot()


def export_session(out_dir: str):
    """Write all exporters for the process session under ``out_dir``."""
    return export_all(get(), out_dir)
