"""On-device per-feature data-health digests over the binned matrix.

The binned HBM-resident representation at the heart of the design makes
data-quality monitoring nearly free: a per-feature bin-occupancy digest
is ONE scatter-add reduction over the same packed (rows, G) / (G, N_pad)
buffer the histogram kernels already stream (cf. the histogram-centric
designs of arXiv:1706.08359 and the Booster inference accelerator,
arXiv:2011.02022) — a sliver of the MXU work PERF.md budgets per
iteration.  Everything here is integer-exact and comes in two strictly
bit-identical flavors:

* **device** (``bin_counts_device`` / ``bin_counts_device_t`` /
  ``snapshot_device``) — one fused jitted reduction per snapshot, at
  most ONE device→host sync (``jax.device_get`` of the whole result
  tuple).  Never called from the training loop itself (the jaxlint
  ``health.off`` tier-B budget pins the fused train step's lowering as
  health-mode-independent); snapshots are explicit.
* **host** (``bin_counts_host`` / ``margin_hist_host``) — the NumPy
  oracle, also the implementation the serving-path skew digests use
  (serving rows are already host-resident there, so the digest costs
  one vectorized bincount and zero device work).

On top of the raw group-column counts:

* ``per_feature_counts`` unbundles EFB-packed group columns back into
  exact per-original-feature bin occupancy (offset arithmetic only —
  with the project's max_conflict_rate = 0 bundling there are no
  conflicts to approximate);
* ``build_reference_profile`` captures the training-time distribution
  (per-feature bin counts, missing/zero rates, categorical
  cardinalities) as a JSON-able document persisted alongside the model;
* ``psi`` / ``chi2`` / ``rank_skew`` score a serving-time digest
  against that reference (population stability index and the classic
  chi-square statistic) and rank the most-skewed features.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "MARGIN_BUCKETS", "bin_counts_host", "bin_counts_device",
    "bin_counts_device_t", "margin_hist_host",
    "snapshot_device", "per_feature_counts", "build_reference_profile",
    "psi", "chi2", "rank_skew",
]

# prediction-margin log2 histogram: bucket 0 holds zero/underflow
# margins (|m| < 2^-16), buckets 1..33 hold frexp exponents -16..16
# (clipped), i.e. 2^(e-1) <= |m| < 2^e.  Fixed width keeps digests
# mergeable across snapshots.
MARGIN_BUCKETS = 34
_MARGIN_EXP_LO = -16


# ---------------------------------------------------------------------------
# bin-occupancy counts (group-column space)
# ---------------------------------------------------------------------------
def bin_counts_host(binned, num_bins: int) -> np.ndarray:
    """(G, num_bins) int64 occupancy counts of a row-major (n, G)
    packed bin matrix — the NumPy oracle (one flattened bincount)."""
    b = np.asarray(binned)
    if b.ndim != 2:
        raise ValueError("binned must be 2-D (rows, groups)")
    n, G = b.shape
    if G == 0 or n == 0:
        return np.zeros((G, int(num_bins)), dtype=np.int64)
    nb = int(num_bins)
    flat = b.astype(np.int64) + np.arange(G, dtype=np.int64)[None, :] * nb
    return np.bincount(flat.ravel(), minlength=G * nb) \
        .reshape(G, nb).astype(np.int64)


@functools.lru_cache(maxsize=1)
def _dev_counts_fn():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("nb", "t"))
    def impl(b, nb, t):
        b = b.astype(jnp.int32)
        if t:
            G = b.shape[0]
            flat = b + (jnp.arange(G, dtype=jnp.int32) * nb)[:, None]
        else:
            G = b.shape[1]
            flat = b + (jnp.arange(G, dtype=jnp.int32) * nb)[None, :]
        return jnp.zeros((G * nb,), jnp.int32).at[flat.ravel()] \
            .add(1).reshape(G, nb)

    return impl


def _dev_counts(binned, num_bins: int, transposed: bool):
    return _dev_counts_fn()(binned, nb=int(num_bins), t=bool(transposed))


def bin_counts_device(binned, num_bins: int):
    """Device twin of :func:`bin_counts_host` over a row-major (n, G)
    buffer: one jitted scatter-add, result left ON DEVICE (callers
    decide when to pay the single sync — see ``snapshot_device``)."""
    return _dev_counts(binned, num_bins, transposed=False)


def bin_counts_device_t(binned_t, num_bins: int):
    """Feature-major twin over the learner's (G, N_pad) layout (the
    direct-to-device ingest buffer).  Pad columns are all-zero by
    construction; subtract them from bin 0 host-side."""
    return _dev_counts(binned_t, num_bins, transposed=True)


# ---------------------------------------------------------------------------
# prediction-margin log2 histograms
# ---------------------------------------------------------------------------
def margin_hist_host(raw) -> np.ndarray:
    """(MARGIN_BUCKETS,) int64 log2-bucket histogram of margins — the
    NumPy oracle, float32 end to end like the device kernel so the two
    are bit-identical on the same input."""
    r = np.asarray(raw, dtype=np.float32)
    if r.ndim == 2 and r.shape[1] > 1:
        part = np.sort(r, axis=1)
        m = part[:, -1] - part[:, -2]
    else:
        m = np.abs(r.reshape(-1))
    m = np.abs(m)
    if m.size == 0:
        return np.zeros((MARGIN_BUCKETS,), np.int64)
    _, e = np.frexp(m)
    b = np.clip(e - _MARGIN_EXP_LO, 1, MARGIN_BUCKETS - 1)
    b = np.where(np.isfinite(m) & (m > 0), b, 0)
    return np.bincount(b.astype(np.int64),
                       minlength=MARGIN_BUCKETS).astype(np.int64)


@functools.lru_cache(maxsize=1)
def _margin_hist_dev_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def impl(r):
        r = r.astype(jnp.float32)
        if r.ndim == 2 and r.shape[1] > 1:
            part = jnp.sort(r, axis=1)
            m = part[:, -1] - part[:, -2]
        else:
            m = jnp.abs(r.reshape(-1))
        m = jnp.abs(m)
        _, e = jnp.frexp(m)
        b = jnp.clip(e - _MARGIN_EXP_LO, 1, MARGIN_BUCKETS - 1)
        b = jnp.where(jnp.isfinite(m) & (m > 0), b, 0)
        return jnp.zeros((MARGIN_BUCKETS,), jnp.int32) \
            .at[b.astype(jnp.int32)].add(1)

    return impl


def _margin_hist_dev(raw):
    return _margin_hist_dev_fn()(raw)


def snapshot_device(binned, num_bins: int, raw=None,
                    transposed: bool = False,
                    pad_cols: int = 0) -> Dict[str, np.ndarray]:
    """One digest snapshot from device-resident buffers: the fused
    bin-occupancy reduction (plus, optionally, the margin histogram of
    ``raw`` scores) dispatched together and materialized with EXACTLY
    one device→host sync.  ``pad_cols`` all-zero pad columns (the
    (G, N_pad) ingest layout) are subtracted from bin 0."""
    import jax
    counts = _dev_counts(binned, num_bins, transposed)
    parts = [counts]
    if raw is not None:
        parts.append(_margin_hist_dev(raw))
    host = jax.device_get(parts)          # the ONE sync
    counts = np.asarray(host[0], dtype=np.int64)
    if pad_cols:
        counts[:, 0] -= int(pad_cols)
    out = {"group_counts": counts}
    if raw is not None:
        out["margin_hist"] = np.asarray(host[1], dtype=np.int64)
    return out


# ---------------------------------------------------------------------------
# group-column counts -> per-original-feature counts (EFB unbundling)
# ---------------------------------------------------------------------------
def per_feature_counts(groups, bin_mappers, num_data: int,
                       group_counts: np.ndarray
                       ) -> Dict[int, np.ndarray]:
    """Exact per-feature bin occupancy from packed group-column counts.

    Singleton groups ARE the feature.  Bundled features occupy disjoint
    non-default ranges of the shared column (bin b != 0 lives at
    ``offset + b - 1``; every bundle member has most_freq_bin == 0 by
    the bundling precondition), so each member's default-bin count is
    ``num_data`` minus its own non-default occupancy — exact, because
    max_conflict_rate = 0 bundling admits no overlapping rows."""
    out: Dict[int, np.ndarray] = {}
    gc = np.asarray(group_counts, dtype=np.int64)
    for g, grp in enumerate(groups):
        if len(grp.feature_indices) == 1:
            f = grp.feature_indices[0]
            nb = bin_mappers[f].num_bin
            out[f] = gc[g, :nb].copy()
            continue
        for sub, f in enumerate(grp.feature_indices):
            bm = bin_mappers[f]
            nb = bm.num_bin
            offset = grp.bin_offsets[sub]
            c = np.zeros((nb,), np.int64)
            if nb > 1:
                c[1:nb] = gc[g, offset:offset + nb - 1]
            c[0] = int(num_data) - int(c[1:].sum())
            out[f] = c
    return out


# ---------------------------------------------------------------------------
# the reference profile (training-time distribution, model-persisted)
# ---------------------------------------------------------------------------
PROFILE_VERSION = 1


def build_reference_profile(ds, group_counts: np.ndarray,
                            margin_hist: Optional[np.ndarray] = None
                            ) -> Dict[str, Any]:
    """JSON-able training-data profile for a constructed BinnedDataset:
    per-feature bin counts, missing/zero rates and categorical
    cardinalities — the reference every serving-time digest is scored
    against.  ``ds`` duck-types groups / bin_mappers / num_data /
    feature_names."""
    from ..ops.binning import (BIN_CATEGORICAL, MISSING_NAN, MISSING_ZERO)
    n = int(ds.num_data)
    feats = per_feature_counts(ds.groups, ds.bin_mappers, n, group_counts)
    names = list(getattr(ds, "feature_names", []) or [])
    features: List[Dict[str, Any]] = []
    for f in sorted(feats):
        bm = ds.bin_mappers[f]
        counts = feats[f]
        is_cat = bm.bin_type == BIN_CATEGORICAL
        if is_cat:
            missing = int(counts[0])        # NaN/other -> bin 0
            zero = int(counts[bm.categorical_2_bin.get(0, 0)]
                       ) if 0 in bm.categorical_2_bin else 0
            card = int(bm.num_bin - 1)
        else:
            missing = (int(counts[bm.num_bin - 1])
                       if bm.missing_type == MISSING_NAN else
                       int(counts[bm.default_bin])
                       if bm.missing_type == MISSING_ZERO else 0)
            zero = int(counts[bm.default_bin])
            card = None
        features.append({
            "index": int(f),
            "name": names[f] if f < len(names) else f"Column_{f}",
            "num_bin": int(bm.num_bin),
            "bin_type": int(bm.bin_type),
            "missing_type": int(bm.missing_type),
            "counts": [int(c) for c in counts],
            "missing_rate": round(missing / max(n, 1), 6),
            "zero_rate": round(zero / max(n, 1), 6),
            "cardinality": card,
        })
    prof: Dict[str, Any] = {"version": PROFILE_VERSION, "num_data": n,
                            "features": features}
    if margin_hist is not None:
        prof["margin_hist"] = [int(v) for v in margin_hist]
    return prof


# ---------------------------------------------------------------------------
# skew scoring: PSI + chi-square against the reference
# ---------------------------------------------------------------------------
def coarsen(ref_counts, cur_counts, target_bins: int = 16):
    """Merge adjacent fine bins into <= ``target_bins`` groups of
    roughly equal REFERENCE mass before scoring.  255 near-empty fine
    bins against a few hundred serving rows makes eps-floored PSI pure
    sampling noise; equal-mass coarse bins are the standard fix and
    keep the 0.25 rule-of-thumb threshold meaningful at small n.  The
    same cuts apply to both vectors, so a genuine shift survives
    coarsening while per-bin noise cancels."""
    r = np.asarray(ref_counts, np.float64)
    c = np.asarray(cur_counts, np.float64)
    nb = len(r)
    if nb <= target_bins:
        return r, c
    rn = r.sum()
    if rn <= 0:
        return r, c
    quota = rn / target_bins
    cuts = [0]
    acc = 0.0
    for i in range(nb):
        acc += r[i]
        if acc >= quota * len(cuts) and i + 1 < nb:
            cuts.append(i + 1)
    cuts.append(nb)
    rr = np.add.reduceat(r, cuts[:-1])
    cc = np.add.reduceat(c, cuts[:-1])
    return rr, cc


def psi(ref_counts: Sequence[int], cur_counts: Sequence[int],
        eps: float = 1e-4) -> float:
    """Population stability index between two bin-count vectors
    (probabilities floored at ``eps`` so empty bins score finitely).
    Rule of thumb: < 0.1 stable, 0.1-0.25 drifting, > 0.25 shifted."""
    r = np.asarray(ref_counts, np.float64)
    c = np.asarray(cur_counts, np.float64)
    rn, cn = r.sum(), c.sum()
    if rn <= 0 or cn <= 0:
        return 0.0
    p = np.maximum(r / rn, eps)
    q = np.maximum(c / cn, eps)
    return float(np.sum((q - p) * np.log(q / p)))


def chi2(ref_counts: Sequence[int], cur_counts: Sequence[int]) -> float:
    """Pearson chi-square statistic of the observed serving counts
    against expectations scaled from the reference distribution,
    normalized per observed row (scale-free across batch sizes)."""
    r = np.asarray(ref_counts, np.float64)
    c = np.asarray(cur_counts, np.float64)
    rn, cn = r.sum(), c.sum()
    if rn <= 0 or cn <= 0:
        return 0.0
    expected = r / rn * cn
    mask = expected > 0
    extra = c[~mask].sum()                 # observed mass in empty ref bins
    stat = float(np.sum((c[mask] - expected[mask]) ** 2
                        / expected[mask])) + float(extra * cn)
    return float(stat / cn)


def rank_skew(profile: Dict[str, Any],
              cur_feature_counts: Dict[int, np.ndarray],
              topk: int = 0) -> List[Dict[str, Any]]:
    """Per-feature PSI/chi-square of a serving-time digest against the
    reference profile, most-skewed first; ``topk`` trims (0 = all)."""
    out: List[Dict[str, Any]] = []
    for fe in profile.get("features", []):
        f = int(fe["index"])
        cur = cur_feature_counts.get(f)
        if cur is None:
            continue
        ref = fe["counts"]
        if len(cur) != len(ref):
            continue                      # mapper mismatch: not scorable
        cr, cc = coarsen(ref, cur)
        out.append({"feature": f, "name": fe.get("name", str(f)),
                    "psi": round(psi(cr, cc), 6),
                    "chi2": round(chi2(cr, cc), 6),
                    "rows": int(np.asarray(cur).sum())})
    out.sort(key=lambda d: (-d["psi"], d["feature"]))
    return out[:topk] if topk else out
