"""Device-memory accounting: attribute HBM (and pinned host buffers)
to named owners.

``jax.live_arrays()`` answers "how much is alive" but not "who holds
it"; the question an operator actually asks — is it the binned
dataset, the training state, the serving packs, or the continual
buffers? — needs the holders themselves to say what they own.  Each
subsystem registers a named provider (a function over a weakly-held
owner object returning its arrays); :func:`snapshot` walks the
registry, sums bytes per owner split device/host, and pairs that with
the backend totals (``live_arrays`` + ``Device.memory_stats`` where the
backend exposes them — TPU/GPU do, CPU usually returns nothing).

Registration is unconditional and ~free (a weakref in a dict); the
walk only happens when something asks — a span boundary in trace mode,
``Booster.telemetry_report()``, or the benchmark artifact writer.
Providers must never *materialize* device data: they return array
references whose ``nbytes`` is host metadata, so a snapshot is
sync-free like every other telemetry path.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

__all__ = ["MemoryLedger", "LEDGER", "register", "snapshot",
           "snapshot_to"]


def _leaves(tree) -> List[Any]:
    try:
        import jax
        return jax.tree_util.tree_leaves(tree)
    except Exception:
        return tree if isinstance(tree, (list, tuple)) else [tree]


def _is_device_array(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.Array)
    except Exception:
        return False


def _is_deleted(x) -> bool:
    """True for a jax Array whose buffer was donated/deleted — it holds
    no memory, only metadata, and must not be billed to anyone."""
    fn = getattr(x, "is_deleted", None)
    try:
        return bool(fn()) if fn is not None else False
    except Exception:
        return False


def _buffer_key(x):
    """Identity of the underlying device buffer(s), so one buffer shared
    by several owners (single-copy residency: the ingest buffer, the
    learner's ``_part0`` and the fused physical carrier can all be ONE
    allocation) is deduplicated in ``unique`` accounting."""
    try:
        return ("ptr", int(x.unsafe_buffer_pointer()))
    except Exception:
        pass
    try:                                # sharded: one pointer per shard
        return ("shards", tuple(
            int(s.data.unsafe_buffer_pointer())
            for s in x.addressable_shards))
    except Exception:
        return ("id", id(x))


def live_device_bytes() -> Optional[int]:
    """Total bytes of every live ``jax.Array`` in the process, or None
    when the runtime can't enumerate them."""
    try:
        import jax
        return int(sum(getattr(a, "nbytes", 0) or 0
                       for a in jax.live_arrays()))
    except Exception:
        return None


def backend_memory_stats() -> Optional[Dict[str, int]]:
    """Allocator stats of device 0 (``bytes_in_use`` /
    ``peak_bytes_in_use`` / ``bytes_limit`` where present); None when
    the backend doesn't report them (CPU typically doesn't)."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if not stats:
            return None
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")
        out = {k: int(stats[k]) for k in keep if k in stats}
        return out or None
    except Exception:
        return None


class MemoryLedger:
    """Registry of named owners -> array providers (weakly held)."""

    def __init__(self):
        self._lock = threading.Lock()
        # (owner name, id(obj)) -> (weakref, provider)
        self._providers: Dict[Any, Any] = {}

    def register(self, owner: str, obj: Any,
                 provider: Callable[[Any], Any]) -> None:
        """Attribute ``provider(obj)``'s arrays to ``owner``.  ``obj``
        is held weakly — a dead owner leaves the ledger via its weakref
        callback, so registration is leak-free even when telemetry is
        off and snapshot() (the other pruning point) never runs — and
        several instances may share one owner name (their bytes sum)."""
        key = (owner, id(obj))

        def _gone(_r, _k=key):
            # invariant: ONE GIL-atomic dict.pop, no lock — taking
            # self._lock inside a GC callback could deadlock against a
            # lock holder whose allocation triggers collection
            self._providers.pop(_k, None)  # conlint: ok=CL001

        try:
            ref = weakref.ref(obj, _gone)
        except TypeError:
            return                      # unweakrefable: skip, never crash
        with self._lock:
            self._providers[key] = (ref, provider)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            items = list(self._providers.items())
        owners: Dict[str, Dict[str, int]] = {}
        dead = []
        seen_buffers: set = set()
        # walk owners in name order so the dedup attribution (who gets
        # billed for a shared buffer: the FIRST owner to report it) is
        # deterministic across snapshots
        for key, (ref, provider) in sorted(items, key=lambda kv: kv[0][0]):
            obj = ref()
            if obj is None:
                dead.append(key)
                continue
            try:
                leaves = _leaves(provider(obj))
            except Exception:
                continue                # a provider must never sink a report
            dev = host = uniq = 0
            for leaf in leaves:
                nb = getattr(leaf, "nbytes", None)
                if nb is None:
                    continue
                if _is_device_array(leaf):
                    if _is_deleted(leaf):
                        continue        # donated: holds no memory
                    dev += int(nb)
                    bk = _buffer_key(leaf)
                    if bk not in seen_buffers:
                        seen_buffers.add(bk)
                        uniq += int(nb)
                else:
                    host += int(nb)
            slot = owners.setdefault(key[0],
                                     {"device_bytes": 0,
                                      "device_unique_bytes": 0,
                                      "host_bytes": 0})
            slot["device_bytes"] += dev
            slot["device_unique_bytes"] += uniq
            slot["host_bytes"] += host
        if dead:
            with self._lock:
                for key in dead:
                    self._providers.pop(key, None)
        return {"owners": owners,
                # sum of device_unique_bytes: each physical buffer
                # counted once even when several owners reference it
                "dedup_device_bytes": sum(
                    b["device_unique_bytes"] for b in owners.values()),
                "live_device_bytes": live_device_bytes(),
                "device_memory_stats": backend_memory_stats()}


LEDGER = MemoryLedger()


def register(owner: str, obj: Any, provider: Callable[[Any], Any]) -> None:
    LEDGER.register(owner, obj, provider)


def snapshot() -> Dict[str, Any]:
    return LEDGER.snapshot()


def snapshot_to(tel) -> Dict[str, Any]:
    """Take a snapshot and record it as gauges on telemetry session
    ``tel`` (``mem.<owner>.device_bytes`` etc.), so span-boundary
    snapshots land in the exported trace as counter tracks."""
    snap = snapshot()
    for owner, b in snap["owners"].items():
        tel.gauge(f"mem.{owner}.device_bytes", b["device_bytes"])
        tel.gauge(f"mem.{owner}.host_bytes", b["host_bytes"])
    tel.gauge("mem.dedup_device_bytes", snap["dedup_device_bytes"])
    if snap["live_device_bytes"] is not None:
        tel.gauge("mem.live_device_bytes", snap["live_device_bytes"])
    stats = snap["device_memory_stats"]
    if stats:
        for k, v in stats.items():
            tel.gauge(f"mem.backend.{k}", v)
    return snap
