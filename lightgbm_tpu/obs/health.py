"""Model & data health: the session, the training flight recorder and
the training↔serving skew monitor.

PR 7 gave the runtime *system* observability (spans, retrace counters,
HBM ledger); this module watches *model and data* health on top of the
same machinery:

* a process-wide session gated by the ``health=off|counters|trace``
  parameter, riding the telemetry modes (``trace`` also upgrades the
  telemetry session to ``trace`` so health marks export through the
  PR-7 JSONL / Chrome-trace / Prometheus writers with no new writer);
* :class:`FlightRecorder` — per-iteration split decisions (feature,
  bin, gain, leaf counts), gradient-norm digests and effective sample
  counts under GOSS/bagging, recorded from the host tree records the
  trainer ALREADY materializes — zero extra device ops or syncs, which
  is exactly what the jaxlint tier-B ``health.off`` budget pins;
* :class:`SkewMonitor` — rolling serving-time per-feature digests
  (obs/digest.py) scored against the model's reference profile with
  PSI / chi-square, per serving bucket, with threshold-crossing alert
  events on the telemetry ring;
* :func:`attribute_drift` — ranks the features whose serving-window
  distribution moved most against the reference, so a continual-runtime
  regression tick can NAME the offending features instead of only
  flagging "metric regressed".

The contract matches the telemetry layer's: **off is free** (one
attribute load + string compare at every entry point) and **no mode
ever stages device ops** — digests of device buffers happen only in
explicit snapshot calls, each costing at most one device→host sync.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from . import digest
from . import telemetry as obs

__all__ = [
    "MODES", "HealthSession", "get", "enabled", "configure_from_config",
    "FlightRecorder", "SkewMonitor", "attribute_drift",
]

MODES = ("off", "counters", "trace")
_MODE_RANK = {m: i for i, m in enumerate(MODES)}


class HealthSession:
    """Process-wide health mode (one session, like the telemetry one:
    training, serving and the continual runtime all consult it)."""

    def __init__(self, mode: str = "off"):
        self.mode = "off"
        self.set_mode(mode)

    def set_mode(self, mode: str) -> None:
        if mode not in MODES:
            raise ValueError(f"health mode must be one of {MODES}, "
                             f"got {mode!r}")
        self.mode = mode

    def enable(self, mode: str) -> None:
        """Upgrade-only, like telemetry: a component asking for less
        never silences a session another component raised.  ``trace``
        also raises the telemetry session to ``trace`` — health events
        ride its ring and exporters."""
        if mode not in MODES:
            raise ValueError(f"health mode must be one of {MODES}, "
                             f"got {mode!r}")
        if _MODE_RANK[mode] > _MODE_RANK[self.mode]:
            self.mode = mode
        if self.mode == "trace":
            obs.get().enable("trace")

    @property
    def enabled(self) -> bool:
        return self.mode != "off"


_ENV_MODE = os.environ.get("LIGHTGBM_TPU_HEALTH", "off")
_SESSION = HealthSession(_ENV_MODE if _ENV_MODE in MODES else "off")


def get() -> HealthSession:
    return _SESSION


def enabled() -> bool:
    return _SESSION.mode != "off"


def configure_from_config(cfg, from_model_load: bool = False,
                          allow_rearm: bool = None) -> HealthSession:
    """Enable the session from a Config's ``health`` parameter
    (upgrade-only; invalid values fail loudly).  With
    ``from_model_load=True`` re-arming is OPT-IN, exactly like the
    telemetry session (see obs/telemetry.py configure_from_config)."""
    mode = str(getattr(cfg, "health", "off") or "off").strip().lower()
    if mode not in MODES:
        from ..utils import log
        log.fatal("health must be one of %s, got %r",
                  "|".join(MODES), mode)
    if mode != "off":
        if from_model_load:
            from . import telemetry as _tel
            allowed = (_tel.rearm_on_load_allowed(cfg)
                       if allow_rearm is None else allow_rearm)
            if not allowed:
                if _MODE_RANK[mode] > _MODE_RANK[_SESSION.mode]:
                    _tel.warn_rearm_skipped("health", mode)
                return _SESSION
        _SESSION.enable(mode)
    return _SESSION


# ---------------------------------------------------------------------------
# training flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Bounded per-tree record of what training decided and why.

    Everything recorded is a host value the trainer already
    materialized (the device→host tree record): per-split (feature,
    bin, gain), leaf counts, leaf-value norms (the gradient-norm digest
    — leaf outputs are -G/(H+λ), so their magnitudes ARE the scaled
    per-leaf gradient sums), and the iteration's effective sample count
    under GOSS/bagging.  Oldest trees evict first; cumulative
    per-feature totals never evict."""

    MAX_TREES = 8192
    TOP_SPLITS = 3

    def __init__(self, topk: int = 5):
        self.topk = int(topk)
        self._lock = threading.Lock()
        self.entries: deque = deque(maxlen=self.MAX_TREES)
        self.evicted = 0
        self.trees = 0
        # cumulative per-feature totals (survive ring eviction)
        self.feat_splits: Dict[int, int] = {}
        self.feat_gain: Dict[int, float] = {}

    @classmethod
    def from_config(cls, cfg) -> "FlightRecorder":
        return cls(topk=int(getattr(cfg, "health_topk", 5) or 5))

    def record_tree(self, iteration: int, k: int, host_record,
                    num_nodes: int,
                    effective_rows: Optional[int] = None) -> None:
        nn = int(num_nodes)
        entry: Dict[str, Any] = {"it": int(iteration), "k": int(k),
                                 "leaves": nn + 1}
        if effective_rows is not None:
            entry["effective_rows"] = int(effective_rows)
        gain_total = 0.0
        if nn > 0 and "node_feature" in host_record:
            feats = np.asarray(host_record["node_feature"])[:nn]
            gains = (np.asarray(host_record["node_gain"],
                                dtype=np.float64)[:nn]
                     if "node_gain" in host_record else np.zeros(nn))
            bins = (np.asarray(host_record["node_threshold"])[:nn]
                    if "node_threshold" in host_record
                    else np.zeros(nn, np.int64))
            gain_total = float(gains.sum())
            entry["gain_total"] = round(gain_total, 6)
            entry["gain_max"] = round(float(gains.max()), 6)
            order = np.argsort(-gains)[:self.TOP_SPLITS]
            entry["top_splits"] = [
                {"feature": int(feats[i]), "bin": int(bins[i]),
                 "gain": round(float(gains[i]), 6)} for i in order]
        if "leaf_cnt" in host_record:
            cnts = np.asarray(host_record["leaf_cnt"])[:nn + 1]
            if cnts.size:
                entry["leaf_cnt_min"] = int(cnts.min())
                entry["leaf_cnt_max"] = int(cnts.max())
        if "leaf_value" in host_record:
            lv = np.asarray(host_record["leaf_value"],
                            dtype=np.float64)[:nn + 1]
            if lv.size:
                entry["leaf_l2"] = round(float(np.sqrt((lv ** 2).sum())),
                                         6)
                entry["leaf_abs_max"] = round(float(np.abs(lv).max()), 6)
        with self._lock:
            self.trees += 1
            if len(self.entries) == self.entries.maxlen:
                self.evicted += 1
            self.entries.append(entry)
            if nn > 0 and "node_feature" in host_record:
                for f, g in zip(feats.tolist(), gains.tolist()):
                    f = int(f)
                    self.feat_splits[f] = self.feat_splits.get(f, 0) + 1
                    self.feat_gain[f] = self.feat_gain.get(f, 0.0) \
                        + float(g)
        top = entry.get("top_splits")
        obs.instant("health.tree", it=int(iteration), k=int(k),
                    leaves=nn + 1, gain_total=round(gain_total, 6),
                    top_feature=(top[0]["feature"] if top else None))

    # -- reporting ------------------------------------------------------
    def report(self, trajectory: int = 64) -> Dict[str, Any]:
        with self._lock:
            entries = list(self.entries)
            feat = sorted(self.feat_splits,
                          key=lambda f: (-self.feat_gain.get(f, 0.0), f))
            top_features = [
                {"feature": f, "splits": self.feat_splits[f],
                 "gain": round(self.feat_gain.get(f, 0.0), 6)}
                for f in feat[:self.topk]]
            tail = entries[-trajectory:]
            return {
                "trees_recorded": self.trees,
                "entries_retained": len(entries),
                "entries_evicted": self.evicted,
                "top_features": top_features,
                "gain_trajectory": [
                    [e["it"], e.get("gain_total", 0.0)] for e in tail],
                "effective_rows_last": next(
                    (e["effective_rows"] for e in reversed(entries)
                     if "effective_rows" in e), None),
                "last_tree": entries[-1] if entries else None,
            }


# ---------------------------------------------------------------------------
# training<->serving skew monitor
# ---------------------------------------------------------------------------
# ambient tenant id for skew attribution: the service's dispatch wraps
# its predict call in ``tenant_scope`` so the monitor — which observes
# deep inside the serving path, with no tenant in any signature on the
# way down — can key its rolling digests per tenant without widening
# every call chain between admission and the digest
_serving_tenant: contextvars.ContextVar = contextvars.ContextVar(
    "lightgbm_tpu_serving_tenant", default=None)


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]):
    """Attribute every skew observation inside the block to ``tenant``
    (the admission layer's client id; None = unattributed)."""
    tok = _serving_tenant.set(tenant)
    try:
        yield
    finally:
        _serving_tenant.reset(tok)


class SkewMonitor:
    """Rolling serving-time digests per bucket, scored against the
    model's reference profile (obs/digest.py).  All host NumPy — the
    serving path's rows are already host-resident where this runs, so
    observation costs one vectorized bincount and ZERO device work."""

    ROLL_ROWS = 1 << 21        # halve counts beyond ~2M rows: "rolling"
    # threshold-scan throttle: the full per-feature PSI scan costs a
    # few ms, so it runs on a WALL-CLOCK cadence (an alert pipeline
    # reads seconds anyway), never per observation — a scan landing
    # inside a hot serving window was the dominant cost of the layer
    # (measured ~3% of warm predict before the throttle, ~0.3% after)
    CHECK_INTERVAL_S = 15.0
    # per-observation digest cap: batches beyond this are stride-
    # sampled (deterministic, unbiased for any row order) so the
    # serving hot path pays O(cap) per call, not O(batch) — the ≤2%
    # overhead budget PERF.md holds the layer to.  2k rows/call keeps
    # PSI over 16 coarse bins accurate to ~±0.02 while the digest
    # stays ~0.5 ms on the 2-core host
    OBSERVE_CAP = 2048
    # tenant ids are client-supplied strings: bound the per-tenant
    # digest map exactly like the service bounds tenant latency
    # histograms — overflow tenants fold into one "~other" bucket
    TENANT_MAX = 32

    def __init__(self, profile: Dict[str, Any], groups, bin_mappers,
                 num_bins: int, topk: int = 5, threshold: float = 0.25):
        self.profile = profile
        self.groups = groups
        self.bin_mappers = bin_mappers
        self.nb = int(num_bins)
        self.topk = int(topk)
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        self.counts: Dict[Any, np.ndarray] = {}     # bucket -> (G, nb)
        self.rows: Dict[Any, int] = {}              # rows DIGESTED
        self.seen: Dict[Any, int] = {}              # rows served
        self.tenant_counts: Dict[str, np.ndarray] = {}  # tenant -> (G, nb)
        self.tenant_rows: Dict[str, int] = {}
        self.margin = np.zeros(digest.MARGIN_BUCKETS, np.int64)
        self.alerts = 0
        self._alerted: set = set()
        self._last_check = time.monotonic()

    @classmethod
    def from_dataset(cls, profile: Dict[str, Any], ds, cfg
                     ) -> "SkewMonitor":
        return cls(profile, ds.groups, ds.bin_mappers, ds.max_group_bins,
                   topk=int(getattr(cfg, "health_topk", 5) or 5),
                   threshold=float(getattr(cfg, "health_psi_threshold",
                                           0.25) or 0.25))

    # -- observation ----------------------------------------------------
    def observe_binned(self, rows: np.ndarray, bucket=None) -> None:
        """Fold one (n, G) packed bin-space batch into the rolling
        digest for ``bucket`` (stride-sampled beyond OBSERVE_CAP)."""
        n = rows.shape[0]
        if n == 0:
            return
        if n > self.OBSERVE_CAP:
            rows = rows[::n // self.OBSERVE_CAP + 1]
        c = digest.bin_counts_host(rows, self.nb)
        tenant = _serving_tenant.get()
        with self._lock:
            prev = self.counts.get(bucket)
            self.counts[bucket] = c if prev is None else prev + c
            self.rows[bucket] = self.rows.get(bucket, 0) + rows.shape[0]
            self.seen[bucket] = self.seen.get(bucket, 0) + n
            if tenant is not None:
                tkey = str(tenant)
                if tkey not in self.tenant_counts and \
                        len(self.tenant_counts) >= self.TENANT_MAX:
                    tkey = "~other"
                tprev = self.tenant_counts.get(tkey)
                # copy, never alias counts[bucket]: the rolling halve
                # below is in-place and must hit each map exactly once
                self.tenant_counts[tkey] = \
                    c.copy() if tprev is None else tprev + c
                self.tenant_rows[tkey] = \
                    self.tenant_rows.get(tkey, 0) + rows.shape[0]
            total = sum(self.rows.values())
            if total > 2 * self.ROLL_ROWS:
                for b in self.counts:
                    self.counts[b] //= 2
                    self.rows[b] //= 2
                for t in self.tenant_counts:
                    self.tenant_counts[t] //= 2
                    self.tenant_rows[t] //= 2
            now = time.monotonic()
            check = now - self._last_check >= self.CHECK_INTERVAL_S
            if check:
                self._last_check = now
        if check:
            self._check_thresholds()

    def observe_margins(self, raw) -> None:
        raw = np.asarray(raw)
        if raw.shape[0] > self.OBSERVE_CAP:
            raw = raw[::raw.shape[0] // self.OBSERVE_CAP + 1]
        h = digest.margin_hist_host(raw)
        with self._lock:
            self.margin += h

    # -- scoring --------------------------------------------------------
    def feature_counts(self) -> Dict[int, np.ndarray]:
        with self._lock:
            if not self.counts:
                return {}
            total = sum(self.counts.values())
            n = sum(self.rows.values())
        return digest.per_feature_counts(self.groups, self.bin_mappers,
                                         n, total)

    def scores(self, topk: Optional[int] = None) -> List[Dict[str, Any]]:
        fc = self.feature_counts()
        if not fc:
            return []
        return digest.rank_skew(self.profile, fc,
                                self.topk if topk is None else topk)

    def tenant_scores(self, topk: Optional[int] = None
                      ) -> Dict[str, Dict[str, Any]]:
        """Per-tenant PSI against the SAME reference profile: which
        client's traffic drifted, not just that some traffic did."""
        with self._lock:
            snap = {t: (c.copy(), int(self.tenant_rows.get(t, 0)))
                    for t, c in self.tenant_counts.items()}
        k = self.topk if topk is None else topk
        out: Dict[str, Dict[str, Any]] = {}
        for t, (c, n) in sorted(snap.items()):
            if n <= 0:
                continue
            fc = digest.per_feature_counts(self.groups, self.bin_mappers,
                                           n, c)
            top = digest.rank_skew(self.profile, fc, k)
            out[t] = {"rows": n,
                      "psi_max": (top[0]["psi"] if top else 0.0),
                      "top": top}
        return out

    def _check_thresholds(self) -> None:
        for s in self.scores(topk=0):
            if s["psi"] <= self.threshold:
                continue
            with self._lock:
                # membership test and insert under ONE lock hold: two
                # threads crossing the same feature's threshold in the
                # same scan window must not both count the alert
                # (check-then-act race on _alerted)
                if s["feature"] in self._alerted:
                    continue
                self._alerted.add(s["feature"])
                self.alerts += 1
            # telemetry emission outside the lock: the session has its
            # own lock and must never nest inside a monitor's
            obs.counter("health.skew.alerts")
            obs.instant("health.skew", feature=s["feature"],
                        feature_name=s["name"], psi=s["psi"],
                        threshold=self.threshold)

    def report(self) -> Dict[str, Any]:
        # a report is an explicit snapshot point: crossings observed
        # since the last periodic scan must not wait CHECK_EVERY more
        # observations to surface
        self._check_thresholds()
        with self._lock:
            rows = {str(k): int(v) for k, v in sorted(
                self.rows.items(), key=lambda kv: str(kv[0]))}
            seen = {str(k): int(v) for k, v in sorted(
                self.seen.items(), key=lambda kv: str(kv[0]))}
            margin = [int(v) for v in self.margin]
            alerts = self.alerts
        return {"rows_by_bucket": rows, "rows_total": sum(rows.values()),
                "rows_seen": sum(seen.values()),
                "alerts": alerts, "psi_threshold": self.threshold,
                "top": self.scores(), "margin_hist": margin,
                "tenants": self.tenant_scores()}


# ---------------------------------------------------------------------------
# drift attribution (the continual runtime's regression ticks)
# ---------------------------------------------------------------------------
def attribute_drift(profile: Dict[str, Any], ds,
                    batch_counts: List[np.ndarray], rows: int,
                    topk: int = 5) -> List[Dict[str, Any]]:
    """Rank features by how far the RECENT serving window's digest
    (summed per-batch group counts) moved from the reference profile —
    the answer to "the metric regressed: WHICH feature drifted?"."""
    if not batch_counts:
        return []
    total = batch_counts[0].copy()
    for c in batch_counts[1:]:
        total += c
    fc = digest.per_feature_counts(ds.groups, ds.bin_mappers,
                                   int(rows), total)
    return digest.rank_skew(profile, fc, topk)
