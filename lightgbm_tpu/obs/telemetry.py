"""Runtime telemetry: spans, counters, gauges, and a retrace/compile
detector for a *running* trainer or server.

Until this module, every performance claim lived in hand-run PERF.md
rounds and test-time guards (jaxlint tier-B budgets, compile-count
pins): there was no way to observe which iteration re-traced, how long
a continual tick really took, or what HBM the packed forests hold.
This is the runtime counterpart of those static guards — the same
signals the serving/continual comparison baselines report at runtime
(per-bucket latency percentiles, compile events, device-memory
residency; cf. the Gemma-on-TPU serving notes and the Booster GBDT
inference accelerator in PAPERS.md).

The contract (pinned by the jaxlint tier-B ``telemetry.off`` budget and
``tests/test_telemetry.py``):

* **Zero-HLO** — nothing here ever stages a device op.  Spans and
  counters are host-side `time.perf_counter` bookkeeping; the compile
  detector is a Python side effect that only runs while `jax.jit`
  traces.  The lowered train while-body is op-for-op identical with
  telemetry off or at full trace mode.
* **Zero-sync** — spans never call ``block_until_ready``: they time
  dispatch as issued and rely on boundaries the caller already syncs
  (eval ticks, the bucketed serving path's host materialization).
  ``telemetry=off`` is therefore bit-identical *and* timing-neutral
  end-to-end.
* **Off is (almost) free** — with the session off, every module-level
  entry point is one attribute load and one string compare; no
  objects allocate, no locks take.

Modes: ``off`` (default) < ``counters`` (aggregate spans/counters/
compile events on the host) < ``trace`` (counters plus a bounded
event ring exportable as Chrome trace / JSONL / Prometheus — see
:mod:`lightgbm_tpu.obs.exporters` — with ``jax.profiler``
``TraceAnnotation`` bridging so device profiles carry our span names).

One process-wide session: training, serving and the continual runtime
all write to it, so one exported trace shows the whole pipeline.
``Booster.telemetry_report()`` reads it; the ``telemetry=`` config
parameter enables it (upgrade-only: a second booster asking for
``counters`` never downgrades a session already at ``trace``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "MODES", "Telemetry", "get", "enabled", "configure_from_config",
    "span", "counter", "gauge", "compile_event", "instant",
    "observe_span", "NULL",
]

MODES = ("off", "counters", "trace")
_MODE_RANK = {m: i for i, m in enumerate(MODES)}

# bounded trace-event ring: a forever-running continual loop must not
# grow without bound.  A true ring — the OLDEST events evict first, so
# the exported trace always holds the most recent window (the one an
# operator wants after an incident); evictions are counted, never
# silent.
MAX_EVENTS = 200_000


class _NullSpan:
    """Shared no-op context manager — the disabled fast path allocates
    nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL = _NullSpan()


class Histogram:
    """Log2-bucketed duration histogram (microsecond buckets).

    Fixed memory per metric, O(1) observe, and quantiles good to a
    factor-of-two bucket width — the right fidelity for p50/p99 serving
    latency without keeping raw samples."""

    NBUCKETS = 40            # bucket i holds durations < 2^i us (~13 days)
    __slots__ = ("count", "total_s", "min_s", "max_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.max_s = 0.0
        self.buckets = [0] * self.NBUCKETS

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        if seconds < self.min_s:
            self.min_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds
        b = int(seconds * 1e6).bit_length()      # 0us -> bucket 0
        self.buckets[min(b, self.NBUCKETS - 1)] += 1

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-quantile, in seconds."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                return min((1 << i) * 1e-6, self.max_s)
        return self.max_s

    def to_json(self) -> Dict[str, Any]:
        return {"count": self.count,
                "total_s": round(self.total_s, 6),
                "min_s": round(self.min_s, 6) if self.count else 0.0,
                "max_s": round(self.max_s, 6),
                "mean_s": round(self.total_s / self.count, 6)
                if self.count else 0.0,
                "p50_s": round(self.quantile(0.50), 6),
                "p99_s": round(self.quantile(0.99), 6)}


class _Span:
    """One timed section.  Never syncs the device; in trace mode it
    also enters a ``jax.profiler.TraceAnnotation`` so device profiles
    (TensorBoard/Perfetto) carry the same name."""

    __slots__ = ("tel", "name", "args", "t0", "ann")

    def __init__(self, tel: "Telemetry", name: str, args: Dict[str, Any]):
        self.tel = tel
        self.name = name
        self.args = args
        self.ann = None

    def __enter__(self):
        tel = self.tel
        tel._stack().append(self.name)
        if tel.mode == "trace" and tel.profiler_bridge:
            try:
                import jax
                self.ann = jax.profiler.TraceAnnotation(self.name)
                self.ann.__enter__()
            except Exception:
                self.ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        tel = self.tel
        if self.ann is not None:
            try:
                self.ann.__exit__(*exc)
            except Exception:
                pass
        stack = tel._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        tel._record_span(self.name, self.t0, t1 - self.t0, self.args)
        return False


class Telemetry:
    """One telemetry session (see module docstring).  Thread-safe: the
    continual runtime's background retrain and concurrent serving calls
    write from their own threads."""

    def __init__(self, mode: str = "off", max_events: int = MAX_EVENTS):
        self._lock = threading.Lock()
        self._local = threading.local()
        self.max_events = int(max_events)
        # jax.profiler TraceAnnotation bridging in trace mode (cheap —
        # a TraceMe — but switchable for pure-host unit tests)
        self.profiler_bridge = True
        self.mode = "off"
        self.reset(mode=mode)

    # -- lifecycle ------------------------------------------------------
    def set_mode(self, mode: str) -> None:
        if mode not in MODES:
            raise ValueError(f"telemetry mode must be one of {MODES}, "
                             f"got {mode!r}")
        self.mode = mode

    def enable(self, mode: str) -> None:
        """Upgrade-only mode switch: off -> counters -> trace.  A
        booster asking for less never silences a session another
        component already raised."""
        if mode not in MODES:
            raise ValueError(f"telemetry mode must be one of {MODES}, "
                             f"got {mode!r}")
        if _MODE_RANK[mode] > _MODE_RANK[self.mode]:
            self.mode = mode

    def reset(self, mode: Optional[str] = None) -> None:
        """Clear every counter, histogram and event (the clean-slate
        the pickle/deepcopy round-trip test asserts); optionally set
        the mode."""
        import collections
        with self._lock:
            self.counters: Dict[str, int] = {}
            self.gauges: Dict[str, float] = {}
            self.spans: Dict[str, Histogram] = {}
            self.compiles: Dict[str, int] = {}
            self.compile_spans: Dict[str, Optional[str]] = {}
            self.events = collections.deque(maxlen=self.max_events)
            self.events_dropped = 0
            self.epoch = time.perf_counter()
            self.epoch_unix = time.time()
        if mode is not None:
            self.set_mode(mode)

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # -- span plumbing --------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span(self) -> Optional[str]:
        st = self._stack()
        return st[-1] if st else None

    def span(self, name: str, **args):
        """Context manager timing a section under ``name``; ``args``
        ride trace events only (aggregation is keyed by the name, so
        bake low-cardinality dimensions — e.g. the serving bucket —
        into the name itself)."""
        if self.mode == "off":
            return NULL
        return _Span(self, name, args)

    def observe_span(self, name: str, seconds: float, **args) -> None:
        """Record an ALREADY-measured duration into ``name``'s span
        histogram (the serving plane's per-tenant latency: the service
        measures one submit->complete latency per request and folds it
        in here, so per-tenant p50/p99 ride the same report/Prometheus
        path as real spans).  Host bookkeeping only — same zero-HLO /
        zero-sync contract as ``span``."""
        if self.mode == "off":
            return
        self._record_span(name, time.perf_counter() - seconds,
                          float(seconds), args)

    def _record_span(self, name: str, t0: float, dur: float,
                     args: Dict[str, Any]) -> None:
        with self._lock:
            h = self.spans.get(name)
            if h is None:
                h = self.spans[name] = Histogram()
            h.observe(dur)
            if self.mode == "trace":
                self._event({"ph": "X", "name": name,
                             "ts": int((t0 - self.epoch) * 1e6),
                             "dur": max(int(dur * 1e6), 1),
                             "args": args or {}})

    def _event(self, ev: Dict[str, Any]) -> None:
        # lock held by the caller (conlint verifies this statically:
        # every call site sits in a `with self._lock:` block, and the
        # private-method inheritance rule analyzes _event as holding
        # it).  The ring append therefore never races report()'s
        # `len(self.events)` / snapshot_events()' `list(self.events)`
        # drains, which take the same lock — audited for ISSUE 19's
        # append-vs-drain sweep; nothing to fix, nothing pinned.
        # The deque's maxlen evicts the OLDEST event so the ring
        # always keeps the most recent window
        if len(self.events) >= self.max_events:
            self.events_dropped += 1
        ev.setdefault("pid", os.getpid())
        ev.setdefault("tid", threading.get_ident() % 0x7fffffff)
        self.events.append(ev)

    # -- counters / gauges ----------------------------------------------
    def counter(self, name: str, inc: int = 1) -> None:
        if self.mode == "off":
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc

    def gauge(self, name: str, value: float) -> None:
        if self.mode == "off":
            return
        with self._lock:
            self.gauges[name] = value
            if self.mode == "trace":
                self._event({"ph": "C", "name": name,
                             "ts": int((time.perf_counter() - self.epoch)
                                       * 1e6),
                             "args": {"value": value}})

    def instant(self, name: str, **args) -> None:
        """One instant ("i") event on the trace ring — trace mode only
        (there is no aggregate to keep in counters mode).  Used by the
        health layer for flight-recorder / skew-alert marks so the
        PR-7 exporters carry them without any new writer."""
        if self.mode != "trace":
            return
        with self._lock:
            self._event({"ph": "i", "s": "t", "name": name,
                         "ts": int((time.perf_counter() - self.epoch)
                                   * 1e6),
                         "args": args or {}})

    # -- retrace/compile detector ---------------------------------------
    def compile_event(self, key: str) -> None:
        """Call this from INSIDE a function handed to ``jax.jit``: the
        Python body only executes while XLA traces, so one call == one
        compile of that entry point — the runtime retrace detector,
        attributed to the innermost active span.  Zero HLO (a host side
        effect), zero work when the session is off."""
        if self.mode == "off":
            return
        owner = self.current_span()
        with self._lock:
            self.compiles[key] = self.compiles.get(key, 0) + 1
            if owner is not None or key not in self.compile_spans:
                self.compile_spans[key] = owner
            if self.mode == "trace":
                self._event({"ph": "i", "s": "t", "name": f"compile:{key}",
                             "ts": int((time.perf_counter() - self.epoch)
                                       * 1e6),
                             "args": {"span": owner}})

    # -- reporting ------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": self.mode,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "spans": {n: h.to_json()
                          for n, h in sorted(self.spans.items())},
                "compiles": dict(self.compiles),
                "compile_spans": dict(self.compile_spans),
                "events_recorded": len(self.events),
                "events_dropped": self.events_dropped,
            }

    def snapshot_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.events)


# ---------------------------------------------------------------------------
# the process-wide session + allocation-free module entry points
# ---------------------------------------------------------------------------
_ENV_MODE = os.environ.get("LIGHTGBM_TPU_TELEMETRY", "off")
_SESSION = Telemetry(_ENV_MODE if _ENV_MODE in MODES else "off")


def get() -> Telemetry:
    return _SESSION


def enabled() -> bool:
    return _SESSION.mode != "off"


_REARM_WARNED = {"telemetry": False, "health": False}


def rearm_on_load_allowed(cfg) -> bool:
    """Whether a MODEL-LOAD path may arm the process-wide obs sessions
    from the loaded model's saved params.  Off by default: a model file
    is data, and loading one should not silently turn on process-wide
    bookkeeping.  Opt back in per-load (``obs_rearm_on_load=True``) or
    process-wide (``LIGHTGBM_TPU_OBS_REARM_ON_LOAD=1``)."""
    if bool(getattr(cfg, "obs_rearm_on_load", False)):
        return True
    env = os.environ.get("LIGHTGBM_TPU_OBS_REARM_ON_LOAD", "")
    return env.strip().lower() not in ("", "0", "false", "no", "off")


def warn_rearm_skipped(kind: str, mode: str) -> None:
    """One-time (per kind, per process) notice that a loaded model
    carried an armed obs mode which was NOT applied."""
    if _REARM_WARNED.get(kind):
        return
    _REARM_WARNED[kind] = True
    from ..utils import log
    log.warning(
        "loaded model was saved with %s=%s; the process-wide %s session "
        "is NOT re-armed on load.  Pass obs_rearm_on_load=True (or set "
        "LIGHTGBM_TPU_OBS_REARM_ON_LOAD=1) to opt in.  (warned once)",
        kind, mode, kind)


def configure_from_config(cfg, from_model_load: bool = False,
                          allow_rearm: bool = None) -> Telemetry:
    """Enable the session from a Config's ``telemetry`` parameter
    (upgrade-only; invalid values fail loudly like any other bad
    parameter).  With ``from_model_load=True`` (the Booster model
    file/string restore paths) re-arming is OPT-IN: the saved mode is
    ignored with a one-time warning unless allowed.  ``allow_rearm``
    overrides the cfg/env probe — the load paths pass the LOADING
    call's opt-in, never the saved model's (a saved
    ``obs_rearm_on_load`` must not re-enable itself)."""
    mode = str(getattr(cfg, "telemetry", "off") or "off").strip().lower()
    if mode not in MODES:
        from ..utils import log
        log.fatal("telemetry must be one of %s, got %r",
                  "|".join(MODES), mode)
    if mode != "off":
        allowed = (rearm_on_load_allowed(cfg) if allow_rearm is None
                   else allow_rearm)
        if from_model_load and not allowed:
            # only loud when it would actually have upgraded the session
            if _MODE_RANK[mode] > _MODE_RANK[_SESSION.mode]:
                warn_rearm_skipped("telemetry", mode)
            return _SESSION
        _SESSION.enable(mode)
    return _SESSION


def span(name: str, **args):
    if _SESSION.mode == "off":
        return NULL
    return _SESSION.span(name, **args)


def counter(name: str, inc: int = 1) -> None:
    if _SESSION.mode == "off":
        return
    _SESSION.counter(name, inc)


def observe_span(name: str, seconds: float, **args) -> None:
    if _SESSION.mode == "off":
        return
    _SESSION.observe_span(name, seconds, **args)


def gauge(name: str, value: float) -> None:
    if _SESSION.mode == "off":
        return
    _SESSION.gauge(name, value)


def compile_event(key: str) -> None:
    if _SESSION.mode == "off":
        return
    _SESSION.compile_event(key)


def instant(name: str, **args) -> None:
    if _SESSION.mode != "trace":
        return
    _SESSION.instant(name, **args)
