"""Telemetry exporters: JSONL event log, Chrome-trace/Perfetto
``trace.json``, and a Prometheus-style text dump.

All three read one :class:`~lightgbm_tpu.obs.telemetry.Telemetry`
session and write atomically (temp + rename) so a crash mid-export
never leaves a truncated artifact.  The Chrome trace loads directly in
``chrome://tracing`` / Perfetto; spans are complete ("X") events,
memory gauges are counter ("C") tracks and compile events are instant
("i") marks — ``tools/trace_report.py`` validates and summarizes the
same format.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict

__all__ = ["export_chrome_trace", "export_jsonl", "export_prometheus",
           "prometheus_text", "export_all"]


def _atomic_write(path: str, text: str) -> str:
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def _atomic_append(path: str, line: str) -> str:
    """Append ``line`` to an append-only log in ONE ``write`` syscall
    through an ``O_APPEND`` descriptor — POSIX makes the offset bump +
    write atomic, so concurrent writers (two bench processes, a pytest
    session and a profile tool) interleave whole lines, never splice
    them.  If the file's last byte is not a newline (a writer died
    mid-write), a leading newline detaches this record from the torn
    tail so only the torn line is lost, not both."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = line if line.endswith("\n") else line + "\n"
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        try:
            with open(path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) not in (b"\n", b""):
                    payload = "\n" + payload
        except OSError:
            pass                      # empty file: nothing to detach
        os.write(fd, payload.encode("utf-8"))
    finally:
        os.close(fd)
    return path


def export_chrome_trace(tel, path: str) -> str:
    """Write ``path`` as a Chrome-trace JSON object (the
    ``traceEvents`` array format Perfetto also loads)."""
    events = tel.snapshot_events()
    meta = [{"ph": "M", "name": "process_name", "pid": os.getpid(),
             "ts": 0, "args": {"name": "lightgbm_tpu"}}]
    doc = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "lightgbm_tpu.obs",
            "mode": tel.mode,
            "epoch_unix": tel.epoch_unix,
            "events_dropped": tel.events_dropped,
        },
    }
    return _atomic_write(path, json.dumps(doc))


def export_jsonl(tel, path: str) -> str:
    """One JSON object per line: a ``report`` header (the aggregate
    counters/spans/compiles) followed by every recorded event."""
    lines = [json.dumps({"type": "report", **tel.report()},
                        sort_keys=True)]
    for ev in tel.snapshot_events():
        lines.append(json.dumps({"type": "event", **ev}))
    return _atomic_write(path, "\n".join(lines) + "\n")


def _esc(v: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote and LINE FEED are the three characters with escape
    sequences (an unescaped newline truncates the sample line and
    corrupts every line after it)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(tel) -> str:
    """Prometheus exposition-format dump of the aggregate state (a
    text snapshot, not a live scrape endpoint — pipe it wherever the
    fleet's node exporter picks up textfiles).

    Strictly conformant to the text format (round-tripped through a
    full parser in tests/test_telemetry.py): one ``# TYPE`` per metric
    family, and the span-latency summary owns its ``_sum``/``_count``
    series — they are part of the summary family, never declared as a
    separate counter (the Prometheus parser rejects a family whose
    name collides with another family's reserved suffix)."""
    rep = tel.report()
    out = []
    out.append("# TYPE lightgbm_tpu_span_count counter")
    for name, h in sorted(rep["spans"].items()):
        out.append('lightgbm_tpu_span_count{name="%s"} %s'
                   % (_esc(name), h["count"]))
    out.append("# TYPE lightgbm_tpu_span_seconds summary")
    for name, h in sorted(rep["spans"].items()):
        lbl = _esc(name)
        for q, qv in (("p50_s", "0.5"), ("p99_s", "0.99")):
            out.append('lightgbm_tpu_span_seconds{name="%s",quantile="%s"}'
                       ' %s' % (lbl, qv, h[q]))
        out.append(f'lightgbm_tpu_span_seconds_sum{{name="{lbl}"}} '
                   f'{h["total_s"]}')
        out.append(f'lightgbm_tpu_span_seconds_count{{name="{lbl}"}} '
                   f'{h["count"]}')
    out.append("# TYPE lightgbm_tpu_counter_total counter")
    for name, v in sorted(rep["counters"].items()):
        out.append(f'lightgbm_tpu_counter_total{{name="{_esc(name)}"}} {v}')
    out.append("# TYPE lightgbm_tpu_compiles_total counter")
    for key, v in sorted(rep["compiles"].items()):
        out.append(f'lightgbm_tpu_compiles_total{{key="{_esc(key)}"}} {v}')
    out.append("# TYPE lightgbm_tpu_gauge gauge")
    for name, v in sorted(rep["gauges"].items()):
        out.append(f'lightgbm_tpu_gauge{{name="{_esc(name)}"}} {float(v)}')
    out.append("# TYPE lightgbm_tpu_events_dropped counter")
    out.append(f"lightgbm_tpu_events_dropped {rep['events_dropped']}")
    return "\n".join(out) + "\n"


def export_prometheus(tel, path: str) -> str:
    return _atomic_write(path, prometheus_text(tel))


def export_all(tel, out_dir: str) -> Dict[str, str]:
    """Write all three artifacts under ``out_dir``; returns their
    paths (the CLI's ``telemetry_out=`` entry point)."""
    os.makedirs(out_dir, exist_ok=True)
    return {
        "jsonl": export_jsonl(tel, os.path.join(out_dir,
                                                "telemetry.jsonl")),
        "trace": export_chrome_trace(tel, os.path.join(out_dir,
                                                       "trace.json")),
        "prometheus": export_prometheus(tel, os.path.join(out_dir,
                                                          "metrics.prom")),
    }
