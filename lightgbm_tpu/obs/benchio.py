"""Machine-readable benchmark artifacts: ``BENCH_obs.json``.

The perf trajectory to date lives in PERF.md prose; every bench/ab_bench
run now also drops one structured artifact so rounds can be diffed,
plotted and regression-checked by tooling.  One file per run (atomic
write), schema::

    {"schema": "lightgbm-tpu/bench-obs/v2",
     "tool": "bench" | "ab_bench" | ...,
     "unix_time": ..., "backend": "cpu"|"tpu"|...,
     "config": {...},            # the knobs that shaped the run
     "timings": {...},           # the tool's own timing report
     "compile_counts": {...},    # telemetry compile events (key -> n)
     "memory_peaks": {...},      # ledger owners + backend allocator stats
     "health": {...}}            # v2: model/data-health section — digest
                                 # overhead numbers, skew scores from the
                                 # drift drill, flight-recorder summary
                                 # (null when the run carried none)

Schema history: v1 had no ``health`` key; v2 adds it (always present,
possibly null).  ``validate_bench_obs`` checks the v2 shape — the
``ab_bench --drift`` lane asserts its health numbers and
``trace_report --smoke`` validates the document structure.

Path: ``--obs-out``/caller argument, else ``$BENCH_OBS_PATH``, else
``BENCH_obs.json`` in the working directory.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from . import memory as obs_memory
from . import telemetry as obs_telemetry
from .exporters import _atomic_write

SCHEMA = "lightgbm-tpu/bench-obs/v2"

__all__ = ["SCHEMA", "default_path", "collect_compile_counts",
           "collect_memory_peaks", "write_bench_obs",
           "validate_bench_obs"]


def default_path() -> str:
    return os.environ.get("BENCH_OBS_PATH", "BENCH_obs.json")


def collect_compile_counts() -> Dict[str, int]:
    return dict(obs_telemetry.get().report()["compiles"])


def collect_memory_peaks() -> Dict[str, Any]:
    snap = obs_memory.snapshot()
    out: Dict[str, Any] = {
        "owners": snap["owners"],
        "live_device_bytes": snap["live_device_bytes"],
    }
    if snap["device_memory_stats"]:
        out["backend"] = snap["device_memory_stats"]
    return out


def write_bench_obs(tool: str, config: Dict[str, Any],
                    timings: Dict[str, Any],
                    compile_counts: Optional[Dict[str, int]] = None,
                    memory_peaks: Optional[Dict[str, Any]] = None,
                    health: Optional[Dict[str, Any]] = None,
                    path: Optional[str] = None) -> str:
    """Write the artifact; never raises past a warning (a failed
    artifact write must not sink a finished benchmark).  ``health``
    is the v2 model/data-health section (skew scores, digest overhead
    — see the module docstring); the key is always present so schema
    consumers need no version branch."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    doc = {
        "schema": SCHEMA,
        "tool": tool,
        "unix_time": round(time.time(), 3),
        "backend": backend,
        "config": config,
        "timings": timings,
        "compile_counts": (collect_compile_counts()
                           if compile_counts is None else compile_counts),
        "memory_peaks": (collect_memory_peaks()
                         if memory_peaks is None else memory_peaks),
        "health": health,
    }
    out = path or default_path()
    try:
        return _atomic_write(out, json.dumps(doc, sort_keys=True,
                                             default=str) + "\n")
    except OSError as exc:
        from ..utils import log
        log.warning("could not write %s: %s", out, exc)
        return out


def validate_bench_obs(doc: Dict[str, Any]) -> List[str]:
    """Structural problems of a BENCH_obs document against schema v2
    (empty list = valid).  Used by ``trace_report --smoke`` and the
    ``ab_bench --drift`` lane so a malformed artifact fails loudly."""
    problems: List[str] = []
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, want {SCHEMA!r}")
    for key, typ in (("tool", str), ("config", dict), ("timings", dict),
                     ("compile_counts", dict), ("memory_peaks", dict)):
        if not isinstance(doc.get(key), typ):
            problems.append(f"{key} missing or not a {typ.__name__}")
    if "health" not in doc:
        problems.append("health key missing (v2 requires it, null ok)")
    elif doc["health"] is not None:
        h = doc["health"]
        if not isinstance(h, dict):
            problems.append("health is not an object")
        elif not any(k in h for k in ("skew_top", "digest_overhead_pct",
                                      "flight_recorder", "planted_rank")):
            problems.append("health section carries none of the known "
                            "keys (skew_top / digest_overhead_pct / "
                            "flight_recorder / planted_rank)")
    return problems
