"""Machine-readable benchmark artifacts: ``BENCH_obs.json``.

The perf trajectory to date lives in PERF.md prose; every bench/ab_bench
run now also drops one structured artifact so rounds can be diffed,
plotted and regression-checked by tooling.  One file per run (atomic
write), schema::

    {"schema": "lightgbm-tpu/bench-obs/v3",
     "tool": "bench" | "ab_bench" | ...,
     "unix_time": ..., "backend": "cpu"|"tpu"|...,
     "fingerprint": {...},        # v3: hardware/config identity
                                  # (obs/regress.py — device kind/count,
                                  # CPU cores, jax versions, x64, shape
                                  # band, tpu_* knobs)
     "aborted": false,            # v3: true when the measured tool died
                                  # and the artifact records the wreck
     "config": {...},             # the knobs that shaped the run
     "timings": {...},            # the tool's own timing report
     "compile_counts": {...},     # telemetry compile events (key -> n)
     "memory_peaks": {...},       # ledger owners + backend allocator stats
     "health": {...}}             # model/data-health section (null when
                                  # the run carried none)

Schema history: v1 had no ``health`` key; v2 added it (always present,
possibly null); v3 adds ``fingerprint`` + ``aborted`` and every write
also APPENDS a compact entry to the ``BENCH_history.jsonl`` trajectory
(:mod:`lightgbm_tpu.obs.regress`) so the measurement survives past the
one-file artifact.  ``validate_bench_obs`` checks v3 and still accepts
v2 documents (older artifacts stay readable).

Path: ``--obs-out``/caller argument, else ``$BENCH_OBS_PATH``, else
``BENCH_obs.json`` in the working directory.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from . import memory as obs_memory
from . import regress
from . import telemetry as obs_telemetry
from .exporters import _atomic_write

SCHEMA = "lightgbm-tpu/bench-obs/v3"
SCHEMA_V2 = "lightgbm-tpu/bench-obs/v2"

__all__ = ["SCHEMA", "SCHEMA_V2", "default_path",
           "collect_compile_counts", "collect_memory_peaks",
           "write_bench_obs", "validate_bench_obs", "abort_guard"]


def default_path() -> str:
    return os.environ.get("BENCH_OBS_PATH", "BENCH_obs.json")


def collect_compile_counts() -> Dict[str, int]:
    return dict(obs_telemetry.get().report()["compiles"])


def collect_memory_peaks() -> Dict[str, Any]:
    snap = obs_memory.snapshot()
    out: Dict[str, Any] = {
        "owners": snap["owners"],
        "live_device_bytes": snap["live_device_bytes"],
    }
    if snap["device_memory_stats"]:
        out["backend"] = snap["device_memory_stats"]
    return out


def _auto_metrics(timings: Dict[str, Any]) -> Dict[str, float]:
    """Fallback trajectory metrics: the numeric scalars at the top
    level of the timings report (producers that care pass ``metrics``
    explicitly)."""
    return {k: float(v) for k, v in (timings or {}).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def write_bench_obs(tool: str, config: Dict[str, Any],
                    timings: Dict[str, Any],
                    compile_counts: Optional[Dict[str, int]] = None,
                    memory_peaks: Optional[Dict[str, Any]] = None,
                    health: Optional[Dict[str, Any]] = None,
                    path: Optional[str] = None,
                    metrics: Optional[Dict[str, float]] = None,
                    aborted: bool = False,
                    rows: Optional[int] = None,
                    features: Optional[int] = None,
                    fingerprint_extra: Optional[Dict[str, Any]] = None,
                    history_path: Optional[str] = None) -> str:
    """Write the artifact AND append a fingerprinted entry to the
    ``BENCH_history.jsonl`` trajectory; never raises past a warning (a
    failed artifact write must not sink a finished benchmark).
    ``metrics`` selects the scalars the trajectory tracks (default:
    the numeric top level of ``timings``); ``aborted`` marks a run
    whose measured tool died — the detector skips it, the evidence
    persists."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    fp = regress.fingerprint(config, rows=rows, features=features,
                             extra=fingerprint_extra)
    doc = {
        "schema": SCHEMA,
        "tool": tool,
        "unix_time": round(time.time(), 3),
        "backend": backend,
        "fingerprint": fp,
        "aborted": bool(aborted),
        "config": config,
        "timings": timings,
        "compile_counts": (collect_compile_counts()
                           if compile_counts is None else compile_counts),
        "memory_peaks": (collect_memory_peaks()
                         if memory_peaks is None else memory_peaks),
        "health": health,
    }
    out = path or default_path()
    try:
        out = _atomic_write(out, json.dumps(doc, sort_keys=True,
                                            default=str) + "\n")
    except OSError as exc:
        from ..utils import log
        log.warning("could not write %s: %s", out, exc)
    try:
        regress.append_entry(
            tool, metrics if metrics is not None else _auto_metrics(timings),
            config=config, fingerprint_doc=fp, aborted=aborted,
            path=history_path)
    except OSError as exc:
        from ..utils import log
        log.warning("could not append %s: %s",
                    history_path or regress.default_path(), exc)
    return out


class _ObsGuard:
    def __init__(self, tool: str, config: Dict[str, Any],
                 path: Optional[str], history_path: Optional[str]):
        self.tool = tool
        self.config = config
        self.path = path
        self.history_path = history_path
        self.written = False

    def write(self, timings: Dict[str, Any], **kw: Any) -> str:
        self.written = True
        kw.setdefault("tool", self.tool)
        kw.setdefault("config", self.config)
        kw.setdefault("path", self.path)
        kw.setdefault("history_path", self.history_path)
        return write_bench_obs(kw.pop("tool"), kw.pop("config"),
                               timings, **kw)


@contextlib.contextmanager
def abort_guard(tool: str, config: Dict[str, Any],
                path: Optional[str] = None,
                history_path: Optional[str] = None):
    """Export-on-failure for BENCH_obs writers (the CLI telemetry
    contract): if the measured block dies before ``guard.write(...)``
    ran, an artifact with ``aborted: true`` and the error text is
    emitted anyway — a crashed benchmark leaves evidence, not a missing
    file — and the failure propagates unchanged (the tool's exit code
    survives)."""
    guard = _ObsGuard(tool, config, path, history_path)
    try:
        yield guard
    except BaseException as exc:
        if not guard.written:
            guard.write({"error": f"{type(exc).__name__}: {exc}"[:300]},
                        metrics={}, aborted=True)
        raise


def validate_bench_obs(doc: Dict[str, Any]) -> List[str]:
    """Structural problems of a BENCH_obs document against schema v3
    (empty list = valid); v2 documents remain valid — the trajectory
    predates the fingerprint and old artifacts must stay readable.
    Used by ``trace_report --smoke``, the ``ab_bench --drift`` lane and
    tests so a malformed artifact fails loudly."""
    problems: List[str] = []
    schema = doc.get("schema")
    if schema not in (SCHEMA, SCHEMA_V2):
        problems.append(f"schema is {schema!r}, want {SCHEMA!r} "
                        f"(or the still-readable {SCHEMA_V2!r})")
    for key, typ in (("tool", str), ("config", dict), ("timings", dict),
                     ("compile_counts", dict), ("memory_peaks", dict)):
        if not isinstance(doc.get(key), typ):
            problems.append(f"{key} missing or not a {typ.__name__}")
    if "health" not in doc:
        problems.append("health key missing (v2+ requires it, null ok)")
    elif doc["health"] is not None:
        h = doc["health"]
        if not isinstance(h, dict):
            problems.append("health is not an object")
        elif not any(k in h for k in ("skew_top", "digest_overhead_pct",
                                      "flight_recorder", "planted_rank")):
            problems.append("health section carries none of the known "
                            "keys (skew_top / digest_overhead_pct / "
                            "flight_recorder / planted_rank)")
    if schema == SCHEMA:
        fp = doc.get("fingerprint")
        if not isinstance(fp, dict):
            problems.append("fingerprint missing or not an object "
                            "(v3 requires it)")
        else:
            for k in ("device_kind", "device_count", "cpu_count",
                      "x64", "shape_band", "knobs"):
                if k not in fp:
                    problems.append(f"fingerprint.{k} missing")
        if not isinstance(doc.get("aborted", False), bool):
            problems.append("aborted is not a boolean")
    return problems
